"""Monitor: the cluster-map authority.

Mini-cluster twin of the reference monitor's OSDMonitor role
(src/mon/OSDMonitor.cc): owns the OSDMap, advances epochs on osd
boot/failure/out, serves map subscriptions, and executes admin commands
— EC profile set, pool create (profile -> plugin factory -> CRUSH rule,
the seam OSDMonitor::prepare_new_pool / crush_rule_create_erasure
drives, OSDMonitor.cc:7339,7466-7523), osd down/out.

Every mutation is committed through the Paxos quorum (ceph_tpu/mon/
paxos.py) before it takes effect, and the MonitorDBStore twin
(ceph_tpu/mon/store.py) makes the committed state durable; mutating
commands are leader-only and peons forward (PaxosService semantics).
The monitor also aggregates the OSDs' per-PG stat reports (beacons
carry them — the MPGStats/DaemonServer plane) and serves status /
health / pg stat with real checks (OSD_DOWN, MON_DOWN, PG_DEGRADED;
reference src/mon/HealthMonitor.cc, src/mon/MgrStatMonitor.cc).

Failure handling: failure reports (MOSDFailure) mark the target down
immediately (reference grace logic OSDMonitor::check_failure collapses
to one report in a mini cluster), and a beacon-liveness sweep marks
OSDs down/out when beacons stop — both produce new map epochs that are
pushed to every subscriber, which is what triggers peer OSDs to
re-peer and recover.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import time

from ceph_tpu.crush.types import CrushMap
from ceph_tpu.ec import registry as ec_registry
from ceph_tpu.msg.messages import (
    MConfig,
    MMonCommand,
    MMonCommandAck,
    MMonSubscribe,
    MOSDBeacon,
    MOSDBoot,
    MOSDFailure,
    MOSDMap,
    MOSDScrub,
    MOSDScrubReply,
)
from ceph_tpu.msg.messenger import Connection, Message, Messenger
from ceph_tpu.osd.mapenc import (
    decode_osdmap,
    diff_osdmap,
    encode_incremental,
    encode_osdmap,
)
from ceph_tpu.osd.osdmap import OSDMap
from ceph_tpu.osd.types import PgPool, PoolType

log = logging.getLogger("ceph_tpu.mon")


class Monitor:
    def __init__(
        self,
        crush: CrushMap | None = None,
        beacon_grace: float = 0.0,
        out_interval: float = 0.0,
        rank: int = 0,
        n_mons: int = 1,
        store=None,
        min_down_reporters: int | None = None,
        paxos_trim_max: int = 500,
        paxos_trim_keep: int = 250,
        conf=None,
        auth=None,
    ):
        """``beacon_grace``/``out_interval``: seconds without a beacon
        before an OSD is marked down / out; 0 disables the sweep (tests
        drive failure via MOSDFailure or commands).

        ``store``: an ObjectStore giving the monitor MonitorDBStore-like
        durability — paxos promises/commits persist there and a restart
        replays snapshot + committed tail (pass a FileStore for a
        monitor that survives kill -9).  None = volatile.

        Multi-monitor quorums: construct each member with its ``rank``
        and the total ``n_mons``, ``start()`` them all, then call
        ``open_quorum(monmap)`` with every member's address — the
        rank-based election picks a leader and all state mutations
        replicate through Paxos (ceph_tpu/mon/paxos.py)."""
        from ceph_tpu.mon.paxos import Paxos
        from ceph_tpu.mon.store import MonStore

        self.rank = rank
        self.n_mons = n_mons
        self.monmap: list[tuple[str, int]] = []
        self.osdmap = OSDMap(crush=crush or CrushMap())
        conf0 = conf
        if conf0 is None:
            from ceph_tpu.common import ConfigProxy as _CP

            conf0 = _CP()
        self.messenger = Messenger(
            ("mon", rank), self._dispatch, on_reset=self._on_reset,
            auth=auth,
            compress_mode=conf0["ms_compress_mode"],
            compress_algorithm=conf0["ms_compress_algorithm"],
            compress_min_size=conf0["ms_compress_min_size"],
            handshake_timeout=conf0["ms_connection_ready_timeout"],
        )
        self.store = MonStore(store) if store is not None else None
        self.paxos = Paxos(
            rank, n_mons, self._send_mon, self._apply_committed,
            store=self.store,
            get_snapshot=self._state_snapshot,
            install_snapshot=self._install_snapshot,
        )
        self._state_version = 0
        if conf is None:
            from ceph_tpu.common import ConfigProxy

            conf = ConfigProxy()
        self.conf = conf
        self.min_down_reporters = (
            min_down_reporters if min_down_reporters is not None
            else conf["mon_osd_min_down_reporters"]
        )
        self.paxos_trim_max = paxos_trim_max
        self.paxos_trim_keep = paxos_trim_keep
        # failed osd -> {reporter: report time} (OSDMonitor failure_info)
        self._failure_reports: dict[int, dict[int, float]] = {}
        self.beacon_grace = beacon_grace
        self.out_interval = out_interval
        self._epoch_blobs: dict[int, bytes] = {}
        self._epoch_incs: dict[int, bytes] = {}
        self._subscribers: dict[tuple[str, int], Connection] = {}
        self._last_beacon: dict[int, float] = {}
        self._down_at: dict[int, float] = {}
        # derived replicated state: last boot incarnation per osd
        # (applied deterministically by every member in _apply_op)
        self._osd_incarnation: dict[int, int] = {}
        # epoch at which each osd was last marked up (up_from): failure
        # reports older than this are from before the reboot
        self._up_from: dict[int, int] = {}
        self._pool_ids: dict[str, int] = {}
        # ConfigMonitor database: section ('global', 'osd', 'osd.3',
        # 'mon', 'client') -> {option: value}; replicated via paxos and
        # pushed to every subscriber as MConfig
        self._config_db: dict[str, dict[str, str]] = {}
        # AuthMonitor database: entity -> {"key": hex, "caps": {...}},
        # paxos-replicated, mirrored into the live AuthContext keyring
        self._auth_db: dict[str, dict] = {}
        # construction-keyring identities: the root of trust the
        # command plane may never rebind, clobber, or delete
        self._bootstrap_entities: set[str] = (
            set(auth.keyring) if auth is not None else set()
        )
        self._next_pool = 1
        self._tids = itertools.count(1)
        self._scrub_waiters: dict[int, asyncio.Future] = {}
        self._tick_task: asyncio.Task | None = None
        self._probe_task = None
        self._admin = None
        self.addr: tuple[str, int] | None = None
        self._snapshot()

    # -- lifecycle -----------------------------------------------------

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> tuple[str, int]:
        self.addr = await self.messenger.bind(host, port)
        sock_path = self.conf["admin_socket"]
        if sock_path:
            from ceph_tpu.common import AdminSocket

            self._admin = AdminSocket(
                sock_path.replace("$id", f"mon{self.rank}")
            )
            self._admin.register(
                "config show", "effective configuration",
                lambda cmd: self.conf.show(),
            )
            self._admin.register(
                "quorum_status", "election/quorum state",
                lambda cmd: {
                    "rank": self.rank,
                    "leader": self.paxos.leader,
                    "election_epoch": self.paxos.election_epoch,
                    "quorum": sorted(self.paxos.quorum),
                    "last_committed": self.paxos.last_committed,
                },
            )
            self._admin.register(
                "status", "cluster status",
                lambda cmd: {
                    "epoch": self.osdmap.epoch,
                    "num_pools": len(self.osdmap.pools),
                },
            )
            await self._admin.start()
        await self._replay()
        if self.beacon_grace > 0:
            self._tick_task = asyncio.ensure_future(self._tick())
        if self.conf["mon_pg_autoscale_interval"] > 0:
            self._autoscale_task = asyncio.ensure_future(
                self._autoscale_tick())
        return self.addr

    async def _replay(self) -> None:
        """Restart recovery: install the persisted snapshot (if any),
        then re-apply the committed tail in paxos order — the
        MonitorDBStore replay that makes a mon restart lossless."""
        if self.store is None:
            return
        st = self.store.load()
        self._replaying = True
        try:
            if st["snapshot"] is not None and st["snapshot"][0] > 0:
                await self._install_snapshot(*st["snapshot"], publish=False)
            for v in sorted(self.paxos.values):
                if v > self._state_version and self.paxos.values[v]:
                    await self._apply_committed(v, self.paxos.values[v])
        finally:
            self._replaying = False
        await self._maybe_trim()

    # -- state-machine snapshots (trim / full-sync / restart) ----------

    def _state_snapshot(self) -> tuple[int, bytes]:
        """(version, blob): everything _apply_op derives, captured
        atomically at _state_version."""
        import json

        from ceph_tpu.msg.denc import Encoder

        enc = Encoder()
        enc.u64(self._state_version)
        enc.bytes_(encode_osdmap(self.osdmap))
        enc.str_(json.dumps({
            "pool_ids": self._pool_ids,
            "next_pool": self._next_pool,
            "incarnations": {
                str(k): v for k, v in self._osd_incarnation.items()
            },
            "up_from": {str(k): v for k, v in self._up_from.items()},
            "config_db": self._config_db,
            "auth_db": self._auth_db,
        }))
        return self._state_version, enc.bytes()

    async def _install_snapshot(
        self, version: int, blob: bytes, publish: bool = True
    ) -> None:
        import json

        from ceph_tpu.msg.denc import Decoder

        dec = Decoder(blob)
        snap_version = dec.u64()
        self.osdmap = decode_osdmap(dec.bytes_())
        aux = json.loads(dec.str_())
        self._pool_ids = dict(aux["pool_ids"])
        self._next_pool = aux["next_pool"]
        self._osd_incarnation = {
            int(k): v for k, v in aux["incarnations"].items()
        }
        self._config_db = dict(aux.get("config_db", {}))
        self._auth_db = dict(aux.get("auth_db", {}))
        self._sync_auth_keyring()
        self._apply_config_locally()
        self._up_from = {
            int(k): v for k, v in aux.get("up_from", {}).items()
        }
        self._state_version = max(version, snap_version)
        self._epoch_blobs = {}
        self._epoch_incs = {}
        self._prev_snapshot = None
        self._snapshot()
        if publish:
            await self._publish()

    async def _maybe_trim(self) -> None:
        """Bound the committed log: snapshot the state machine, then
        drop values older than the keep window (Paxos::trim)."""
        if getattr(self, "_replaying", False):
            # NEVER trim mid-replay: ``below`` derives from the final
            # last_committed, so trimming here would delete committed
            # ops the replay loop has not applied yet — both from RAM
            # (KeyError on the next iteration) and, worse, durably
            return
        px = self.paxos
        if len(px.values) <= self.paxos_trim_max:
            return
        below = px.last_committed - self.paxos_trim_keep + 1
        if self.store is not None:
            await self.store.put_snapshot(*self._state_snapshot())
        px.values = {v: b for v, b in px.values.items() if v >= below}
        px.first_committed = below
        if self.store is not None:
            await self.store.trim_values(below)

    async def open_quorum(self, monmap: list[tuple[str, int]]) -> None:
        """Join the quorum: learn everyone's address, run an election
        (call on every member after all have start()ed — or, with the
        probe below, merely *around* the same time)."""
        assert len(monmap) == self.n_mons
        self.monmap = list(monmap)
        await self.paxos.start_election()
        if self.n_mons > 1 and self._probe_task is None:
            self._probe_task = asyncio.ensure_future(self._quorum_probe())

    async def _quorum_probe(self) -> None:
        """A member outside a stable quorum re-runs the election until
        it joins (the reference's probe/join phase): a mon whose first
        election raced its peers' boot — multi-process deployments bind
        at slightly different times — missed VICTORY and would
        otherwise wait forever."""
        while True:
            await asyncio.sleep(2.0)
            if not self.paxos.stable.is_set():
                try:
                    await self.paxos.start_election()
                except (ConnectionError, OSError):
                    continue

    async def wait_stable(self, timeout: float = 10.0) -> None:
        await asyncio.wait_for(self.paxos.stable.wait(), timeout)

    async def stop(self) -> None:
        if self._admin is not None:
            await self._admin.stop()
        if self._tick_task:
            self._tick_task.cancel()
        if self._probe_task:
            self._probe_task.cancel()
        if getattr(self, "_autoscale_task", None):
            self._autoscale_task.cancel()
        await self.messenger.shutdown()

    # -- quorum plumbing ----------------------------------------------

    async def _send_mon(self, rank: int, msg: Message) -> None:
        if rank < len(self.monmap):
            conn = await self.messenger.connect_to(
                ("mon", rank), *self.monmap[rank]
            )
        else:
            # a peer reached us before our own open_quorum(): reply over
            # the connection it already established
            conn = self.messenger.get_connection(("mon", rank))
            if conn is None:
                raise ConnectionError(f"mon.{rank} address unknown")
        await conn.send_message(msg)

    async def _on_reset(self, conn) -> None:
        peer = conn.peer
        if (
            peer is not None
            and peer[0] == "mon"
            and self.n_mons > 1
            and (
                self.paxos.leader == peer[1]
                # a leader losing ANY voting-quorum member must re-form
                # the quorum, or BEGINs starve waiting on the dead vote
                or (self.paxos.is_leader and peer[1] in self.paxos.quorum)
            )
        ):
            if not self.paxos.stable.is_set():
                return  # already electing: don't stack another round
            # both sides dial each other, so duplicate-connection
            # teardown is routine — only elect if the leader is truly
            # unreachable (a false election churns accepted_pn under
            # in-flight BEGINs and stalls proposes for their timeout)
            try:
                if peer[1] < len(self.monmap):
                    await asyncio.wait_for(self.messenger.connect_to(
                        ("mon", peer[1]), *self.monmap[peer[1]]
                    ), 2.0)
                    return  # reconnected: not a leader loss
            except (ConnectionError, OSError, asyncio.TimeoutError):
                pass
            log.info(
                "mon.%d: quorum peer mon.%d lost; electing",
                self.rank, peer[1],
            )
            await self.paxos.start_election()

    async def _apply_committed(self, version: int, value: bytes) -> None:
        import json

        op = json.loads(value.decode())
        await self._apply_op(op)
        self._state_version = version
        await self._maybe_trim()

    async def _propose(self, op: dict) -> None:
        """Replicate one state mutation through Paxos (leader only;
        single-mon quorums commit immediately).  One retry after a
        mid-propose election (quorum-member loss): every replicated op
        is replay-idempotent, so a rare double-commit is harmless."""
        import json

        value = json.dumps(op).encode()
        last: Exception | None = None
        for _attempt in range(5):
            try:
                await self.paxos.propose(value)
                return
            except ConnectionError as e:
                last = e
                try:
                    await asyncio.wait_for(self.paxos.stable.wait(), 10)
                except asyncio.TimeoutError:
                    raise e
                if not self.is_leader:
                    raise
                await asyncio.sleep(0.05)
        raise last

    @property
    def is_leader(self) -> bool:
        return self.paxos.is_leader

    # -- map publication ----------------------------------------------

    def _snapshot(self) -> None:
        from ceph_tpu.osd.mapenc import crush_sections

        epoch = self.osdmap.epoch
        blob = self._epoch_blobs[epoch] = encode_osdmap(self.osdmap)
        # delta vs the previous epoch (OSDMap::Incremental): cheap
        # publication; subscribers land bit-identical to the full map.
        # The previous epoch's decoded map and crush encodes are cached
        # so an epoch tick costs one diff, not two decodes + four
        # crush encodes.
        sections = crush_sections(self.osdmap)
        prev = getattr(self, "_prev_snapshot", None)
        if prev is not None and prev[0] == epoch - 1:
            inc = diff_osdmap(
                prev[1], self.osdmap,
                old_sections=prev[2], new_sections=sections,
            )
            self._epoch_incs[epoch] = encode_incremental(inc)
        self._prev_snapshot = (epoch, decode_osdmap(blob), sections)
        # bound history
        for e in sorted(self._epoch_blobs)[:-500]:
            del self._epoch_blobs[e]
        for e in sorted(self._epoch_incs)[:-500]:
            del self._epoch_incs[e]

    async def _new_epoch(self) -> None:
        self.osdmap.epoch += 1
        self._snapshot()
        await self._publish()

    async def _publish(self) -> None:
        epoch = self.osdmap.epoch
        inc = self._epoch_incs.get(epoch)
        if inc is not None:
            msg = MOSDMap(incs={epoch: inc})
        else:
            msg = MOSDMap(maps={epoch: self._epoch_blobs[epoch]})
        for peer, conn in list(self._subscribers.items()):
            try:
                await conn.send_message(msg)
            except ConnectionError:
                self._subscribers.pop(peer, None)

    def _maps_since(self, start_epoch: int) -> "MOSDMap":
        """Catch-up payload for a subscriber at ``start_epoch``:
        incrementals when the whole (start, current] range is on hand,
        else the latest full map (OSDMonitor::send_incremental)."""
        epoch = self.osdmap.epoch
        if 0 < start_epoch <= epoch:
            want = range(start_epoch + 1, epoch + 1)
            if all(e in self._epoch_incs for e in want):
                return MOSDMap(incs={e: self._epoch_incs[e] for e in want})
        return MOSDMap(maps={epoch: self._epoch_blobs[epoch]})

    # -- dispatch ------------------------------------------------------

    async def _dispatch(self, msg: Message) -> None:
        from ceph_tpu.mon.paxos import MMonElection, MMonPaxos

        if isinstance(msg, MMonElection):
            await self.paxos.handle_election(msg, msg.src[1])
        elif isinstance(msg, MMonPaxos):
            await self.paxos.handle_paxos(msg, msg.src[1])
        elif isinstance(msg, MOSDBoot):
            await self._handle_boot(msg)
        elif isinstance(msg, MOSDBeacon):
            if self.is_leader:
                self._last_beacon[msg.osd] = time.monotonic()
                if msg.pg_stats:
                    self._ingest_pg_stats(msg.osd, msg.epoch, msg.pg_stats)
                if msg.statfs:
                    await self._ingest_statfs(msg.osd, msg.statfs)
            else:
                await self._forward_to_leader(msg)
        elif isinstance(msg, MOSDFailure):
            await self._handle_failure(msg)
        elif isinstance(msg, MMonSubscribe):
            self._subscribers[msg.src] = msg.conn
            await msg.conn.send_message(self._maps_since(msg.start_epoch))
            secs = self._config_sections_for(msg.src)
            if secs:
                await msg.conn.send_message(MConfig(sections=secs))
        elif isinstance(msg, MOSDScrubReply):
            fut = self._scrub_waiters.get(msg.tid)
            if fut and not fut.done():
                fut.set_result(msg)
        elif isinstance(msg, MMonCommand):
            code, rs, data = await self._command(
                msg.cmd, caps=getattr(msg.conn, "peer_caps", None))
            await msg.conn.send_message(
                MMonCommandAck(tid=msg.tid, code=code, rs=rs, data=data)
            )

    async def _forward_to_leader(self, msg: Message) -> None:
        """Peons forward state-changing daemon messages to the leader
        (the reference's Monitor::forward_request_leader)."""
        leader = self.paxos.leader
        if leader is None or leader == self.rank or not self.monmap:
            return
        try:
            await self._send_mon(leader, msg)
        except (ConnectionError, OSError):
            pass

    async def _handle_boot(self, m: MOSDBoot) -> None:
        if not self.is_leader:
            await self._forward_to_leader(m)
            return
        log.info("mon: osd.%d booted at %s:%d", m.osd, m.host, m.port)
        self._last_beacon[m.osd] = time.monotonic()
        self._down_at.pop(m.osd, None)
        self._failure_reports.pop(m.osd, None)
        await self._propose({
            "op": "boot", "osd": m.osd, "host": m.host, "port": m.port,
            "weight": m.weight, "incarnation": m.incarnation,
        })

    async def _handle_failure(self, m: MOSDFailure) -> None:
        if not self.is_leader:
            await self._forward_to_leader(m)
            return
        om = self.osdmap
        if 0 <= m.failed < om.max_osd and om.is_up(m.failed):
            if m.epoch < self._up_from.get(m.failed, 0):
                # the report predates the target's latest boot: a
                # straggler from before the reboot, not fresh evidence
                # (OSDMonitor::check_failure vs up_from)
                return
            now = time.monotonic()
            reporters = self._failure_reports.setdefault(m.failed, {})
            reporters[m.reporter] = now
            # expire stale reports (the reference ages failure_info by
            # grace; 60 s here)
            for r, t0 in list(reporters.items()):
                if now - t0 > 60.0:
                    del reporters[r]
            if len(reporters) < self.min_down_reporters:
                log.info(
                    "mon: osd.%d failure report %d/%d (from osd.%d)",
                    m.failed, len(reporters), self.min_down_reporters,
                    m.reporter,
                )
                return
            log.info(
                "mon: osd.%d reported failed by %s", m.failed,
                sorted(reporters),
            )
            self._failure_reports.pop(m.failed, None)
            self._down_at[m.failed] = now
            await self._propose({"op": "down", "osd": m.failed})

    # -- the replicated state machine ----------------------------------

    async def _apply_op(self, op: dict) -> None:
        """Apply one committed mutation deterministically — runs on
        every quorum member in paxos order."""
        kind = op["op"]
        om = self.osdmap
        if kind == "boot":
            osd, addr = op["osd"], (op["host"], op["port"])
            inc = op.get("incarnation", 0)
            stored = self._osd_incarnation.get(osd, 0)
            if inc and inc < stored:
                # reordered boot from an EARLIER daemon start (e.g. a
                # delayed peon-forwarded duplicate): drop it entirely so
                # it can neither bump the epoch nor regress the address
                return
            if (
                om.is_up(osd)
                and om.osd_addrs.get(osd) == addr
                and om.osd_weight[osd] == op["weight"]
                and inc == stored
            ):
                # paxos replay of the same boot: no epoch bump.  A
                # genuine fast restart carries a NEW incarnation and
                # must bump the epoch so peers re-peer/recover toward
                # the fresh (empty) daemon.
                return
            self._osd_incarnation[osd] = inc
            om.new_osd(osd, weight=op["weight"], up=True)
            om.osd_addrs[osd] = addr
            self._up_from[osd] = om.epoch + 1  # the epoch this op creates
        elif kind == "down":
            if not (0 <= op["osd"] < om.max_osd) or not om.is_up(op["osd"]):
                return  # no-op: no epoch bump
            om.mark_down(op["osd"])
        elif kind == "out":
            if not (0 <= op["osd"] < om.max_osd) or om.is_out(op["osd"]):
                return
            om.mark_out(op["osd"])
        elif kind == "full_state":
            from ceph_tpu.osd.osdmap import CEPH_OSD_FULL_MASK

            osd = op["osd"]
            if not om.exists(osd):
                return
            cur = om.osd_state[osd]
            new = (cur & ~CEPH_OSD_FULL_MASK) | (
                op["bits"] & CEPH_OSD_FULL_MASK)
            if new == cur:
                return  # replay: no epoch
            om.osd_state[osd] = new
        elif kind == "profile":
            om.erasure_code_profiles[op["name"]] = dict(op["profile"])
        elif kind == "pool_create":
            self._apply_pool_create(op)
        elif kind == "config_set":
            db = self._config_db.setdefault(op["who"], {})
            db[op["name"]] = op["value"]
            self._apply_config_locally()
            await self._push_config()
            return  # config changes don't mint osdmap epochs
        elif kind == "config_rm":
            self._config_db.get(op["who"], {}).pop(op["name"], None)
            self._apply_config_locally()
            await self._push_config()
            return
        elif kind == "crush_reweight":
            from ceph_tpu.crush import builder as _builder

            if not _builder.reweight_item(
                    om.crush, op["item"], op["weight"]):
                return  # unknown item: no epoch
        elif kind == "crush_add_bucket":
            from ceph_tpu.crush import builder as _builder

            if op["name"] in om.crush.bucket_names:
                return  # replay
            _builder.add_bucket(om.crush, op["name"], op["type"])
        elif kind == "crush_move":
            from ceph_tpu.crush import builder as _builder

            name = op["item_name"]
            if name.startswith("osd."):
                item = int(name[4:])
            elif name in om.crush.bucket_names:
                item = om.crush.bucket_names[name]
            else:
                return
            parent = om.crush.bucket_names.get(op["loc"])
            if parent is None:
                return
            if not _builder.move_item(
                    om.crush, item, parent, op.get("weight")):
                return  # cycle: no epoch
        elif kind == "crush_rm":
            from ceph_tpu.crush import builder as _builder

            name = op["item_name"]
            if name.startswith("osd."):
                item = int(name[4:])
            elif name in om.crush.bucket_names:
                item = om.crush.bucket_names[name]
            else:
                return
            if item < 0 and om.crush.buckets.get(item, None) is not None \
                    and om.crush.buckets[item].items:
                return  # became non-empty since validation: refuse
            if not _builder.remove_item(om.crush, item):
                return
        elif kind == "snap_alloc":
            pool = om.pools[op["pool"]]
            pool.snap_seq = max(pool.snap_seq, op["snapid"])
            if op.get("name"):
                pool.pool_snaps[op["name"]] = op["snapid"]
        elif kind == "snap_rm":
            pool = om.pools[op["pool"]]
            pool.removed_snaps.add(op["snapid"])
            if op.get("name"):
                pool.pool_snaps.pop(op["name"], None)
        elif kind == "upmap":
            from ceph_tpu.osd.types import pg_t

            for pool, ps, pairs in op["items"]:
                om.pg_upmap_items[pg_t(pool, ps)] = [
                    (f, t) for f, t in pairs
                ]
        elif kind == "pool_set":
            pool = om.pools.get(op["pool"])
            if pool is None:
                return
            var, val = op["var"], op["val"]
            if var == "pg_num":
                n = int(val)
                if n == pool.pg_num or n < 1:
                    return  # replay / stale
                # pgp_num follows pg_num in one step: on growth,
                # children place independently at once and recovery
                # pulls from the parent's prior interval
                # (ancestor-aware); on shrink, OSDs fold dissolving
                # children into their targets (PG::merge_from) and
                # targets pull from the children's prior homes
                pool.pg_num = n
                pool.pgp_num = n
                om.invalidate_mapping_cache()
                # reports for dissolved children are meaningless now
                book = getattr(self, "_pg_stats", {}) or {}
                for pgid in [
                    k for k in book
                    if int(k.split(".")[0]) == op["pool"]
                    and int(k.split(".")[1]) >= n
                ]:
                    del book[pgid]
            elif var == "size":
                pool.size = int(val)
            elif var == "min_size":
                pool.min_size = int(val)
            else:
                pool.extra[var] = val
        elif kind == "pool_rm":
            pid = op["pool"]
            if pid not in om.pools:
                return
            name = om.pool_names.pop(pid, None)
            om.pools.pop(pid, None)
            if name is not None:
                self._pool_ids.pop(name, None)
            # dead placement overrides must not haunt the map forever
            # (the reference clears upmap/pg_temp on pool deletion)
            for d in (om.pg_upmap, om.pg_upmap_items, om.pg_temp):
                for key in [k for k in d if k.pool == pid]:
                    del d[key]
        elif kind == "in":
            osd = op["osd"]
            if not om.exists(osd) or not om.is_out(osd):
                return
            om.osd_weight[osd] = 0x10000
        elif kind == "tier_add":
            tier = om.pools.get(op["tier"])
            if tier is None or op["base"] not in om.pools:
                return
            tier.extra["tier_of"] = str(op["base"])
            tier.extra.setdefault("cache_mode", "none")
        elif kind == "tier_rm":
            tier = om.pools.get(op["tier"])
            if tier is None:
                return
            tier.extra.pop("tier_of", None)
            tier.extra.pop("cache_mode", None)
        elif kind == "tier_mode":
            tier = om.pools.get(op["tier"])
            if tier is None:
                return
            tier.extra["cache_mode"] = op["mode"]
        elif kind == "tier_overlay":
            base = om.pools.get(op["base"])
            if base is None:
                return
            if op["tier"] < 0:
                base.extra.pop("read_tier", None)
                base.extra.pop("write_tier", None)
            else:
                base.extra["read_tier"] = str(op["tier"])
                base.extra["write_tier"] = str(op["tier"])
        elif kind == "auth_upsert":
            self._auth_db[op["entity"]] = {
                "key": op["key"], "caps": dict(op["caps"]),
            }
            self._sync_auth_keyring()
            return  # auth changes don't mint osdmap epochs
        elif kind == "auth_del":
            self._auth_db.pop(op["entity"], None)
            self._sync_auth_keyring()
            return
        else:
            log.error("mon.%d: unknown committed op %r", self.rank, kind)
            return
        await self._new_epoch()

    async def _tick(self) -> None:
        was_leader = False
        last_tick = time.monotonic()
        while True:
            await asyncio.sleep(self.beacon_grace / 4)
            now = time.monotonic()
            starved = now - last_tick > self.beacon_grace
            last_tick = now
            if not self.is_leader:
                was_leader = False
                continue
            if starved:
                # the event loop stalled (big computation, GC, swap):
                # beacons queued but undelivered are not missing OSDs —
                # re-seed rather than mass-mark the cluster down
                was_leader = False
            om = self.osdmap
            if not was_leader:
                # fresh leadership: beacons were landing on the old
                # leader, so give every up OSD one full grace period to
                # re-home before judging it (the reference's equivalent
                # is last_beacon reset on win_election)
                was_leader = True
                for osd in range(om.max_osd):
                    if om.is_up(osd):
                        self._last_beacon[osd] = now
                continue
            try:
                for osd, last in list(self._last_beacon.items()):
                    if om.is_up(osd) and now - last > self.beacon_grace:
                        log.info("mon: osd.%d beacon timeout -> down", osd)
                        self._down_at[osd] = now
                        await self._propose({"op": "down", "osd": osd})
                if self.out_interval > 0:
                    for osd, when in list(self._down_at.items()):
                        if not om.is_out(osd) and now - when > self.out_interval:
                            log.info("mon: osd.%d down too long -> out", osd)
                            await self._propose({"op": "out", "osd": osd})
            except ConnectionError:
                continue  # lost quorum mid-sweep; retry next tick

    def _ingest_pg_stats(self, osd: int, epoch: int, raw: bytes) -> None:
        """MgrStatMonitor/DaemonServer role: fold one OSD's per-PG
        report into the cluster pg map (newest epoch wins per pg)."""
        import json
        import re

        try:
            stats = json.loads(raw)
            if not isinstance(stats, dict):
                return
        except ValueError:
            return
        book = getattr(self, "_pg_stats", None)
        if book is None:
            book = self._pg_stats = {}
        for pgid, st in stats.items():
            # shape-check: a version-skewed OSD must not be able to
            # poison the status plane
            if not (isinstance(pgid, str) and re.fullmatch(r"\d+\.\d+", pgid)
                    and isinstance(st, dict)
                    and isinstance(st.get("state"), str)):
                continue
            cur = book.get(pgid)
            if cur is None or cur.get("epoch", 0) <= epoch:
                st = dict(st)
                st["epoch"] = epoch
                st["primary"] = osd
                book[pgid] = st

    async def _ingest_statfs(self, osd: int, raw: bytes) -> None:
        """Fold one OSD's store usage into the fullness plane
        (reference OSDMonitor full-state tracking,
        src/mon/OSDMonitor.cc:669-671 ratios + OSD.cc:773
        recalc_full_state): keep the latest statfs for `df`, derive
        the osd's fullness bits from the configured ratios, and commit
        a map change whenever the bits flip so every daemon and client
        gates on the same epoch's truth."""
        import json

        try:
            sf = json.loads(raw)
            total = int(sf["total"])
            used = int(sf["used"])
        except (ValueError, KeyError, TypeError):
            return
        book = getattr(self, "_osd_statfs", None)
        if book is None:
            book = self._osd_statfs = {}
        book[osd] = sf
        ratio = (used / total) if total > 0 else 0.0
        from ceph_tpu.osd.osdmap import (
            CEPH_OSD_BACKFILLFULL,
            CEPH_OSD_FULL,
            CEPH_OSD_FULL_MASK,
            CEPH_OSD_NEARFULL,
        )

        bits = 0
        if ratio >= self.conf["mon_osd_full_ratio"]:
            bits = CEPH_OSD_FULL
        elif ratio >= self.conf["mon_osd_backfillfull_ratio"]:
            bits = CEPH_OSD_BACKFILLFULL
        elif ratio >= self.conf["mon_osd_nearfull_ratio"]:
            bits = CEPH_OSD_NEARFULL
        om = self.osdmap
        if not om.exists(osd):
            return
        cur = om.osd_state[osd] & CEPH_OSD_FULL_MASK
        if cur != bits:
            await self._propose({
                "op": "full_state", "osd": osd, "bits": bits,
            })

    def _pg_summary(self) -> dict:
        """Aggregate pg states (the `ceph -s` pgs block)."""
        book = getattr(self, "_pg_stats", {}) or {}
        om = self.osdmap
        expected = sum(p.pg_num for p in om.pools.values())
        by_state: dict[str, int] = {}
        objects = 0
        min_epoch = om.epoch
        primaries = self._pg_primaries(om)
        for pgid, st in book.items():
            pid_s, ps_s = pgid.split(".")
            pid = int(pid_s)
            if pid not in om.pools:
                continue
            if int(ps_s) >= om.pools[pid].pg_num:
                continue  # dissolved merge child (late beacon)
            state = st.get("state", "unknown")
            # a report from a primary that is now down — or that is no
            # longer THE primary after a remap — is STALE until the
            # current primary reports (reference pg_state stale
            # semantics: stats are per-interval)
            reporter = st.get("primary", -1)
            cur_primary = primaries.get((pid, int(ps_s)), -1)
            if not om.is_up(reporter) or reporter != cur_primary:
                state = "stale"
            by_state[state] = by_state.get(state, 0) + 1
            objects += int(st.get("objects", 0))
            min_epoch = min(min_epoch, int(st.get("epoch", 0)))
        reported = sum(by_state.values())
        return {
            "num_pgs": expected,
            "num_reported": reported,
            "by_state": by_state,
            "num_objects": objects,
            # the oldest osdmap epoch any counted report was computed
            # at: a waiter that just forced a map change can require
            # min_reported_epoch >= that epoch so pre-change
            # active+clean reports can't satisfy it (the qa-helper
            # wait_for_clean checks last_epoch_clean the same way)
            "min_reported_epoch": (
                min_epoch if reported else 0),
        }

    def _pg_primaries(self, om) -> dict[tuple[int, int], int]:
        """pg -> current primary, CACHED PER EPOCH: status/health are
        the hottest mon read path and a full CRUSH pass per call would
        stall beacon dispatch (the balancer learned this the hard way
        — see the to_thread note there)."""
        from ceph_tpu.osd.types import pg_t as _pg_t

        cache_epoch, out, seen = getattr(
            self, "_primaries_cache", (None, {}, set()))
        if cache_epoch != om.epoch:
            out, seen = {}, set()
            self._primaries_cache = (om.epoch, out, seen)
        # memoize per epoch, computing only the pgids actually present
        # in the stats book (bounded by reports, not pools x pg_num) —
        # lazily, so pgids whose first report lands mid-epoch still
        # resolve; `seen` keeps warm calls near-O(1)
        book = getattr(self, "_pg_stats", {}) or {}
        if len(seen) != len(book):
            for pgid in book:
                if pgid in seen:
                    continue
                seen.add(pgid)
                pid_s, ps_s = pgid.split(".")
                pid, ps = int(pid_s), int(ps_s)
                if pid not in om.pools:
                    continue
                _u, _up, _a, primary = om.pg_to_up_acting_osds(
                    _pg_t(pid, ps), folded=True)
                out[(pid, ps)] = primary
        return out

    def _health_checks(self, pgsum: dict | None = None) -> dict:
        """HealthMonitor role (reference src/mon/HealthMonitor.cc +
        per-map checks): OSD_DOWN, MON_DOWN, PG_DEGRADED."""
        om = self.osdmap
        checks: dict[str, dict] = {}
        # down+IN only: a drained (down+out) osd is not a warning
        # (HealthMonitor counts num_down_in_osds)
        down = [
            o for o in range(om.max_osd)
            if om.exists(o) and not om.is_up(o) and not om.is_out(o)
        ]
        if down:
            checks["OSD_DOWN"] = {
                "severity": "HEALTH_WARN",
                "summary": f"{len(down)} osds down",
                "detail": [f"osd.{o} is down" for o in down],
            }
        if self.n_mons > 1:
            q = sorted(self.paxos.quorum)
            if len(q) < self.n_mons:
                missing = [r for r in range(self.n_mons) if r not in q]
                checks["MON_DOWN"] = {
                    "severity": "HEALTH_WARN",
                    "summary": (
                        f"{len(missing)}/{self.n_mons} mons out of quorum"
                    ),
                    "detail": [f"mon.{r} out of quorum" for r in missing],
                }
        if pgsum is None:
            pgsum = self._pg_summary()
        bad = {
            st: n for st, n in pgsum["by_state"].items()
            if "degraded" in st or "recovering" in st or "stale" in st
        }
        if bad:
            checks["PG_DEGRADED"] = {
                "severity": "HEALTH_WARN",
                "summary": (
                    f"{sum(bad.values())} pgs not clean: "
                    + ", ".join(f"{n} {st}" for st, n in sorted(bad.items()))
                ),
                "detail": [],
            }
        # fullness (reference OSD_FULL/OSD_BACKFILLFULL/OSD_NEARFULL
        # health checks): FULL is an error — writes are bouncing
        full = [o for o in range(om.max_osd) if om.is_full(o)]
        bfull = [
            o for o in range(om.max_osd)
            if om.is_backfillfull(o) and o not in full
        ]
        near = [
            o for o in range(om.max_osd)
            if om.is_nearfull(o) and o not in full and o not in bfull
        ]
        if full:
            checks["OSD_FULL"] = {
                "severity": "HEALTH_ERR",
                "summary": f"{len(full)} full osd(s); writes blocked",
                "detail": [f"osd.{o} is full" for o in full],
            }
        if bfull:
            checks["OSD_BACKFILLFULL"] = {
                "severity": "HEALTH_WARN",
                "summary": (
                    f"{len(bfull)} backfillfull osd(s); backfill paused"
                ),
                "detail": [f"osd.{o} is backfillfull" for o in bfull],
            }
        if near:
            checks["OSD_NEARFULL"] = {
                "severity": "HEALTH_WARN",
                "summary": f"{len(near)} nearfull osd(s)",
                "detail": [f"osd.{o} is nearfull" for o in near],
            }
        if any(c["severity"] == "HEALTH_ERR" for c in checks.values()):
            status = "HEALTH_ERR"
        else:
            status = "HEALTH_OK" if not checks else "HEALTH_WARN"
        return {"status": status, "checks": checks}

    def _config_sections_for(self, who: tuple[str, int]) -> dict:
        """The sections addressing one entity, in precedence order
        (global < type < type.id), pre-merged for the receiver."""
        kind, ident = who
        out: dict[str, dict[str, str]] = {}
        for sec in ("global", kind, f"{kind}.{ident}"):
            if sec in self._config_db:
                out[sec] = dict(self._config_db[sec])
        return out

    def _autoscale_rows(self) -> list[dict]:
        """pg_autoscaler sizing math: ideal pg count ~ eligible osds *
        mon_target_pg_per_osd / size, rounded to a power of two."""
        om2 = self.osdmap
        target = self.conf["mon_target_pg_per_osd"]

        def _eligible(pool) -> int:
            rule = om2.crush.rules.get(pool.crush_rule)
            cls = getattr(rule, "device_class", None)
            n = sum(
                1 for o in range(om2.max_osd)
                if om2.exists(o) and not om2.is_out(o)
                and (cls is None
                     or om2.crush.device_classes.get(o) == cls)
            )
            return n or 1

        rows = []
        for pid, pool in sorted(om2.pools.items()):
            n_in = _eligible(pool)
            ideal = max(1, n_in * target // max(1, pool.size))
            # nearest power of two, min 1
            p2 = 1 << max(0, ideal.bit_length() - 1)
            if ideal - p2 > (p2 * 2) - ideal:
                p2 *= 2
            rows.append({
                "pool": om2.pool_names.get(pid, str(pid)),
                "pool_id": pid,
                "size": pool.size,
                "pg_num": pool.pg_num,
                "new_pg_num": p2,
                "autoscale_mode": pool.extra.get(
                    "pg_autoscale_mode", "off"),
                "would_adjust": p2 != pool.pg_num,
            })
        return rows

    async def _autoscale_tick(self) -> None:
        """The acting half of the pg_autoscaler: pools that opted in
        (pg_autoscale_mode=on) get their advised pg_num APPLIED through
        paxos — reference src/pybind/mgr/pg_autoscaler/module.py
        _maybe_adjust.  Shrinks as well as grows (pg merge); like the
        reference's threshold, a shrink only fires when the advised
        count is under half the current one, so the scaler can't
        oscillate around a boundary."""
        interval = self.conf["mon_pg_autoscale_interval"]
        while True:
            await asyncio.sleep(interval)
            if not self.is_leader:
                continue
            try:
                for row in self._autoscale_rows():
                    pool = self.osdmap.pools.get(row["pool_id"])
                    if pool is None or pool.extra.get(
                            "pg_autoscale_mode") != "on":
                        continue
                    new = row["new_pg_num"]
                    if new == pool.pg_num or (
                        new < pool.pg_num and new * 2 > pool.pg_num
                    ):
                        continue
                    log.info("mon.%d: autoscaler resizing pool %d "
                             "pg_num %d -> %d", self.rank,
                             row["pool_id"], pool.pg_num,
                             row["new_pg_num"])
                    await self._propose({
                        "op": "pool_set", "pool": row["pool_id"],
                        "var": "pg_num",
                        "val": str(row["new_pg_num"]),
                    })
            except Exception:
                log.exception("mon.%d: autoscale tick failed", self.rank)

    def _pool_by_name(self, name: str):
        import errno

        pid = self.osdmap.lookup_pg_pool_name(name)
        if pid < 0:
            raise OSError(errno.ENOENT, f"no pool {name!r}")
        return pid, self.osdmap.pools[pid]

    async def _pool_set(self, cmd: dict[str, str]) -> tuple[int, str, bytes]:
        """osd pool set <pool> <var> <val> (OSDMonitor::prepare_command
        pool ops, src/mon/OSDMonitor.cc:7339+).  pg_num increases split
        PGs on the OSDs; decreases merge them (PG::merge_from,
        src/osd/PG.cc:563)."""
        import errno

        pid, pool = self._pool_by_name(cmd["pool"])
        var, val = cmd["var"], cmd["val"]
        if var == "pg_num":
            n = int(val)
            if n == pool.pg_num:
                return 0, "no change", b""
            if n < 1:
                return -errno.EINVAL, "pg_num must be >= 1", b""
            if n > 65536:
                return -errno.ERANGE, "pg_num too large", b""
            if n < pool.pg_num:
                # merge only commits on a CLEAN pool (the reference's
                # ready_to_merge gate, OSDMonitor pg_num_pending
                # machinery): the dissolving children's logs fold into
                # targets with incomparable version sequences, which
                # is only safe when nothing is degraded or pending
                book = getattr(self, "_pg_stats", {}) or {}
                for ps in range(pool.pg_num):
                    st = book.get(f"{pid}.{ps}")
                    if (
                        st is None
                        or st.get("state") != "active+clean"
                        or not self.osdmap.is_up(st.get("primary", -1))
                    ):
                        return (-errno.EBUSY,
                                "pool not clean; merge requires every "
                                "pg active+clean", b"")
        elif var in ("size", "min_size"):
            n = int(val)
            if not 1 <= n <= 16:
                return -errno.EINVAL, f"bad {var}", b""
            if var == "size" and pool.type != 1:  # replicated only
                return -errno.EPERM, "size is fixed for EC pools", b""
            if var == "size" and n < pool.min_size:
                return -errno.EINVAL, "size < min_size", b""
            if var == "min_size" and n > pool.size:
                return -errno.EINVAL, "min_size > size", b""
        elif var == "pg_autoscale_mode":
            if val not in ("on", "off"):
                return -errno.EINVAL, "pg_autoscale_mode: on|off", b""
        elif var == "target_max_bytes":
            if int(val) < 0:
                return -errno.EINVAL, "target_max_bytes >= 0", b""
        elif var == "fast_read":
            if val not in ("0", "1"):
                return -errno.EINVAL, "fast_read: 0|1", b""
        else:
            return -errno.EINVAL, f"unsettable var {var!r}", b""
        await self._propose({
            "op": "pool_set", "pool": pid, "var": var, "val": str(val),
        })
        return 0, f"set pool {cmd['pool']} {var} to {val}", b""

    async def _pool_rm(self, cmd: dict[str, str]) -> tuple[int, str, bytes]:
        """osd pool rm <pool> <pool-again> --yes-i-really-really-mean-it
        (the reference's double-confirmation)."""
        import errno

        pid, _pool = self._pool_by_name(cmd["pool"])
        if cmd.get("pool2") != cmd["pool"] or cmd.get(
                "sure") != "--yes-i-really-really-mean-it":
            return (-errno.EPERM,
                    "pass the pool name twice and "
                    "--yes-i-really-really-mean-it", b"")
        await self._propose({"op": "pool_rm", "pool": pid})
        return 0, f"pool {cmd['pool']} removed", b""

    async def _tier_command(
        self, prefix: str, cmd: dict[str, str],
    ) -> tuple[int, str, bytes]:
        """Cache-tier admin (OSDMonitor::prepare_command tier verbs,
        src/mon/OSDMonitor.cc 'osd tier add/remove/cache-mode/
        set-overlay/remove-overlay')."""
        import errno

        _bpid, base = self._pool_by_name(cmd["pool"])
        if prefix in ("osd tier add", "osd tier remove",
                      "osd tier cache-mode", "osd tier set-overlay"):
            tier_name = cmd.get("tierpool") or cmd.get("pool2", "")
            if prefix == "osd tier cache-mode":
                tier_name = cmd["pool"]
        if prefix == "osd tier add":
            tpid, tier = self._pool_by_name(tier_name)
            if tpid == _bpid:
                return -errno.EINVAL, "a pool cannot tier itself", b""
            if tier.extra.get("tier_of"):
                return -errno.EINVAL, "already a tier", b""
            if base.extra.get("tier_of"):
                return (-errno.EINVAL,
                        "base is itself a tier (no tier chains)", b"")
            if tier.type != 1:
                return (-errno.EINVAL,
                        "cache tier must be replicated (omap)", b"")
            await self._propose({
                "op": "tier_add", "base": _bpid, "tier": tpid,
            })
            return 0, f"{tier_name} is now a tier of {cmd['pool']}", b""
        if prefix == "osd tier remove":
            tpid, tier = self._pool_by_name(tier_name)
            if tier.extra.get("tier_of") != str(_bpid):
                return (-errno.ENOENT,
                        f"{tier_name} is not a tier of {cmd['pool']}", b"")
            if base.extra.get("read_tier") == str(tpid):
                return -errno.EBUSY, "remove the overlay first", b""
            await self._propose({
                "op": "tier_rm", "base": _bpid, "tier": tpid,
            })
            return 0, "tier removed", b""
        if prefix == "osd tier cache-mode":
            mode = cmd["mode"]
            if mode not in ("writeback", "none"):
                return -errno.EINVAL, "mode: writeback|none", b""
            if not base.extra.get("tier_of"):
                return -errno.EINVAL, f"{cmd['pool']} is not a tier", b""
            await self._propose({
                "op": "tier_mode", "tier": _bpid, "mode": mode,
            })
            return 0, f"cache-mode {mode}", b""
        if prefix == "osd tier set-overlay":
            tpid, tier = self._pool_by_name(tier_name)
            if tier.extra.get("tier_of") != str(_bpid):
                return -errno.EINVAL, "not a tier of that pool", b""
            await self._propose({
                "op": "tier_overlay", "base": _bpid, "tier": tpid,
            })
            return 0, "overlay set", b""
        if prefix == "osd tier remove-overlay":
            await self._propose({"op": "tier_overlay", "base": _bpid,
                                 "tier": -1})
            return 0, "overlay removed", b""
        return -errno.EOPNOTSUPP, prefix, b""

    async def _auth_command(
        self, prefix: str, cmd: dict[str, str],
    ) -> tuple[int, str, bytes]:
        """The AuthMonitor command slice (src/mon/AuthMonitor.cc
        prepare_command): add / get-or-create / del / caps / get / ls.
        ``caps`` argument is a JSON object {"mon": "allow r", ...}."""
        import errno
        import json

        from ceph_tpu.common.caps import CapsError, validate
        from ceph_tpu.msg.auth import make_secret

        def parse_caps() -> dict[str, str]:
            raw = cmd.get("caps", "")
            caps = json.loads(raw) if raw else {}
            if not isinstance(caps, dict):
                raise CapsError("caps must be an object")
            validate(caps)
            return caps

        entity = cmd.get("entity", "")
        if prefix in ("auth add", "auth get-or-create", "auth del",
                      "auth caps", "auth get") and not entity:
            return -errno.EINVAL, "entity required", b""
        if entity in getattr(self, "_bootstrap_entities", set()):
            # construction-keyring identities are the cluster's root of
            # trust (client.admin bootstrap): the command plane must
            # not be able to rebind or delete them
            return -errno.EPERM, f"{entity} is a bootstrap entity", b""
        try:
            if prefix == "auth add":
                if entity in self._auth_db:
                    return -errno.EEXIST, f"entity {entity} exists", b""
                key = cmd.get("key") or make_secret().hex()
                try:
                    if len(bytes.fromhex(key)) not in (16, 24, 32):
                        raise ValueError
                except ValueError:
                    # never let a malformed key reach paxos: applying
                    # it would poison every restart's replay
                    return -errno.EINVAL, "key must be 16/24/32 hex bytes", b""
                await self._propose({
                    "op": "auth_upsert", "entity": entity, "key": key,
                    "caps": parse_caps(),
                })
                return 0, "added", json.dumps({"key": key}).encode()
            if prefix == "auth get-or-create":
                existing = self._auth_db.get(entity)
                if existing is not None:
                    if cmd.get("caps"):
                        if parse_caps() != existing["caps"]:
                            # the reference's EINVAL on caps mismatch:
                            # a get-or-create never silently diverges
                            # from what the caller asked for
                            return (-errno.EINVAL,
                                    "entity exists with different caps", b"")
                    return 0, "exists", json.dumps(
                        {"key": existing["key"]}).encode()
                key = make_secret().hex()
                await self._propose({
                    "op": "auth_upsert", "entity": entity, "key": key,
                    "caps": parse_caps(),
                })
                return 0, "created", json.dumps({"key": key}).encode()
            if prefix == "auth del":
                if entity not in self._auth_db:
                    return -errno.ENOENT, f"no entity {entity}", b""
                await self._propose({"op": "auth_del", "entity": entity})
                return 0, "removed", b""
            if prefix == "auth caps":
                rec = self._auth_db.get(entity)
                if rec is None:
                    return -errno.ENOENT, f"no entity {entity}", b""
                await self._propose({
                    "op": "auth_upsert", "entity": entity,
                    "key": rec["key"], "caps": parse_caps(),
                })
                return 0, "caps updated", b""
            if prefix == "auth get":
                rec = self._auth_db.get(entity)
                if rec is None:
                    return -errno.ENOENT, f"no entity {entity}", b""
                return 0, "", json.dumps(
                    {"entity": entity, **rec}).encode()
            if prefix == "auth ls":
                return 0, "", json.dumps({
                    e: {"caps": r["caps"]}
                    for e, r in sorted(self._auth_db.items())
                }).encode()
        except (CapsError, json.JSONDecodeError) as e:
            return -errno.EINVAL, f"bad caps: {e}", b""
        return -errno.EOPNOTSUPP, f"unknown {prefix!r}", b""

    def _sync_auth_keyring(self) -> None:
        """Mirror the paxos-committed auth database into the live
        AuthContext so grants/tickets reflect it immediately (the
        AuthMonitor -> KeyServer update path).  Statically-keyed
        bootstrap entities (construction keyring) stay untouched."""
        a = self.messenger.auth
        if a is None:
            return
        synced = getattr(self, "_auth_synced", set())
        for entity in synced - set(self._auth_db):
            a.keyring.pop(entity, None)
            a.caps_db.pop(entity, None)
        ok: set[str] = set()
        for entity, rec in self._auth_db.items():
            if entity in self._bootstrap_entities:
                continue  # never clobber the root of trust
            try:
                key = bytes.fromhex(rec["key"])
                if len(key) not in (16, 24, 32):
                    raise ValueError(len(key))
            except ValueError:
                # a poisoned record must degrade to "that entity can't
                # auth", never to "the monitor can't restart"
                log.error("mon.%d: unusable key for %s in auth db — "
                          "skipped", self.rank, entity)
                continue
            a.keyring[entity] = key
            a.caps_db[entity] = dict(rec["caps"])
            ok.add(entity)
        self._auth_synced = ok

    def _apply_config_locally(self) -> None:
        for sec in ("global", "mon", f"mon.{self.rank}"):
            for name, value in self._config_db.get(sec, {}).items():
                try:
                    self.conf.set(name, value, source="mon")
                except (KeyError, ValueError):
                    pass

    async def _push_config(self) -> None:
        for peer, conn in list(self._subscribers.items()):
            secs = self._config_sections_for(peer)
            try:
                await conn.send_message(MConfig(sections=secs))
            except (ConnectionError, OSError):
                self._subscribers.pop(peer, None)

    def _snap_alloc_lock(self, pool_id: int):
        locks = getattr(self, "_snap_locks", None)
        if locks is None:
            locks = self._snap_locks = {}
        if pool_id not in locks:
            import asyncio as _asyncio

            locks[pool_id] = _asyncio.Lock()
        return locks[pool_id]

    # -- commands (the MonCommands.h slice) ----------------------------

    WRITE_PREFIXES = frozenset({
        "osd erasure-code-profile set", "osd pool create",
        "osd down", "osd out", "osd balance",
        "osd pool selfmanaged-snap create",
        "osd pool selfmanaged-snap rm",
        "osd pool mksnap", "osd pool rmsnap",
        "config set", "config rm", "osd crush reweight",
        "osd crush add-bucket", "osd crush move", "osd crush add",
        "osd crush rm",
        "osd pg-upmap-items",
        "auth add", "auth get-or-create", "auth del", "auth caps",
        "osd pool set", "osd pool rm", "osd in",
        "osd tier add", "osd tier remove", "osd tier cache-mode",
        "osd tier set-overlay", "osd tier remove-overlay",
    })

    async def _command(
        self, cmd: dict[str, str], caps: dict[str, str] | None = None,
    ) -> tuple[int, str, bytes]:
        import errno
        import json

        prefix = cmd.get("prefix", "")
        if caps is not None:
            # MonCap admission (Monitor::_allowed_command): mutations
            # need mon w, everything else mon r — EXCEPT the auth
            # plane, which is admin-only end to end (the reference
            # tags MonCommands.h auth verbs with mon rwx): 'auth get'
            # returns secret keys and 'auth caps' rewrites grants, so
            # plain r/w must not reach either
            from ceph_tpu.common.caps import capable

            if prefix.startswith("auth "):
                need = "rwx"
            else:
                need = "w" if prefix in self.WRITE_PREFIXES else "r"
            if not capable(caps, "mon", need):
                return -errno.EACCES, "access denied", b""
        mutating = prefix in self.WRITE_PREFIXES or prefix in (
            # not mutations, but only the leader ingests pg stats and
            # knows the live quorum: redirect so peons don't serve an
            # empty status plane
            "status", "health", "pg stat", "df", "osd df",
        )
        if mutating and not self.is_leader:
            leader = self.paxos.leader if self.paxos.leader is not None else -1
            return -errno.EAGAIN, f"ENOTLEADER {leader}", b""
        try:
            if prefix == "osd erasure-code-profile set":
                name = cmd["name"]
                profile = dict(
                    kv.split("=", 1) for kv in cmd.get("profile", "").split() if kv
                )
                profile.setdefault("plugin", "jax")
                # instantiate once to validate + fill defaults
                ec_registry.factory(profile["plugin"], profile)
                await self._propose({
                    "op": "profile", "name": name, "profile": profile,
                })
                return 0, f"profile {name} set", b""
            if prefix == "osd pool create":
                return await self._pool_create(cmd)
            if prefix.startswith("auth "):
                return await self._auth_command(prefix, cmd)
            if prefix == "osd pool set":
                return await self._pool_set(cmd)
            if prefix == "osd pool rm":
                return await self._pool_rm(cmd)
            if prefix.startswith("osd tier "):
                return await self._tier_command(prefix, cmd)
            if prefix == "osd in":
                osd = int(cmd["id"])
                om = self.osdmap
                if not om.exists(osd):
                    return -errno.ENOENT, f"osd.{osd} does not exist", b""
                if not om.is_out(osd):
                    return 0, f"osd.{osd} is already in", b""
                await self._propose({"op": "in", "osd": osd})
                return 0, f"marked in osd.{osd}", b""
            if prefix == "osd pool selfmanaged-snap create":
                pid = self._pool_ids[cmd["pool"]]
                # serialize id allocation: two concurrent creates must
                # not both read snap_seq before either commits
                async with self._snap_alloc_lock(pid):
                    snapid = self.osdmap.pools[pid].snap_seq + 1
                    await self._propose({
                        "op": "snap_alloc", "pool": pid, "snapid": snapid,
                    })
                return 0, f"snap {snapid}", json.dumps(
                    {"snapid": snapid}).encode()
            if prefix == "osd pool selfmanaged-snap rm":
                pid = self._pool_ids[cmd["pool"]]
                snapid = int(cmd["snapid"])
                if snapid not in self.osdmap.pools[pid].removed_snaps:
                    await self._propose({
                        "op": "snap_rm", "pool": pid, "snapid": snapid,
                    })
                return 0, f"snap {snapid} removed", b""
            if prefix == "osd pool mksnap":
                pid = self._pool_ids[cmd["pool"]]
                name = cmd["snap"]
                async with self._snap_alloc_lock(pid):
                    pool = self.osdmap.pools[pid]
                    if name in pool.pool_snaps:
                        return -errno.EEXIST, f"snap {name} exists", b""
                    snapid = pool.snap_seq + 1
                    await self._propose({
                        "op": "snap_alloc", "pool": pid, "snapid": snapid,
                        "name": name,
                    })
                return 0, f"created pool snap {name}", json.dumps(
                    {"snapid": snapid}).encode()
            if prefix == "osd pool rmsnap":
                pid = self._pool_ids[cmd["pool"]]
                name = cmd["snap"]
                pool = self.osdmap.pools[pid]
                if name not in pool.pool_snaps:
                    return -errno.ENOENT, f"no snap {name}", b""
                await self._propose({
                    "op": "snap_rm", "pool": pid,
                    "snapid": pool.pool_snaps[name], "name": name,
                })
                return 0, f"removed pool snap {name}", b""
            if prefix == "osd down":
                osd = int(cmd["id"])
                if self.osdmap.is_up(osd):
                    await self._propose({"op": "down", "osd": osd})
                return 0, f"osd.{osd} down", b""
            if prefix == "osd out":
                osd = int(cmd["id"])
                if not self.osdmap.is_out(osd):
                    await self._propose({"op": "out", "osd": osd})
                return 0, f"osd.{osd} out", b""
            if prefix == "osd balance":
                import json

                from ceph_tpu.osd.balancer import UpmapBalancer
                from ceph_tpu.osd.mapenc import decode_osdmap, encode_osdmap

                try:
                    fd = self.osdmap.crush.type_id("host")
                except KeyError:
                    fd = 1
                # the census is seconds of pure computation: run it on a
                # SNAPSHOT in a worker thread so the event loop keeps
                # dispatching beacons (a blocked loop looks like every
                # OSD going silent at once)
                snapshot = decode_osdmap(encode_osdmap(self.osdmap))
                max_swaps = int(cmd.get("max_swaps", "64"))

                def _optimize():
                    bal = UpmapBalancer(snapshot, failure_domain_type=fd)
                    return bal.optimize(max_swaps=max_swaps)

                items = await asyncio.to_thread(_optimize)
                if items:
                    await self._propose({
                        "op": "upmap",
                        "items": [
                            [pg.pool, pg.ps, [list(p) for p in pairs]]
                            for pg, pairs in items.items()
                        ],
                    })
                return 0, f"{len(items)} upmap items installed", json.dumps(
                    {"swaps": len(items)}
                ).encode()
            if prefix in ("pg scrub", "pg deep-scrub", "pg repair"):
                return await self._scrub(
                    cmd, deep=prefix != "pg scrub",
                    repair=prefix == "pg repair")
            if prefix == "df":
                # `ceph df` (reference MgrStatMonitor/`df` detail):
                # cluster raw totals from beacon statfs + per-pool
                # logical usage aggregated from pg stats
                om = self.osdmap
                book = getattr(self, "_osd_statfs", {}) or {}
                live = {o: s for o, s in book.items() if om.exists(o)}
                pools: dict[str, dict] = {}
                for pgid, st in (getattr(self, "_pg_stats", {}) or {}).items():
                    pid = int(pgid.split(".")[0])
                    if pid not in om.pools:
                        continue
                    name = om.pool_names.get(pid, str(pid))
                    d = pools.setdefault(
                        name, {"id": pid, "objects": 0, "bytes_used": 0})
                    d["objects"] += int(st.get("objects", 0))
                    d["bytes_used"] += int(st.get("bytes", 0))
                data = json.dumps({
                    "stats": {
                        "total_bytes": sum(
                            int(s.get("total", 0)) for s in live.values()),
                        "total_used_bytes": sum(
                            int(s.get("used", 0)) for s in live.values()),
                        "total_avail_bytes": sum(
                            int(s.get("available", 0))
                            for s in live.values()),
                    },
                    "pools": pools,
                }).encode()
                return 0, "", data
            if prefix == "osd df":
                # `ceph osd df`: per-osd usage + fullness state
                om = self.osdmap
                book = getattr(self, "_osd_statfs", {}) or {}
                nodes = []
                for o in range(om.max_osd):
                    if not om.exists(o):
                        continue
                    sf = book.get(o, {})
                    t = int(sf.get("total", 0))
                    u = int(sf.get("used", 0))
                    state = []
                    if om.is_full(o):
                        state.append("full")
                    elif om.is_backfillfull(o):
                        state.append("backfillfull")
                    elif om.is_nearfull(o):
                        state.append("nearfull")
                    nodes.append({
                        "id": o,
                        "total": t,
                        "used": u,
                        "available": int(sf.get("available", 0)),
                        "utilization": (u / t) if t else 0.0,
                        "state": state,
                    })
                return 0, "", json.dumps({"nodes": nodes}).encode()
            if prefix == "status":
                om = self.osdmap
                pgsum = self._pg_summary()
                up = sum(om.is_up(o) for o in range(om.max_osd))
                inn = sum(
                    not om.is_out(o) for o in range(om.max_osd) if om.exists(o)
                )
                data = json.dumps({
                    "epoch": om.epoch,
                    "num_osds": sum(om.exists(o) for o in range(om.max_osd)),
                    "num_up_osds": up,
                    "num_in_osds": inn,
                    "quorum": sorted(self.paxos.quorum),
                    "pools": {
                        str(pid): {"name": name, "pg_num": om.pools[pid].pg_num}
                        for name, pid in self._pool_ids.items()
                    },
                    "pgs": pgsum,
                    "health": self._health_checks(pgsum),
                }).encode()
                return 0, "", data
            if prefix == "config set":
                who = cmd.get("who", "global")
                name, value = cmd["name"], cmd["value"]
                from ceph_tpu.common.config import OPTIONS

                opt = OPTIONS.get(name)
                if opt is None:
                    return -errno.ENOENT, f"unknown option {name!r}", b""
                try:
                    opt.cast(value)
                except (ValueError, TypeError) as e:
                    return -errno.EINVAL, str(e), b""
                await self._propose({
                    "op": "config_set", "who": who,
                    "name": name, "value": value,
                })
                return 0, f"set {who}/{name}", b""
            if prefix == "config rm":
                await self._propose({
                    "op": "config_rm", "who": cmd.get("who", "global"),
                    "name": cmd["name"],
                })
                return 0, "removed", b""
            if prefix == "config dump":
                return 0, "", json.dumps(self._config_db).encode()
            if prefix == "config get":
                who = cmd.get("who", "global")
                kind = who.split(".")[0]
                merged: dict[str, str] = {}
                for sec in ("global", kind, who):
                    merged.update(self._config_db.get(sec, {}))
                if "name" in cmd:
                    if cmd["name"] not in merged:
                        return -errno.ENOENT, "not set", b""
                    return 0, "", merged[cmd["name"]].encode()
                return 0, "", json.dumps(merged).encode()
            if prefix == "osd pg-upmap-items":
                # explicit placement override pairs (reference
                # OSDMonitor osd pg-upmap-items): pgid from to [...]
                pool_id, ps = cmd["pgid"].split(".", 1)
                pool_id = int(pool_id)
                ps = int(ps, 16) if ps.startswith("0x") else int(ps)
                pool = self.osdmap.pools.get(pool_id)
                if pool is None:
                    return -errno.ENOENT, f"no pool {pool_id}", b""
                if not 0 <= ps < pool.pg_num:
                    return -errno.ENOENT, f"no pg {cmd['pgid']}", b""
                pairs_raw = cmd["pairs"].split()
                if len(pairs_raw) % 2:
                    return -errno.EINVAL, "pairs must be from/to pairs", b""
                items = [
                    [int(pairs_raw[i]), int(pairs_raw[i + 1])]
                    for i in range(0, len(pairs_raw), 2)
                ]
                for frm, to in items:
                    if not (self.osdmap.exists(frm)
                            and self.osdmap.exists(to)):
                        return (-errno.ENOENT,
                                f"osd {frm} or {to} does not exist", b"")
                await self._propose({
                    "op": "upmap",
                    "items": [[pool_id, ps, items]],
                })
                return 0, f"upmap set on {cmd['pgid']}", b""
            if prefix == "osd crush reweight":
                name = cmd["name"]
                om2 = self.osdmap
                if name.startswith("osd."):
                    item = int(name[4:])
                elif name in om2.crush.bucket_names:
                    item = om2.crush.bucket_names[name]
                else:
                    return -errno.ENOENT, f"no item {name!r}", b""
                if not any(
                    item in b.items for b in om2.crush.buckets.values()
                ):
                    return -errno.ENOENT, f"{name!r} not in the map", b""
                weight = int(float(cmd["weight"]) * 0x10000)
                await self._propose({
                    "op": "crush_reweight", "item": item,
                    "weight": weight,
                })
                return 0, f"reweighted {name} to {cmd['weight']}", b""
            if prefix == "osd crush add-bucket":
                # OSDMonitor 'osd crush add-bucket <name> <type>'
                name, tname = cmd["name"], cmd["type"]
                om2 = self.osdmap
                try:
                    om2.crush.type_id(tname)
                except KeyError:
                    return -errno.EINVAL, f"unknown type {tname!r}", b""
                if name in om2.crush.bucket_names:
                    return 0, f"bucket {name!r} already exists", b""
                await self._propose({
                    "op": "crush_add_bucket", "name": name,
                    "type": tname,
                })
                return 0, f"added bucket {name}", b""
            if prefix in ("osd crush move", "osd crush add"):
                # 'osd crush move <name> <loc>' relocates an existing
                # item; 'osd crush add osd.N <weight> <loc>' places a
                # device (create-or-move).  <loc> is type=name, e.g.
                # root=default or host=host3 (CrushWrapper::move_bucket
                # / insert_item)
                name = cmd["name"]
                loc = cmd.get("loc") or cmd.get("args", "")
                if "=" not in loc:
                    return -errno.EINVAL, f"bad location {loc!r}", b""
                _ltype, lname = loc.split("=", 1)
                om2 = self.osdmap
                if lname not in om2.crush.bucket_names:
                    return -errno.ENOENT, f"no bucket {lname!r}", b""
                if name.startswith("osd."):
                    item = int(name[4:])
                    if prefix == "osd crush add" and \
                            not om2.exists(item):
                        return -errno.ENOENT, \
                            f"osd.{item} does not exist", b""
                elif prefix == "osd crush add":
                    # the reference restricts 'crush add' to devices:
                    # an explicit weight on a bucket would desync the
                    # parent's stored weight from the subtree sum
                    return -errno.EINVAL, \
                        "'osd crush add' takes an osd.N id (use " \
                        "'osd crush move' for buckets)", b""
                elif name in om2.crush.bucket_names:
                    item = om2.crush.bucket_names[name]
                else:
                    return -errno.ENOENT, f"no item {name!r}", b""
                from ceph_tpu.crush.builder import would_cycle

                if would_cycle(
                        om2.crush, item,
                        om2.crush.bucket_names[lname]):
                    return -errno.EINVAL, \
                        f"moving {name!r} under {lname!r} would " \
                        "create a loop", b""
                op = {
                    "op": "crush_move", "item_name": name,
                    "loc": lname,
                }
                if prefix == "osd crush add":
                    op["weight"] = int(float(cmd["weight"]) * 0x10000)
                await self._propose(op)
                return 0, f"moved {name} under {lname}", b""
            if prefix == "osd crush rm":
                name = cmd["name"]
                om2 = self.osdmap
                if name.startswith("osd."):
                    item = int(name[4:])
                elif name in om2.crush.bucket_names:
                    item = om2.crush.bucket_names[name]
                else:
                    return -errno.ENOENT, f"no item {name!r}", b""
                if item < 0 and om2.crush.buckets[item].items:
                    return -errno.ENOTEMPTY, \
                        f"bucket {name!r} is not empty", b""
                await self._propose({
                    "op": "crush_rm", "item_name": name,
                })
                return 0, f"removed {name}", b""
            if prefix == "osd pool autoscale-status":
                # the pg_autoscaler mgr module's sizing math
                # (reference src/pybind/mgr/pg_autoscaler).  Advisory
                # here; pools with pg_autoscale_mode=on get the advice
                # APPLIED by _autoscale_tick (pg splitting exists now)
                return 0, "", json.dumps(self._autoscale_rows()).encode()
            if prefix == "health":
                h = self._health_checks()
                return 0, h["status"], json.dumps(h).encode()
            if prefix == "pg stat":
                book = getattr(self, "_pg_stats", {}) or {}
                return 0, "", json.dumps({
                    "pg_stats": book, "summary": self._pg_summary(),
                }).encode()
            return -errno.EINVAL, f"unknown command {prefix!r}", b""
        except KeyError as e:
            return -errno.EINVAL, f"missing arg {e}", b""
        except Exception as e:  # command errors must not kill the mon
            eno = getattr(e, "errno", None) or errno.EINVAL
            return -eno, str(e) or type(e).__name__, b""

    async def _scrub(self, cmd: dict[str, str], deep: bool,
                     repair: bool = False) -> tuple[int, str, bytes]:
        """Forward a scrub request to the PG's primary and return its
        report (OSDMonitor scrub command -> MOSDScrub to the OSD)."""
        import errno

        from ceph_tpu.osd.types import pg_t

        pool_id, ps = cmd["pgid"].split(".", 1)
        pool_id, ps = int(pool_id), int(ps, 16) if ps.startswith("0x") else int(ps)
        om = self.osdmap
        if om.get_pg_pool(pool_id) is None:
            return -errno.ENOENT, f"no pool {pool_id}", b""
        _, _, _, primary = om.pg_to_up_acting_osds(pg_t(pool_id, ps), folded=True)
        if primary < 0:
            return -errno.EAGAIN, f"pg {cmd['pgid']} has no primary", b""
        addr = om.osd_addrs.get(primary)
        conn = self._subscribers.get(("osd", primary))
        if conn is None and addr is not None:
            conn = await self.messenger.connect_to(("osd", primary), *addr)
        if conn is None:
            return -errno.EAGAIN, f"primary osd.{primary} unreachable", b""
        tid = next(self._tids)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._scrub_waiters[tid] = fut
        try:
            await conn.send_message(
                MOSDScrub(tid=tid, pool=pool_id, ps=ps, deep=deep,
                          repair=repair)
            )
            # shorter than the client command timeout (30s): a slow
            # scrub returns an error here instead of the client
            # resending and stacking duplicate scrubs
            reply: MOSDScrubReply = await asyncio.wait_for(fut, 25)
        except asyncio.TimeoutError:
            return -errno.ETIMEDOUT, "scrub did not finish in 25s", b""
        finally:
            self._scrub_waiters.pop(tid, None)
        return reply.result, "", reply.report

    async def _pool_create(self, cmd: dict[str, str]) -> tuple[int, str, bytes]:
        """OSDMonitor::prepare_new_pool (OSDMonitor.cc:7339): leader
        validates, then the creation replicates through paxos and
        applies deterministically on every member."""
        import errno
        import json

        name = cmd["name"]
        if name in self._pool_ids:
            pid = self._pool_ids[name]
            return 0, f"pool {name!r} already exists", json.dumps({"pool_id": pid}).encode()
        pool_type = cmd.get("pool_type", "replicated")
        om = self.osdmap
        if pool_type == "erasure":
            profile_name = cmd.get("erasure_code_profile", "default")
            profile = om.erasure_code_profiles.get(profile_name)
            if profile is None:
                return -errno.ENOENT, f"no profile {profile_name!r}", b""
            ec_registry.factory(profile["plugin"], dict(profile))  # validate
        elif om.crush.bucket_names.get("default") is None and (
            cmd.get("rule", "replicated_rule") not in om.crush.rule_names
        ):
            return -errno.ENOENT, "no default crush root", b""
        await self._propose({
            "op": "pool_create", "name": name,
            "pg_num": int(cmd.get("pg_num", "8")),
            "pool_type": pool_type,
            "size": int(cmd.get("size", "3")),
            "rule": cmd.get("rule", ""),
            "erasure_code_profile": cmd.get("erasure_code_profile", "default"),
            "fast_read": cmd.get("fast_read", "") in ("1", "true", "yes"),
        })
        pid = self._pool_ids[name]
        return 0, f"pool {name!r} created", json.dumps({"pool_id": pid}).encode()

    def _apply_pool_create(self, op: dict) -> None:
        """Deterministic half of pool creation (same inputs + same map
        state -> same pool id, rule id and crush mutation on every
        quorum member)."""
        name = op["name"]
        if name in self._pool_ids:
            return
        om = self.osdmap
        pid = self._next_pool
        if op["pool_type"] == "erasure":
            profile_name = op["erasure_code_profile"]
            profile = om.erasure_code_profiles[profile_name]
            ec = ec_registry.factory(profile["plugin"], dict(profile))
            rule_name = op["rule"] or name
            if rule_name in om.crush.rule_names:
                rule = om.crush.rule_names[rule_name]
            else:
                rule = ec.create_rule(rule_name, om.crush)
            k = ec.get_data_chunk_count()
            m = ec.get_coding_chunk_count()
            pool = PgPool(
                id=pid, type=PoolType.ERASURE, size=k + m, min_size=k,
                crush_rule=rule, pg_num=op["pg_num"], pgp_num=op["pg_num"],
                erasure_code_profile=profile_name,
            )
        else:
            rule_name = op["rule"] or "replicated_rule"
            if rule_name in om.crush.rule_names:
                rule = om.crush.rule_names[rule_name]
            else:
                from ceph_tpu.crush import builder

                root = om.crush.bucket_names["default"]
                try:
                    fd = om.crush.type_id("host")
                except KeyError:
                    fd = 1
                rule = builder.add_simple_rule(om.crush, root, fd, mode="firstn")
                om.crush.rule_names[rule_name] = rule
            pool = PgPool(
                id=pid, type=PoolType.REPLICATED, size=op["size"],
                min_size=max(1, op["size"] - 1), crush_rule=rule,
                pg_num=op["pg_num"], pgp_num=op["pg_num"],
            )
        if op.get("fast_read"):
            # pool fast_read flag (pg_pool_t FLAG_..., ECCommon.cc:531
            # read-all-decode-first-k)
            pool.extra["fast_read"] = "1"
        om.pools[pid] = pool
        om.pool_names[pid] = name
        self._pool_ids[name] = pid
        self._next_pool += 1
