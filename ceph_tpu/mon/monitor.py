"""Monitor: the cluster-map authority.

Mini-cluster twin of the reference monitor's OSDMonitor role
(src/mon/OSDMonitor.cc): owns the OSDMap, advances epochs on osd
boot/failure/out, serves map subscriptions, and executes admin commands
— EC profile set, pool create (profile -> plugin factory -> CRUSH rule,
the seam OSDMonitor::prepare_new_pool / crush_rule_create_erasure
drives, OSDMonitor.cc:7339,7466-7523), osd down/out.

Every mutation is committed through the Paxos quorum (ceph_tpu/mon/
paxos.py) before it takes effect, and the MonitorDBStore twin
(ceph_tpu/mon/store.py) makes the committed state durable; mutating
commands are leader-only and peons forward (PaxosService semantics).
The monitor also aggregates the OSDs' per-PG stat reports (beacons
carry them — the MPGStats/DaemonServer plane) and serves status /
health / pg stat with real checks (OSD_DOWN, MON_DOWN, PG_DEGRADED;
reference src/mon/HealthMonitor.cc, src/mon/MgrStatMonitor.cc).

Failure handling: failure reports (MOSDFailure) mark the target down
immediately (reference grace logic OSDMonitor::check_failure collapses
to one report in a mini cluster), and a beacon-liveness sweep marks
OSDs down/out when beacons stop — both produce new map epochs that are
pushed to every subscriber, which is what triggers peer OSDs to
re-peer and recover.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import time

from ceph_tpu.crush.types import CrushMap
from ceph_tpu.msg.messages import (
    MConfig,
    MLog,
    MMgrBeacon,
    MMonCommand,
    MMonCommandAck,
    MMonMgrReport,
    MMonSubscribe,
    MOSDBeacon,
    MOSDBoot,
    MOSDFailure,
    MOSDScrubReply,
)
from ceph_tpu.msg.messenger import Connection, Message, Messenger
from ceph_tpu.osd.mapenc import decode_osdmap, encode_osdmap
from ceph_tpu.osd.osdmap import OSDMap

log = logging.getLogger("ceph_tpu.mon")


from ceph_tpu.mon.auth_service import AuthServiceMixin  # noqa: E402
from ceph_tpu.mon.commands import CommandMixin  # noqa: E402
from ceph_tpu.mon.config_service import ConfigServiceMixin  # noqa: E402
from ceph_tpu.mon.log_service import LogServiceMixin  # noqa: E402
from ceph_tpu.mon.mgr_service import MgrServiceMixin  # noqa: E402
from ceph_tpu.mon.osd_service import OSDMonitorMixin  # noqa: E402
from ceph_tpu.mon.stats_service import StatsServiceMixin  # noqa: E402


class Monitor(OSDMonitorMixin, StatsServiceMixin, MgrServiceMixin,
              LogServiceMixin, AuthServiceMixin, ConfigServiceMixin,
              CommandMixin):
    def __init__(
        self,
        crush: CrushMap | None = None,
        beacon_grace: float | None = None,
        out_interval: float | None = None,
        rank: int = 0,
        n_mons: int = 1,
        store=None,
        min_down_reporters: int | None = None,
        paxos_trim_max: int = 500,
        paxos_trim_keep: int = 250,
        conf=None,
        auth=None,
    ):
        """``beacon_grace``/``out_interval``: seconds without a beacon
        before an OSD is marked down / out; 0 disables the sweep (tests
        drive failure via MOSDFailure or commands).

        ``store``: an ObjectStore giving the monitor MonitorDBStore-like
        durability — paxos promises/commits persist there and a restart
        replays snapshot + committed tail (pass a FileStore for a
        monitor that survives kill -9).  None = volatile.

        Multi-monitor quorums: construct each member with its ``rank``
        and the total ``n_mons``, ``start()`` them all, then call
        ``open_quorum(monmap)`` with every member's address — the
        rank-based election picks a leader and all state mutations
        replicate through Paxos (ceph_tpu/mon/paxos.py)."""
        from ceph_tpu.mon.paxos import Paxos
        from ceph_tpu.mon.store import MonStore

        self.rank = rank
        self.n_mons = n_mons
        self.monmap: list[tuple[str, int]] = []
        self.osdmap = OSDMap(crush=crush or CrushMap())
        conf0 = conf
        if conf0 is None:
            from ceph_tpu.common import ConfigProxy as _CP

            conf0 = _CP()
        self.messenger = Messenger(
            ("mon", rank), self._dispatch, on_reset=self._on_reset,
            auth=auth,
            compress_mode=conf0["ms_compress_mode"],
            compress_algorithm=conf0["ms_compress_algorithm"],
            compress_min_size=conf0["ms_compress_min_size"],
            handshake_timeout=conf0["ms_connection_ready_timeout"],
        )
        self.store = MonStore(store) if store is not None else None
        self.paxos = Paxos(
            rank, n_mons, self._send_mon, self._apply_committed,
            store=self.store,
            get_snapshot=self._state_snapshot,
            install_snapshot=self._install_snapshot,
        )
        self._state_version = 0
        if conf is None:
            from ceph_tpu.common import ConfigProxy

            conf = ConfigProxy()
        self.conf = conf
        self.min_down_reporters = (
            min_down_reporters if min_down_reporters is not None
            else conf["mon_osd_min_down_reporters"]
        )
        self.paxos_trim_max = paxos_trim_max
        self.paxos_trim_keep = paxos_trim_keep
        # failed osd -> {reporter: report time} (OSDMonitor failure_info)
        self._failure_reports: dict[int, dict[int, float]] = {}
        # None = take the declared option defaults (both 0.0 = sweep
        # disabled); an explicit constructor arg wins, matching the
        # conf precedence tests rely on
        self.beacon_grace = (
            conf["mon_osd_beacon_grace"] if beacon_grace is None
            else beacon_grace)
        self.out_interval = (
            conf["mon_osd_down_out_interval"] if out_interval is None
            else out_interval)
        # per-subsystem gated debug logging (debug_mon), live-updatable
        # via the config observer like the reference's
        # `ceph tell mon.* config set debug_mon N`
        from ceph_tpu.common.dout import DoutLogger

        self.dlog = DoutLogger("mon", conf, name_suffix=str(rank))
        self._epoch_blobs: dict[int, bytes] = {}
        self._epoch_incs: dict[int, bytes] = {}
        self._subscribers: dict[tuple[str, int], Connection] = {}
        self._last_beacon: dict[int, float] = {}
        self._down_at: dict[int, float] = {}
        # derived replicated state: last boot incarnation per osd
        # (applied deterministically by every member in _apply_op)
        self._osd_incarnation: dict[int, int] = {}
        # epoch at which each osd was last marked up (up_from): failure
        # reports older than this are from before the reboot
        self._up_from: dict[int, int] = {}
        self._pool_ids: dict[str, int] = {}
        # ConfigMonitor database: section ('global', 'osd', 'osd.3',
        # 'mon', 'client') -> {option: value}; replicated via paxos and
        # pushed to every subscriber as MConfig
        self._config_db: dict[str, dict[str, str]] = {}
        # AuthMonitor database: entity -> {"key": hex, "caps": {...}},
        # paxos-replicated, mirrored into the live AuthContext keyring
        self._auth_db: dict[str, dict] = {}
        # construction-keyring identities: the root of trust the
        # command plane may never rebind, clobber, or delete
        self._bootstrap_entities: set[str] = (
            set(auth.keyring) if auth is not None else set()
        )
        self._next_pool = 1
        # MgrMap state (mon/mgr_service.py) — must predate replay
        self._init_mgr_service()
        # cluster log + health history/mute state (mon/log_service.py)
        # — replicated, must predate replay too
        self._init_log_service()
        # the mon's own report stream to the active mgr (every daemon
        # carries one); fed the map directly on publish — the mon is
        # its own MgrMap source
        from ceph_tpu.common import get_perf_counters
        from ceph_tpu.mgr.client import MgrClient

        self.perf = get_perf_counters(f"mon.{rank}")
        from ceph_tpu.common.tracing import Tracer

        self.tracer = Tracer(
            f"mon.{rank}",
            ring_max=conf0["trace_ring_max"],
            sample_rate=conf0["trace_sample_rate"],
            tail_slow_s=(conf0["trace_tail_slow_s"] or None),
        )
        self.messenger.tracer = self.tracer
        self.mgr_client = MgrClient(
            f"mon.{rank}", self.messenger, conf0, self._mgr_collect,
            tracers=(self.tracer,))
        self._tids = itertools.count(1)
        self._scrub_waiters: dict[int, asyncio.Future] = {}
        self._tick_task: asyncio.Task | None = None
        self._probe_task = None
        self._admin = None
        self.addr: tuple[str, int] | None = None
        self._snapshot()

    # -- lifecycle -----------------------------------------------------

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> tuple[str, int]:
        self.addr = await self.messenger.bind(host, port)
        sock_path = self.conf["admin_socket"]
        if sock_path:
            from ceph_tpu.common import AdminSocket

            self._admin = AdminSocket(
                sock_path.replace("$id", f"mon{self.rank}")
            )
            self._admin.register(
                "config show", "effective configuration",
                lambda cmd: self.conf.show(),
            )
            self._admin.register(
                "quorum_status", "election/quorum state",
                lambda cmd: {
                    "rank": self.rank,
                    "leader": self.paxos.leader,
                    "election_epoch": self.paxos.election_epoch,
                    "quorum": sorted(self.paxos.quorum),
                    "last_committed": self.paxos.last_committed,
                },
            )
            self._admin.register(
                "status", "cluster status",
                lambda cmd: {
                    "epoch": self.osdmap.epoch,
                    "num_pools": len(self.osdmap.pools),
                },
            )
            self._admin.register(
                "dump_chaos", "chaos-engine event counters + recent "
                "event spans (process-wide, ceph_tpu/chaos)",
                lambda cmd: __import__(
                    "ceph_tpu.chaos", fromlist=["dump_chaos"]
                ).dump_chaos(),
            )
            self._admin.register(
                "dump_traces", "recent spans (blkin/otel role)",
                lambda cmd: self.tracer.dump(),
            )
            self._admin.register(
                "dump_log", "cluster-log/health-history service state "
                "(ring sizes, mute book, per-entity seqs)",
                lambda cmd: self.dump_log_service(),
            )
            self._admin.register(
                "perf dump", "dump perf counters",
                lambda cmd: self.perf.dump(),
            )
            await self._admin.start()
        await self._replay()
        self._start_mgr_tick()
        self._start_health_tick()
        self.mgr_client.start()
        if self.beacon_grace > 0:
            self._tick_task = asyncio.ensure_future(self._tick())
        if self.conf["mon_pg_autoscale_interval"] > 0:
            self._autoscale_task = asyncio.ensure_future(
                self._autoscale_tick())
        return self.addr

    async def _replay(self) -> None:
        """Restart recovery: install the persisted snapshot (if any),
        then re-apply the committed tail in paxos order — the
        MonitorDBStore replay that makes a mon restart lossless."""
        if self.store is None:
            return
        st = self.store.load()
        self._replaying = True
        try:
            if st["snapshot"] is not None and st["snapshot"][0] > 0:
                await self._install_snapshot(*st["snapshot"], publish=False)
            for v in sorted(self.paxos.values):
                if v > self._state_version and self.paxos.values[v]:
                    await self._apply_committed(v, self.paxos.values[v])
        finally:
            self._replaying = False
        await self._maybe_trim()

    # -- state-machine snapshots (trim / full-sync / restart) ----------

    def _state_snapshot(self) -> tuple[int, bytes]:
        """(version, blob): everything _apply_op derives, captured
        atomically at _state_version."""
        import json

        from ceph_tpu.msg.denc import Encoder

        enc = Encoder()
        enc.u64(self._state_version)
        enc.bytes_(encode_osdmap(self.osdmap))
        enc.str_(json.dumps({
            "pool_ids": self._pool_ids,
            "next_pool": self._next_pool,
            "incarnations": {
                str(k): v for k, v in self._osd_incarnation.items()
            },
            "up_from": {str(k): v for k, v in self._up_from.items()},
            "config_db": self._config_db,
            "auth_db": self._auth_db,
            "mgr_map": self._mgr_map,
            "log_service": self._log_service_snapshot(),
        }))
        return self._state_version, enc.bytes()

    async def _install_snapshot(
        self, version: int, blob: bytes, publish: bool = True
    ) -> None:
        import json

        from ceph_tpu.msg.denc import Decoder

        dec = Decoder(blob)
        snap_version = dec.u64()
        self.osdmap = decode_osdmap(dec.bytes_())
        aux = json.loads(dec.str_())
        self._pool_ids = dict(aux["pool_ids"])
        self._next_pool = aux["next_pool"]
        self._osd_incarnation = {
            int(k): v for k, v in aux["incarnations"].items()
        }
        self._config_db = dict(aux.get("config_db", {}))
        self._auth_db = dict(aux.get("auth_db", {}))
        if aux.get("mgr_map"):
            self._mgr_map = dict(aux["mgr_map"])
        self._install_log_service(aux.get("log_service") or {})
        self._sync_auth_keyring()
        self._apply_config_locally()
        self._up_from = {
            int(k): v for k, v in aux.get("up_from", {}).items()
        }
        self._state_version = max(version, snap_version)
        self._epoch_blobs = {}
        self._epoch_incs = {}
        self._prev_snapshot = None
        self._snapshot()
        if publish:
            await self._publish()

    async def _maybe_trim(self) -> None:
        """Bound the committed log: snapshot the state machine, then
        drop values older than the keep window (Paxos::trim)."""
        if getattr(self, "_replaying", False):
            # NEVER trim mid-replay: ``below`` derives from the final
            # last_committed, so trimming here would delete committed
            # ops the replay loop has not applied yet — both from RAM
            # (KeyError on the next iteration) and, worse, durably
            return
        px = self.paxos
        if len(px.values) <= self.paxos_trim_max:
            return
        below = px.last_committed - self.paxos_trim_keep + 1
        if self.store is not None:
            await self.store.put_snapshot(*self._state_snapshot())
        px.values = {v: b for v, b in px.values.items() if v >= below}
        px.first_committed = below
        if self.store is not None:
            await self.store.trim_values(below)

    async def open_quorum(self, monmap: list[tuple[str, int]]) -> None:
        """Join the quorum: learn everyone's address, run an election
        (call on every member after all have start()ed — or, with the
        probe below, merely *around* the same time)."""
        assert len(monmap) == self.n_mons
        self.monmap = list(monmap)
        await self.paxos.start_election()
        if self.n_mons > 1 and self._probe_task is None:
            self._probe_task = asyncio.ensure_future(self._quorum_probe())

    async def _quorum_probe(self) -> None:
        """A member outside a stable quorum re-runs the election until
        it joins (the reference's probe/join phase): a mon whose first
        election raced its peers' boot — multi-process deployments bind
        at slightly different times — missed VICTORY and would
        otherwise wait forever."""
        while True:
            await asyncio.sleep(2.0)
            if not self.paxos.stable.is_set():
                try:
                    await self.paxos.start_election()
                except (ConnectionError, OSError):
                    continue

    async def wait_stable(self, timeout: float = 10.0) -> None:
        await asyncio.wait_for(self.paxos.stable.wait(), timeout)

    async def stop(self) -> None:
        await self.mgr_client.stop()
        if self._admin is not None:
            await self._admin.stop()
        if self._tick_task:
            self._tick_task.cancel()
        if self._mgr_tick_task:
            self._mgr_tick_task.cancel()
        if self._health_tick_task:
            self._health_tick_task.cancel()
        if self._probe_task:
            self._probe_task.cancel()
        if getattr(self, "_autoscale_task", None):
            self._autoscale_task.cancel()
        await self.messenger.shutdown()

    # -- quorum plumbing ----------------------------------------------

    async def _send_mon(self, rank: int, msg: Message) -> None:
        if rank < len(self.monmap):
            conn = await self.messenger.connect_to(
                ("mon", rank), *self.monmap[rank]
            )
        else:
            # a peer reached us before our own open_quorum(): reply over
            # the connection it already established
            conn = self.messenger.get_connection(("mon", rank))
            if conn is None:
                raise ConnectionError(f"mon.{rank} address unknown")
        await conn.send_message(msg)

    async def _on_reset(self, conn) -> None:
        peer = conn.peer
        if (
            peer is not None
            and peer[0] == "mon"
            and self.n_mons > 1
            and (
                self.paxos.leader == peer[1]
                # a leader losing ANY voting-quorum member must re-form
                # the quorum, or BEGINs starve waiting on the dead vote
                or (self.paxos.is_leader and peer[1] in self.paxos.quorum)
            )
        ):
            if not self.paxos.stable.is_set():
                return  # already electing: don't stack another round
            # both sides dial each other, so duplicate-connection
            # teardown is routine — only elect if the leader is truly
            # unreachable (a false election churns accepted_pn under
            # in-flight BEGINs and stalls proposes for their timeout)
            try:
                if peer[1] < len(self.monmap):
                    await asyncio.wait_for(self.messenger.connect_to(
                        ("mon", peer[1]), *self.monmap[peer[1]]
                    ), 2.0)
                    return  # reconnected: not a leader loss
            except (ConnectionError, OSError, asyncio.TimeoutError):
                pass
            self.dlog.dout(
                0, "mon.%d: quorum peer mon.%d lost; electing",
                self.rank, peer[1],
            )
            await self.paxos.start_election()

    async def _apply_committed(self, version: int, value: bytes) -> None:
        import json

        op = json.loads(value.decode())
        await self._apply_op(op)
        self._state_version = version
        await self._maybe_trim()

    async def _propose(self, op: dict) -> None:
        """Replicate one state mutation through Paxos (leader only;
        single-mon quorums commit immediately).  One retry after a
        mid-propose election (quorum-member loss): every replicated op
        is replay-idempotent, so a rare double-commit is harmless."""
        import json

        value = json.dumps(op).encode()
        last: Exception | None = None
        for _attempt in range(5):
            try:
                await self.paxos.propose(value)
                return
            except ConnectionError as e:
                last = e
                try:
                    await asyncio.wait_for(self.paxos.stable.wait(), 10)
                except asyncio.TimeoutError:
                    raise e
                if not self.is_leader:
                    raise
                await asyncio.sleep(0.05)
        raise last

    async def _apply_op(self, op: dict) -> None:
        """Route one committed mutation to its owning service (the
        PaxosService::update_from_paxos split, PaxosService.h:28)."""
        kind = op["op"]
        if kind in ("config_set", "config_rm"):
            await self._apply_config_op(op)
            return  # config changes don't mint osdmap epochs
        if kind in ("auth_upsert", "auth_del"):
            await self._apply_auth_op(op)
            return  # auth changes don't mint osdmap epochs
        if kind in ("mgr_beacon", "mgr_down", "mgr_module"):
            await self._apply_mgr_op(op)
            return  # MgrMap has its own epoch sequence
        if kind == "clog":
            self._apply_clog_op(op)
            return  # log entries don't mint osdmap epochs
        if kind == "health_history":
            self._apply_health_history_op(op)
            return
        if kind in ("health_mute", "health_unmute"):
            self._apply_health_mute_op(op)
            return
        if await self._apply_osd_op(op):
            await self._new_epoch()

    @property
    def is_leader(self) -> bool:
        return self.paxos.is_leader

    def _mgr_collect(self) -> dict:
        """This monitor's MMgrReport raw material."""
        self.perf.set_gauge("osdmap_epoch", float(self.osdmap.epoch))
        self.perf.set_gauge(
            "paxos_last_committed", float(self.paxos.last_committed))
        return {
            "counters": {
                k: v for k, v in self.perf.dump().items()
                if k not in ("osdmap_epoch", "paxos_last_committed")
            },
            "gauges": {
                "osdmap_epoch": float(self.osdmap.epoch),
                "quorum_size": float(len(self.paxos.quorum)),
            },
            "status": {
                "leader": self.paxos.leader,
                "is_leader": self.is_leader,
            },
        }

    # -- map publication ----------------------------------------------





    # -- dispatch ------------------------------------------------------

    async def _dispatch(self, msg: Message) -> None:
        from ceph_tpu.mon.paxos import MMonElection, MMonPaxos

        if isinstance(msg, MMonElection):
            await self.paxos.handle_election(msg, msg.src[1])
        elif isinstance(msg, MMonPaxos):
            await self.paxos.handle_paxos(msg, msg.src[1])
        elif isinstance(msg, MOSDBoot):
            await self._handle_boot(msg)
        elif isinstance(msg, MOSDBeacon):
            if self.is_leader:
                self._last_beacon[msg.osd] = time.monotonic()
                if msg.pg_stats:
                    self._ingest_pg_stats(msg.osd, msg.epoch, msg.pg_stats)
                if msg.statfs:
                    await self._ingest_statfs(msg.osd, msg.statfs)
                om = self.osdmap
                if (0 <= msg.osd < om.max_osd and om.exists(msg.osd)
                        and (not om.is_up(msg.osd)
                             or msg.epoch < om.epoch)):
                    # a beacon from an OSD the map says is DOWN, or one
                    # whose epoch lags the current map: it is alive but
                    # never saw the newer epochs (publish raced its
                    # reboot, a false failure report landed while its
                    # subscription was being re-established, or a netem
                    # fault on the mon link made a publish fail and
                    # popped it from _subscribers).  Hand it the
                    # catch-up payload so the "map says I'm down;
                    # re-booting" defense can fire / the stale daemon
                    # converges — without this the daemon beacons into
                    # the void forever and its PGs wedge in peering or
                    # report clean at a dead epoch (soak-chaos-found;
                    # stale-epoch arm chaos-fuzz-found, control-net).
                    if msg.src == ("osd", msg.osd):
                        # the beacon proves this path is healthy again:
                        # re-register the subscription a failed publish
                        # dropped (peon-forwarded beacons carry the
                        # peon's conn — don't register those)
                        self._subscribers[msg.src] = msg.conn
                    try:
                        await msg.conn.send_message(
                            self._maps_since(msg.epoch))
                    except (ConnectionError, OSError):
                        pass
            else:
                await self._forward_to_leader(msg)
        elif isinstance(msg, MOSDFailure):
            await self._handle_failure(msg)
        elif isinstance(msg, MLog):
            await self._handle_log(msg)
        elif isinstance(msg, MMgrBeacon):
            await self._handle_mgr_beacon(msg)
        elif isinstance(msg, MMonMgrReport):
            await self._handle_mgr_report(msg)
        elif isinstance(msg, MMonSubscribe):
            self._subscribers[msg.src] = msg.conn
            await msg.conn.send_message(self._maps_since(msg.start_epoch))
            await msg.conn.send_message(self._mgr_map_msg())
            secs = self._config_sections_for(msg.src)
            if secs:
                await msg.conn.send_message(MConfig(sections=secs))
        elif isinstance(msg, MOSDScrubReply):
            fut = self._scrub_waiters.get(msg.tid)
            if fut and not fut.done():
                fut.set_result(msg)
        elif isinstance(msg, MMonCommand):
            code, rs, data = await self._command(
                msg.cmd, caps=getattr(msg.conn, "peer_caps", None))
            await msg.conn.send_message(
                MMonCommandAck(tid=msg.tid, code=code, rs=rs, data=data)
            )

    async def _forward_to_leader(self, msg: Message) -> None:
        """Peons forward state-changing daemon messages to the leader
        (the reference's Monitor::forward_request_leader)."""
        leader = self.paxos.leader
        if leader is None or leader == self.rank or not self.monmap:
            return
        try:
            await self._send_mon(leader, msg)
        except (ConnectionError, OSError):
            pass



    # -- the replicated state machine ----------------------------------




















    # -- commands (the MonCommands.h slice) ----------------------------

    WRITE_PREFIXES = frozenset({
        "osd erasure-code-profile set", "osd pool create",
        "osd down", "osd out", "osd balance",
        "osd pool selfmanaged-snap create",
        "osd pool selfmanaged-snap rm",
        "osd pool mksnap", "osd pool rmsnap",
        "config set", "config rm", "osd crush reweight",
        "osd crush add-bucket", "osd crush move", "osd crush add",
        "osd crush rm",
        "osd pg-upmap-items",
        "auth add", "auth get-or-create", "auth del", "auth caps",
        "osd pool set", "osd pool rm", "osd in",
        "osd tier add", "osd tier remove", "osd tier cache-mode",
        "osd tier set-overlay", "osd tier remove-overlay",
        "mgr module enable", "mgr module disable", "mgr fail",
        "health mute", "health unmute",
        "crash archive", "crash archive-all",
    })




