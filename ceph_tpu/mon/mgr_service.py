"""MgrMonitor service: the MgrMap's PaxosService.

Behavioral twin of src/mon/MgrMonitor.cc: mgr daemons beacon in
(MMgrBeacon), the FIRST becomes active and the rest queue as standbys;
the map (active + standbys + enabled-module set) replicates through
paxos and is published to every subscriber as MMgrMap.  When the
active's beacons stop (or its daemon resets), the leader drops it and
promotes the first standby — standby failover, visible to every
daemon's MgrClient within one publish.

The active mgr's MMonMgrReport digests (per-OSD perf rows, analytics
summary, module health, rendered prometheus text) land here too —
volatile leader state, like the pg-stat book — and back `ceph osd
perf`, the `ceph status` mgr line and the dashboard's mgr views.
"""

from __future__ import annotations

import json
import logging
import time

from ceph_tpu.msg.messages import MMgrBeacon, MMgrMap, MMonMgrReport

log = logging.getLogger("ceph_tpu.mon")

#: modules enabled in a fresh map (mirror of mgr/modules.py
#: DEFAULT_MODULES without importing the mgr package into the mon)
_DEFAULT_MODULES = ("crash", "devicehealth", "progress", "prometheus")


class MgrServiceMixin:
    def _init_mgr_service(self) -> None:
        """Called from Monitor.__init__ (state must predate replay)."""
        self._mgr_map: dict = {
            "epoch": 0,
            "active": None,          # {"name", "gid", "addr": [h, p]}
            "standbys": [],          # same shape, promotion order
            "modules": sorted(_DEFAULT_MODULES),
        }
        self._mgr_last_beacon: dict[str, float] = {}
        self._mgr_digest: dict | None = None
        self._mgr_digest_at: float = 0.0
        self._mgr_tick_task = None

    # -- beacon intake -------------------------------------------------

    async def _handle_mgr_beacon(self, msg: MMgrBeacon) -> None:
        if not self.is_leader:
            await self._forward_to_leader(msg)
            return
        self._mgr_last_beacon[msg.name] = time.monotonic()
        rec = {"name": msg.name, "gid": msg.gid,
               "addr": [msg.host, msg.port]}
        if self._mgr_beacon_changes_map(rec):
            await self._propose({"op": "mgr_beacon", **rec})
        # always answer with the current map so a fresh mgr learns its
        # role immediately (publication also reaches subscribers)
        try:
            await msg.conn.send_message(self._mgr_map_msg())
        except (ConnectionError, OSError):
            pass

    def _mgr_beacon_changes_map(self, rec: dict) -> bool:
        m = self._mgr_map
        for existing in [m["active"], *m["standbys"]]:
            if existing and existing["name"] == rec["name"]:
                return (existing["gid"] != rec["gid"]
                        or existing["addr"] != rec["addr"])
        return True  # unknown mgr: joins the map

    # -- the replicated state machine ----------------------------------

    async def _apply_mgr_op(self, op: dict) -> None:
        """Deterministic MgrMap mutations (every quorum member, paxos
        order).  MgrMap epochs are its own sequence — mgr changes mint
        no osdmap epochs."""
        kind = op["op"]
        m = self._mgr_map
        changed = False
        if kind == "mgr_beacon":
            rec = {"name": op["name"], "gid": op["gid"],
                   "addr": list(op["addr"])}
            slot = None
            if m["active"] and m["active"]["name"] == rec["name"]:
                slot = "active"
                changed = m["active"] != rec
                m["active"] = rec
            else:
                for i, sb in enumerate(m["standbys"]):
                    if sb["name"] == rec["name"]:
                        slot = "standby"
                        changed = sb != rec
                        m["standbys"][i] = rec
                        break
            if slot is None:
                if m["active"] is None:
                    m["active"] = rec
                else:
                    m["standbys"].append(rec)
                changed = True
        elif kind == "mgr_down":
            name = op["name"]
            if m["active"] and m["active"]["name"] == name:
                m["active"] = (
                    m["standbys"].pop(0) if m["standbys"] else None)
                changed = True
            else:
                before = len(m["standbys"])
                m["standbys"] = [
                    sb for sb in m["standbys"] if sb["name"] != name]
                changed = len(m["standbys"]) != before
        elif kind == "mgr_module":
            mods = set(m["modules"])
            if op["enable"]:
                changed = op["module"] not in mods
                mods.add(op["module"])
            else:
                changed = op["module"] in mods
                mods.discard(op["module"])
            m["modules"] = sorted(mods)
        else:
            log.error("mon.%d: unknown mgr op %r", self.rank, kind)
            return
        if changed:
            m["epoch"] += 1
            await self._publish_mgr_map()

    # -- publication ---------------------------------------------------

    def _mgr_map_msg(self) -> MMgrMap:
        return MMgrMap(
            epoch=self._mgr_map["epoch"],
            blob=json.dumps(self._mgr_map).encode(),
        )

    async def _publish_mgr_map(self) -> None:
        if getattr(self, "_replaying", False):
            return  # subscribers re-learn the final map on subscribe
        msg = self._mgr_map_msg()
        # the mon's own MgrClient learns the map at the source
        mc = getattr(self, "mgr_client", None)
        if mc is not None:
            mc.handle_mgr_map(msg)
        for peer, conn in list(self._subscribers.items()):
            try:
                await conn.send_message(msg)
            except ConnectionError:
                self._subscribers.pop(peer, None)

    # -- liveness sweep (beacon grace -> failover) ---------------------

    def _start_mgr_tick(self) -> None:
        import asyncio

        if self.conf["mon_mgr_beacon_grace"] > 0:
            self._mgr_tick_task = asyncio.ensure_future(self._mgr_tick())

    async def _mgr_tick(self) -> None:
        import asyncio

        grace = self.conf["mon_mgr_beacon_grace"]
        was_leader = False
        while True:
            await asyncio.sleep(max(grace / 4, 0.05))
            if not self.is_leader:
                was_leader = False
                continue
            now = time.monotonic()
            if not was_leader:
                # fresh leadership: beacons were landing elsewhere —
                # one full grace before judging anyone
                was_leader = True
                m = self._mgr_map
                for rec in [m["active"], *m["standbys"]]:
                    if rec:
                        self._mgr_last_beacon[rec["name"]] = now
                continue
            m = self._mgr_map
            try:
                for rec in [m["active"], *list(m["standbys"])]:
                    if rec is None:
                        continue
                    last = self._mgr_last_beacon.get(rec["name"], 0.0)
                    if now - last > grace:
                        log.info("mon.%d: mgr.%s beacon timeout -> "
                                 "dropped from MgrMap", self.rank,
                                 rec["name"])
                        await self._propose({
                            "op": "mgr_down", "name": rec["name"]})
            except ConnectionError:
                continue  # lost quorum mid-sweep; retry next tick

    # -- digest intake -------------------------------------------------

    async def _handle_mgr_report(self, msg: MMonMgrReport) -> None:
        if not self.is_leader:
            await self._forward_to_leader(msg)
            return
        try:
            digest = json.loads(msg.blob or b"{}")
        except ValueError:
            return
        # only the ACTIVE mgr's digest counts (a demoted mgr's last
        # in-flight report must not shadow its successor's)
        act = self._mgr_map.get("active")
        if act is None or digest.get("gid") != act.get("gid"):
            return
        self._mgr_digest = digest
        self._mgr_digest_at = time.monotonic()

    # -- command surface helpers ---------------------------------------

    def _mgr_status_block(self) -> dict:
        m = self._mgr_map
        return {
            "epoch": m["epoch"],
            "active": m["active"]["name"] if m["active"] else None,
            "standbys": [sb["name"] for sb in m["standbys"]],
            "modules": list(m["modules"]),
            "available": m["active"] is not None,
        }

    def _mgr_stat(self) -> dict:
        """`ceph mgr stat`: map summary + digest freshness (what the
        chaos invariant polls to prove report streams resumed)."""
        now = time.monotonic()
        d = self._mgr_digest or {}
        return {
            **self._mgr_status_block(),
            "digest_age": (round(now - self._mgr_digest_at, 3)
                           if self._mgr_digest is not None else None),
            "reporting": d.get("daemons", []),
            "reports_rx": d.get("reports_rx", 0),
            "engine": d.get("engine", {}),
        }
