"""Stats/health service: the MgrStatMonitor + HealthMonitor plane.

Aggregates per-PG stats and per-OSD statfs from beacons into the
cluster pg map, fullness bits, and health checks (reference
src/mon/MgrStatMonitor.cc, src/mon/HealthMonitor.cc, and the
DaemonServer ingestion path).
"""

from __future__ import annotations

import logging

log = logging.getLogger("ceph_tpu.mon")


class StatsServiceMixin:
    def _ingest_pg_stats(self, osd: int, epoch: int, raw: bytes) -> None:
        """MgrStatMonitor/DaemonServer role: fold one OSD's per-PG
        report into the cluster pg map (newest epoch wins per pg)."""
        import json
        import re

        try:
            stats = json.loads(raw)
            if not isinstance(stats, dict):
                return
        except ValueError:
            return
        book = getattr(self, "_pg_stats", None)
        if book is None:
            book = self._pg_stats = {}
        for pgid, st in stats.items():
            # shape-check: a version-skewed OSD must not be able to
            # poison the status plane
            if not (isinstance(pgid, str) and re.fullmatch(r"\d+\.\d+", pgid)
                    and isinstance(st, dict)
                    and isinstance(st.get("state"), str)):
                continue
            cur = book.get(pgid)
            if cur is None or cur.get("epoch", 0) <= epoch:
                st = dict(st)
                st["epoch"] = epoch
                st["primary"] = osd
                book[pgid] = st

    async def _ingest_statfs(self, osd: int, raw: bytes) -> None:
        """Fold one OSD's store usage into the fullness plane
        (reference OSDMonitor full-state tracking,
        src/mon/OSDMonitor.cc:669-671 ratios + OSD.cc:773
        recalc_full_state): keep the latest statfs for `df`, derive
        the osd's fullness bits from the configured ratios, and commit
        a map change whenever the bits flip so every daemon and client
        gates on the same epoch's truth."""
        import json

        try:
            sf = json.loads(raw)
            total = int(sf["total"])
            used = int(sf["used"])
        except (ValueError, KeyError, TypeError):
            return
        book = getattr(self, "_osd_statfs", None)
        if book is None:
            book = self._osd_statfs = {}
        book[osd] = sf
        ratio = (used / total) if total > 0 else 0.0
        from ceph_tpu.osd.osdmap import (
            CEPH_OSD_BACKFILLFULL,
            CEPH_OSD_FULL,
            CEPH_OSD_FULL_MASK,
            CEPH_OSD_NEARFULL,
        )

        bits = 0
        if ratio >= self.conf["mon_osd_full_ratio"]:
            bits = CEPH_OSD_FULL
        elif ratio >= self.conf["mon_osd_backfillfull_ratio"]:
            bits = CEPH_OSD_BACKFILLFULL
        elif ratio >= self.conf["mon_osd_nearfull_ratio"]:
            bits = CEPH_OSD_NEARFULL
        om = self.osdmap
        if not om.exists(osd):
            return
        cur = om.osd_state[osd] & CEPH_OSD_FULL_MASK
        if cur != bits:
            await self._propose({
                "op": "full_state", "osd": osd, "bits": bits,
            })

    def _pg_summary(self) -> dict:
        """Aggregate pg states (the `ceph -s` pgs block)."""
        book = getattr(self, "_pg_stats", {}) or {}
        om = self.osdmap
        expected = sum(p.pg_num for p in om.pools.values())
        by_state: dict[str, int] = {}
        objects = 0
        min_epoch = om.epoch
        primaries = self._pg_primaries(om)
        for pgid, st in book.items():
            pid_s, ps_s = pgid.split(".")
            pid = int(pid_s)
            if pid not in om.pools:
                continue
            if int(ps_s) >= om.pools[pid].pg_num:
                continue  # dissolved merge child (late beacon)
            state = st.get("state", "unknown")
            # a report from a primary that is now down — or that is no
            # longer THE primary after a remap — is STALE until the
            # current primary reports (reference pg_state stale
            # semantics: stats are per-interval)
            reporter = st.get("primary", -1)
            cur_primary = primaries.get((pid, int(ps_s)), -1)
            if not om.is_up(reporter) or reporter != cur_primary:
                state = "stale"
            by_state[state] = by_state.get(state, 0) + 1
            objects += int(st.get("objects", 0))
            min_epoch = min(min_epoch, int(st.get("epoch", 0)))
        reported = sum(by_state.values())
        return {
            "num_pgs": expected,
            "num_reported": reported,
            "by_state": by_state,
            "num_objects": objects,
            # the oldest osdmap epoch any counted report was computed
            # at: a waiter that just forced a map change can require
            # min_reported_epoch >= that epoch so pre-change
            # active+clean reports can't satisfy it (the qa-helper
            # wait_for_clean checks last_epoch_clean the same way)
            "min_reported_epoch": (
                min_epoch if reported else 0),
        }

    def _pg_primaries(self, om) -> dict[tuple[int, int], int]:
        """pg -> current primary, CACHED PER EPOCH: status/health are
        the hottest mon read path and a full CRUSH pass per call would
        stall beacon dispatch (the balancer learned this the hard way
        — see the to_thread note there)."""
        from ceph_tpu.osd.types import pg_t as _pg_t

        cache_epoch, out, seen = getattr(
            self, "_primaries_cache", (None, {}, set()))
        if cache_epoch != om.epoch:
            out, seen = {}, set()
            self._primaries_cache = (om.epoch, out, seen)
        # memoize per epoch, computing only the pgids actually present
        # in the stats book (bounded by reports, not pools x pg_num) —
        # lazily, so pgids whose first report lands mid-epoch still
        # resolve; `seen` keeps warm calls near-O(1)
        book = getattr(self, "_pg_stats", {}) or {}
        if len(seen) != len(book):
            for pgid in book:
                if pgid in seen:
                    continue
                seen.add(pgid)
                pid_s, ps_s = pgid.split(".")
                pid, ps = int(pid_s), int(ps_s)
                if pid not in om.pools:
                    continue
                _u, _up, _a, primary = om.pg_to_up_acting_osds(
                    _pg_t(pid, ps), folded=True)
                out[(pid, ps)] = primary
        return out

    def _health_checks(self, pgsum: dict | None = None) -> dict:
        """HealthMonitor role (reference src/mon/HealthMonitor.cc +
        per-map checks): OSD_DOWN, MON_DOWN, PG_DEGRADED."""
        om = self.osdmap
        checks: dict[str, dict] = {}
        # down+IN only: a drained (down+out) osd is not a warning
        # (HealthMonitor counts num_down_in_osds)
        down = [
            o for o in range(om.max_osd)
            if om.exists(o) and not om.is_up(o) and not om.is_out(o)
        ]
        if down:
            checks["OSD_DOWN"] = {
                "severity": "HEALTH_WARN",
                "summary": f"{len(down)} osds down",
                "detail": [f"osd.{o} is down" for o in down],
            }
        if self.n_mons > 1:
            q = sorted(self.paxos.quorum)
            if len(q) < self.n_mons:
                missing = [r for r in range(self.n_mons) if r not in q]
                checks["MON_DOWN"] = {
                    "severity": "HEALTH_WARN",
                    "summary": (
                        f"{len(missing)}/{self.n_mons} mons out of quorum"
                    ),
                    "detail": [f"mon.{r} out of quorum" for r in missing],
                }
        if pgsum is None:
            pgsum = self._pg_summary()
        bad = {
            st: n for st, n in pgsum["by_state"].items()
            if "degraded" in st or "recovering" in st or "stale" in st
        }
        if bad:
            checks["PG_DEGRADED"] = {
                "severity": "HEALTH_WARN",
                "summary": (
                    f"{sum(bad.values())} pgs not clean: "
                    + ", ".join(f"{n} {st}" for st, n in sorted(bad.items()))
                ),
                "detail": [],
            }
        # fullness (reference OSD_FULL/OSD_BACKFILLFULL/OSD_NEARFULL
        # health checks): FULL is an error — writes are bouncing
        full = [o for o in range(om.max_osd) if om.is_full(o)]
        bfull = [
            o for o in range(om.max_osd)
            if om.is_backfillfull(o) and o not in full
        ]
        near = [
            o for o in range(om.max_osd)
            if om.is_nearfull(o) and o not in full and o not in bfull
        ]
        if full:
            checks["OSD_FULL"] = {
                "severity": "HEALTH_ERR",
                "summary": f"{len(full)} full osd(s); writes blocked",
                "detail": [f"osd.{o} is full" for o in full],
            }
        if bfull:
            checks["OSD_BACKFILLFULL"] = {
                "severity": "HEALTH_WARN",
                "summary": (
                    f"{len(bfull)} backfillfull osd(s); backfill paused"
                ),
                "detail": [f"osd.{o} is backfillfull" for o in bfull],
            }
        if near:
            checks["OSD_NEARFULL"] = {
                "severity": "HEALTH_WARN",
                "summary": f"{len(near)} nearfull osd(s)",
                "detail": [f"osd.{o} is nearfull" for o in near],
            }
        if any(c["severity"] == "HEALTH_ERR" for c in checks.values()):
            status = "HEALTH_ERR"
        else:
            status = "HEALTH_OK" if not checks else "HEALTH_WARN"
        return {"status": status, "checks": checks}
