"""Monitor command surface — the MonCommands.h slice.

One dispatcher over every admin verb, delegating mutations to the
owning service mixins (reference src/mon/Monitor.cc handle_command ->
PaxosService::dispatch).
"""

from __future__ import annotations

import asyncio
import logging

from ceph_tpu.ec import registry as ec_registry
from ceph_tpu.msg.messages import MOSDScrub, MOSDScrubReply

log = logging.getLogger("ceph_tpu.mon")


class CommandMixin:
    async def _command(
        self, cmd: dict[str, str], caps: dict[str, str] | None = None,
    ) -> tuple[int, str, bytes]:
        import errno
        import json

        prefix = cmd.get("prefix", "")
        if caps is not None:
            # MonCap admission (Monitor::_allowed_command): mutations
            # need mon w, everything else mon r — EXCEPT the auth
            # plane, which is admin-only end to end (the reference
            # tags MonCommands.h auth verbs with mon rwx): 'auth get'
            # returns secret keys and 'auth caps' rewrites grants, so
            # plain r/w must not reach either
            from ceph_tpu.common.caps import capable

            if prefix.startswith("auth "):
                need = "rwx"
            else:
                need = "w" if prefix in self.WRITE_PREFIXES else "r"
            if not capable(caps, "mon", need):
                return -errno.EACCES, "access denied", b""
        mutating = prefix in self.WRITE_PREFIXES or prefix in (
            # not mutations, but only the leader ingests pg stats /
            # mgr digests and knows the live quorum: redirect so peons
            # don't serve an empty status plane.  `log last` / `health
            # history` are deliberately ABSENT: they serve replicated
            # state, so a follow stream keeps working on any member
            # through a mon failover.
            "status", "health", "pg stat", "df", "osd df",
            "osd perf", "mgr stat", "trace ls", "trace show",
            "progress", "crash ls", "crash info",
        )
        if mutating and not self.is_leader:
            leader = self.paxos.leader if self.paxos.leader is not None else -1
            return -errno.EAGAIN, f"ENOTLEADER {leader}", b""
        if prefix in self.WRITE_PREFIXES:
            # every accepted admin write lands in the AUDIT channel of
            # the replicated cluster log (the reference logs command
            # dispatch through LogChannel("audit"))
            await self._log_append("audit", 1, "from='client' cmd=" + str(
                {k: v for k, v in sorted(cmd.items())}) + ": dispatch")
        try:
            if prefix == "osd erasure-code-profile set":
                name = cmd["name"]
                profile = dict(
                    kv.split("=", 1) for kv in cmd.get("profile", "").split() if kv
                )
                profile.setdefault("plugin", "jax")
                # instantiate once to validate + fill defaults
                ec_registry.factory(profile["plugin"], profile)
                await self._propose({
                    "op": "profile", "name": name, "profile": profile,
                })
                return 0, f"profile {name} set", b""
            if prefix == "osd pool create":
                return await self._pool_create(cmd)
            if prefix.startswith("auth "):
                return await self._auth_command(prefix, cmd)
            if prefix == "osd pool set":
                return await self._pool_set(cmd)
            if prefix == "osd pool rm":
                return await self._pool_rm(cmd)
            if prefix.startswith("osd tier "):
                return await self._tier_command(prefix, cmd)
            if prefix == "osd in":
                osd = int(cmd["id"])
                om = self.osdmap
                if not om.exists(osd):
                    return -errno.ENOENT, f"osd.{osd} does not exist", b""
                if not om.is_out(osd):
                    return 0, f"osd.{osd} is already in", b""
                await self._propose({"op": "in", "osd": osd})
                return 0, f"marked in osd.{osd}", b""
            if prefix == "osd pool selfmanaged-snap create":
                pid = self._pool_ids[cmd["pool"]]
                # serialize id allocation: two concurrent creates must
                # not both read snap_seq before either commits
                async with self._snap_alloc_lock(pid):
                    snapid = self.osdmap.pools[pid].snap_seq + 1
                    await self._propose({
                        "op": "snap_alloc", "pool": pid, "snapid": snapid,
                    })
                return 0, f"snap {snapid}", json.dumps(
                    {"snapid": snapid}).encode()
            if prefix == "osd pool selfmanaged-snap rm":
                pid = self._pool_ids[cmd["pool"]]
                snapid = int(cmd["snapid"])
                if snapid not in self.osdmap.pools[pid].removed_snaps:
                    await self._propose({
                        "op": "snap_rm", "pool": pid, "snapid": snapid,
                    })
                return 0, f"snap {snapid} removed", b""
            if prefix == "osd pool mksnap":
                pid = self._pool_ids[cmd["pool"]]
                name = cmd["snap"]
                async with self._snap_alloc_lock(pid):
                    pool = self.osdmap.pools[pid]
                    if name in pool.pool_snaps:
                        return -errno.EEXIST, f"snap {name} exists", b""
                    snapid = pool.snap_seq + 1
                    await self._propose({
                        "op": "snap_alloc", "pool": pid, "snapid": snapid,
                        "name": name,
                    })
                return 0, f"created pool snap {name}", json.dumps(
                    {"snapid": snapid}).encode()
            if prefix == "osd pool rmsnap":
                pid = self._pool_ids[cmd["pool"]]
                name = cmd["snap"]
                pool = self.osdmap.pools[pid]
                if name not in pool.pool_snaps:
                    return -errno.ENOENT, f"no snap {name}", b""
                await self._propose({
                    "op": "snap_rm", "pool": pid,
                    "snapid": pool.pool_snaps[name], "name": name,
                })
                return 0, f"removed pool snap {name}", b""
            if prefix == "osd down":
                osd = int(cmd["id"])
                if self.osdmap.is_up(osd):
                    await self._propose({"op": "down", "osd": osd})
                return 0, f"osd.{osd} down", b""
            if prefix == "osd out":
                osd = int(cmd["id"])
                if not self.osdmap.is_out(osd):
                    await self._propose({"op": "out", "osd": osd})
                return 0, f"osd.{osd} out", b""
            if prefix == "osd balance":
                import json

                from ceph_tpu.osd.balancer import UpmapBalancer
                from ceph_tpu.osd.mapenc import decode_osdmap, encode_osdmap

                try:
                    fd = self.osdmap.crush.type_id("host")
                except KeyError:
                    fd = 1
                # the census is seconds of pure computation: run it on a
                # SNAPSHOT in a worker thread so the event loop keeps
                # dispatching beacons (a blocked loop looks like every
                # OSD going silent at once)
                snapshot = decode_osdmap(encode_osdmap(self.osdmap))
                max_swaps = int(cmd.get("max_swaps", "64"))

                def _optimize():
                    bal = UpmapBalancer(snapshot, failure_domain_type=fd)
                    return bal.optimize(max_swaps=max_swaps)

                items = await asyncio.to_thread(_optimize)
                if items:
                    await self._propose({
                        "op": "upmap",
                        "items": [
                            [pg.pool, pg.ps, [list(p) for p in pairs]]
                            for pg, pairs in items.items()
                        ],
                    })
                return 0, f"{len(items)} upmap items installed", json.dumps(
                    {"swaps": len(items)}
                ).encode()
            if prefix in ("pg scrub", "pg deep-scrub", "pg repair"):
                return await self._scrub(
                    cmd, deep=prefix != "pg scrub",
                    repair=prefix == "pg repair")
            if prefix == "df":
                # `ceph df` (reference MgrStatMonitor/`df` detail):
                # cluster raw totals from beacon statfs + per-pool
                # logical usage aggregated from pg stats
                om = self.osdmap
                book = getattr(self, "_osd_statfs", {}) or {}
                live = {o: s for o, s in book.items() if om.exists(o)}
                pools: dict[str, dict] = {}
                for pgid, st in (getattr(self, "_pg_stats", {}) or {}).items():
                    pid = int(pgid.split(".")[0])
                    if pid not in om.pools:
                        continue
                    name = om.pool_names.get(pid, str(pid))
                    d = pools.setdefault(
                        name, {"id": pid, "objects": 0, "bytes_used": 0})
                    d["objects"] += int(st.get("objects", 0))
                    d["bytes_used"] += int(st.get("bytes", 0))
                data = json.dumps({
                    "stats": {
                        "total_bytes": sum(
                            int(s.get("total", 0)) for s in live.values()),
                        "total_used_bytes": sum(
                            int(s.get("used", 0)) for s in live.values()),
                        "total_avail_bytes": sum(
                            int(s.get("available", 0))
                            for s in live.values()),
                    },
                    "pools": pools,
                }).encode()
                return 0, "", data
            if prefix == "osd df":
                # `ceph osd df`: per-osd usage + fullness state
                om = self.osdmap
                book = getattr(self, "_osd_statfs", {}) or {}
                nodes = []
                for o in range(om.max_osd):
                    if not om.exists(o):
                        continue
                    sf = book.get(o, {})
                    t = int(sf.get("total", 0))
                    u = int(sf.get("used", 0))
                    state = []
                    if om.is_full(o):
                        state.append("full")
                    elif om.is_backfillfull(o):
                        state.append("backfillfull")
                    elif om.is_nearfull(o):
                        state.append("nearfull")
                    nodes.append({
                        "id": o,
                        "total": t,
                        "used": u,
                        "available": int(sf.get("available", 0)),
                        "utilization": (u / t) if t else 0.0,
                        "state": state,
                    })
                return 0, "", json.dumps({"nodes": nodes}).encode()
            if prefix == "status":
                om = self.osdmap
                pgsum = self._pg_summary()
                up = sum(om.is_up(o) for o in range(om.max_osd))
                inn = sum(
                    not om.is_out(o) for o in range(om.max_osd) if om.exists(o)
                )
                data = json.dumps({
                    "epoch": om.epoch,
                    "num_osds": sum(om.exists(o) for o in range(om.max_osd)),
                    "num_up_osds": up,
                    "num_in_osds": inn,
                    "quorum": sorted(self.paxos.quorum),
                    "pools": {
                        str(pid): {"name": name, "pg_num": om.pools[pid].pg_num}
                        for name, pid in self._pool_ids.items()
                    },
                    "pgs": pgsum,
                    "health": self._render_health(pgsum),
                    # the `ceph status` mgr line (reference mgrmap
                    # summary: "mgr: x(active), standbys: y")
                    "mgr": self._mgr_status_block(),
                    # the mgr progress module's events (recovery /
                    # rebalance completion + ETA), folded into status
                    "progress": (self._mgr_digest or {}).get(
                        "progress", {}),
                }).encode()
                return 0, "", data
            if prefix == "config set":
                who = cmd.get("who", "global")
                name, value = cmd["name"], cmd["value"]
                from ceph_tpu.common.config import OPTIONS

                opt = OPTIONS.get(name)
                if opt is None:
                    return -errno.ENOENT, f"unknown option {name!r}", b""
                try:
                    opt.cast(value)
                except (ValueError, TypeError) as e:
                    return -errno.EINVAL, str(e), b""
                await self._propose({
                    "op": "config_set", "who": who,
                    "name": name, "value": value,
                })
                return 0, f"set {who}/{name}", b""
            if prefix == "config rm":
                await self._propose({
                    "op": "config_rm", "who": cmd.get("who", "global"),
                    "name": cmd["name"],
                })
                return 0, "removed", b""
            if prefix == "config dump":
                return 0, "", json.dumps(self._config_db).encode()
            if prefix == "config get":
                who = cmd.get("who", "global")
                kind = who.split(".")[0]
                merged: dict[str, str] = {}
                for sec in ("global", kind, who):
                    merged.update(self._config_db.get(sec, {}))
                if "name" in cmd:
                    if cmd["name"] not in merged:
                        return -errno.ENOENT, "not set", b""
                    return 0, "", merged[cmd["name"]].encode()
                return 0, "", json.dumps(merged).encode()
            if prefix == "osd pg-upmap-items":
                # explicit placement override pairs (reference
                # OSDMonitor osd pg-upmap-items): pgid from to [...]
                pool_id, ps = cmd["pgid"].split(".", 1)
                pool_id = int(pool_id)
                ps = int(ps, 16) if ps.startswith("0x") else int(ps)
                pool = self.osdmap.pools.get(pool_id)
                if pool is None:
                    return -errno.ENOENT, f"no pool {pool_id}", b""
                if not 0 <= ps < pool.pg_num:
                    return -errno.ENOENT, f"no pg {cmd['pgid']}", b""
                pairs_raw = cmd["pairs"].split()
                if len(pairs_raw) % 2:
                    return -errno.EINVAL, "pairs must be from/to pairs", b""
                items = [
                    [int(pairs_raw[i]), int(pairs_raw[i + 1])]
                    for i in range(0, len(pairs_raw), 2)
                ]
                for frm, to in items:
                    if not (self.osdmap.exists(frm)
                            and self.osdmap.exists(to)):
                        return (-errno.ENOENT,
                                f"osd {frm} or {to} does not exist", b"")
                await self._propose({
                    "op": "upmap",
                    "items": [[pool_id, ps, items]],
                })
                return 0, f"upmap set on {cmd['pgid']}", b""
            if prefix == "osd crush reweight":
                name = cmd["name"]
                om2 = self.osdmap
                if name.startswith("osd."):
                    item = int(name[4:])
                elif name in om2.crush.bucket_names:
                    item = om2.crush.bucket_names[name]
                else:
                    return -errno.ENOENT, f"no item {name!r}", b""
                if not any(
                    item in b.items for b in om2.crush.buckets.values()
                ):
                    return -errno.ENOENT, f"{name!r} not in the map", b""
                weight = int(float(cmd["weight"]) * 0x10000)
                await self._propose({
                    "op": "crush_reweight", "item": item,
                    "weight": weight,
                })
                return 0, f"reweighted {name} to {cmd['weight']}", b""
            if prefix == "osd crush add-bucket":
                # OSDMonitor 'osd crush add-bucket <name> <type>'
                name, tname = cmd["name"], cmd["type"]
                om2 = self.osdmap
                try:
                    om2.crush.type_id(tname)
                except KeyError:
                    return -errno.EINVAL, f"unknown type {tname!r}", b""
                if name in om2.crush.bucket_names:
                    return 0, f"bucket {name!r} already exists", b""
                await self._propose({
                    "op": "crush_add_bucket", "name": name,
                    "type": tname,
                })
                return 0, f"added bucket {name}", b""
            if prefix in ("osd crush move", "osd crush add"):
                # 'osd crush move <name> <loc>' relocates an existing
                # item; 'osd crush add osd.N <weight> <loc>' places a
                # device (create-or-move).  <loc> is type=name, e.g.
                # root=default or host=host3 (CrushWrapper::move_bucket
                # / insert_item)
                name = cmd["name"]
                loc = cmd.get("loc") or cmd.get("args", "")
                if "=" not in loc:
                    return -errno.EINVAL, f"bad location {loc!r}", b""
                _ltype, lname = loc.split("=", 1)
                om2 = self.osdmap
                if lname not in om2.crush.bucket_names:
                    return -errno.ENOENT, f"no bucket {lname!r}", b""
                if name.startswith("osd."):
                    item = int(name[4:])
                    if prefix == "osd crush add" and \
                            not om2.exists(item):
                        return -errno.ENOENT, \
                            f"osd.{item} does not exist", b""
                elif prefix == "osd crush add":
                    # the reference restricts 'crush add' to devices:
                    # an explicit weight on a bucket would desync the
                    # parent's stored weight from the subtree sum
                    return -errno.EINVAL, \
                        "'osd crush add' takes an osd.N id (use " \
                        "'osd crush move' for buckets)", b""
                elif name in om2.crush.bucket_names:
                    item = om2.crush.bucket_names[name]
                else:
                    return -errno.ENOENT, f"no item {name!r}", b""
                from ceph_tpu.crush.builder import would_cycle

                if would_cycle(
                        om2.crush, item,
                        om2.crush.bucket_names[lname]):
                    return -errno.EINVAL, \
                        f"moving {name!r} under {lname!r} would " \
                        "create a loop", b""
                op = {
                    "op": "crush_move", "item_name": name,
                    "loc": lname,
                }
                if prefix == "osd crush add":
                    op["weight"] = int(float(cmd["weight"]) * 0x10000)
                await self._propose(op)
                return 0, f"moved {name} under {lname}", b""
            if prefix == "osd crush rm":
                name = cmd["name"]
                om2 = self.osdmap
                if name.startswith("osd."):
                    item = int(name[4:])
                elif name in om2.crush.bucket_names:
                    item = om2.crush.bucket_names[name]
                else:
                    return -errno.ENOENT, f"no item {name!r}", b""
                if item < 0 and om2.crush.buckets[item].items:
                    return -errno.ENOTEMPTY, \
                        f"bucket {name!r} is not empty", b""
                await self._propose({
                    "op": "crush_rm", "item_name": name,
                })
                return 0, f"removed {name}", b""
            if prefix == "osd pool autoscale-status":
                # the pg_autoscaler mgr module's sizing math
                # (reference src/pybind/mgr/pg_autoscaler).  Advisory
                # here; pools with pg_autoscale_mode=on get the advice
                # APPLIED by _autoscale_tick (pg splitting exists now)
                return 0, "", json.dumps(self._autoscale_rows()).encode()
            if prefix == "mgr dump":
                return 0, "", json.dumps(self._mgr_map).encode()
            if prefix == "mgr stat":
                return 0, "", json.dumps(self._mgr_stat()).encode()
            if prefix == "mgr digest":
                # the analytics/telemetry slice of the active mgr's
                # last MMonMgrReport — what the load harness
                # cross-checks its client-side percentiles against
                # (over the wire, so the whole report->digest->mon
                # chain is what gets verified)
                d = self._mgr_digest or {}
                return 0, "", json.dumps({
                    "active": d.get("active"), "ts": d.get("ts"),
                    "analytics": d.get("analytics", {}),
                    "osd_perf": d.get("osd_perf", {}),
                    "load_clients": d.get("load_clients", {}),
                    "health": sorted(d.get("health", {})),
                    "engine": d.get("engine", {}),
                }).encode()
            if prefix == "mgr module ls":
                from ceph_tpu.mgr.modules import MODULE_REGISTRY

                return 0, "", json.dumps({
                    "enabled_modules": list(self._mgr_map["modules"]),
                    "available_modules": sorted(MODULE_REGISTRY),
                }).encode()
            if prefix in ("mgr module enable", "mgr module disable"):
                from ceph_tpu.mgr.modules import MODULE_REGISTRY

                module = cmd["module"]
                if module not in MODULE_REGISTRY:
                    return -errno.ENOENT, f"no module {module!r}", b""
                enable = prefix.endswith("enable")
                await self._propose({
                    "op": "mgr_module", "module": module,
                    "enable": enable,
                })
                verb = "enabled" if enable else "disabled"
                return 0, f"module {module!r} {verb}", b""
            if prefix == "mgr fail":
                # drop the named (or active) mgr from the map NOW —
                # the operator's manual failover lever
                name = cmd.get("who", "")
                act = self._mgr_map.get("active")
                if not name and act is not None:
                    name = act["name"]
                known = [r["name"] for r in
                         [act, *self._mgr_map["standbys"]] if r]
                if name not in known:
                    return -errno.ENOENT, f"no mgr {name!r}", b""
                await self._propose({"op": "mgr_down", "name": name})
                return 0, f"mgr.{name} failed", b""
            if prefix == "osd perf":
                # per-OSD commit/apply latency from the mgr's
                # time-series store (reference `ceph osd perf`, served
                # by the mgr digest plane)
                d = self._mgr_digest or {}
                return 0, "", json.dumps({
                    "osd_perf_infos": [
                        {"id": int(osd), **row}
                        for osd, row in sorted(
                            d.get("osd_perf", {}).items(),
                            key=lambda kv: int(kv[0]))
                    ],
                    "source_mgr": d.get("active"),
                }).encode()
            if prefix == "trace ls":
                # cross-daemon trace summaries from the active mgr's
                # collector (rides the MMonMgrReport digest)
                d = self._mgr_digest or {}
                traces = d.get("traces", {})
                return 0, "", json.dumps({
                    "traces": traces.get("ls", []),
                    "source_mgr": d.get("active"),
                    "stats": traces.get("stats", {}),
                }).encode()
            if prefix == "trace show":
                d = self._mgr_digest or {}
                trees = (d.get("traces", {}) or {}).get("trees", {})
                tid = str(cmd["trace_id"])
                a = trees.get(tid)
                if a is None:
                    return (-errno.ENOENT,
                            f"trace {tid} not in the digest window "
                            "(only recent + slow traces ride the "
                            "digest; see `trace ls`)", b"")
                from ceph_tpu.mgr.tracer import render_tree

                a = dict(a)
                a["rendered"] = render_tree(a["tree"])
                return 0, "", json.dumps(a).encode()
            if prefix == "health":
                # own checks + mgr-digest module checks, mute-filtered
                # (mon/log_service.py — the reference HealthMonitor +
                # MMonMgrReport health merge)
                h = self._render_health()
                return 0, h["status"], json.dumps(h).encode()
            if prefix == "health history":
                return 0, "", json.dumps({
                    "history": self._health_history,
                    "mutes": self._health_mutes,
                }).encode()
            if prefix == "health mute":
                code_name = cmd["code"]
                ttl = float(cmd.get("ttl") or
                            self.conf["mon_health_mute_ttl_default"])
                import time as _time

                await self._propose({
                    "op": "health_mute", "code": code_name,
                    "until": (_time.time() + ttl) if ttl > 0 else None,
                    "sticky": cmd.get("sticky", "") in
                    ("1", "true", "yes", "on"),
                    "at": _time.time(),
                })
                return 0, f"muted {code_name}" + (
                    f" for {ttl:g}s" if ttl > 0 else ""), b""
            if prefix == "health unmute":
                code_name = cmd["code"]
                if code_name not in self._health_mutes:
                    return -errno.ENOENT, f"{code_name} is not muted", b""
                await self._propose({
                    "op": "health_unmute", "code": code_name})
                return 0, f"unmuted {code_name}", b""
            if prefix == "log last":
                return 0, "", json.dumps(self._log_last(
                    n=int(cmd.get("n", "20")),
                    channel=cmd.get("channel", ""),
                    since=int(cmd.get("since", "0")),
                )).encode()
            if prefix == "progress":
                # recovery/rebalance progress events from the mgr
                # progress module (ride the MMonMgrReport digest)
                d = self._mgr_digest or {}
                prog = d.get("progress", {}) or {}
                return 0, "", json.dumps({
                    "events": prog.get("events", []),
                    "completed": prog.get("completed", []),
                    "source_mgr": d.get("active"),
                }).encode()
            if prefix == "crash ls":
                d = self._mgr_digest or {}
                crash = d.get("crash", {}) or {}
                return 0, "", json.dumps({
                    "crashes": crash.get("crashes", []),
                    "recent": crash.get("recent", 0),
                    "source_mgr": d.get("active"),
                }).encode()
            if prefix == "crash info":
                d = self._mgr_digest or {}
                cid = cmd["id"]
                for meta in (d.get("crash", {}) or {}).get("crashes", []):
                    if meta.get("crash_id") == cid:
                        return 0, "", json.dumps(meta).encode()
                return -errno.ENOENT, f"no crash {cid!r} in the " \
                    "collector window (see `crash ls`)", b""
            if prefix in ("crash archive", "crash archive-all"):
                # the shared crash_dir IS the posted record: archiving
                # marks dumps acknowledged in place; the mgr crash
                # module observes it on its next scan and RECENT_CRASH
                # clears
                from ceph_tpu.common.crash import archive_crash

                cdir = self.conf["crash_dir"]
                if not cdir:
                    return -errno.EINVAL, \
                        "crash_dir is not configured on this mon", b""
                cid = None if prefix.endswith("-all") else cmd["id"]
                n = archive_crash(cdir, cid)
                return 0, f"archived {n} crash dump(s)", json.dumps(
                    {"archived": n}).encode()
            if prefix == "pg stat":
                book = getattr(self, "_pg_stats", {}) or {}
                return 0, "", json.dumps({
                    "pg_stats": book, "summary": self._pg_summary(),
                }).encode()
            return -errno.EINVAL, f"unknown command {prefix!r}", b""
        except KeyError as e:
            return -errno.EINVAL, f"missing arg {e}", b""
        except Exception as e:  # command errors must not kill the mon
            eno = getattr(e, "errno", None) or errno.EINVAL
            return -eno, str(e) or type(e).__name__, b""

    async def _scrub(self, cmd: dict[str, str], deep: bool,
                     repair: bool = False) -> tuple[int, str, bytes]:
        """Forward a scrub request to the PG's primary and return its
        report (OSDMonitor scrub command -> MOSDScrub to the OSD)."""
        import errno

        from ceph_tpu.osd.types import pg_t

        pool_id, ps = cmd["pgid"].split(".", 1)
        pool_id, ps = int(pool_id), int(ps, 16) if ps.startswith("0x") else int(ps)
        om = self.osdmap
        if om.get_pg_pool(pool_id) is None:
            return -errno.ENOENT, f"no pool {pool_id}", b""
        _, _, _, primary = om.pg_to_up_acting_osds(pg_t(pool_id, ps), folded=True)
        if primary < 0:
            return -errno.EAGAIN, f"pg {cmd['pgid']} has no primary", b""
        addr = om.osd_addrs.get(primary)
        conn = self._subscribers.get(("osd", primary))
        if conn is None and addr is not None:
            conn = await self.messenger.connect_to(("osd", primary), *addr)
        if conn is None:
            return -errno.EAGAIN, f"primary osd.{primary} unreachable", b""
        tid = next(self._tids)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._scrub_waiters[tid] = fut
        try:
            await conn.send_message(
                MOSDScrub(tid=tid, pool=pool_id, ps=ps, deep=deep,
                          repair=repair)
            )
            # shorter than the client command timeout (30s): a slow
            # scrub returns an error here instead of the client
            # resending and stacking duplicate scrubs
            reply: MOSDScrubReply = await asyncio.wait_for(fut, 25)
        except asyncio.TimeoutError:
            return -errno.ETIMEDOUT, "scrub did not finish in 25s", b""
        finally:
            self._scrub_waiters.pop(tid, None)
        return reply.result, "", reply.report
