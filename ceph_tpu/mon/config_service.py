"""Config service: the ConfigMonitor plane.

Centralized typed-option distribution (reference
src/mon/ConfigMonitor.cc): a paxos-replicated who->option database
pushed to subscribed daemons as MConfig sections and applied locally.
"""

from __future__ import annotations

import logging

from ceph_tpu.msg.messages import MConfig

log = logging.getLogger("ceph_tpu.mon")


class ConfigServiceMixin:
    async def _apply_config_op(self, op: dict) -> None:
        """Committed config mutation (never mints an osdmap epoch)."""
        if op["op"] == "config_set":
            db = self._config_db.setdefault(op["who"], {})
            db[op["name"]] = op["value"]
        else:  # config_rm
            self._config_db.get(op["who"], {}).pop(op["name"], None)
        self._apply_config_locally()
        await self._push_config()

    def _config_sections_for(self, who: tuple[str, int]) -> dict:
        """The sections addressing one entity, in precedence order
        (global < type < type.id), pre-merged for the receiver."""
        kind, ident = who
        out: dict[str, dict[str, str]] = {}
        for sec in ("global", kind, f"{kind}.{ident}"):
            if sec in self._config_db:
                out[sec] = dict(self._config_db[sec])
        return out

    def _apply_config_locally(self) -> None:
        for sec in ("global", "mon", f"mon.{self.rank}"):
            for name, value in self._config_db.get(sec, {}).items():
                try:
                    self.conf.set(name, value, source="mon")
                except (KeyError, ValueError):
                    pass

    async def _push_config(self) -> None:
        for peer, conn in list(self._subscribers.items()):
            secs = self._config_sections_for(peer)
            try:
                await conn.send_message(MConfig(sections=secs))
            except (ConnectionError, OSError):
                self._subscribers.pop(peer, None)
