"""Cluster control plane (reference src/mon/): the map-authority
monitor of the mini-cluster."""

from ceph_tpu.mon.monitor import Monitor

__all__ = ["Monitor"]
