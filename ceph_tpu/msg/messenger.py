"""Async messenger: connections, dispatch, typed messages.

Behavioral twin of the reference messenger layer (src/msg/Messenger.h,
src/msg/async/AsyncMessenger.cc): an entity (osd.3, mon.0, client.17)
owns one Messenger; connections are established lazily by address,
carry a HELLO handshake (peer identity exchange, ProtocolV2.cc
HelloFrame), and deliver typed messages to the owner's dispatcher.
The asyncio event loop plays the role of the reference's epoll worker
threads; per-connection send serialization replaces the write-queue
locks.

Messages subclass :class:`Message` and register a wire type id; the
MESSAGE frame is [header segment | payload segment] like the
reference's msgr2 message frames (header: type, source entity, seq).
"""

from __future__ import annotations

import asyncio
import contextlib
import logging
from typing import Awaitable, Callable

from ceph_tpu.msg import frames
from ceph_tpu.msg.denc import Decoder, Encoder

log = logging.getLogger("ceph_tpu.msg")

# bound on the banner/HELLO/auth exchange, both directions (the
# reference's ms_connection_ready_timeout, src/common/options/global
# .yaml.in): a half-open peer must fail the dial, not wedge it
HANDSHAKE_TIMEOUT = 10.0

_REGISTRY: dict[int, type] = {}


class Message:
    """Typed wire message.  Subclasses set ``TYPE`` and implement
    encode_payload/decode_payload."""

    TYPE = 0

    def __init_subclass__(cls, **kw):
        super().__init_subclass__(**kw)
        if cls.TYPE:
            prev = _REGISTRY.setdefault(cls.TYPE, cls)
            assert prev is cls, f"duplicate message type {cls.TYPE}"

    # filled in on receive
    src: tuple[str, int] | None = None
    conn: "Connection | None" = None
    # distributed-tracing context riding the frame header (the jaeger
    # context-propagation role): set by the sender, decoded on receive.
    # None = untraced message (zero wire cost beyond one bool).
    trace = None

    def encode_payload(self, enc: Encoder) -> None:  # pragma: no cover
        raise NotImplementedError

    @classmethod
    def decode_payload(cls, dec: Decoder) -> "Message":  # pragma: no cover
        raise NotImplementedError

    def __repr__(self) -> str:
        return f"<{type(self).__name__}>"


def encode_message(msg: Message, src: tuple[str, int], seq: int) -> list[bytes]:
    head = Encoder()
    head.u32(type(msg).TYPE)
    head.str_(src[0])
    head.i64(src[1])
    head.u64(seq)
    # trace context rides the header, not the payload: every message
    # type propagates it without per-type encode changes (the msgr2
    # frame-extension seam)
    trace = getattr(msg, "trace", None)
    head.bool_(trace is not None)
    if trace is not None:
        trace.encode(head)
    payload = Encoder()
    msg.encode_payload(payload)
    return [head.bytes(), payload.bytes()]


def decode_message(segments: list[bytes]) -> Message:
    dec = Decoder(segments[0])
    mtype = dec.u32()
    src = (dec.str_(), dec.i64())
    _seq = dec.u64()
    trace = None
    if dec.bool_():
        from ceph_tpu.common.tracing import TraceContext

        trace = TraceContext.decode(dec)
    cls = _REGISTRY.get(mtype)
    if cls is None:
        raise frames.FrameError(f"unknown message type {mtype}")
    msg = cls.decode_payload(Decoder(segments[1]))
    msg.src = src
    msg.trace = trace
    return msg


class Connection:
    """One established peer session (reference AsyncConnection)."""

    def __init__(
        self,
        messenger: "Messenger",
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        peer: tuple[str, int] | None = None,
    ):
        self.messenger = messenger
        self.reader = reader
        self.writer = writer
        self.peer = peer            # entity, learned in HELLO
        self.peer_addr: tuple[str, int] | None = None  # (host, port), for reconnect
        self._send_lock = asyncio.Lock()
        self._seq = 0
        self._closed = False
        self._reader_task: asyncio.Task | None = None
        # msgr2 SECURE mode: set by the auth handshake; None = crc mode
        self.crypto = None
        # peer authorization from its ticket; None = auth off (allow)
        self.peer_caps: dict[str, str] | None = None
        # negotiated on-wire compressor (None = uncompressed)
        self.compressor = None

    async def send_message(self, msg: Message) -> None:
        if self._closed:
            raise ConnectionError("connection closed")
        # deterministic network emulation (ceph_tpu/chaos/netem.py):
        # per-peer partitions raise, one-way drops swallow the message,
        # delay/reorder holds run here — BEFORE the send lock, so a
        # held message is genuinely overtaken on the wire
        shim = self.messenger.netem
        if shim is not None and self.peer is not None:
            if not await shim.on_send(self.messenger.entity, self.peer):
                return
        n = self.messenger.inject_socket_failures
        if n > 0:
            self.messenger._inject_counter += 1
            if self.messenger._inject_counter % n == 0:
                await self.close(notify=True)
                raise ConnectionError("injected socket failure")
        # ms_inject_delay analogue (reference global.yaml.in:1242-1267):
        # per-send latency, for testing fan-out concurrency
        delay = self.messenger.inject_delay
        if delay > 0:
            await asyncio.sleep(delay)
        trace = getattr(msg, "trace", None)
        tracer = self.messenger.tracer
        span_cm = (
            tracer.span(
                "msg_send", ctx=trace, stage="net",
                msg=type(msg).__name__,
                peer=f"{self.peer[0]}.{self.peer[1]}" if self.peer else "?",
            )
            if tracer is not None and trace is not None and trace.sampled
            else contextlib.nullcontext()
        )
        with span_cm:
            async with self._send_lock:
                self._seq += 1
                segs = encode_message(msg, self.messenger.entity, self._seq)
                tag = frames.Tag.MESSAGE
                if (
                    self.compressor is not None
                    and sum(len(s) for s in segs)
                    >= self.messenger.compress_min_size
                ):
                    segs = [self.compressor.compress(s) for s in segs]
                    tag = frames.Tag.MESSAGE_COMPRESSED
                await frames.write_frame(
                    self.writer, tag, segs, crypto=self.crypto
                )

    async def send_messages(self, msgs: list[Message]) -> None:
        """Send a burst of messages back-to-back under ONE send-lock
        hold (the objecter's per-OSD coalescing seam): frames hit the
        wire consecutively with no interleaved waits, so a batch of
        ops to the same primary costs one writer wakeup instead of N.
        Netem/injection semantics stay per-message (a partitioned peer
        drops each message exactly as single sends would)."""
        if self._closed:
            raise ConnectionError("connection closed")
        shim = self.messenger.netem
        if shim is not None and self.peer is not None:
            kept = []
            for m in msgs:
                if await shim.on_send(self.messenger.entity, self.peer):
                    kept.append(m)
            msgs = kept
        if not msgs:
            return
        n = self.messenger.inject_socket_failures
        if n > 0:
            self.messenger._inject_counter += len(msgs)
            if self.messenger._inject_counter % n < len(msgs):
                await self.close(notify=True)
                raise ConnectionError("injected socket failure")
        delay = self.messenger.inject_delay
        if delay > 0:
            await asyncio.sleep(delay)
        tracer = self.messenger.tracer
        async with self._send_lock:
            for msg in msgs:
                trace = getattr(msg, "trace", None)
                span_cm = (
                    tracer.span(
                        "msg_send", ctx=trace, stage="net",
                        msg=type(msg).__name__,
                        peer=(f"{self.peer[0]}.{self.peer[1]}"
                              if self.peer else "?"),
                    )
                    if tracer is not None and trace is not None
                    and trace.sampled
                    else contextlib.nullcontext()
                )
                with span_cm:
                    self._seq += 1
                    segs = encode_message(
                        msg, self.messenger.entity, self._seq)
                    tag = frames.Tag.MESSAGE
                    if (
                        self.compressor is not None
                        and sum(len(s) for s in segs)
                        >= self.messenger.compress_min_size
                    ):
                        segs = [
                            self.compressor.compress(s) for s in segs
                        ]
                        tag = frames.Tag.MESSAGE_COMPRESSED
                    await frames.write_frame(
                        self.writer, tag, segs, crypto=self.crypto
                    )

    async def _run(self) -> None:
        try:
            # frames that arrived interleaved with the connect-side
            # negotiation (see Messenger.connect) are handled first,
            # in arrival order
            for tag, segs in getattr(self, "_preread", ()):  # noqa: B020
                await self._handle_frame(tag, segs)
            self._preread = ()
            while not self._closed:
                tag, segs = await frames.read_frame(
                    self.reader, crypto=self.crypto
                )
                await self._handle_frame(tag, segs)
        except (
            asyncio.IncompleteReadError, ConnectionError, OSError
        ) as e:
            if not self._closed:
                log.debug("%s: connection lost: %r", self.messenger.entity, e)
        except asyncio.CancelledError:
            pass  # cancelled by local close(); nothing to notify
        finally:
            await self.close(notify=True)

    async def _handle_frame(self, tag: int, segs: list) -> None:
        if getattr(self, "_needs_auth_proof", False):
            # first frame decrypted+authenticated: the peer
            # holds the session key; NOW adopt it for routing
            self._needs_auth_proof = False
            await self.messenger._register(self)
        if tag in (frames.Tag.MESSAGE,
                   frames.Tag.MESSAGE_COMPRESSED):
            if tag == frames.Tag.MESSAGE_COMPRESSED:
                if self.compressor is None:
                    raise frames.FrameError(
                        "compressed frame on an unnegotiated "
                        "connection")
                segs = [
                    self.compressor.decompress(s) for s in segs
                ]
            msg = decode_message(segs)
            msg.conn = self
            tracer = self.messenger.tracer
            if (tracer is not None and msg.trace is not None
                    and msg.trace.sampled):
                # a zero-length arrival marker: the collector pairs it
                # with the sender's msg_send span to bound wire time
                with tracer.span(
                    "msg_recv", ctx=msg.trace, stage="net",
                    msg=type(msg).__name__,
                ):
                    pass
            await self.messenger._dispatch(msg)
        elif tag == frames.Tag.COMPRESSION_REQUEST:
            # inbound negotiation (compression_onwire.cc server
            # role): pick the first of the peer's algorithms we
            # have; empty reply = stay uncompressed
            from ceph_tpu import compressor as _comp

            offered = segs[0].decode().split(",") if segs[0] else []
            if self.messenger.compress_mode == "none":
                offered = []  # 'none = never': refuse politely
            picked = next(
                (a for a in offered
                 if a != "none" and a in _comp.available()), "")
            await frames.write_frame(
                self.writer, frames.Tag.COMPRESSION_DONE,
                [picked.encode()], crypto=self.crypto,
            )
            if picked:
                self.compressor = _comp.create(picked)
        elif tag == frames.Tag.KEEPALIVE2:
            await frames.write_frame(
                self.writer, frames.Tag.KEEPALIVE2_ACK, segs,
                crypto=self.crypto,
            )
        elif tag == frames.Tag.CLOSE:
            raise ConnectionError("peer closed")

    async def close(self, notify: bool = False) -> None:
        if self._closed:
            return
        self._closed = True
        self.messenger._forget(self)
        try:
            self.writer.close()
        except Exception:
            pass
        try:
            task = self._reader_task
            if task is not None and task is not asyncio.current_task():
                task.cancel()
        except RuntimeError:
            return  # event loop already torn down
        if notify:
            await self.messenger._handle_reset(self)


class Messenger:
    """Owns the listener + connection table for one entity."""

    def __init__(
        self,
        entity: tuple[str, int],
        dispatcher: Callable[[Message], Awaitable[None]] | None = None,
        on_reset: Callable[[Connection], Awaitable[None]] | None = None,
        auth=None,
        compress_mode: str = "none",
        compress_algorithm: str = "zlib",
        compress_min_size: int = 1024,
        handshake_timeout: float = HANDSHAKE_TIMEOUT,
    ):
        self.entity = entity
        # ms_connection_ready_timeout role: raise on deployments whose
        # event loops stall for seconds (e.g. many daemons + XLA
        # compiles contending for few cores) or false timeouts cascade
        # into false failure reports
        self.handshake_timeout = handshake_timeout
        self.dispatcher = dispatcher
        self.on_reset = on_reset
        # AuthContext (ceph_tpu.msg.auth) => cephx handshake + SECURE
        # frames on every connection; None => legacy crc mode
        self.auth = auth
        # on-wire compression (reference compression_onwire.cc +
        # compressor_registry.cc): 'force' negotiates on every outbound
        # connection; inbound always answers requests with the best
        # mutually available algorithm
        self.compress_mode = compress_mode
        self.compress_algorithm = compress_algorithm
        self.compress_min_size = compress_min_size
        self._server: asyncio.base_events.Server | None = None
        self._conns: dict[tuple[str, int], Connection] = {}  # by entity
        # every live connection needs a strong root: asyncio's
        # StreamReaderProtocol only holds the reader WEAKLY (py3.8+), so
        # an un-referenced Connection/reader-task cycle would be
        # garbage-collected mid-session, silently closing the socket —
        # which the peer misreads as a daemon failure
        self._live: set[Connection] = set()
        self._connect_locks: dict[tuple[str, int], asyncio.Lock] = {}
        self.addr: tuple[str, int] | None = None
        # fault injection (reference ms_inject_socket_failures,
        # src/common/options/global.yaml.in:1242): every Nth outgoing
        # message tears the connection down instead of sending
        self.inject_socket_failures = 0
        self._inject_counter = 0
        # ms_inject_delay analogue: seconds of latency added to every
        # outgoing message (0 = off)
        self.inject_delay = 0.0
        # deterministic chaos shim (ceph_tpu/chaos/netem.py Netem);
        # None = transparent
        self.netem = None
        # the owning daemon's Tracer: messages carrying a SAMPLED
        # trace context get msg_send/msg_recv spans (stage=net), the
        # wire legs of the cluster-wide span tree; None = no messenger
        # spans (clients of the raw messenger)
        self.tracer = None

    async def _dispatch(self, msg: Message) -> None:
        if self.dispatcher is not None:
            await self.dispatcher(msg)

    async def _handle_reset(self, conn: Connection) -> None:
        if self.on_reset is not None:
            await self.on_reset(conn)

    def _forget(self, conn: Connection) -> None:
        self._live.discard(conn)
        if conn.peer is not None and self._conns.get(conn.peer) is conn:
            del self._conns[conn.peer]

    # -- server side ---------------------------------------------------

    async def bind(self, host: str = "127.0.0.1", port: int = 0) -> tuple[str, int]:
        self._server = await asyncio.start_server(self._accept, host, port)
        sock = self._server.sockets[0]
        self.addr = sock.getsockname()[:2]
        return self.addr

    async def _accept(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        conn = Connection(self, reader, writer)

        async def _handshake() -> None:
            await frames.send_banner(writer)
            await frames.recv_banner(reader)
            # HELLO: peer introduces itself first, then we do
            tag, segs = await frames.read_frame(reader)
            if tag != frames.Tag.HELLO:
                raise frames.FrameError(f"expected HELLO, got {tag}")
            dec = Decoder(segs[0])
            conn.peer = (dec.str_(), dec.i64())
            enc = Encoder()
            enc.str_(self.entity[0])
            enc.i64(self.entity[1])
            await frames.write_frame(writer, frames.Tag.HELLO, [enc.bytes()])
            if self.auth is not None:
                await self._auth_accept(conn)

        try:
            # a dialer that accepted TCP but never completes the
            # banner/HELLO must not pin this task forever (the
            # reference's ms_connection_ready_timeout role)
            await asyncio.wait_for(_handshake(), self.handshake_timeout)
        except (ConnectionError, asyncio.IncompleteReadError, OSError,
                PermissionError, asyncio.TimeoutError):
            writer.close()
            return
        if not getattr(conn, "_needs_auth_proof", False):
            await self._register(conn)
        self._live.add(conn)
        conn._reader_task = asyncio.ensure_future(conn._run())

    async def _register(self, conn: Connection) -> None:
        """Latest connection wins per peer for OUTBOUND routing, but the
        displaced one is NEVER closed here.

        Closing it would tear down a session whose in-flight sub-ops the
        far side misreads as a daemon failure (false MOSDFailure) — so
        cross-dials (A dials B while B dials A) simply leave both
        sockets open, replies always travel on the connection the
        request arrived on, and a displaced predecessor drains until its
        own EOF.  Routing to the NEWEST connection matters when a peer
        restarts and re-dials: the old socket may look healthy locally
        for minutes while every send into it would stall."""
        self._conns[conn.peer] = conn

    # -- client side ---------------------------------------------------

    async def connect_to(
        self, peer: tuple[str, int], host: str, port: int
    ) -> Connection:
        """Connection to a known peer, deduplicated: reuses a live
        session (either direction) and serializes concurrent dials so
        only one socket per peer exists."""
        conn = self._conns.get(peer)
        if conn is not None and not conn._closed:
            return conn
        lock = self._connect_locks.setdefault(peer, asyncio.Lock())
        async with lock:
            conn = self._conns.get(peer)
            if conn is not None and not conn._closed:
                return conn
            conn = await self.connect(host, port)
            if conn.peer != peer:
                await conn.close()
                raise ConnectionError(
                    f"dialed {host}:{port} expecting {peer}, got {conn.peer}"
                )
            return conn

    async def connect(self, host: str, port: int) -> Connection:
        """Dial, then handshake bounded by HANDSHAKE_TIMEOUT: a
        half-open peer (accepted TCP, wedged before HELLO) must surface
        as ConnectionError, not hang the dial — connect_to holds the
        per-peer dial lock, so an unbounded dial would wedge EVERY
        future message to that peer (found by the interleaving fuzzer,
        tests/test_interleave_fuzz.py).

        The TCP connect itself is deliberately NOT under the timeout:
        on the loopback deployments we run, connect() either completes
        or refuses immediately, and cancelling asyncio's sock_connect
        mid-flight leaves a stale selector registration that a reused
        fd number then trips over (the CPython _sock_write_done /
        _ensure_fd_no_transport race — also fuzzer-found)."""
        reader, writer = await asyncio.open_connection(host, port)
        try:
            return await asyncio.wait_for(
                self._handshake_out(reader, writer, host, port),
                self.handshake_timeout)
        except asyncio.TimeoutError:
            writer.close()
            raise ConnectionError(
                f"handshake with {host}:{port} timed out") from None
        except BaseException:
            # handshake failure: the socket must not leak (the
            # retrying callers re-dial every pass)
            writer.close()
            raise

    async def _handshake_out(self, reader, writer, host, port) -> Connection:
        conn = Connection(self, reader, writer)
        conn.peer_addr = (host, port)
        await frames.recv_banner(reader)
        await frames.send_banner(writer)
        enc = Encoder()
        enc.str_(self.entity[0])
        enc.i64(self.entity[1])
        await frames.write_frame(writer, frames.Tag.HELLO, [enc.bytes()])
        tag, segs = await frames.read_frame(reader)
        if tag != frames.Tag.HELLO:
            raise frames.FrameError(f"expected HELLO, got {tag}")
        dec = Decoder(segs[0])
        conn.peer = (dec.str_(), dec.i64())
        if self.auth is not None:
            await self._auth_connect(conn)
        if self.compress_mode == "force":
            # client-driven negotiation (COMPRESSION_REQUEST before the
            # reader loop starts; the acceptor answers from its loop)
            from ceph_tpu import compressor as _comp

            offer = ",".join(
                [self.compress_algorithm]
                + [a for a in _comp.available()
                   if a not in (self.compress_algorithm, "none")]
            )
            await frames.write_frame(
                writer, frames.Tag.COMPRESSION_REQUEST,
                [offer.encode()], crypto=conn.crypto,
            )
            # the acceptor registers us for routing before its reader
            # loop answers the request, so its own traffic can arrive
            # interleaved ahead of COMPRESSION_DONE: buffer it (the
            # reader task drains _preread first)
            preread = []
            while True:
                tag, segs = await frames.read_frame(
                    reader, crypto=conn.crypto)
                if tag == frames.Tag.COMPRESSION_DONE:
                    break
                preread.append((tag, segs))
                if len(preread) > 256:
                    raise frames.FrameError(
                        "no COMPRESSION_DONE in 256 frames")
            conn._preread = preread
            picked = segs[0].decode()
            if picked:
                conn.compressor = _comp.create(picked)
        await self._register(conn)
        self._live.add(conn)
        conn._reader_task = asyncio.ensure_future(conn._run())
        return conn

    # -- cephx handshake (see ceph_tpu/msg/auth.py) --------------------

    async def _auth_connect(self, conn: Connection) -> None:
        """Outbound side: present a ticket (cluster daemons self-mint;
        clients use the one granted by the mon) or, first mon contact,
        request a grant.  Ends with the connection in SECURE mode."""
        import os as _os

        from ceph_tpu.msg.auth import FrameCrypto

        a = self.auth
        nonce_c = _os.urandom(12)
        if a.service_secret is not None:
            ticket, session_key = a.self_ticket()
        elif a.ticket is not None:
            ticket, session_key = a.ticket, a.session_key
        else:
            ticket, session_key = None, None  # mon grant flow
        enc = Encoder()
        enc.str_(a.entity)
        enc.bool_(ticket is not None)
        enc.bytes_(ticket or b"")
        enc.bytes_(nonce_c)
        await frames.write_frame(
            conn.writer, frames.Tag.AUTH_REQUEST, [enc.bytes()]
        )
        tag, segs = await frames.read_frame(conn.reader)
        if tag != frames.Tag.AUTH_DONE:
            raise frames.FrameError(f"expected AUTH_DONE, got {tag}")
        dec = Decoder(segs[0])
        granted = dec.bool_()
        sealed = dec.bytes_()
        nonce_s = dec.bytes_()
        if granted:
            try:
                session_key, new_ticket = a.open_grant(sealed)
            except Exception as e:  # InvalidTag: not sealed for OUR key
                raise frames.FrameError(
                    f"grant not decryptable with our secret: {e}"
                )
            # keep the grant for subsequent OSD dials (client flow)
            a.ticket, a.session_key = new_ticket, session_key
        if session_key is None:
            raise frames.FrameError("auth refused")
        conn.crypto = FrameCrypto.from_session(
            session_key, nonce_c, nonce_s, connector=True
        )

    async def _auth_accept(self, conn: Connection) -> None:
        import os as _os

        from ceph_tpu.msg.auth import FrameCrypto, open_ticket

        a = self.auth
        tag, segs = await frames.read_frame(conn.reader)
        if tag != frames.Tag.AUTH_REQUEST:
            raise frames.FrameError(f"expected AUTH_REQUEST, got {tag}")
        dec = Decoder(segs[0])
        entity = dec.str_()
        has_ticket = dec.bool_()
        ticket = dec.bytes_()
        nonce_c = dec.bytes_()
        nonce_s = _os.urandom(12)
        if has_ticket:
            if a.service_secret is None:
                raise PermissionError("cannot validate tickets")
            try:
                t_entity, session_key, peer_caps = open_ticket(
                    a.service_secret, ticket)
            except PermissionError:
                raise
            except Exception as e:  # InvalidTag / malformed blob
                raise PermissionError(f"bad ticket: {type(e).__name__}")
            if t_entity != entity:
                raise PermissionError(
                    f"ticket entity {t_entity!r} != claimed {entity!r}"
                )
            # authorization rides the ticket (AuthCapsInfo): op
            # admission reads it off the connection
            conn.peer_caps = peer_caps
            enc = Encoder()
            enc.bool_(False)
            enc.bytes_(b"")
            enc.bytes_(nonce_s)
            await frames.write_frame(
                conn.writer, frames.Tag.AUTH_DONE, [enc.bytes()]
            )
        else:
            res = a.grant(entity)
            if res is None:
                raise PermissionError(f"unknown entity {entity!r}")
            sealed, session_key, _ticket, peer_caps = res
            conn.peer_caps = peer_caps
            enc = Encoder()
            enc.bool_(True)
            enc.bytes_(sealed)
            enc.bytes_(nonce_s)
            await frames.write_frame(
                conn.writer, frames.Tag.AUTH_DONE, [enc.bytes()]
            )
        # the claimed entity must match the HELLO identity
        kind, _, num = entity.partition(".")
        try:
            claimed = (kind, int(num))
        except ValueError:
            raise PermissionError(f"malformed entity {entity!r}")
        if conn.peer != claimed:
            raise PermissionError(
                f"auth entity {entity!r} != hello identity {conn.peer}"
            )
        conn.crypto = FrameCrypto.from_session(
            session_key, nonce_c, nonce_s, connector=False
        )
        # identity is CLAIMED until the peer proves possession of the
        # session key by sending a frame that authenticates: outbound
        # routing must not be hijackable by a keyless impostor
        conn._needs_auth_proof = True

    def get_connection(self, peer: tuple[str, int]) -> Connection | None:
        return self._conns.get(peer)

    async def shutdown(self) -> None:
        if self._server is not None:
            self._server.close()
        # close connections FIRST: in py3.12 Server.wait_closed() also
        # waits for accepted transports, which our reader tasks hold open
        for conn in list(self._conns.values()) + list(self._live):
            await conn.close()
        self._conns.clear()
        self._live.clear()
        await asyncio.sleep(0)  # let cancelled reader tasks unwind
        if self._server is not None:
            try:
                await asyncio.wait_for(self._server.wait_closed(), 2)
            except asyncio.TimeoutError:
                pass
