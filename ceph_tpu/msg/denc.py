"""Versioned wire encoding — the denc/encoding.h twin.

The reference encodes every wire/disk struct with ENCODE_START(v,
compat, bl) ... ENCODE_FINISH(bl) (src/include/encoding.h): a leading
(version, compat_version, length) header per struct so old decoders can
skip unknown tails and new decoders can reject too-old peers.  This
module is the same contract over little-endian struct packing:

    enc = Encoder()
    with enc.versioned(2, 1):
        enc.u32(x); enc.str_(name)
    wire = enc.bytes()

    dec = Decoder(wire)
    with dec.versioned(compat=1) as v:
        x = dec.u32()
        name = dec.str_()
        # fields added in later versions guarded by `v`
    # decoder skips any unread tail of the struct (DECODE_FINISH)
"""

from __future__ import annotations

import contextlib
import struct


class EncodingError(Exception):
    pass


class Encoder:
    def __init__(self) -> None:
        self._buf = bytearray()

    # scalars (little-endian, like ceph_le types)
    def u8(self, v: int) -> None:
        self._buf += struct.pack("<B", v & 0xFF)

    def u16(self, v: int) -> None:
        self._buf += struct.pack("<H", v & 0xFFFF)

    def u32(self, v: int) -> None:
        self._buf += struct.pack("<I", v & 0xFFFFFFFF)

    def u64(self, v: int) -> None:
        self._buf += struct.pack("<Q", v & 0xFFFFFFFFFFFFFFFF)

    def i32(self, v: int) -> None:
        self._buf += struct.pack("<i", v)

    def i64(self, v: int) -> None:
        self._buf += struct.pack("<q", v)

    def bool_(self, v: bool) -> None:
        self.u8(1 if v else 0)

    def bytes_(self, b: bytes) -> None:
        self.u32(len(b))
        self._buf += b

    def str_(self, s: str) -> None:
        self.bytes_(s.encode("utf-8"))

    def raw(self, b: bytes) -> None:
        self._buf += b

    @contextlib.contextmanager
    def versioned(self, version: int, compat: int):
        """ENCODE_START/ENCODE_FINISH: u8 v, u8 compat, u32 length."""
        self.u8(version)
        self.u8(compat)
        pos = len(self._buf)
        self.u32(0)  # placeholder
        yield
        length = len(self._buf) - pos - 4
        self._buf[pos : pos + 4] = struct.pack("<I", length)

    def bytes(self) -> bytes:
        return bytes(self._buf)

    def __len__(self) -> int:
        return len(self._buf)


class Decoder:
    def __init__(self, data: bytes | bytearray | memoryview, off: int = 0):
        self._d = memoryview(data)
        self._off = off

    def _take(self, n: int) -> memoryview:
        if self._off + n > len(self._d):
            raise EncodingError(
                f"buffer underrun: need {n} at {self._off}/{len(self._d)}"
            )
        v = self._d[self._off : self._off + n]
        self._off += n
        return v

    def u8(self) -> int:
        return self._take(1)[0]

    def u16(self) -> int:
        return struct.unpack("<H", self._take(2))[0]

    def u32(self) -> int:
        return struct.unpack("<I", self._take(4))[0]

    def u64(self) -> int:
        return struct.unpack("<Q", self._take(8))[0]

    def i32(self) -> int:
        return struct.unpack("<i", self._take(4))[0]

    def i64(self) -> int:
        return struct.unpack("<q", self._take(8))[0]

    def bool_(self) -> bool:
        return bool(self.u8())

    def bytes_(self) -> bytes:
        n = self.u32()
        return bytes(self._take(n))

    def str_(self) -> str:
        return self.bytes_().decode("utf-8")

    def raw(self, n: int) -> bytes:
        return bytes(self._take(n))

    def remaining(self) -> int:
        return len(self._d) - self._off

    @contextlib.contextmanager
    def versioned(self, compat: int = 1):
        """DECODE_START/DECODE_FINISH: yields the peer's struct version;
        skips the unread tail, errors if the struct's compat is newer
        than what we understand."""
        v = self.u8()
        struct_compat = self.u8()
        length = self.u32()
        end = self._off + length
        if end > len(self._d):
            raise EncodingError("versioned struct overruns buffer")
        if struct_compat > compat:
            # peer says decoders older than struct_compat can't parse it
            raise EncodingError(
                f"struct compat {struct_compat} > supported {compat}"
            )
        yield v
        if self._off > end:
            raise EncodingError("versioned struct over-read")
        self._off = end  # skip what we did not understand
