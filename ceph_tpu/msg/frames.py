"""msgr2-style framed wire protocol.

Behavioral twin of the reference's protocol v2 framing
(src/msg/async/frames_v2.h:40-143): a banner exchange, then segmented
frames — preamble (tag, segment count, segment lengths, preamble crc)
followed by the segments and an epilogue carrying per-segment crc32c.
crc mode matches the reference's rev1 epilogue semantics.

SECURE mode (the reference's crypto_onwire.cc): once a connection's
auth handshake establishes a session key, ``write_frame``/``read_frame``
take a :class:`~ceph_tpu.msg.auth.FrameCrypto` and every frame ships as
``u32 length || AES-GCM(tag || nseg || seg_lens || segments)`` with
per-direction keys and counter nonces — confidentiality + integrity
replace the crc epilogue, and any tamper or replay fails the AEAD tag.

All crcs use the native crc32c runtime (ceph_tpu/native), seeded -1
like the reference frame crcs.
"""

from __future__ import annotations

import asyncio
import struct

from ceph_tpu.native import crc32c

BANNER = b"ceph_tpu msgr2.0\n"
MAX_SEGMENTS = 4
MAX_FRAME_LEN = 256 * 1024 * 1024


class Tag:
    """frames_v2.h:40-54 (the subset the mini-cluster speaks)."""

    HELLO = 1
    AUTH_REQUEST = 2
    AUTH_DONE = 3
    MESSAGE = 17
    KEEPALIVE2 = 14
    KEEPALIVE2_ACK = 15
    ACK = 16
    CLOSE = 18
    # on-wire compression negotiation (frames_v2.h:60-61; the reference
    # marks compressed frames via a preamble flag bit — here a distinct
    # tag carries the same information)
    COMPRESSION_REQUEST = 21
    COMPRESSION_DONE = 22
    MESSAGE_COMPRESSED = 23


class FrameError(ConnectionError):
    pass


async def send_banner(writer: asyncio.StreamWriter, features: int = 1) -> None:
    writer.write(BANNER + struct.pack("<Q", features))
    await writer.drain()


async def recv_banner(reader: asyncio.StreamReader) -> int:
    got = await reader.readexactly(len(BANNER))
    if got != BANNER:
        raise FrameError(f"bad banner {got!r}")
    (features,) = struct.unpack("<Q", await reader.readexactly(8))
    return features


def _preamble(tag: int, seg_lens: list[int]) -> bytes:
    head = struct.pack(
        "<BB4I", tag, len(seg_lens),
        *(seg_lens + [0] * (MAX_SEGMENTS - len(seg_lens))),
    )
    return head + struct.pack("<I", crc32c(head))


async def write_frame(
    writer: asyncio.StreamWriter, tag: int, segments: list[bytes],
    crypto=None,
) -> None:
    assert 0 < len(segments) <= MAX_SEGMENTS
    segs = [bytes(s) for s in segments]
    if crypto is not None:
        plain = struct.pack(
            "<BB4I", tag, len(segs),
            *([len(s) for s in segs] + [0] * (MAX_SEGMENTS - len(segs))),
        ) + b"".join(segs)
        ct = crypto.encrypt(plain)
        writer.write(struct.pack("<I", len(ct)) + ct)
        await writer.drain()
        return
    writer.write(_preamble(tag, [len(s) for s in segs]))
    for s in segs:
        writer.write(s)
    # epilogue: one crc32c per present segment (frames_v2.h:124-143)
    writer.write(struct.pack(f"<{len(segs)}I", *(crc32c(s) for s in segs)))
    await writer.drain()


async def read_frame(
    reader: asyncio.StreamReader, crypto=None,
) -> tuple[int, list[bytes]]:
    if crypto is not None:
        (ln,) = struct.unpack("<I", await reader.readexactly(4))
        if ln > MAX_FRAME_LEN:
            raise FrameError("secure frame too large")
        try:
            plain = crypto.decrypt(await reader.readexactly(ln))
        except Exception as e:  # InvalidTag and friends
            raise FrameError(f"secure frame authentication failed: {e}")
        tag, nseg = plain[0], plain[1]
        if not 0 < nseg <= MAX_SEGMENTS:
            raise FrameError(f"bad segment count {nseg}")
        seg_lens = struct.unpack_from("<4I", plain, 2)[:nseg]
        off = 2 + 16
        segs = []
        for n in seg_lens:
            segs.append(plain[off : off + n])
            off += n
        if off != len(plain):
            raise FrameError("secure frame length mismatch")
        return tag, segs
    head = await reader.readexactly(18)
    (want_crc,) = struct.unpack("<I", await reader.readexactly(4))
    if crc32c(head) != want_crc:
        raise FrameError("preamble crc mismatch")
    tag, nseg = head[0], head[1]
    if not 0 < nseg <= MAX_SEGMENTS:
        raise FrameError(f"bad segment count {nseg}")
    seg_lens = struct.unpack("<4I", head[2:])[:nseg]
    if sum(seg_lens) > MAX_FRAME_LEN:
        raise FrameError("frame too large")
    segs = [await reader.readexactly(n) for n in seg_lens]
    crcs = struct.unpack(f"<{nseg}I", await reader.readexactly(4 * nseg))
    for s, c in zip(segs, crcs):
        if crc32c(s) != c:
            raise FrameError("segment crc mismatch")
    return tag, list(segs)
