"""Typed wire messages — the src/messages/ analogue.

One class per message, mirroring the reference's protocol surface for
the mini-cluster slice: mon boot/beacon/failure/subscription + command
(MOSDBoot, MOSDBeacon, MOSDFailure, MMonSubscribe, MMonCommand,
src/messages/MOSDBoot.h etc.), map distribution (MOSDMap), the client
op envelope (MOSDOp/MOSDOpReply), EC shard sub-ops
(MOSDECSubOpWrite/Read + replies, src/messages/MOSDECSubOp*.h), the
replication sub-op (MOSDRepOp), and recovery push (MOSDPGPush).

Wire type ids follow the reference's message numbers where one exists
(src/include/msgr.h / messages).
"""

from __future__ import annotations

from ceph_tpu.msg.denc import Decoder, Encoder
from ceph_tpu.msg.messenger import Message
from ceph_tpu.osd.types import pg_t


def _enc_pg(enc: Encoder, pg: pg_t, shard: int = -1) -> None:
    enc.i64(pg.pool)
    enc.u32(pg.ps)
    enc.i32(shard)


def _dec_pg(dec: Decoder) -> tuple[pg_t, int]:
    pool = dec.i64()
    ps = dec.u32()
    return pg_t(pool, ps), dec.i32()


def _enc_map_str_bytes(enc: Encoder, d: dict[str, bytes]) -> None:
    enc.u32(len(d))
    for k in sorted(d):
        enc.str_(k)
        enc.bytes_(d[k])


def _dec_map_str_bytes(dec: Decoder) -> dict[str, bytes]:
    return {dec.str_(): dec.bytes_() for _ in range(dec.u32())}


# -- mon <-> osd / client ---------------------------------------------------

class MOSDBoot(Message):
    """osd -> mon: I'm up at this address (src/messages/MOSDBoot.h)."""

    TYPE = 71

    def __init__(
        self, osd: int = 0, host: str = "", port: int = 0,
        weight: int = 0x10000, incarnation: int = 0,
    ):
        self.osd, self.host, self.port, self.weight = osd, host, port, weight
        # fresh per daemon start (the reference's boot_epoch role):
        # distinguishes a genuine fast restart from a paxos replay of
        # the same boot command
        self.incarnation = incarnation

    def encode_payload(self, enc):
        enc.i32(self.osd)
        enc.str_(self.host)
        enc.u32(self.port)
        enc.u32(self.weight)
        enc.u64(self.incarnation)

    @classmethod
    def decode_payload(cls, dec):
        return cls(dec.i32(), dec.str_(), dec.u32(), dec.u32(), dec.u64())


class MOSDBeacon(Message):
    """osd -> mon liveness beacon (src/messages/MOSDBeacon.h), carrying
    per-PG stats for the PGs this OSD leads — the MPGStats/DaemonServer
    reporting plane (reference src/messages/MPGStats.h, src/mgr/
    DaemonServer.cc) folded onto the beacon cadence."""

    TYPE = 97

    def __init__(self, osd: int = 0, epoch: int = 0, pg_stats: bytes = b"",
                 statfs: bytes = b""):
        self.osd, self.epoch = osd, epoch
        self.pg_stats = pg_stats  # json: {"pool.ps": {state, objects}}
        # json {"total", "used", "available"} from ObjectStore.statfs —
        # the osd_stat_t usage block of the reference's MPGStats
        self.statfs = statfs

    def encode_payload(self, enc):
        enc.i32(self.osd)
        enc.u32(self.epoch)
        enc.bytes_(self.pg_stats)
        enc.bytes_(self.statfs)

    @classmethod
    def decode_payload(cls, dec):
        return cls(dec.i32(), dec.u32(), dec.bytes_(), dec.bytes_())


class MOSDFailure(Message):
    """osd -> mon: peer looks dead (src/messages/MOSDFailure.h)."""

    TYPE = 72

    def __init__(self, reporter: int = 0, failed: int = 0, epoch: int = 0):
        self.reporter, self.failed, self.epoch = reporter, failed, epoch

    def encode_payload(self, enc):
        enc.i32(self.reporter)
        enc.i32(self.failed)
        enc.u32(self.epoch)

    @classmethod
    def decode_payload(cls, dec):
        return cls(dec.i32(), dec.i32(), dec.u32())


class MMonSubscribe(Message):
    """client/osd -> mon: send me maps from this epoch on
    (src/messages/MMonSubscribe.h)."""

    TYPE = 15

    def __init__(self, start_epoch: int = 0):
        self.start_epoch = start_epoch

    def encode_payload(self, enc):
        enc.u32(self.start_epoch)

    @classmethod
    def decode_payload(cls, dec):
        return cls(dec.u32())


class MOSDMap(Message):
    """mon -> *: encoded maps by epoch — full and/or incremental
    (src/messages/MOSDMap.h carries both maps and incremental_maps)."""

    TYPE = 41

    def __init__(
        self,
        maps: dict[int, bytes] | None = None,
        incs: dict[int, bytes] | None = None,
    ):
        self.maps = maps or {}
        self.incs = incs or {}

    def encode_payload(self, enc):
        enc.u32(len(self.maps))
        for epoch in sorted(self.maps):
            enc.u32(epoch)
            enc.bytes_(self.maps[epoch])
        enc.u32(len(self.incs))
        for epoch in sorted(self.incs):
            enc.u32(epoch)
            enc.bytes_(self.incs[epoch])

    @classmethod
    def decode_payload(cls, dec):
        return cls(
            {dec.u32(): dec.bytes_() for _ in range(dec.u32())},
            {dec.u32(): dec.bytes_() for _ in range(dec.u32())},
        )


class MConfig(Message):
    """mon -> daemons/clients: the centralized config database
    (reference src/messages/MConfig.h, ConfigMonitor distribution).
    Carries the full {section: {option: value}} map; receivers apply
    the sections that address them at the 'mon' config source."""

    TYPE = 62

    def __init__(self, sections: dict[str, dict[str, str]] | None = None):
        self.sections = sections or {}

    def encode_payload(self, enc):
        enc.u32(len(self.sections))
        for who in sorted(self.sections):
            enc.str_(who)
            kv = self.sections[who]
            enc.u32(len(kv))
            for k in sorted(kv):
                enc.str_(k)
                enc.str_(kv[k])

    @classmethod
    def decode_payload(cls, dec):
        return cls({
            dec.str_(): {
                dec.str_(): dec.str_() for _ in range(dec.u32())
            }
            for _ in range(dec.u32())
        })


class MMonCommand(Message):
    """CLI/admin command as json-ish kv (src/messages/MMonCommand.h)."""

    TYPE = 50

    def __init__(self, tid: int = 0, cmd: dict[str, str] | None = None):
        self.tid = tid
        self.cmd = cmd or {}

    def encode_payload(self, enc):
        enc.u64(self.tid)
        enc.u32(len(self.cmd))
        for k in sorted(self.cmd):
            enc.str_(k)
            enc.str_(self.cmd[k])

    @classmethod
    def decode_payload(cls, dec):
        tid = dec.u64()
        return cls(tid, {dec.str_(): dec.str_() for _ in range(dec.u32())})


class MMonCommandAck(Message):
    TYPE = 51

    def __init__(self, tid: int = 0, code: int = 0, rs: str = "", data: bytes = b""):
        self.tid, self.code, self.rs, self.data = tid, code, rs, data

    def encode_payload(self, enc):
        enc.u64(self.tid)
        enc.i32(self.code)
        enc.str_(self.rs)
        enc.bytes_(self.data)

    @classmethod
    def decode_payload(cls, dec):
        return cls(dec.u64(), dec.i32(), dec.str_(), dec.bytes_())


# -- client ops -------------------------------------------------------------

# Read class
OP_READ = 1
OP_STAT = 4
OP_GETXATTR = 11
OP_GETXATTRS = 13
OP_OMAP_GETKEYS = 15
OP_OMAP_GETVALS = 16
OP_OMAP_GETVALSBYKEYS = 19
# Write class
OP_WRITE_FULL = 2
OP_DELETE = 3
OP_WRITE = 5
OP_APPEND = 6
OP_ZERO = 7
OP_TRUNCATE = 8
OP_CREATE = 9        # exclusive create: EEXIST when the object exists
OP_SETXATTR = 10
OP_RMXATTR = 12
OP_OMAP_SETKEYS = 14
OP_OMAP_RMKEYS = 17
OP_OMAP_CLEAR = 18
# Watch/notify (PrimaryLogPG::do_osd_ops CEPH_OSD_OP_WATCH/NOTIFY)
OP_WATCH = 20
OP_UNWATCH = 21
OP_NOTIFY = 22
# Object-class call (cls dispatch, src/objclass/)
OP_CALL = 23

OP_ROLLBACK = 24     # CEPH_OSD_OP_ROLLBACK: restore head from a snap
OP_LIST_SNAPS = 25   # CEPH_OSD_OP_LIST_SNAPS: dump the object's SnapSet
# internal effect op (primary -> replica/shard): clone head -> clone
# object before applying the rest of the vector (make_writeable COW);
# off = clone id, data = json list of covered snaps
OP_SNAP_CLONE = 26

# cache tiering (CEPH_OSD_OP_CACHE_FLUSH/CACHE_EVICT/COPY_FROM,
# src/osd/PrimaryLogPG.cc cache ops): flush writes a dirty cache
# object back to the base pool; evict drops a clean one; copy-from
# copies "srcpool:srcoid" (OSDOp.name) into the target object
OP_CACHE_FLUSH = 27
OP_CACHE_EVICT = 28
OP_COPY_FROM = 29

WRITE_OPS = frozenset({
    OP_WRITE_FULL, OP_DELETE, OP_WRITE, OP_APPEND, OP_ZERO, OP_TRUNCATE,
    OP_CREATE, OP_SETXATTR, OP_RMXATTR, OP_OMAP_SETKEYS, OP_OMAP_RMKEYS,
    OP_OMAP_CLEAR, OP_ROLLBACK, OP_SNAP_CLONE,
    OP_CACHE_FLUSH, OP_CACHE_EVICT, OP_COPY_FROM,
})


class OSDOp:
    """One op of an MOSDOp vector (reference OSDOp, src/osd/osd_types.h:
    op code + extent + name + indata; compound client operations are a
    vector of these applied atomically, PrimaryLogPG::do_osd_ops)."""

    __slots__ = ("op", "off", "length", "name", "data", "kv", "keys")

    def __init__(
        self, op: int, off: int = 0, length: int = 0, name: str = "",
        data: bytes = b"", kv: dict[str, bytes] | None = None,
        keys: list[str] | None = None,
    ):
        self.op, self.off, self.length, self.name = op, off, length, name
        self.data = data
        self.kv = kv or {}
        self.keys = keys or []

    def __repr__(self):
        return (f"OSDOp(op={self.op}, off={self.off}, len={self.length}, "
                f"name={self.name!r}, data={len(self.data)}B)")

    def encode(self, enc: Encoder) -> None:
        enc.u8(self.op)
        enc.u64(self.off)
        enc.u64(self.length)
        enc.str_(self.name)
        enc.bytes_(self.data)
        _enc_map_str_bytes(enc, self.kv)
        enc.u32(len(self.keys))
        for k in self.keys:
            enc.str_(k)

    @classmethod
    def decode(cls, dec: Decoder) -> "OSDOp":
        return cls(
            dec.u8(), dec.u64(), dec.u64(), dec.str_(), dec.bytes_(),
            _dec_map_str_bytes(dec), [dec.str_() for _ in range(dec.u32())],
        )

    def is_write(self) -> bool:
        if self.op == OP_CALL:
            from ceph_tpu.cls import method_is_write

            c, _, m = self.name.partition(".")
            return method_is_write(c, m)
        return self.op in WRITE_OPS


class MOSDOp(Message):
    """client -> primary OSD (src/messages/MOSDOp.h): a vector of ops
    on one object, applied atomically — the reference's compound-op
    envelope dispatched by PrimaryLogPG::do_osd_ops
    (PrimaryLogPG.cc:5979)."""

    TYPE = 42

    def __init__(
        self, tid: int = 0, pool: int = 0, oid: str = "",
        op: int | None = None, off: int = 0, length: int = 0,
        data: bytes = b"", epoch: int = 0,
        ops: list[OSDOp] | None = None, reqid: str = "",
        snap_seq: int = 0, snaps: list[int] | None = None,
        snapid: int | None = None, qos_class: str = "",
    ):
        self.tid, self.pool, self.oid = tid, pool, oid
        self.epoch = epoch
        # dmclock tenant tag: the OSD's mClock gate admits the op
        # under this client class ('' = the built-in client class) —
        # how multi-tenant QoS differentiation reaches the scheduler
        self.qos_class = qos_class
        # write SnapContext (MOSDOp snapc: seq + existing snaps,
        # newest first) and read snap id (CEPH_NOSNAP = head)
        from ceph_tpu.osd.snaps import NOSNAP

        self.snap_seq = snap_seq
        self.snaps = snaps or []
        self.snapid = NOSNAP if snapid is None else snapid
        # stable across client resends (osd_reqid_t): the OSD's pg-log
        # dup detection answers a retried non-idempotent op instead of
        # re-applying it
        self.reqid = reqid
        if ops is not None:
            self.ops = ops
        elif op is not None:  # single-op convenience form
            self.ops = [OSDOp(op, off=off, length=length, data=data)]
        else:
            self.ops = []

    @property
    def op(self) -> int:
        """First op code (single-op convenience accessor)."""
        return self.ops[0].op if self.ops else 0

    @property
    def data(self) -> bytes:
        return self.ops[0].data if self.ops else b""

    def is_write(self) -> bool:
        return any(o.is_write() for o in self.ops)

    def encode_payload(self, enc):
        enc.u64(self.tid)
        enc.i64(self.pool)
        enc.str_(self.oid)
        enc.u32(len(self.ops))
        for o in self.ops:
            o.encode(enc)
        enc.u32(self.epoch)
        enc.str_(self.reqid)
        enc.u64(self.snap_seq)
        enc.u32(len(self.snaps))
        for s in self.snaps:
            enc.u64(s)
        enc.u64(self.snapid)
        enc.str_(self.qos_class)

    @classmethod
    def decode_payload(cls, dec):
        tid, pool, oid = dec.u64(), dec.i64(), dec.str_()
        ops = [OSDOp.decode(dec) for _ in range(dec.u32())]
        msg = cls(tid, pool, oid, epoch=dec.u32(), ops=ops, reqid=dec.str_())
        msg.snap_seq = dec.u64()
        msg.snaps = [dec.u64() for _ in range(dec.u32())]
        msg.snapid = dec.u64()
        msg.qos_class = dec.str_()
        return msg


class MOSDOpReply(Message):
    """Per-op results mirror the reference's ops-vector echo with
    outdata; ``result``/``data``/``size`` summarize op 0 for the
    single-op common case."""

    TYPE = 43

    def __init__(
        self, tid: int = 0, result: int = 0, data: bytes = b"",
        epoch: int = 0, size: int = 0,
        outs: list[tuple[int, bytes, dict[str, bytes]]] | None = None,
    ):
        self.tid, self.result, self.data = tid, result, data
        self.epoch, self.size = epoch, size
        # one (result, outdata, out_kv) per request op
        self.outs = outs or []

    def encode_payload(self, enc):
        enc.u64(self.tid)
        enc.i32(self.result)
        enc.bytes_(self.data)
        enc.u32(self.epoch)
        enc.u64(self.size)
        enc.u32(len(self.outs))
        for r, d, kv in self.outs:
            enc.i32(r)
            enc.bytes_(d)
            _enc_map_str_bytes(enc, kv)

    @classmethod
    def decode_payload(cls, dec):
        tid, result, data, epoch, size = (
            dec.u64(), dec.i32(), dec.bytes_(), dec.u32(), dec.u64()
        )
        outs = [
            (dec.i32(), dec.bytes_(), _dec_map_str_bytes(dec))
            for _ in range(dec.u32())
        ]
        return cls(tid, result, data, epoch, size, outs)


# -- EC sub ops (src/messages/MOSDECSubOpWrite.h / MOSDECSubOpRead.h) -------

class MOSDECSubOpWrite(Message):
    """primary -> shard OSD: apply this shard chunk write."""

    TYPE = 108

    def __init__(
        self, tid: int = 0, pg: pg_t = pg_t(0, 0), shard: int = 0,
        from_osd: int = 0, oid: str = "", off: int = 0,
        data: bytes = b"", attrs: dict[str, bytes] | None = None,
        epoch: int = 0, truncate: int = -1, delete: bool = False,
        version=None, guard=None, rmattrs: list[str] | None = None,
        reqid: str = "", clone_snap: int = 0, clone_snaps: bytes = b"",
        prev_version=None, guarded: bool = False,
    ):
        from ceph_tpu.osd.pglog import ZERO

        self.tid, self.pg, self.shard, self.from_osd = tid, pg, shard, from_osd
        self.oid, self.off, self.data = oid, off, data
        # COW directive: before applying the payload, clone the local
        # head shard to (oid, snap=clone_snap); clone_snaps is the json
        # covered-snaps list stored on the clone (make_writeable twin)
        self.clone_snap = clone_snap
        self.clone_snaps = clone_snaps
        # stale-shard write guard: when ``guarded``, the shard applies
        # only if its local object version equals ``prev_version`` (the
        # primary's base) — a shard that missed earlier writes must be
        # recovered first, not stamped current by a partial write (the
        # reference blocks writes on missing objects until recovery,
        # PrimaryLogPG::is_missing_object wait)
        self.prev_version = prev_version if prev_version is not None else ZERO
        self.guarded = guarded
        self.attrs = attrs or {}
        self.epoch, self.truncate, self.delete = epoch, truncate, delete
        # attr names to remove (rmxattr; e.g. hinfo drop on RMW)
        self.rmattrs = rmattrs or []
        # client reqid carried into the shard's pg-log entry
        self.reqid = reqid
        from ceph_tpu.osd.pglog import ZERO

        # the pg-log eversion this write commits at (ZERO = unlogged,
        # e.g. recovery pushes)
        self.version = version if version is not None else ZERO
        # recovery delete-replay guard: skip if the local object is
        # newer than this (ZERO = unconditional)
        self.guard = guard if guard is not None else ZERO

    def encode_payload(self, enc):
        enc.u64(self.tid)
        _enc_pg(enc, self.pg, self.shard)
        enc.i32(self.from_osd)
        enc.str_(self.oid)
        enc.u64(self.off)
        enc.bytes_(self.data)
        _enc_map_str_bytes(enc, self.attrs)
        enc.u32(self.epoch)
        enc.i64(self.truncate)
        enc.bool_(self.delete)
        _enc_ev(enc, self.version)
        _enc_ev(enc, self.guard)
        enc.u32(len(self.rmattrs))
        for n in self.rmattrs:
            enc.str_(n)
        enc.str_(self.reqid)
        enc.u64(self.clone_snap)
        enc.bytes_(self.clone_snaps)
        _enc_ev(enc, self.prev_version)
        enc.bool_(self.guarded)

    @classmethod
    def decode_payload(cls, dec):
        tid = dec.u64()
        pg, shard = _dec_pg(dec)
        msg = cls(
            tid, pg, shard, dec.i32(), dec.str_(), dec.u64(),
            dec.bytes_(), _dec_map_str_bytes(dec), dec.u32(),
            dec.i64(), dec.bool_(), _dec_ev(dec), _dec_ev(dec),
        )
        msg.rmattrs = [dec.str_() for _ in range(dec.u32())]
        msg.reqid = dec.str_()
        msg.clone_snap = dec.u64()
        msg.clone_snaps = dec.bytes_()
        msg.prev_version = _dec_ev(dec)
        msg.guarded = dec.bool_()
        return msg


class MOSDECSubOpWriteReply(Message):
    TYPE = 109

    def __init__(
        self, tid: int = 0, pg: pg_t = pg_t(0, 0), shard: int = 0,
        from_osd: int = 0, result: int = 0, epoch: int = 0,
        floored: bool = False,
    ):
        self.tid, self.pg, self.shard = tid, pg, shard
        self.from_osd, self.result, self.epoch = from_osd, result, epoch
        # this apply pinned the replica's log-contiguity floor (it
        # rejoined mid-traffic and skipped a version window): the
        # primary must queue a recovery pass NOW — with no later map
        # change there is no other trigger, and the member's earlier
        # objects stay stale until scrub finds them
        self.floored = floored

    def encode_payload(self, enc):
        enc.u64(self.tid)
        _enc_pg(enc, self.pg, self.shard)
        enc.i32(self.from_osd)
        enc.i32(self.result)
        enc.u32(self.epoch)
        enc.bool_(self.floored)

    @classmethod
    def decode_payload(cls, dec):
        tid = dec.u64()
        pg, shard = _dec_pg(dec)
        return cls(tid, pg, shard, dec.i32(), dec.i32(), dec.u32(),
                   dec.bool_())


class MOSDECSubOpRead(Message):
    """primary -> shard OSD: read chunk extents (+ attrs on demand).

    ``extents`` (list of (off, len) byte runs) is how CLAY sub-chunk
    repair reads ride the wire: the reply carries the concatenation of
    the runs, so a regenerating repair moves only sub_chunk_no/q of
    each helper chunk (reference ECCommon.cc:262-299 passing
    minimum_to_decode's runs down to shard reads)."""

    TYPE = 110

    def __init__(
        self, tid: int = 0, pg: pg_t = pg_t(0, 0), shard: int = 0,
        from_osd: int = 0, oid: str = "", off: int = 0, length: int = 0,
        want_attrs: bool = False, epoch: int = 0,
        extents: list[tuple[int, int]] | None = None,
        snap: int | None = None,
    ):
        from ceph_tpu.osd.snaps import NOSNAP

        self.tid, self.pg, self.shard, self.from_osd = tid, pg, shard, from_osd
        self.oid, self.off, self.length = oid, off, length
        self.want_attrs, self.epoch = want_attrs, epoch
        self.extents = extents or []
        # which snap object of oid to read (NOSNAP = head shard)
        self.snap = NOSNAP if snap is None else snap

    def encode_payload(self, enc):
        enc.u64(self.tid)
        _enc_pg(enc, self.pg, self.shard)
        enc.i32(self.from_osd)
        enc.str_(self.oid)
        enc.u64(self.off)
        enc.u64(self.length)
        enc.bool_(self.want_attrs)
        enc.u32(self.epoch)
        enc.u32(len(self.extents))
        for o, ln in self.extents:
            enc.u64(o)
            enc.u64(ln)
        enc.u64(self.snap)

    @classmethod
    def decode_payload(cls, dec):
        tid = dec.u64()
        pg, shard = _dec_pg(dec)
        msg = cls(
            tid, pg, shard, dec.i32(), dec.str_(), dec.u64(), dec.u64(),
            dec.bool_(), dec.u32(),
        )
        msg.extents = [
            (dec.u64(), dec.u64()) for _ in range(dec.u32())
        ]
        msg.snap = dec.u64()
        return msg


class MOSDECSubOpReadReply(Message):
    TYPE = 111

    def __init__(
        self, tid: int = 0, pg: pg_t = pg_t(0, 0), shard: int = 0,
        from_osd: int = 0, result: int = 0, data: bytes = b"",
        attrs: dict[str, bytes] | None = None, epoch: int = 0,
    ):
        self.tid, self.pg, self.shard = tid, pg, shard
        self.from_osd, self.result, self.data = from_osd, result, data
        self.attrs = attrs or {}
        self.epoch = epoch

    def encode_payload(self, enc):
        enc.u64(self.tid)
        _enc_pg(enc, self.pg, self.shard)
        enc.i32(self.from_osd)
        enc.i32(self.result)
        enc.bytes_(self.data)
        _enc_map_str_bytes(enc, self.attrs)
        enc.u32(self.epoch)

    @classmethod
    def decode_payload(cls, dec):
        tid = dec.u64()
        pg, shard = _dec_pg(dec)
        return cls(
            tid, pg, shard, dec.i32(), dec.i32(), dec.bytes_(),
            _dec_map_str_bytes(dec), dec.u32(),
        )


# -- replicated sub op (src/messages/MOSDRepOp.h) ---------------------------

class MOSDRepOp(Message):
    """primary -> replica: the deterministic effect of one client write
    vector (the reference ships the encoded ObjectStore::Transaction in
    MOSDRepOp; here the primary resolves context-dependent ops like
    append into deterministic ones and ships those)."""

    TYPE = 112

    def __init__(
        self, tid: int = 0, pg: pg_t = pg_t(0, 0), from_osd: int = 0,
        oid: str = "", data: bytes = b"", attrs: dict[str, bytes] | None = None,
        delete: bool = False, epoch: int = 0, version=None,
        ops: list[OSDOp] | None = None, reqid: str = "",
    ):
        self.tid, self.pg, self.from_osd = tid, pg, from_osd
        self.oid, self.data = oid, data
        self.attrs = attrs or {}
        self.delete, self.epoch = delete, epoch
        # effect vector (deterministic write ops); empty = legacy
        # full-object payload in ``data``
        self.ops = ops or []
        self.reqid = reqid
        from ceph_tpu.osd.pglog import ZERO

        self.version = version if version is not None else ZERO

    def encode_payload(self, enc):
        enc.u64(self.tid)
        _enc_pg(enc, self.pg)
        enc.i32(self.from_osd)
        enc.str_(self.oid)
        enc.bytes_(self.data)
        _enc_map_str_bytes(enc, self.attrs)
        enc.bool_(self.delete)
        enc.u32(self.epoch)
        _enc_ev(enc, self.version)
        enc.u32(len(self.ops))
        for o in self.ops:
            o.encode(enc)
        enc.str_(self.reqid)

    @classmethod
    def decode_payload(cls, dec):
        tid = dec.u64()
        pg, _ = _dec_pg(dec)
        msg = cls(
            tid, pg, dec.i32(), dec.str_(), dec.bytes_(),
            _dec_map_str_bytes(dec), dec.bool_(), dec.u32(), _dec_ev(dec),
        )
        msg.ops = [OSDOp.decode(dec) for _ in range(dec.u32())]
        msg.reqid = dec.str_()
        return msg


class MOSDRepOpReply(Message):
    TYPE = 113

    def __init__(
        self, tid: int = 0, pg: pg_t = pg_t(0, 0), from_osd: int = 0,
        result: int = 0, epoch: int = 0, floored: bool = False,
    ):
        self.tid, self.pg, self.from_osd = tid, pg, from_osd
        self.result, self.epoch = result, epoch
        # see MOSDECSubOpWriteReply.floored — same contract for the
        # replicated sub-op path
        self.floored = floored

    def encode_payload(self, enc):
        enc.u64(self.tid)
        _enc_pg(enc, self.pg)
        enc.i32(self.from_osd)
        enc.i32(self.result)
        enc.u32(self.epoch)
        enc.bool_(self.floored)

    @classmethod
    def decode_payload(cls, dec):
        tid = dec.u64()
        pg, _ = _dec_pg(dec)
        return cls(tid, pg, dec.i32(), dec.i32(), dec.u32(),
                   dec.bool_())


# -- recovery push (src/messages/MOSDPGPush.h) ------------------------------

class MOSDPGPush(Message):
    """primary -> peer: reconstructed shard/object payloads."""

    TYPE = 105

    def __init__(
        self, pg: pg_t = pg_t(0, 0), shard: int = -1, from_osd: int = 0,
        pushes: list[tuple[str, bytes, dict[str, bytes]]] | None = None,
        epoch: int = 0, force: bool = False, tid: int = 0,
    ):
        self.pg, self.shard, self.from_osd = pg, shard, from_osd
        self.pushes = pushes or []
        self.epoch = epoch
        # divergent rollback: overwrite even a newer local version (the
        # newer write is being rolled back; its log entry is stripped)
        self.force = force
        # correlates the reply: concurrent pushes of different objects
        # to the same (pg, shard, osd) are in flight at once under
        # osd_recovery_max_active
        self.tid = tid

    def encode_payload(self, enc):
        _enc_pg(enc, self.pg, self.shard)
        enc.i32(self.from_osd)
        enc.u32(self.epoch)
        enc.u32(len(self.pushes))
        for oid, data, attrs in self.pushes:
            enc.str_(oid)
            enc.bytes_(data)
            _enc_map_str_bytes(enc, attrs)
        enc.bool_(self.force)
        enc.u64(self.tid)

    @classmethod
    def decode_payload(cls, dec):
        pg, shard = _dec_pg(dec)
        from_osd = dec.i32()
        epoch = dec.u32()
        pushes = [
            (dec.str_(), dec.bytes_(), _dec_map_str_bytes(dec))
            for _ in range(dec.u32())
        ]
        msg = cls(pg, shard, from_osd, pushes, epoch)
        msg.force = dec.bool_()
        msg.tid = dec.u64()
        return msg


class MOSDPGPushReply(Message):
    TYPE = 106

    def __init__(self, pg: pg_t = pg_t(0, 0), shard: int = -1,
                 from_osd: int = 0, epoch: int = 0, tid: int = 0):
        self.pg, self.shard, self.from_osd, self.epoch = pg, shard, from_osd, epoch
        self.tid = tid

    def encode_payload(self, enc):
        _enc_pg(enc, self.pg, self.shard)
        enc.i32(self.from_osd)
        enc.u32(self.epoch)
        enc.u64(self.tid)

    @classmethod
    def decode_payload(cls, dec):
        pg, shard = _dec_pg(dec)
        return cls(pg, shard, dec.i32(), dec.u32(), dec.u64())


# -- peering / log exchange (src/messages/MOSDPGQuery.h, MOSDPGInfo.h,
# MOSDPGLog.h — simplified to the primary-serialized model) -----------------

def _enc_ev(enc: Encoder, ev) -> None:
    enc.u32(ev[0] if isinstance(ev, tuple) else ev.epoch)
    enc.u64(ev[1] if isinstance(ev, tuple) else ev.version)


def _dec_ev(dec: Decoder):
    from ceph_tpu.osd.pglog import eversion_t

    return eversion_t(dec.u32(), dec.u64())


class MOSDPGQuery(Message):
    """primary -> acting member: send me your pg_info (+ log entries
    after ``since``, + your object list when ``want_objects``)."""

    TYPE = 114

    def __init__(
        self, tid: int = 0, pg: pg_t = pg_t(0, 0), shard: int = -1,
        from_osd: int = 0, since=None, want_objects: bool = False,
        epoch: int = 0, clear_merge: bool = False,
    ):
        from ceph_tpu.osd.pglog import ZERO

        self.tid, self.pg, self.shard, self.from_osd = tid, pg, shard, from_osd
        self.since = since if since is not None else ZERO
        self.want_objects, self.epoch = want_objects, epoch
        # primary finished the post-merge reconcile: drop your
        # merge_pending marker (see RecoveryMixin._merge_pending)
        self.clear_merge = clear_merge

    def encode_payload(self, enc):
        enc.u64(self.tid)
        _enc_pg(enc, self.pg, self.shard)
        enc.i32(self.from_osd)
        _enc_ev(enc, self.since)
        enc.bool_(self.want_objects)
        enc.u32(self.epoch)
        enc.bool_(self.clear_merge)

    @classmethod
    def decode_payload(cls, dec):
        tid = dec.u64()
        pg, shard = _dec_pg(dec)
        return cls(
            tid, pg, shard, dec.i32(), _dec_ev(dec), dec.bool_(),
            dec.u32(), dec.bool_(),
        )


class MOSDPGInfo(Message):
    """Reply to MOSDPGQuery: pg_info + optional log delta + objects."""

    TYPE = 115

    def __init__(
        self, tid: int = 0, pg: pg_t = pg_t(0, 0), shard: int = -1,
        from_osd: int = 0, last_update=None, log_tail=None,
        entries: list[bytes] | None = None,
        objects: list[tuple[str, bytes]] | None = None, epoch: int = 0,
        past_acting: bytes = b"", merge_pending: bool = False,
        missing: list[str] | None = None, contig_floor: bytes = b"",
    ):
        from ceph_tpu.osd.pglog import ZERO

        self.tid, self.pg, self.shard, self.from_osd = tid, pg, shard, from_osd
        self.last_update = last_update if last_update is not None else ZERO
        self.log_tail = log_tail if log_tail is not None else ZERO
        self.entries = entries or []
        self.objects = objects or []
        self.epoch = epoch
        # json chain of previous acting sets this member witnessed
        # (PastIntervals sharing via pg info, newest last)
        self.past_acting = past_acting
        # this member's shard coll carries a not-yet-reconciled pg
        # merge (its listing may include objects other members' logs
        # cannot order) — the primary must not stray-reap this pass
        self.merge_pending = merge_pending
        # the member's SELF-AUDITED missing set (reference pg_missing_t
        # via PGLog::rebuild_missing_set_with_repair): oids its own log
        # names at versions its store does not serve.  last_update
        # alone cannot carry this — log entries travel without data
        # (adoption while briefly primary, MOSDPGLog sync), so a
        # member can be log-current yet object-stale, invisible to the
        # primary's missing_from() scoping (the stale-shard flake).
        self.missing = missing or []
        # encoded eversion key ("epoch.version") of this member's
        # log-contiguity floor, empty when contiguous: a gapped log's
        # last_update must not be trusted past this point (PGLog
        # contig_floor — the missed-window marker)
        self.contig_floor = contig_floor

    def encode_payload(self, enc):
        enc.u64(self.tid)
        _enc_pg(enc, self.pg, self.shard)
        enc.i32(self.from_osd)
        _enc_ev(enc, self.last_update)
        _enc_ev(enc, self.log_tail)
        enc.u32(len(self.entries))
        for e in self.entries:
            enc.bytes_(e)
        enc.u32(len(self.objects))
        for oid, v in self.objects:
            enc.str_(oid)
            enc.bytes_(v)
        enc.u32(self.epoch)
        enc.bytes_(self.past_acting)
        enc.bool_(self.merge_pending)
        enc.u32(len(self.missing))
        for oid in self.missing:
            enc.str_(oid)
        enc.bytes_(self.contig_floor)

    @classmethod
    def decode_payload(cls, dec):
        tid = dec.u64()
        pg, shard = _dec_pg(dec)
        from_osd = dec.i32()
        lu = _dec_ev(dec)
        lt = _dec_ev(dec)
        entries = [dec.bytes_() for _ in range(dec.u32())]
        objects = [(dec.str_(), dec.bytes_()) for _ in range(dec.u32())]
        epoch = dec.u32()
        past_acting = dec.bytes_()
        merge_pending = dec.bool_()
        missing = [dec.str_() for _ in range(dec.u32())]
        return cls(tid, pg, shard, from_osd, lu, lt, entries, objects,
                   epoch, past_acting, merge_pending, missing,
                   dec.bytes_())


class MOSDPGLog(Message):
    """primary -> recovered member: log entries beyond its last_update
    so its pg_info catches up after object recovery."""

    TYPE = 116

    def __init__(
        self, tid: int = 0, pg: pg_t = pg_t(0, 0), shard: int = -1,
        from_osd: int = 0, entries: list[bytes] | None = None, epoch: int = 0,
        tail=None, clear_floor: bool = False,
    ):
        from ceph_tpu.osd.pglog import ZERO

        self.tid, self.pg, self.shard, self.from_osd = tid, pg, shard, from_osd
        self.entries = entries or []
        self.epoch = epoch
        # sender's log_tail: lets a backfilled peer know its own log has
        # a gap below this point
        self.tail = tail if tail is not None else ZERO
        # primary-verified heal: every object through the receiver's
        # contiguity gap was reconciled and the entries shipped here
        # FILL its content holes — the receiver may clear its floor
        self.clear_floor = clear_floor

    def encode_payload(self, enc):
        enc.u64(self.tid)
        _enc_pg(enc, self.pg, self.shard)
        enc.i32(self.from_osd)
        enc.u32(len(self.entries))
        for e in self.entries:
            enc.bytes_(e)
        enc.u32(self.epoch)
        _enc_ev(enc, self.tail)
        enc.bool_(self.clear_floor)

    @classmethod
    def decode_payload(cls, dec):
        tid = dec.u64()
        pg, shard = _dec_pg(dec)
        from_osd = dec.i32()
        entries = [dec.bytes_() for _ in range(dec.u32())]
        return cls(tid, pg, shard, from_osd, entries, dec.u32(),
                   _dec_ev(dec), dec.bool_())


class MOSDPGLogAck(Message):
    TYPE = 117

    def __init__(self, tid: int = 0, pg: pg_t = pg_t(0, 0), shard: int = -1,
                 from_osd: int = 0, result: int = 0, epoch: int = 0):
        self.tid, self.pg, self.shard = tid, pg, shard
        self.from_osd, self.result, self.epoch = from_osd, result, epoch

    def encode_payload(self, enc):
        enc.u64(self.tid)
        _enc_pg(enc, self.pg, self.shard)
        enc.i32(self.from_osd)
        enc.i32(self.result)
        enc.u32(self.epoch)

    @classmethod
    def decode_payload(cls, dec):
        tid = dec.u64()
        pg, shard = _dec_pg(dec)
        return cls(tid, pg, shard, dec.i32(), dec.i32(), dec.u32())


# -- watch/notify (src/messages/MWatchNotify.h) -----------------------------

class MWatchNotify(Message):
    """primary OSD -> watching client: a notify fired on an object the
    client watches (reference MWatchNotify; the client acks with
    MWatchNotifyAck and the notifier's OP_NOTIFY completes when every
    watcher acked or timed out)."""

    TYPE = 73

    def __init__(
        self, notify_id: int = 0, cookie: int = 0, oid: str = "",
        pool: int = 0, payload: bytes = b"",
    ):
        self.notify_id, self.cookie = notify_id, cookie
        self.oid, self.pool, self.payload = oid, pool, payload

    def encode_payload(self, enc):
        enc.u64(self.notify_id)
        enc.u64(self.cookie)
        enc.str_(self.oid)
        enc.i64(self.pool)
        enc.bytes_(self.payload)

    @classmethod
    def decode_payload(cls, dec):
        return cls(dec.u64(), dec.u64(), dec.str_(), dec.i64(), dec.bytes_())


class MWatchNotifyAck(Message):
    TYPE = 74

    def __init__(
        self, notify_id: int = 0, cookie: int = 0, reply: bytes = b"",
    ):
        self.notify_id, self.cookie, self.reply = notify_id, cookie, reply

    def encode_payload(self, enc):
        enc.u64(self.notify_id)
        enc.u64(self.cookie)
        enc.bytes_(self.reply)

    @classmethod
    def decode_payload(cls, dec):
        return cls(dec.u64(), dec.u64(), dec.bytes_())


# -- heartbeats (src/messages/MOSDPing.h) -----------------------------------

PING = 1
PING_REPLY = 2


class MOSDPing(Message):
    """osd <-> osd liveness ping (reference MOSDPing over the front/back
    heartbeat messengers, OSD::handle_osd_ping src/osd/OSD.cc:5735).
    ``stamp`` echoes back so the sender can compute RTT."""

    TYPE = 70

    def __init__(
        self, op: int = PING, from_osd: int = 0, epoch: int = 0,
        stamp: int = 0,
    ):
        self.op, self.from_osd, self.epoch, self.stamp = (
            op, from_osd, epoch, stamp,
        )

    def encode_payload(self, enc):
        enc.u8(self.op)
        enc.i32(self.from_osd)
        enc.u32(self.epoch)
        enc.u64(self.stamp)

    @classmethod
    def decode_payload(cls, dec):
        return cls(dec.u8(), dec.i32(), dec.u32(), dec.u64())


# -- scrub (src/messages/MOSDScrub2.h) --------------------------------------

class MOSDScrub(Message):
    """mon -> primary OSD: scrub one PG (deep compares payload crcs vs
    the HashInfo chains; repair reconstructs bad shards afterwards —
    the `ceph pg repair` verb)."""

    TYPE = 118

    def __init__(self, tid: int = 0, pool: int = 0, ps: int = 0,
                 deep: bool = False, repair: bool = False):
        self.tid, self.pool, self.ps, self.deep = tid, pool, ps, deep
        self.repair = repair

    def encode_payload(self, enc):
        enc.u64(self.tid)
        enc.i64(self.pool)
        enc.u32(self.ps)
        enc.bool_(self.deep)
        enc.bool_(self.repair)

    @classmethod
    def decode_payload(cls, dec):
        return cls(dec.u64(), dec.i64(), dec.u32(), dec.bool_(), dec.bool_())


class MOSDScrubReply(Message):
    TYPE = 119

    def __init__(self, tid: int = 0, result: int = 0, report: bytes = b""):
        self.tid, self.result, self.report = tid, result, report

    def encode_payload(self, enc):
        enc.u64(self.tid)
        enc.i32(self.result)
        enc.bytes_(self.report)

    @classmethod
    def decode_payload(cls, dec):
        return cls(dec.u64(), dec.i32(), dec.bytes_())


class MBackfillReserve(Message):
    """Backfill-reservation handshake between a recovering primary and
    its acting-set replicas (src/messages/MBackfillReserve.h): REQUEST
    asks the replica for one of its osd_max_backfills remote slots;
    the replica answers GRANT or REJECT_TOOFULL (non-blocking — the
    primary retries after osd_backfill_retry_interval); RELEASE frees
    the slot when the PG goes clean."""

    TYPE = 99  # MSG_OSD_BACKFILL_RESERVE (src/include/msgr.h)

    REQUEST = 0
    GRANT = 1
    REJECT_TOOFULL = 2
    RELEASE = 3

    def __init__(self, tid: int = 0, op: int = 0, pool: int = 0,
                 ps: int = 0, from_osd: int = 0, priority: int = 0):
        self.tid, self.op = tid, op
        self.pool, self.ps = pool, ps
        self.from_osd, self.priority = from_osd, priority

    def encode_payload(self, enc):
        enc.u64(self.tid)
        enc.u8(self.op)
        enc.i64(self.pool)
        enc.u32(self.ps)
        enc.i32(self.from_osd)
        enc.i32(self.priority)

    @classmethod
    def decode_payload(cls, dec):
        return cls(dec.u64(), dec.u8(), dec.i64(), dec.u32(), dec.i32(),
                   dec.i32())


# -- mgr plane (src/messages/MMgrBeacon.h, MMgrMap.h, MMgrOpen.h,
# MMgrReport.h, MMgrConfigure.h, MMonMgrReport.h) ---------------------------

def _enc_map_str_f64(enc: Encoder, d: dict[str, float]) -> None:
    """Float maps ride as repr strings (the denc layer is int/bytes
    only; repr round-trips doubles exactly)."""
    enc.u32(len(d))
    for k in sorted(d):
        enc.str_(k)
        enc.str_(repr(float(d[k])))


def _dec_map_str_f64(dec: Decoder) -> dict[str, float]:
    return {dec.str_(): float(dec.str_()) for _ in range(dec.u32())}


class MMgrBeacon(Message):
    """mgr -> mon: I exist (active or standby is the MON's call —
    reference MMgrBeacon / MgrMonitor::prepare_beacon).  ``gid`` is
    fresh per daemon start, so the mon can tell a restarted mgr from a
    paxos replay of the same beacon."""

    TYPE = 120

    def __init__(self, name: str = "", gid: int = 0, host: str = "",
                 port: int = 0):
        self.name, self.gid, self.host, self.port = name, gid, host, port

    def encode_payload(self, enc):
        enc.str_(self.name)
        enc.u64(self.gid)
        enc.str_(self.host)
        enc.u32(self.port)

    @classmethod
    def decode_payload(cls, dec):
        return cls(dec.str_(), dec.u64(), dec.str_(), dec.u32())


class MMgrMap(Message):
    """mon -> subscribers: the MgrMap (reference MMgrMap) — who is the
    active mgr, the standbys, and the enabled-module set.  ``blob`` is
    the json map; ``epoch`` is the MgrMap's own epoch (NOT an osdmap
    epoch)."""

    TYPE = 121

    def __init__(self, epoch: int = 0, blob: bytes = b""):
        self.epoch, self.blob = epoch, blob

    def encode_payload(self, enc):
        enc.u32(self.epoch)
        enc.bytes_(self.blob)

    @classmethod
    def decode_payload(cls, dec):
        return cls(dec.u32(), dec.bytes_())


class MMgrOpen(Message):
    """daemon -> active mgr: open a report session (reference
    MMgrOpen).  The mgr answers with MMgrConfigure."""

    TYPE = 122

    def __init__(self, daemon: str = "", metadata: bytes = b""):
        self.daemon = daemon  # "osd.0", "mon.1", "mds.0", "rgw.main"
        self.metadata = metadata  # json daemon metadata

    def encode_payload(self, enc):
        enc.str_(self.daemon)
        enc.bytes_(self.metadata)

    @classmethod
    def decode_payload(cls, dec):
        return cls(dec.str_(), dec.bytes_())


class MMgrConfigure(Message):
    """active mgr -> daemon: report-stream tuning (reference
    MMgrConfigure: stats_period).  ``scrub_deprioritize`` closes the
    analytics loop: the active mgr's outlier detection flags a slow
    OSD and tells it to defer background scrubs (the slow-OSD-aware
    scrub scheduling hook)."""

    TYPE = 123

    def __init__(self, period: float = 1.0,
                 scrub_deprioritize: bool = False):
        self.period = period
        self.scrub_deprioritize = scrub_deprioritize

    def encode_payload(self, enc):
        enc.str_(repr(float(self.period)))
        enc.bool_(self.scrub_deprioritize)

    @classmethod
    def decode_payload(cls, dec):
        return cls(float(dec.str_()), dec.bool_())


class MMgrReport(Message):
    """daemon -> active mgr: one telemetry report (reference
    MMgrReport carrying packed PerfCounterInstances).

    - ``counters``: perf-counter DELTAS since the previous report
      (the mgr accumulates them back into cumulative series);
    - ``gauges``: instantaneous values (also the per-interval latency
      means the time-series ring buffers ingest);
    - ``histograms``: cumulative fixed-bucket log2 latency histograms
      (common/optracker.py LatencyHistogram), mergeable as arrays;
    - ``status``: json side-channel (pg-state summary, the disk
      read-error ledger, daemon health bits);
    - ``spans``: json list of finished trace spans drained from the
      daemon's tracer export buffers — the side channel the mgr's
      TraceCollector assembles cluster-wide traces from.
    """

    TYPE = 124

    def __init__(self, daemon: str = "", counters: dict | None = None,
                 gauges: dict | None = None,
                 histograms: dict[str, list[int]] | None = None,
                 status: bytes = b"", spans: bytes = b""):
        self.daemon = daemon
        self.counters = counters or {}
        self.gauges = gauges or {}
        self.histograms = histograms or {}
        self.status = status
        self.spans = spans

    def encode_payload(self, enc):
        enc.str_(self.daemon)
        _enc_map_str_f64(enc, self.counters)
        _enc_map_str_f64(enc, self.gauges)
        enc.u32(len(self.histograms))
        for k in sorted(self.histograms):
            enc.str_(k)
            buckets = self.histograms[k]
            enc.u32(len(buckets))
            for b in buckets:
                enc.u64(int(b))
        enc.bytes_(self.status)
        enc.bytes_(self.spans)

    @classmethod
    def decode_payload(cls, dec):
        daemon = dec.str_()
        counters = _dec_map_str_f64(dec)
        gauges = _dec_map_str_f64(dec)
        histograms = {
            dec.str_(): [dec.u64() for _ in range(dec.u32())]
            for _ in range(dec.u32())
        }
        return cls(daemon, counters, gauges, histograms, dec.bytes_(),
                   dec.bytes_())


class MMonMgrReport(Message):
    """active mgr -> mon: the cluster digest (reference MMonMgrReport:
    health + service digest).  ``blob`` is json — per-OSD perf rows
    for `ceph osd perf`, the analytics summary (percentiles, outlier
    OSDs, top-slow list), module health checks, and optionally the
    rendered prometheus exposition the dashboard serves."""

    TYPE = 125

    def __init__(self, blob: bytes = b""):
        self.blob = blob

    def encode_payload(self, enc):
        enc.bytes_(self.blob)

    @classmethod
    def decode_payload(cls, dec):
        return cls(dec.bytes_())


# -- cluster log (src/messages/MLog.h, MLogAck.h) ---------------------------

class MLog(Message):
    """daemon -> mon: a batch of cluster-log entries from one daemon's
    LogClient (reference MLog carrying LogEntry vectors).  ``entity``
    identifies the sender once for the whole batch; each entry carries
    its per-entity ``seq`` so the mon's LogMonitor twin can dedup
    resends across flushes and mon failovers.  Entries are dicts
    {"seq", "stamp", "channel", "level", "message"}."""

    TYPE = 126

    def __init__(self, entity: str = "", entries: list[dict] | None = None):
        self.entity = entity
        self.entries = entries or []

    def encode_payload(self, enc):
        enc.str_(self.entity)
        enc.u32(len(self.entries))
        for e in self.entries:
            enc.u64(int(e["seq"]))
            enc.str_(repr(float(e["stamp"])))
            enc.str_(e["channel"])
            enc.u8(int(e["level"]))
            enc.str_(e["message"])

    @classmethod
    def decode_payload(cls, dec):
        entity = dec.str_()
        entries = [
            {
                "seq": dec.u64(),
                "stamp": float(dec.str_()),
                "channel": dec.str_(),
                "level": dec.u8(),
                "message": dec.str_(),
            }
            for _ in range(dec.u32())
        ]
        return cls(entity, entries)


class MLogAck(Message):
    """mon -> daemon: entries up to ``last_seq`` are committed in the
    replicated cluster log (reference MLogAck); the LogClient drops
    them from its resend buffer."""

    TYPE = 127

    def __init__(self, last_seq: int = 0):
        self.last_seq = last_seq

    def encode_payload(self, enc):
        enc.u64(self.last_seq)

    @classmethod
    def decode_payload(cls, dec):
        return cls(dec.u64())


# -- cephfs client <-> mds (src/messages/MClientRequest.h) ------------------

class MClientRequest(Message):
    """Filesystem metadata request (CEPH_MSG_CLIENT_REQUEST=24).  The
    reference carries op-specific structs; the lite MDS takes the op
    name + JSON args (paths resolve server-side, single-MDS v1)."""

    TYPE = 24

    def __init__(self, tid: int = 0, op: str = "", args: dict | None = None):
        self.tid, self.op, self.args = tid, op, args or {}

    def encode_payload(self, enc):
        import json

        enc.u64(self.tid)
        enc.str_(self.op)
        enc.bytes_(json.dumps(self.args).encode())

    @classmethod
    def decode_payload(cls, dec):
        import json

        tid = dec.u64()
        op = dec.str_()
        return cls(tid, op, json.loads(dec.bytes_() or b"{}"))


class MClientCaps(Message):
    """CEPH_MSG_CLIENT_CAPS=0x310 analogue: the cap traffic between
    MDS (Locker) and fs clients.  ops:

    - GRANT  (mds->client): you now hold ``caps`` on ``ino``;
    - REVOKE (mds->client): give back everything above ``caps``; flush
      buffered dirty state first;
    - FLUSH  (client->mds): dirty size/mtime for ``path``/``ino`` (the
      cap-flush that makes the MDS the size authority);
    - ACK    (client->mds): revoke done (after any FLUSH);
    - SNAPC  (mds->client): the data pool's snap context changed
      (a .snap was created/removed) — update write snapc NOW.
    """

    TYPE = 25
    GRANT, REVOKE, FLUSH, ACK, SNAPC = 0, 1, 2, 3, 4

    def __init__(self, tid: int = 0, op: int = 0, ino: int = 0,
                 caps: int = 0, path: str = "", size: int = -1,
                 mtime: float = -1.0, snap_seq: int = 0,
                 snaps: list[int] | None = None):
        self.tid, self.op, self.ino, self.caps = tid, op, ino, caps
        self.path, self.size, self.mtime = path, size, mtime
        self.snap_seq = snap_seq
        self.snaps = snaps or []

    def encode_payload(self, enc):
        enc.u64(self.tid)
        enc.u8(self.op)
        enc.u64(self.ino)
        enc.u32(self.caps)
        enc.str_(self.path)
        enc.i64(self.size)
        enc.str_(repr(self.mtime))
        enc.u64(self.snap_seq)
        enc.u32(len(self.snaps))
        for s in self.snaps:
            enc.u64(s)

    @classmethod
    def decode_payload(cls, dec):
        tid = dec.u64()
        op = dec.u8()
        ino = dec.u64()
        caps = dec.u32()
        path = dec.str_()
        size = dec.i64()
        mtime = float(dec.str_())
        seq = dec.u64()
        snaps = [dec.u64() for _ in range(dec.u32())]
        return cls(tid, op, ino, caps, path, size, mtime, seq, snaps)


class MClientReply(Message):
    """CEPH_MSG_CLIENT_REPLY=26: result code + JSON payload."""

    TYPE = 26

    def __init__(self, tid: int = 0, result: int = 0, out: dict | None = None):
        self.tid, self.result, self.out = tid, result, out or {}

    def encode_payload(self, enc):
        import json

        enc.u64(self.tid)
        enc.i32(self.result)
        enc.bytes_(json.dumps(self.out).encode())

    @classmethod
    def decode_payload(cls, dec):
        import json

        tid = dec.u64()
        result = dec.i32()
        return cls(tid, result, json.loads(dec.bytes_() or b"{}"))
