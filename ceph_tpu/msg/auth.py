"""cephx-style authentication + AES-GCM connection crypto.

Behavioral twin of the reference's auth stack (src/auth/cephx/
CephxProtocol.h, src/msg/async/crypto_onwire.cc), shaped to the same
trust model:

- every entity (mon.N, osd.N, client.N) has a symmetric secret in the
  monitor's keyring (``ceph auth`` / keyring files);
- cluster daemons additionally hold the SERVICE secret, so they can
  both mint and validate service tickets (the reference's rotating
  service keys, minus rotation);
- a client authenticates to the mon by being able to decrypt the
  session key the mon returns under the client's own secret (cephx's
  proof-of-possession, collapsed into the grant: an impostor receives
  only ciphertext it cannot use, and the first AEAD frame it sends
  fails authentication);
- the mon's AUTH_DONE also carries a service TICKET =
  AES-GCM(service_secret, {entity, session_key}) which the client
  presents when dialing OSDs (CephxTicketBlob);
- once both sides share the session key, the connection switches to
  msgr2 SECURE mode: every frame is AES-GCM'd with per-direction keys
  derived from (session key, both nonces) and counter nonces
  (crypto_onwire.cc AES128GCM_OnWireTxRx; 256-bit keys here).

Deliberate simplifications vs the reference, documented: one service
secret instead of per-service rotating keys; no ticket renewal (tickets
carry an expiry and validators enforce it); no CEPHX_V2 legacy
challenge paths.
"""

from __future__ import annotations

import os
import struct
import time

try:
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM

    HAVE_AESGCM = True
except ImportError:  # env without the cryptography wheel
    # degrade cleanly: the module stays importable (messengers built
    # WITHOUT an AuthContext never touch AEAD), and anything that
    # actually needs sealing fails with a clear message instead of an
    # import-time crash taking unrelated test collection down with it.
    # The stdlib has HMAC but no AES — an authenticate-only fallback
    # would silently drop the confidentiality the reference's SECURE
    # mode promises, so secured clusters simply require the wheel.
    HAVE_AESGCM = False

    class AESGCM:  # type: ignore[no-redef]
        def __init__(self, key: bytes):
            raise RuntimeError(
                "cephx SECURE mode needs the 'cryptography' package "
                "(AES-GCM); it is not installed"
            )

from ceph_tpu.msg.denc import Decoder, Encoder

KEY_BYTES = 32
NONCE_BYTES = 12
TICKET_TTL = 3600.0


def make_secret() -> bytes:
    return os.urandom(KEY_BYTES)


def _hkdf(key: bytes, salt: bytes, info: bytes) -> bytes:
    """HKDF-SHA256 (extract+expand, single block)."""
    import hashlib
    import hmac as _hmac

    prk = _hmac.new(salt, key, hashlib.sha256).digest()
    return _hmac.new(prk, info + b"\x01", hashlib.sha256).digest()


def seal(secret: bytes, plaintext: bytes) -> bytes:
    nonce = os.urandom(NONCE_BYTES)
    return nonce + AESGCM(secret).encrypt(nonce, plaintext, b"")


def unseal(secret: bytes, blob: bytes) -> bytes:
    nonce, ct = blob[:NONCE_BYTES], blob[NONCE_BYTES:]
    return AESGCM(secret).decrypt(nonce, ct, b"")


# -- tickets ----------------------------------------------------------------

def mint_ticket(
    service_secret: bytes, entity: str, session_key: bytes,
    ttl: float = TICKET_TTL, caps: dict[str, str] | None = None,
) -> bytes:
    """Caps ride INSIDE the sealed ticket (the reference's
    CephXServiceTicketInfo carrying AuthCapsInfo): validators learn
    the peer's authorization without asking the mon."""
    import json

    enc = Encoder()
    enc.str_(entity)
    enc.bytes_(session_key)
    enc.u64(int((time.time() + ttl) * 1000))
    enc.str_(json.dumps(caps if caps is not None else {}))
    return seal(service_secret, enc.bytes())


def open_ticket(
    service_secret: bytes, blob: bytes,
) -> tuple[str, bytes, dict[str, str]]:
    """Returns (entity, session_key, caps); raises on tamper/expiry."""
    import json

    dec = Decoder(unseal(service_secret, blob))
    entity = dec.str_()
    session_key = dec.bytes_()
    expiry_ms = dec.u64()
    if time.time() * 1000 > expiry_ms:
        raise PermissionError(f"ticket for {entity} expired")
    caps = json.loads(dec.str_())
    return entity, session_key, caps


# -- per-connection AEAD framing -------------------------------------------

class FrameCrypto:
    """Per-direction AES-GCM with counter nonces
    (crypto_onwire.cc:AES128GCM_OnWireTxRx semantics)."""

    def __init__(self, tx_key: bytes, rx_key: bytes):
        self._tx = AESGCM(tx_key)
        self._rx = AESGCM(rx_key)
        self._tx_ctr = 0
        self._rx_ctr = 0

    @classmethod
    def from_session(
        cls, session_key: bytes, nonce_c: bytes, nonce_s: bytes,
        connector: bool,
    ) -> "FrameCrypto":
        salt = nonce_c + nonce_s
        c2s = _hkdf(session_key, salt, b"ceph_tpu c2s")
        s2c = _hkdf(session_key, salt, b"ceph_tpu s2c")
        return cls(c2s, s2c) if connector else cls(s2c, c2s)

    def _nonce(self, ctr: int) -> bytes:
        return struct.pack("<4xQ", ctr)

    def encrypt(self, plaintext: bytes) -> bytes:
        self._tx_ctr += 1
        return self._tx.encrypt(self._nonce(self._tx_ctr), plaintext, b"")

    def decrypt(self, ciphertext: bytes) -> bytes:
        self._rx_ctr += 1
        return self._rx.decrypt(self._nonce(self._rx_ctr), ciphertext, b"")


# -- entity-side contexts ----------------------------------------------------

class AuthContext:
    """What one entity carries into its messenger.

    - clients: ``secret`` (their own), ticket acquired from the mon
      in-band on the first mon connection;
    - cluster daemons (osd/mon): ``service_secret`` (can mint + open
      tickets themselves) and, for mons, the ``keyring``.
    """

    def __init__(
        self,
        entity: str,
        secret: bytes | None = None,
        service_secret: bytes | None = None,
        keyring: dict[str, bytes] | None = None,
        caps_db: dict[str, dict[str, str]] | None = None,
    ):
        self.entity = entity
        self.secret = secret
        self.service_secret = service_secret
        self.keyring = keyring or {}
        # entity -> caps dict (the AuthMonitor's view); keyring entries
        # absent here get ADMIN caps — a statically-keyed entity is the
        # client.admin bootstrap role
        self.caps_db = caps_db or {}
        self.ticket: bytes | None = None       # from the mon (clients)
        self.session_key: bytes | None = None  # paired with self.ticket

    # server side: grant or validate -----------------------------------

    def caps_of(self, entity: str) -> dict[str, str]:
        got = self.caps_db.get(entity)
        if got is not None:
            return got
        from ceph_tpu.common.caps import ADMIN_CAPS

        return dict(ADMIN_CAPS)

    def grant(self, entity: str) -> tuple[bytes, bytes, bytes, dict] | None:
        """Mon-side (keyring holder): returns (sealed_grant, session_key,
        ticket, caps) for a known entity, None for an unknown one.  The
        grant is sealed under the ENTITY's keyring secret — only the
        genuine entity can recover the session key (cephx proof of
        possession); its caps are sealed into the ticket."""
        peer_secret = self.keyring.get(entity)
        if peer_secret is None or self.service_secret is None:
            return None
        session_key = make_secret()
        caps = self.caps_of(entity)
        ticket = mint_ticket(
            self.service_secret, entity, session_key, caps=caps)
        enc = Encoder()
        enc.bytes_(session_key)
        enc.bytes_(ticket)
        return seal(peer_secret, enc.bytes()), session_key, ticket, caps

    def open_grant(self, sealed: bytes) -> tuple[bytes, bytes]:
        """Client-side: recover (session_key, ticket) with our secret."""
        assert self.secret is not None
        dec = Decoder(unseal(self.secret, sealed))
        return dec.bytes_(), dec.bytes_()

    def self_ticket(self) -> tuple[bytes, bytes]:
        """Cluster daemons mint their own (ticket, session_key) — they
        hold the service secret, like the reference's OSDs holding the
        rotating service keys."""
        from ceph_tpu.common.caps import ADMIN_CAPS

        assert self.service_secret is not None
        session_key = make_secret()
        return (
            mint_ticket(self.service_secret, self.entity, session_key,
                        caps=dict(ADMIN_CAPS)),
            session_key,
        )
