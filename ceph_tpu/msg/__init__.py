"""Wire transport (reference src/msg/): denc encoding, msgr2-style
frames, the asyncio messenger, and the typed message set."""

from ceph_tpu.msg.denc import Decoder, Encoder, EncodingError
from ceph_tpu.msg.messenger import Connection, Message, Messenger

__all__ = [
    "Connection",
    "Decoder",
    "Encoder",
    "EncodingError",
    "Message",
    "Messenger",
]
