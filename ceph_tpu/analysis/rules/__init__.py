"""ctlint rule families.  Each module contributes one Rule subclass;
``ALL_RULES`` is the suite ``tools/lint.py`` and the tier-1 gate run."""

from ceph_tpu.analysis.rules.configrule import ConfigRegistryRule
from ceph_tpu.analysis.rules.determinism import DeterminismRule
from ceph_tpu.analysis.rules.device import DeviceDisciplineRule
from ceph_tpu.analysis.rules.locks import LockOrderRule
from ceph_tpu.analysis.rules.transfer import TransferRule
from ceph_tpu.analysis.rules.wire import WireProtocolRule

ALL_RULES = (
    DeviceDisciplineRule,
    LockOrderRule,
    WireProtocolRule,
    ConfigRegistryRule,
    DeterminismRule,
    TransferRule,
)

#: rule-id -> one-line description (the catalog tools/lint.py prints)
RULE_CATALOG: dict[str, str] = {}
for _cls in ALL_RULES:
    RULE_CATALOG.update(_cls.catalog)
