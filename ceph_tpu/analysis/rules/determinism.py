"""Rule family 5: schedule determinism.

The chaos contract is that a trace is a pure function of ``(seed,
scenario)`` — the committed ``trace_hash`` values in CHAOS_*.json
re-derive bit-identically forever.  Three things silently break that
purity: the wall clock, the shared ``random`` module state, and
iteration order over unordered sets (hash-randomized for str-keyed
content, and a refactor hazard even for ints).

Scope: ``ceph_tpu/chaos/schedule.py`` and
``ceph_tpu/loadgen/schedule.py`` (their committed trace hashes carry
the same purity contract) plus any module carrying a
``# ctlint: pure-trace`` marker.

- ``det-wallclock`` — ``time.time()``/``monotonic()``/
  ``datetime.now()`` etc.
- ``det-random`` — module-level ``random.<fn>()`` calls (seeded
  ``random.Random(...)`` instances are the sanctioned source).
- ``det-set-iter`` — iterating a set expression (literal, ``set()``
  call, set algebra, or a name assigned from one) without ``sorted()``.
"""

from __future__ import annotations

import ast

from ceph_tpu.analysis.core import SEV_ERROR, Finding, Project, Rule
from ceph_tpu.analysis.rules.common import attr_chain, call_name, last_name

PURE_TRACE_PATHS = (
    "ceph_tpu/chaos/schedule.py",
    "ceph_tpu/loadgen/schedule.py",
    # the fuzz plane's pure half: mutants, fingerprints, corpus
    # bookkeeping and ddmin all carry the committed-hash contract
    # (FUZZ_*.json lineages re-derive bit-identically forever)
    "ceph_tpu/fuzz/mutate.py",
    "ceph_tpu/fuzz/coverage.py",
    "ceph_tpu/fuzz/corpus.py",
    "ceph_tpu/fuzz/minimize.py",
)

_WALLCLOCK = {
    "time.time", "time.time_ns", "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns",
    "datetime.now", "datetime.utcnow", "datetime.today",
    "datetime.datetime.now", "datetime.datetime.utcnow", "date.today",
}

#: order-insensitive wrappers: iterating their result is fine
_ORDER_FREE = {"sorted", "len", "sum", "min", "max", "any", "all"}


def _in_scope(sf) -> bool:
    return sf.path in PURE_TRACE_PATHS or sf.pure_trace


def _is_setish(node: ast.AST, set_names: set[str]) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        name = call_name(node)
        if name and name.split(".")[-1] in ("set", "frozenset"):
            return True
        return False
    if isinstance(node, ast.BinOp) and isinstance(
            node.op, (ast.Sub, ast.BitOr, ast.BitAnd, ast.BitXor)):
        return (_is_setish(node.left, set_names)
                or _is_setish(node.right, set_names))
    name = last_name(node)
    return bool(name and name in set_names)


def _collect_set_names(tree: ast.Module) -> set[str]:
    """Names/attrs assigned from set expressions anywhere in the module
    (attribute granularity: ``self.alive = set()`` marks ``alive``)."""
    names: set[str] = set()
    # two passes so `a = b` where b is a known set propagates once
    for _ in range(2):
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and node.targets:
                if _is_setish(node.value, names):
                    for t in node.targets:
                        n = last_name(t)
                        if n:
                            names.add(n)
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                if _is_setish(node.value, names):
                    n = last_name(node.target)
                    if n:
                        names.add(n)
    return names


class DeterminismRule(Rule):
    name = "determinism"
    rules = ("det-wallclock", "det-random", "det-set-iter")
    catalog = {
        "det-wallclock":
            "wall-clock read in a pure-trace path (trace must be a "
            "function of (seed, scenario) only)",
        "det-random":
            "shared random-module global in a pure-trace path (use a "
            "seeded random.Random instance)",
        "det-set-iter":
            "iteration over an unordered set in a pure-trace path "
            "(wrap in sorted())",
    }

    def run(self, project: Project) -> list[Finding]:
        findings: list[Finding] = []
        for sf in project.files:
            if not _in_scope(sf):
                continue
            set_names = _collect_set_names(sf.tree)
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.Call):
                    findings.extend(self._check_call(sf, node))
                elif isinstance(node, (ast.For, ast.AsyncFor)):
                    findings.extend(self._check_iter(
                        sf, node.iter, set_names))
                elif isinstance(node, (ast.ListComp, ast.SetComp,
                                       ast.GeneratorExp, ast.DictComp)):
                    for gen in node.generators:
                        findings.extend(self._check_iter(
                            sf, gen.iter, set_names))
        return findings

    def _check_call(self, sf, node: ast.Call) -> list[Finding]:
        name = call_name(node)
        if not name:
            return []
        if name in _WALLCLOCK or any(
                name.endswith("." + w) for w in _WALLCLOCK):
            return [Finding(
                "det-wallclock", SEV_ERROR, sf.path, node.lineno,
                f"{name}() in a pure-trace path — traces must derive "
                f"from (seed, scenario) only, never the wall clock",
            )]
        parts = name.split(".")
        if (len(parts) == 2 and parts[0] == "random"
                and parts[1] != "Random"):
            return [Finding(
                "det-random", SEV_ERROR, sf.path, node.lineno,
                f"{name}() uses the shared random-module state — draw "
                f"from a seeded random.Random instance instead",
            )]
        return []

    def _check_iter(self, sf, it: ast.AST,
                    set_names: set[str]) -> list[Finding]:
        # unwrap order-free wrappers: sorted(x), enumerate(sorted(x))
        expr = it
        while isinstance(expr, ast.Call):
            fname = call_name(expr)
            short = fname.split(".")[-1] if fname else ""
            if short in _ORDER_FREE:
                return []  # sorted()/etc. already canonicalizes
            if short == "enumerate" and expr.args:
                expr = expr.args[0]
                continue
            break
        if _is_setish(expr, set_names):
            label = attr_chain(expr) or ast.dump(expr)[:40]
            return [Finding(
                "det-set-iter", SEV_ERROR, sf.path, it.lineno,
                f"iteration over unordered set {label!r} in a "
                f"pure-trace path — wrap it in sorted() or the trace "
                f"(and its committed hash) depends on hash order",
            )]
        return []
