"""Rule family 3: wire protocol.

Scope: any class that declares a ``TYPE`` frame id and an
``encode_payload``/``decode_payload`` pair (in the live tree that is
``msg/messages.py``; fixtures mimic the shape).

- ``wire-frame-id`` — duplicate frame ids across message classes, and
  classes with an encode/decode pair but no registered (non-zero)
  ``TYPE``: the messenger registry would either assert at import or
  silently never route the frame.
- ``wire-asymmetry`` — the primitive sequence written by
  ``encode_payload`` must match what ``decode_payload`` reads.  The
  comparison is over *wire widths* (``u32``/``i32`` both occupy 4
  bytes; ``str_`` is a ``bytes_`` on the wire), with module-level
  ``_enc_*``/``_dec_*`` helper splicing, counted-loop normalization
  (``enc.u32(len(x))`` + loop == decode loop over ``range(dec.u32())``)
  and branch-tolerant matching for version gates.
"""

from __future__ import annotations

import ast

from ceph_tpu.analysis.core import SEV_ERROR, Finding, Project, Rule
from ceph_tpu.analysis.rules.common import call_name

#: primitive -> canonical wire token (widths, not signedness)
_PRIMS = {
    "u8": "b1", "bool_": "b1",
    "u16": "b2",
    "u32": "b4", "i32": "b4",
    "u64": "b8", "i64": "b8",
    "str_": "blob", "bytes_": "blob",
    "raw": "raw",
}

# sequence node kinds: ("p", token) | ("loop", body, counted) | ("opt",
# then, orelse) | ("ver", body).  ``counted`` marks a loop whose length
# prefix is embedded (decode's ``range(dec.u32())``): it must NOT
# absorb a preceding b4 during normalization — that b4 is a real field.


class _SeqBuilder:
    """Extracts the canonical wire sequence from one payload method."""

    def __init__(self, role: str, helpers: dict[str, list]):
        self.role = role          # "enc" | "dec"
        self.helpers = helpers    # resolved module helper sequences

    def body_seq(self, stmts: list[ast.stmt]) -> list:
        out: list = []
        for st in stmts:
            self._stmt(st, out)
        return _normalize(out)

    # -- statements ----------------------------------------------------

    def _stmt(self, st: ast.stmt, out: list) -> None:
        if isinstance(st, (ast.For, ast.AsyncFor, ast.While)):
            body: list = []
            counted = False
            if isinstance(st, ast.For):
                counted = self._counted_iter(st.iter, body)
            for s in st.body:
                self._stmt(s, body)
            body = _normalize(body)
            if body or counted:
                out.append(("loop", tuple(body), counted))
            return
        if isinstance(st, ast.If):
            then: list = []
            orelse: list = []
            for s in st.body:
                self._stmt(s, then)
            for s in st.orelse:
                self._stmt(s, orelse)
            then, orelse = _normalize(then), _normalize(orelse)
            if then or orelse:
                out.append(("opt", tuple(then), tuple(orelse)))
            return
        if isinstance(st, (ast.With, ast.AsyncWith)):
            ver = any(
                isinstance(item.context_expr, ast.Call)
                and (call_name(item.context_expr) or "").endswith("versioned")
                for item in st.items
            )
            inner: list = []
            for s in st.body:
                self._stmt(s, inner)
            inner = _normalize(inner)
            if ver:
                out.append(("ver", tuple(inner)))
            else:
                out.extend(inner)
            return
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            return  # nested defs don't run at encode time
        # expression statements / assigns / returns: walk the exprs
        self._expr(st, out)

    # -- expressions ---------------------------------------------------

    def _expr(self, node: ast.AST, out: list) -> None:
        """Evaluation-order walk emitting primitive tokens; loops
        embedded in comprehensions become counted loop nodes."""
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp,
                             ast.DictComp)):
            body: list = []
            counted = False
            for gen in node.generators:
                counted |= self._counted_iter(gen.iter, body)
                for cond in gen.ifs:
                    self._expr(cond, body)
            if isinstance(node, ast.DictComp):
                self._expr(node.key, body)
                self._expr(node.value, body)
            else:
                self._expr(node.elt, body)
            body = _normalize(body)
            if body or counted:
                out.append(("loop", tuple(body), counted))
            return
        if isinstance(node, ast.Call):
            # args first (evaluation order), then the call itself
            emitted = _emit_call(node, self.role, self.helpers, out,
                                 expr_walker=self._expr)
            if emitted:
                return
        for child in ast.iter_child_nodes(node):
            self._expr(child, out)

    def _counted_iter(self, it: ast.AST, body: list) -> bool:
        """``range(dec.u32())``-style iterator: emit nothing (the count
        is part of the loop node) and report counted=True.  A plain
        iterator just gets walked for stray prims."""
        if (isinstance(it, ast.Call) and isinstance(it.func, ast.Name)
                and it.func.id == "range" and len(it.args) == 1):
            arg = it.args[0]
            if (isinstance(arg, ast.Call)
                    and _prim_of(arg, self.role) == "b4"):
                return True
        self._expr(it, body)
        return False


def _prim_of(call: ast.Call, role: str) -> str | None:
    """Wire token when ``call`` is ``enc.<prim>(...)``/``dec.<prim>()``
    for the given role's receiver, else None."""
    name = call_name(call)
    if not name or "." not in name:
        return None
    recv, meth = name.rsplit(".", 1)
    if recv.split(".")[-1] not in ("enc", "dec", "encoder", "decoder"):
        return None
    return _PRIMS.get(meth)


def _emit_call(call: ast.Call, role: str, helpers, out: list,
               expr_walker=None) -> bool:
    """Emit tokens for one call node.  Returns True when the call was
    fully handled (helper splice or primitive)."""
    # helper splice: _enc_x(enc, ...) / _dec_x(dec)
    if isinstance(call.func, ast.Name):
        seq = helpers.get(call.func.id)
        if seq is not None:
            if expr_walker is not None:
                for arg in call.args:
                    if not isinstance(arg, ast.Name):
                        expr_walker(arg, out)
            out.extend(seq)
            return True
    tok = _prim_of(call, role)
    if tok is not None:
        # argument prims evaluate before the write (enc.u32(len(x)))
        if expr_walker is not None:
            for arg in call.args:
                expr_walker(arg, out)
        out.append(("p", tok))
        return True
    # nested struct: any call handed the raw enc/dec object
    # (``o.encode(enc)`` / ``OSDOp.decode(dec)``) is an opaque
    # sub-struct — both sides must have one at the same position
    for arg in call.args:
        if isinstance(arg, ast.Name) and arg.id in (
                "enc", "dec", "encoder", "decoder"):
            if expr_walker is not None:
                for other in call.args:
                    if other is not arg:
                        expr_walker(other, out)
            out.append(("p", "struct"))
            return True
    return False


def _normalize(seq: list) -> list:
    """Counted-loop merge: a ``b4`` write immediately followed by an
    *uncounted* loop is the loop's length prefix (``enc.u32(len(d))``
    + ``for``); fold it in so it matches a decode-side
    ``range(dec.u32())`` loop, whose count is already embedded."""
    out: list = []
    for item in seq:
        if (item[0] == "loop" and not item[2]
                and out and out[-1] == ("p", "b4")):
            out.pop()
            item = (item[0], item[1], True)
        out.append(item)
    return out


def _match(a: tuple, b: tuple) -> bool:
    """Structural sequence match with branch tolerance: an ``opt`` node
    may match the other side's nothing (skipped gate) or either of its
    branches may be compared positionally."""
    return _match_seq(list(a), list(b))


def _match_seq(a: list, b: list) -> bool:
    if not a and not b:
        return True
    # allow an optional group on either side to be skipped or taken
    for x, y in ((a, b), (b, a)):
        if x and x[0][0] == "opt":
            head, rest = x[0], x[1:]
            for branch in (head[1], head[2]):
                if _match_seq(list(branch) + rest, y):
                    return True
            return False
    if not a or not b:
        return False
    ha, hb = a[0], b[0]
    if ha[0] == "p" and hb[0] == "p":
        return ha[1] == hb[1] and _match_seq(a[1:], b[1:])
    if ha[0] == "loop" and hb[0] == "loop":
        # counted flags may differ (length prefix folded on one side)
        return _match_seq(list(ha[1]), list(hb[1])) and _match_seq(
            a[1:], b[1:])
    if ha[0] == "ver" and hb[0] == "ver":
        return _match_seq(list(ha[1]), list(hb[1])) and _match_seq(
            a[1:], b[1:])
    return False


def _render(seq) -> str:
    parts = []
    for item in seq:
        if item[0] == "p":
            parts.append(item[1])
        elif item[0] == "loop":
            parts.append(f"loop[{_render(item[1])}]")
        elif item[0] == "ver":
            parts.append(f"ver[{_render(item[1])}]")
        elif item[0] == "opt":
            parts.append(f"opt[{_render(item[1])}|{_render(item[2])}]")
    return " ".join(parts)


def _is_abstract(fn: ast.FunctionDef) -> bool:
    """encode/decode bodies that only raise (NotImplementedError) are
    the Message base-class stubs, not wire surface."""
    stmts = [s for s in fn.body
             if not (isinstance(s, ast.Expr)
                     and isinstance(s.value, ast.Constant))]
    return all(isinstance(s, ast.Raise) for s in stmts) if stmts else True


def _module_helpers(tree: ast.Module, role: str) -> dict[str, list]:
    """Resolve module-level ``_enc_*``/``_dec_*`` helpers to their wire
    sequences (one level of nesting between helpers is resolved by
    fixpoint iteration)."""
    prefix = "_enc" if role == "enc" else "_dec"
    defs = {
        n.name: n for n in tree.body
        if isinstance(n, ast.FunctionDef) and (
            n.name.startswith(prefix) or n.name.startswith(
                "_encode" if role == "enc" else "_decode"))
    }
    helpers: dict[str, list] = {}
    for _ in range(3):  # helpers calling helpers: tiny fixpoint
        for name, fn in defs.items():
            b = _SeqBuilder(role, helpers)
            helpers[name] = tuple(b.body_seq(fn.body))
    return {k: list(v) for k, v in helpers.items()}


class WireProtocolRule(Rule):
    name = "wire-protocol"
    rules = ("wire-frame-id", "wire-asymmetry")
    catalog = {
        "wire-frame-id":
            "duplicate or unregistered (zero/missing) message TYPE",
        "wire-asymmetry":
            "encode_payload writes a different wire sequence than "
            "decode_payload reads",
    }

    def run(self, project: Project) -> list[Finding]:
        findings: list[Finding] = []
        for sf in project.files:
            classes = [
                n for n in sf.tree.body if isinstance(n, ast.ClassDef)
            ]
            msgs = []
            for cls in classes:
                enc = dec = None
                type_val = type_line = None
                for item in cls.body:
                    if isinstance(item, ast.FunctionDef):
                        if item.name == "encode_payload":
                            enc = item
                        elif item.name == "decode_payload":
                            dec = item
                    elif (isinstance(item, ast.Assign)
                          and len(item.targets) == 1
                          and isinstance(item.targets[0], ast.Name)
                          and item.targets[0].id == "TYPE"
                          and isinstance(item.value, ast.Constant)
                          and isinstance(item.value.value, int)):
                        type_val = item.value.value
                        type_line = item.lineno
                if enc is not None and dec is not None and not (
                        _is_abstract(enc) or _is_abstract(dec)):
                    msgs.append((cls, enc, dec, type_val, type_line))
            if not msgs:
                continue
            findings.extend(self._check_file(sf, msgs))
        return findings

    def _check_file(self, sf, msgs) -> list[Finding]:
        findings: list[Finding] = []
        enc_helpers = _module_helpers(sf.tree, "enc")
        dec_helpers = _module_helpers(sf.tree, "dec")
        by_type: dict[int, list] = {}
        for cls, enc, dec, type_val, type_line in msgs:
            if type_val:
                by_type.setdefault(type_val, []).append((cls, type_line))
            else:
                findings.append(Finding(
                    "wire-frame-id", SEV_ERROR, sf.path, cls.lineno,
                    f"message class {cls.name} has an encode/decode "
                    f"pair but no non-zero TYPE: the messenger registry "
                    f"will never route this frame",
                ))
            e_seq = tuple(_SeqBuilder("enc", enc_helpers).body_seq(enc.body))
            d_seq = tuple(_SeqBuilder("dec", dec_helpers).body_seq(dec.body))
            if not _match(e_seq, d_seq):
                findings.append(Finding(
                    "wire-asymmetry", SEV_ERROR, sf.path, enc.lineno,
                    f"{cls.name}: encode_payload writes "
                    f"[{_render(e_seq)}] but decode_payload reads "
                    f"[{_render(d_seq)}] — a peer decoding this frame "
                    f"mis-frames the payload",
                ))
        for type_val, owners in sorted(by_type.items()):
            if len(owners) > 1:
                names = ", ".join(cls.name for cls, _ in owners)
                cls, line = owners[1]
                findings.append(Finding(
                    "wire-frame-id", SEV_ERROR, sf.path, line or cls.lineno,
                    f"frame id {type_val} claimed by multiple messages "
                    f"({names}): the registry assert fires at import "
                    f"and routing is ambiguous",
                ))
        return findings
