"""Rule family 6: device-residency / transfer discipline.

The zero-copy buffer plane the ROADMAP targets dies by a thousand
quiet host round-trips: a ``np.asarray`` two calls below a launch, a
``bytes()`` on a result that never needed to leave the device, a
re-``device_put`` of data that was already resident, an ``if`` on a
device scalar that stalls the dispatch queue.  BENCH_ALL_r07 charges
most of the batched-vs-host gap to exactly these.  This family rides
the interprocedural engine (:mod:`ceph_tpu.analysis.dataflow`) so a
transfer is caught wherever it hides in the call graph:

- ``device-host-sink`` — a device-resident value reaches a
  host-materializing op (``np.asarray``/``np.array``, ``bytes``,
  ``.tobytes()``/``.tolist()``/``.item()``, ``jax.device_get``)
  inside the I/O-path module set (osd/, parallel/, mgr/analytics.py
  and everything they import).  ``device_get`` counts: it is the
  *sanctioned* exit operator, but every use must be a justified
  by-design host boundary (baseline) — anything else is a hidden
  round-trip the zero-copy plane will pay for.
- ``device-redundant-put`` — ``jax.device_put``/``jnp.asarray`` fed
  an already device-resident value: a no-op at best, a copy at worst.
- ``device-nondonated-inout`` — a buffer both passed into and
  reassigned from a jitted call without a donation declaration in
  ``prewarm_registry.DONATED``: the launch must allocate a second
  output buffer every time instead of aliasing in place.
- ``device-implicit-sync`` — a device value evaluated for control
  flow (``if``/``while``/``assert``/comparison) or through
  ``bool()``/``float()``/``int()``: an implicit blocking sync that
  serializes the dispatch pipeline.
"""

from __future__ import annotations

import ast

from ceph_tpu.analysis.core import (
    SEV_ERROR,
    SEV_WARNING,
    Finding,
    Project,
    Rule,
)
from ceph_tpu.analysis.dataflow import DEVICE, attr_chain, engine_for
from ceph_tpu.analysis.prewarm_registry import DONATED, JIT_ENTRYPOINTS


def _io_path_roots(project: Project) -> set[str]:
    roots = set()
    for sf in project.files:
        if (sf.path.startswith("ceph_tpu/osd/")
                or sf.path.startswith("ceph_tpu/parallel/")
                or sf.path == "ceph_tpu/mgr/analytics.py"):
            roots.add(sf.module)
    return roots


class TransferRule(Rule):
    name = "transfer"
    rules = (
        "device-host-sink",
        "device-redundant-put",
        "device-nondonated-inout",
        "device-implicit-sync",
    )
    catalog = {
        "device-host-sink":
            "device-resident value reaches a host-materializing op "
            "(np.asarray/bytes/tobytes/tolist/device_get) on the I/O "
            "path — declare the host exit or keep the buffer on device",
        "device-redundant-put":
            "device_put/jnp.asarray applied to an already "
            "device-resident value (no-op round-trip)",
        "device-nondonated-inout":
            "buffer passed into and returned from a jitted call "
            "without a prewarm_registry.DONATED declaration",
        "device-implicit-sync":
            "device value evaluated for control flow or via "
            "bool()/float()/int() — an implicit blocking sync",
    }

    def run(self, project: Project) -> list[Finding]:
        engine = engine_for(project)
        roots = _io_path_roots(project)
        scope = project.reachable_from(roots) | roots
        findings: list[Finding] = []
        seen: set[tuple] = set()

        def add(rule: str, sev: str, path: str, line: int,
                msg: str) -> None:
            key = (rule, path, line, msg)
            if key in seen:
                return
            seen.add(key)
            findings.append(Finding(rule, sev, path, line, msg))

        donated_names = {
            key.split(":")[-1].split(".")[-1]: args
            for key, args in DONATED.items()
        }

        for fn in engine.functions_in({sf.module for sf in project.files}):
            where = f"{fn.module}:{fn.qual}"
            in_scope = fn.module in scope

            def on_event(kind, node, payload, fn=fn, where=where,
                         in_scope=in_scope):
                line = getattr(node, "lineno", 1)
                if kind == "host_sink" and in_scope:
                    op, why = payload
                    add("device-host-sink", SEV_ERROR, fn.path, line,
                        f"device-resident value reaches {op} in {where} "
                        f"— {why}; keep the buffer on device across the "
                        f"pipeline or baseline this as a by-design host "
                        f"exit")
                elif kind == "redundant_put":
                    (op,) = payload
                    add("device-redundant-put", SEV_WARNING, fn.path,
                        line,
                        f"{op} applied to an already device-resident "
                        f"value in {where} — the put round-trips a "
                        f"buffer that never left the device; drop it")
                elif kind == "implicit_sync":
                    what, why = payload
                    add("device-implicit-sync", SEV_ERROR, fn.path, line,
                        f"device value evaluated via {what} in {where} "
                        f"— {why}; hoist the predicate into the kernel "
                        f"or fetch the scalar once, explicitly")

            engine.replay(fn, on_event)
            findings_inout = self._inout_pass(
                engine, fn, where, donated_names)
            for f in findings_inout:
                key = (f.rule, f.path, f.line, f.message)
                if key not in seen:
                    seen.add(key)
                    findings.append(f)
        return findings

    # -- device-nondonated-inout --------------------------------------

    def _inout_pass(self, engine, fn, where: str,
                    donated_names: dict) -> list[Finding]:
        out: list[Finding] = []
        for node in ast.walk(fn.node):
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)
                    and len(node.targets) == 1):
                continue
            target = node.targets[0]
            tname = None
            if isinstance(target, ast.Name):
                tname = target.id
            elif isinstance(target, ast.Attribute):
                tname = attr_chain(target)
            if tname is None:
                continue
            call = node.value
            chain = attr_chain(call.func)
            short = chain.split(".")[-1] if chain else None
            fid = engine.graph.resolve(fn, call)
            is_jit = (fid is not None and fid in engine.graph.jit_defs) \
                or (short in JIT_ENTRYPOINTS)
            if not is_jit:
                continue
            for ix, arg in enumerate(call.args):
                aname = None
                if isinstance(arg, ast.Name):
                    aname = arg.id
                elif isinstance(arg, ast.Attribute):
                    aname = attr_chain(arg)
                if aname != tname:
                    continue
                key = fid.replace(":", ":", 1) if fid else None
                donated = (DONATED.get(key, ())
                           if key is not None else ()) \
                    or donated_names.get(short or "", ())
                if ix in donated:
                    continue
                out.append(Finding(
                    "device-nondonated-inout", SEV_WARNING, fn.path,
                    node.lineno,
                    f"buffer {tname!r} is passed into and reassigned "
                    f"from jitted {short}() in {where} without a "
                    f"donation declaration — the launch allocates a "
                    f"fresh output buffer every call; declare the "
                    f"donated arg in prewarm_registry.DONATED (and "
                    f"donate_argnums on the jit) or rename the result",
                ))
        return out
