"""Rule family 1: device discipline.

The runtime invariant is ``cold_launches == 0`` — no XLA compile and no
unplanned device sync inside the I/O path.  Statically that decomposes
into three checks:

- ``device-prewarm`` — every jit/pmap/shard_map site in a module
  reachable (via the import graph) from the I/O-path roots (``osd/``,
  ``parallel/``, ``mgr/analytics.py``) must be declared in
  :mod:`ceph_tpu.analysis.prewarm_registry` with a note naming the
  warmup that compiles it.
- ``device-raw-shape`` — arguments fed to the known jitted entry
  points from I/O-path modules must not contain a raw ``len(...)`` or
  ``.shape`` expression: dynamic dims mint fresh compiled shapes; go
  through ``pow2_bucket`` / ``bucket_lanes``.
- ``device-sync-under-lock`` — no ``block_until_ready`` / ``device_put``
  while a lock is held: a device sync (worse, a compile) under a lock
  serializes every other thread behind XLA.  Calls under the lock are
  resolved through the project call graph
  (:mod:`ceph_tpu.analysis.dataflow`), so a helper that syncs three
  frames below the critical section is caught too, with the chain
  named in the finding.
"""

from __future__ import annotations

import ast

from ceph_tpu.analysis.core import SEV_ERROR, Finding, Project, Rule
from ceph_tpu.analysis.prewarm_registry import (
    BUCKET_HELPERS,
    JIT_ENTRYPOINTS,
    PREWARMED,
)
from ceph_tpu.analysis.rules.common import (
    ScopedVisitor,
    attr_chain,
    call_name,
    is_lockish,
)

#: wrappers whose application creates a compiled program
_JIT_WRAPPERS = {"jax.jit", "jax.pmap", "pjit", "shard_map"}
_SYNC_CALLS = {"block_until_ready", "device_put"}


def _is_jit_wrapper(node: ast.AST) -> bool:
    """True for ``jax.jit`` / ``pjit`` / ``shard_map`` / ``jax.pmap``
    name nodes (exact match on the dotted or bare name — the
    encode_farm facade is itself named ``shard_map``)."""
    chain = attr_chain(node)
    if chain is None:
        return False
    return chain in _JIT_WRAPPERS or chain.split(".")[-1] in {
        "pjit", "pmap"} or chain == "jit" or chain.endswith(".jit")


def _is_partial_of_jit(call: ast.Call) -> bool:
    name = call_name(call)
    if name not in ("functools.partial", "partial"):
        return False
    return bool(call.args) and _is_jit_wrapper(call.args[0])


class _JitSiteVisitor(ScopedVisitor):
    """Collects (qualname, line) of every program-creating site."""

    def __init__(self):
        super().__init__()
        self.sites: list[tuple[str, int]] = []

    def _check_decorators(self, node):
        for dec in node.decorator_list:
            if _is_jit_wrapper(dec):
                self.sites.append(
                    (".".join(self.scope + [node.name]), node.lineno))
            elif isinstance(dec, ast.Call) and (
                    _is_jit_wrapper(dec.func) or _is_partial_of_jit(dec)):
                self.sites.append(
                    (".".join(self.scope + [node.name]), node.lineno))

    def visit_FunctionDef(self, node):
        self._check_decorators(node)
        self._push(node)

    def visit_AsyncFunctionDef(self, node):
        self._check_decorators(node)
        self._push(node)

    def visit_Call(self, node):
        if _is_jit_wrapper(node.func):
            self.sites.append((self.qualname, node.lineno))
        self.generic_visit(node)


def _io_path_roots(project: Project) -> set[str]:
    roots = set()
    for sf in project.files:
        if (sf.path.startswith("ceph_tpu/osd/")
                or sf.path.startswith("ceph_tpu/parallel/")
                or sf.path == "ceph_tpu/mgr/analytics.py"):
            roots.add(sf.module)
    return roots


class DeviceDisciplineRule(Rule):
    name = "device-discipline"
    rules = ("device-prewarm", "device-raw-shape", "device-sync-under-lock")
    catalog = {
        "device-prewarm":
            "jit/pmap/shard_map site reachable from the I/O path is "
            "not declared in the prewarm registry",
        "device-raw-shape":
            "raw len()/.shape fed to a jitted entry point instead of a "
            "pow2-bucketed dimension",
        "device-sync-under-lock":
            "block_until_ready/device_put while holding a lock",
    }

    def run(self, project: Project) -> list[Finding]:
        findings: list[Finding] = []
        roots = _io_path_roots(project)
        reachable = project.reachable_from(roots) | roots
        mods = project.by_module()

        # -- device-prewarm ---------------------------------------------
        for mod in sorted(reachable):
            sf = mods.get(mod)
            if sf is None:
                continue
            v = _JitSiteVisitor()
            v.visit(sf.tree)
            for qual, line in v.sites:
                key = f"{mod}:{qual}"
                if key not in PREWARMED:
                    findings.append(Finding(
                        "device-prewarm", SEV_ERROR, sf.path, line,
                        f"jitted callable {key} is not in the prewarm "
                        f"registry (ceph_tpu/analysis/prewarm_registry."
                        f"py) — declare which warmup compiles it, or it "
                        f"will compile inside the I/O path",
                    ))

        # stale registry entries point at renamed/removed kernels —
        # only meaningful when the project actually contains the
        # registry module (fixture projects don't)
        cfg_path = "ceph_tpu/analysis/prewarm_registry.py"
        if any(sf.path == cfg_path for sf in project.files):
            live_keys = set()
            for mod in mods:
                v = _JitSiteVisitor()
                v.visit(mods[mod].tree)
                live_keys |= {f"{mod}:{q}" for q, _ in v.sites}
            for key in sorted(set(PREWARMED) - live_keys):
                findings.append(Finding(
                    "device-prewarm", SEV_ERROR, cfg_path, 1,
                    f"prewarm registry entry {key} matches no jit site "
                    f"in the tree (renamed or removed kernel?)",
                ))

        # -- device-raw-shape / device-sync-under-lock ------------------
        from ceph_tpu.analysis.dataflow import engine_for

        engine = engine_for(project)
        for sf in project.files:
            in_io_path = sf.module in roots
            findings.extend(_scan_module(sf, in_io_path, engine))
        return findings


def _scan_module(sf, in_io_path: bool, engine=None) -> list[Finding]:
    findings: list[Finding] = []
    seen: set[tuple] = set()

    class V(ScopedVisitor):
        def __init__(self):
            super().__init__()
            self.lock_depth = 0

        def visit_With(self, node):
            held = sum(
                1 for item in node.items if is_lockish(item.context_expr))
            self.lock_depth += held
            self.generic_visit(node)
            self.lock_depth -= held

        visit_AsyncWith = visit_With

        def _check_callee_syncs(self, node, name: str) -> None:
            """Call-graph pass: the callee (transitively, bounded
            depth) forces a device sync while our lock is held."""
            if engine is None:
                return
            caller = _enclosing(engine, sf.module, self.qualname)
            if caller is None:
                return
            fid = engine.graph.resolve(caller, node)
            if fid is None:
                return
            hit = engine.may_sync(fid)
            if hit is None:
                return
            sync, chain = hit
            callee = engine.graph.functions[fid]
            via = " -> ".join(
                f"{c}()" for c in (callee.name,) + tuple(
                    x for x in chain if x != callee.name))
            key = (sf.path, name, via)
            if key in seen:
                return
            seen.add(key)
            findings.append(Finding(
                "device-sync-under-lock", SEV_ERROR, sf.path,
                node.lineno,
                f"call to {name}() while holding a lock in "
                f"{sf.module}:{self.qualname} — {via} forces a device "
                f"sync (via the call graph); every waiter stalls "
                f"behind XLA; move the launch outside the critical "
                f"section",
            ))

        def visit_Call(self, node):
            name = call_name(node)
            short = name.split(".")[-1] if name else None
            if self.lock_depth and short in _SYNC_CALLS:
                findings.append(Finding(
                    "device-sync-under-lock", SEV_ERROR, sf.path,
                    node.lineno,
                    f"{short}() while holding a lock in "
                    f"{sf.module}:{self.qualname} — a device sync (or "
                    f"compile) under a lock stalls every waiter; move "
                    f"the launch outside the critical section",
                ))
            elif self.lock_depth and name is not None:
                self._check_callee_syncs(node, name)
            if in_io_path and short in JIT_ENTRYPOINTS:
                for arg in list(node.args) + [k.value for k in node.keywords]:
                    bad = _raw_dim(arg)
                    if bad is not None:
                        findings.append(Finding(
                            "device-raw-shape", SEV_ERROR, sf.path,
                            bad.lineno,
                            f"argument of jitted entry point {short}() "
                            f"in {sf.module}:{self.qualname} contains a "
                            f"raw {_describe(bad)} — dynamic dims mint "
                            f"new compiled shapes; route the size "
                            f"through pow2_bucket()/bucket_lanes()",
                        ))
                        break
            self.generic_visit(node)

    V().visit(sf.tree)
    return findings


def _enclosing(engine, module: str, qualname: str):
    """FunctionInfo for the visitor's scope chain (longest known def
    prefix), shared shape with rules/locks.py."""
    if qualname == "<module>":
        return None
    parts = qualname.split(".")
    for end in range(len(parts), 0, -1):
        fid = f"{module}:{'.'.join(parts[:end])}"
        fn = engine.graph.functions.get(fid)
        if fn is not None:
            return fn
    return None


def _raw_dim(arg: ast.AST) -> ast.AST | None:
    """First raw ``len(...)`` call or ``.shape`` access in the argument
    expression that is not wrapped by a bucket helper."""
    guarded: set[int] = set()
    for sub in ast.walk(arg):
        if isinstance(sub, ast.Call):
            name = call_name(sub)
            if name and name.split(".")[-1] in BUCKET_HELPERS:
                for inner in ast.walk(sub):
                    guarded.add(id(inner))
    for sub in ast.walk(arg):
        if id(sub) in guarded:
            continue
        if (isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Name)
                and sub.func.id == "len"):
            return sub
        if isinstance(sub, ast.Attribute) and sub.attr == "shape":
            return sub
    return None


def _describe(node: ast.AST) -> str:
    return "len() call" if isinstance(node, ast.Call) else ".shape access"
