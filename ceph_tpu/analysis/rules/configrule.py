"""Rule family 4: config registry hygiene.

``common/config.py`` is the single source of truth for options — a
read of an undeclared key raises ``KeyError`` at runtime (but only
when that code path runs), and a declared option nothing reads is
documentation debt pretending to be a knob.

- ``config-undeclared`` — every literal config-key read
  (``conf["k"]`` / ``conf.get("k")`` / observer registration /
  ``DoutLogger("sub", ...)`` implying ``debug_<sub>``) must name a
  declared Option.
- ``config-dead`` — every declared Option must be read somewhere in
  the tree (``ceph_tpu/`` plus the tools/tests evidence set; env
  ``CEPH_TPU_<KEY>`` references count).
"""

from __future__ import annotations

import ast
import re

from ceph_tpu.analysis.core import SEV_ERROR, SEV_WARNING, Finding, Project, Rule
from ceph_tpu.analysis.rules.common import call_name, last_name

CONFIG_MODULE = "ceph_tpu/common/config.py"

#: receivers treated as a ConfigProxy (exact last-segment match)
_CONF_NAMES = {"conf", "conf0", "config", "cfg", "sc_conf", "mon_conf"}

_ENV_RE = re.compile(r"CEPH_TPU_([A-Z0-9_]{3,})")


def _conf_receiver(node: ast.AST) -> bool:
    return last_name(node) in _CONF_NAMES


def _literal_key(node: ast.AST) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def collect_declared(project: Project) -> dict[str, tuple[str, int]]:
    """Option name -> (path, line), parsed statically from
    ``Option("name", ...)`` calls (in the live tree these all live in
    ``common/config.py``; fixture projects declare inline)."""
    out: dict[str, tuple[str, int]] = {}
    for sf in project.files:
        for node in ast.walk(sf.tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "Option" and node.args):
                key = _literal_key(node.args[0])
                if key:
                    out.setdefault(key, (sf.path, node.lineno))
    return out


def collect_reads(sf) -> tuple[list[tuple[str, int]], list[tuple[str, int]]]:
    """(proxy_reads, env_reads) as (key, line) lists.  Proxy reads are
    subject to the undeclared check; env spellings
    (``CEPH_TPU_<KEY>``) only count as liveness *evidence* — raw
    ``os.environ`` knobs that deliberately bypass the config system
    (compile-cache switches, pre-config constants) are not findings."""
    reads: list[tuple[str, int]] = []
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Subscript) and _conf_receiver(node.value):
            key = _literal_key(node.slice)
            if key:
                reads.append((key, node.lineno))
        elif isinstance(node, ast.Call):
            name = call_name(node)
            if not name:
                continue
            parts = name.split(".")
            meth = parts[-1]
            recv_ok = len(parts) > 1 and parts[-2] in _CONF_NAMES
            if recv_ok and meth in ("get", "set", "rm") and node.args:
                key = _literal_key(node.args[0])
                if key:
                    reads.append((key, node.lineno))
            elif recv_ok and meth == "add_observer" and node.args:
                arg = node.args[0]
                if isinstance(arg, (ast.List, ast.Tuple)):
                    for el in arg.elts:
                        key = _literal_key(el)
                        if key:
                            reads.append((key, node.lineno))
            elif recv_ok and meth == "apply_changes" and node.args:
                arg = node.args[0]
                if isinstance(arg, ast.Dict):
                    for k in arg.keys:
                        key = _literal_key(k)
                        if key:
                            reads.append((key, node.lineno))
            elif meth in ("DoutLogger", "Dout") and node.args:
                sub = _literal_key(node.args[0])
                if sub:
                    reads.append((f"debug_{sub}", node.lineno))
    env_reads: list[tuple[str, int]] = []
    for i, line in enumerate(sf.lines, start=1):
        for m in _ENV_RE.finditer(line):
            env_reads.append((m.group(1).lower(), i))
    return reads, env_reads


class ConfigRegistryRule(Rule):
    name = "config-registry"
    rules = ("config-undeclared", "config-dead")
    catalog = {
        "config-undeclared":
            "config key read without a registered Option default "
            "(KeyError the first time that path runs)",
        "config-dead":
            "registered Option that nothing in the tree reads",
    }

    def run(self, project: Project) -> list[Finding]:
        findings: list[Finding] = []
        declared = collect_declared(project)
        if not declared:
            return findings  # fixture projects without a config module
        aux_ids = {id(sf) for sf in project.aux_files}
        read_keys: set[str] = set()
        for sf in project.files + project.aux_files:
            reads, env_reads = collect_reads(sf)
            read_keys |= {k for k, _ in reads}
            read_keys |= {k for k, _ in env_reads}
            if id(sf) in aux_ids:
                continue
            for key, line in reads:
                if key not in declared:
                    findings.append(Finding(
                        "config-undeclared", SEV_ERROR, sf.path, line,
                        f"config key {key!r} is read but not declared "
                        f"in common/config.py OPTIONS — this raises "
                        f"KeyError the first time the path runs",
                    ))
        for key, (path, line) in sorted(declared.items()):
            if key not in read_keys:
                findings.append(Finding(
                    "config-dead", SEV_WARNING, path, line,
                    f"option {key!r} is declared but never read "
                    f"anywhere in the tree — wire it up or delete it",
                ))
        return findings
