"""Rule family 2: lock order and blocking-under-lock.

- ``lock-cycle`` — the cross-module lock-acquisition graph (built from
  lexically nested ``with <lock>`` blocks and ``.acquire()`` calls
  under a held lock) must be acyclic; a cycle is a potential deadlock
  the interleave fuzzer can only find by luck.
- ``lock-blocking`` — no blocking call (sleep, fsync, subprocess,
  socket send, dynamic import, store commit) while a lock is held.
  One level of same-module call inlining is applied, so a method that
  takes a lock and then calls a sibling that blocks is still caught.
"""

from __future__ import annotations

import ast

from ceph_tpu.analysis.core import SEV_ERROR, SEV_WARNING, Finding, Project, Rule
from ceph_tpu.analysis.rules.common import (
    ScopedVisitor,
    call_name,
    is_lockish,
    lock_ident,
)

#: dotted (or trailing) call names that block the calling thread
_BLOCKING = {
    "time.sleep": "sleeps",
    "os.fsync": "does disk I/O (fsync)",
    "os.fdatasync": "does disk I/O (fdatasync)",
    "subprocess.run": "spawns a process",
    "subprocess.check_call": "spawns a process",
    "subprocess.check_output": "spawns a process",
    "subprocess.Popen": "spawns a process",
    "importlib.import_module": "does a dynamic import (module-level "
                               "code + disk I/O)",
    "socket.create_connection": "does network I/O",
}
#: method names that block regardless of receiver
_BLOCKING_METHODS = {
    "sendall": "does network I/O",
    "apply_transaction": "commits to the store",
    "queue_transaction": "commits to the store",
}


def _blocking_reason(name: str | None) -> str | None:
    if not name:
        return None
    if name in _BLOCKING:
        return _BLOCKING[name]
    short = name.split(".")[-1]
    # match dotted suffixes like self._sock.sendall
    for dotted, why in _BLOCKING.items():
        if name.endswith("." + dotted):
            return why
    return _BLOCKING_METHODS.get(short)


class _LockVisitor(ScopedVisitor):
    """Per-module pass: collects acquisition-order edges, blocking
    calls under locks, and (for the inlining pass) which functions
    block or lock internally."""

    def __init__(self, sf):
        super().__init__()
        self.sf = sf
        self.held: list[tuple[str, int]] = []   # (lock ident, line)
        self.edges: list[tuple[str, str, str, int]] = []  # a, b, path, line
        self.blocking: list[tuple[str, int, str]] = []
        #: qualname -> (reason, line) for defs that block unconditionally
        self.fn_blocks: dict[str, tuple[str, int]] = {}
        #: qualname -> lock idents the def acquires
        self.fn_locks: dict[str, list[tuple[str, int]]] = {}
        #: calls made under a held lock: (callee short name, line,
        #: holder qualname) — resolved against fn_blocks/fn_locks later
        self.calls_under_lock: list[tuple[str, int]] = []

    def _enter_locks(self, node) -> int:
        n = 0
        for item in node.items:
            if is_lockish(item.context_expr):
                ident = lock_ident(
                    self.sf.module, self.scope, item.context_expr)
                if self.held:
                    self.edges.append((
                        self.held[-1][0], ident, self.sf.path, node.lineno))
                self.held.append((ident, node.lineno))
                n += 1
        return n

    def visit_With(self, node):
        n = self._enter_locks(node)
        self.generic_visit(node)
        if n:
            del self.held[-n:]

    visit_AsyncWith = visit_With

    def visit_Call(self, node):
        name = call_name(node)
        short = name.split(".")[-1] if name else None
        if short == "acquire" and name and is_lockish(node.func.value):
            ident = lock_ident(self.sf.module, self.scope, node.func.value)
            if self.held:
                self.edges.append((
                    self.held[-1][0], ident, self.sf.path, node.lineno))
        if self.held:
            reason = _blocking_reason(name)
            if reason is not None:
                self.blocking.append((name, node.lineno, reason))
            elif name and name.startswith("self."):
                self.calls_under_lock.append((short, node.lineno))
        else:
            reason = _blocking_reason(name)
            if reason is not None and self.scope:
                self.fn_blocks.setdefault(
                    self.scope[-1], (reason, node.lineno))
        self.generic_visit(node)


class LockOrderRule(Rule):
    name = "lock-order"
    rules = ("lock-cycle", "lock-blocking")
    catalog = {
        "lock-cycle":
            "cycle in the cross-module lock-acquisition graph "
            "(potential deadlock)",
        "lock-blocking":
            "blocking call (sleep/fsync/subprocess/import/commit) "
            "while holding a lock",
    }

    def run(self, project: Project) -> list[Finding]:
        findings: list[Finding] = []
        edges: dict[str, set[str]] = {}
        edge_at: dict[tuple[str, str], tuple[str, int]] = {}
        visitors = []
        for sf in project.files:
            v = _LockVisitor(sf)
            v.visit(sf.tree)
            visitors.append(v)
            for a, b, path, line in v.edges:
                if a == b:
                    continue  # re-entrant nesting of one lock: RLock
                edges.setdefault(a, set()).add(b)
                edge_at.setdefault((a, b), (path, line))
            for name, line, reason in v.blocking:
                findings.append(Finding(
                    "lock-blocking", SEV_ERROR, sf.path, line,
                    f"{name}() under a held lock {reason} — every "
                    f"other acquirer stalls behind it; shrink the "
                    f"critical section",
                ))
            # one-level inlining: self.<m>() under a lock where <m>
            # blocks in its own body (same module)
            for short, line in v.calls_under_lock:
                hit = v.fn_blocks.get(short)
                if hit is not None:
                    reason, _ = hit
                    findings.append(Finding(
                        "lock-blocking", SEV_WARNING, sf.path, line,
                        f"call to self.{short}() under a held lock — "
                        f"{short}() {reason} (defined in this module); "
                        f"the lock is held across that",
                    ))

        for cycle in _cycles(edges):
            a, b = cycle[0], cycle[1 % len(cycle)]
            path, line = edge_at.get((a, b), ("ceph_tpu", 1))
            findings.append(Finding(
                "lock-cycle", SEV_ERROR, path, line,
                "lock-order cycle: " + " -> ".join(cycle + [cycle[0]]),
            ))
        return findings


def _cycles(edges: dict[str, set[str]]) -> list[list[str]]:
    """Elementary cycles via DFS; each reported once, rotated so the
    lexicographically smallest node leads (stable messages)."""
    seen: set[tuple[str, ...]] = set()
    out: list[list[str]] = []

    def dfs(start: str, node: str, path: list[str], visited: set[str]):
        for nxt in sorted(edges.get(node, ())):
            if nxt == start:
                i = path.index(min(path))
                canon = tuple(path[i:] + path[:i])
                if canon not in seen:
                    seen.add(canon)
                    out.append(list(canon))
            elif nxt not in visited and nxt > start:
                # only walk nodes > start: each cycle found exactly
                # once, from its smallest member
                dfs(start, nxt, path + [nxt], visited | {nxt})

    for start in sorted(edges):
        dfs(start, start, [start], {start})
    return out
