"""Rule family 2: lock order and blocking-under-lock.

- ``lock-cycle`` — the cross-module lock-acquisition graph (built from
  lexically nested ``with <lock>`` blocks and ``.acquire()`` calls
  under a held lock) must be acyclic; a cycle is a potential deadlock
  the interleave fuzzer can only find by luck.
- ``lock-blocking`` — no blocking call (sleep, fsync, subprocess,
  socket send, dynamic import, store commit) while a lock is held.
  Calls under a lock are resolved through the project call graph
  (:mod:`ceph_tpu.analysis.dataflow`: ``self.method``, module
  functions, imported functions, class methods across modules), so a
  method that takes a lock and then calls a helper three frames away
  that blocks is still caught — the chain that blocks is named in the
  finding.  Depth is bounded by the engine's
  ``CEPH_TPU_CTLINT_TRANSFER_MAX_DEPTH`` rounds; deeper chains widen
  to "not proven" rather than slowing the lint down.
"""

from __future__ import annotations

from ceph_tpu.analysis.core import SEV_ERROR, SEV_WARNING, Finding, Project, Rule
from ceph_tpu.analysis.dataflow import (
    BLOCKING_CALLS,
    BLOCKING_METHODS,
    engine_for,
)
from ceph_tpu.analysis.rules.common import (
    ScopedVisitor,
    call_name,
    is_lockish,
    lock_ident,
)

# the seed sets live in dataflow (shared with the summary pass); kept
# importable here for back-compat with older rule consumers
_BLOCKING = BLOCKING_CALLS
_BLOCKING_METHODS = BLOCKING_METHODS


def _blocking_reason(name: str | None) -> str | None:
    if not name:
        return None
    if name in _BLOCKING:
        return _BLOCKING[name]
    # match dotted suffixes like self._sock.sendall
    for dotted, why in _BLOCKING.items():
        if name.endswith("." + dotted):
            return why
    return _BLOCKING_METHODS.get(name.split(".")[-1])


class _LockVisitor(ScopedVisitor):
    """Per-module pass: collects acquisition-order edges, blocking
    calls under locks, and every call made under a held lock (for the
    call-graph resolution pass)."""

    def __init__(self, sf):
        super().__init__()
        self.sf = sf
        self.held: list[tuple[str, int]] = []   # (lock ident, line)
        self.edges: list[tuple[str, str, str, int]] = []  # a, b, path, line
        self.blocking: list[tuple[str, int, str]] = []
        #: calls made under a held lock, for interprocedural
        #: resolution: (call node, display name, line, holder qualname)
        self.calls_under_lock: list[tuple] = []

    def _enter_locks(self, node) -> int:
        n = 0
        for item in node.items:
            if is_lockish(item.context_expr):
                ident = lock_ident(
                    self.sf.module, self.scope, item.context_expr)
                if self.held:
                    self.edges.append((
                        self.held[-1][0], ident, self.sf.path, node.lineno))
                self.held.append((ident, node.lineno))
                n += 1
        return n

    def visit_With(self, node):
        n = self._enter_locks(node)
        self.generic_visit(node)
        if n:
            del self.held[-n:]

    visit_AsyncWith = visit_With

    def visit_Call(self, node):
        name = call_name(node)
        short = name.split(".")[-1] if name else None
        if short == "acquire" and name and is_lockish(node.func.value):
            ident = lock_ident(self.sf.module, self.scope, node.func.value)
            if self.held:
                self.edges.append((
                    self.held[-1][0], ident, self.sf.path, node.lineno))
        if self.held:
            reason = _blocking_reason(name)
            if reason is not None:
                self.blocking.append((name, node.lineno, reason))
            elif name is not None:
                self.calls_under_lock.append(
                    (node, name, node.lineno, self.qualname))
        self.generic_visit(node)


class LockOrderRule(Rule):
    name = "lock-order"
    rules = ("lock-cycle", "lock-blocking")
    catalog = {
        "lock-cycle":
            "cycle in the cross-module lock-acquisition graph "
            "(potential deadlock)",
        "lock-blocking":
            "blocking call (sleep/fsync/subprocess/import/commit) "
            "while holding a lock — directly or via the call graph",
    }

    def run(self, project: Project) -> list[Finding]:
        findings: list[Finding] = []
        engine = engine_for(project)
        edges: dict[str, set[str]] = {}
        edge_at: dict[tuple[str, str], tuple[str, int]] = {}
        by_module = {sf.module: sf for sf in project.files}
        for sf in project.files:
            v = _LockVisitor(sf)
            v.visit(sf.tree)
            for a, b, path, line in v.edges:
                if a == b:
                    continue  # re-entrant nesting of one lock: RLock
                edges.setdefault(a, set()).add(b)
                edge_at.setdefault((a, b), (path, line))
            for name, line, reason in v.blocking:
                findings.append(Finding(
                    "lock-blocking", SEV_ERROR, sf.path, line,
                    f"{name}() under a held lock {reason} — every "
                    f"other acquirer stalls behind it; shrink the "
                    f"critical section",
                ))
            # call-graph pass: a call under a lock whose resolved
            # callee (transitively, bounded depth) blocks
            seen: set[tuple] = set()
            for node, name, line, holder in v.calls_under_lock:
                caller = self._enclosing(engine, sf.module, holder)
                if caller is None:
                    continue
                fid = engine.graph.resolve(caller, node)
                if fid is None:
                    continue
                hit = engine.may_block(fid)
                if hit is None:
                    continue
                reason, chain = hit
                callee = engine.graph.functions[fid]
                via = " -> ".join(
                    f"{c}()" for c in (callee.name,) + tuple(
                        x for x in chain if x != callee.name))
                key = ("lock-blocking", sf.path, name, via)
                if key in seen:
                    continue
                seen.add(key)
                findings.append(Finding(
                    "lock-blocking", SEV_WARNING, sf.path, line,
                    f"call to {name}() under a held lock — {via} "
                    f"{reason} (via the call graph); the lock is held "
                    f"across that",
                ))

        for cycle in _cycles(edges):
            a, b = cycle[0], cycle[1 % len(cycle)]
            path, line = edge_at.get((a, b), ("ceph_tpu", 1))
            findings.append(Finding(
                "lock-cycle", SEV_ERROR, path, line,
                "lock-order cycle: " + " -> ".join(cycle + [cycle[0]]),
            ))
        _ = by_module
        return findings

    @staticmethod
    def _enclosing(engine, module: str, qualname: str):
        """FunctionInfo whose qualname matches the visitor scope chain
        (longest prefix of the scope that is a known def)."""
        if qualname == "<module>":
            return None
        parts = qualname.split(".")
        for end in range(len(parts), 0, -1):
            fid = f"{module}:{'.'.join(parts[:end])}"
            fn = engine.graph.functions.get(fid)
            if fn is not None:
                return fn
        return None


def _cycles(edges: dict[str, set[str]]) -> list[list[str]]:
    """Elementary cycles via DFS; each reported once, rotated so the
    lexicographically smallest node leads (stable messages)."""
    seen: set[tuple[str, ...]] = set()
    out: list[list[str]] = []

    def dfs(start: str, node: str, path: list[str], visited: set[str]):
        for nxt in sorted(edges.get(node, ())):
            if nxt == start:
                i = path.index(min(path))
                canon = tuple(path[i:] + path[:i])
                if canon not in seen:
                    seen.add(canon)
                    out.append(list(canon))
            elif nxt not in visited and nxt > start:
                # only walk nodes > start: each cycle found exactly
                # once, from its smallest member
                dfs(start, nxt, path + [nxt], visited | {nxt})

    for start in sorted(edges):
        dfs(start, start, [start], {start})
    return out
