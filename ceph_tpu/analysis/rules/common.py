"""Shared AST helpers for the rule modules."""

from __future__ import annotations

import ast
import re

#: names that look like locks when used as a ``with`` context or
#: ``.acquire()`` receiver
LOCKISH_RE = re.compile(r"(^|_)(lock|mutex)s?$", re.IGNORECASE)


def attr_chain(node: ast.AST) -> str | None:
    """``a.b.c`` -> "a.b.c"; None for anything not a pure name chain."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def last_name(node: ast.AST) -> str | None:
    """The rightmost identifier of a name/attribute chain."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def is_lockish(node: ast.AST) -> bool:
    name = last_name(node)
    return bool(name and LOCKISH_RE.search(name))


def lock_ident(sf_module: str, scope: list[str], node: ast.AST) -> str:
    """Stable identity for a lock object: ``self._lock`` inside class C
    -> ``module.C._lock``; a module-global -> ``module.NAME``."""
    chain = attr_chain(node) or "?"
    cls = next((s for s in scope if s[:1].isupper()), None)
    if chain.startswith("self."):
        owner = f"{sf_module}.{cls}" if cls else sf_module
        return f"{owner}.{chain[5:]}"
    return f"{sf_module}.{chain}"


class ScopedVisitor(ast.NodeVisitor):
    """NodeVisitor that tracks the enclosing def/class qualname chain
    in ``self.scope`` (list of names, classes included)."""

    def __init__(self):
        self.scope: list[str] = []

    def _push(self, node):
        self.scope.append(node.name)
        self.generic_visit(node)
        self.scope.pop()

    visit_FunctionDef = _push
    visit_AsyncFunctionDef = _push
    visit_ClassDef = _push

    @property
    def qualname(self) -> str:
        return ".".join(self.scope) or "<module>"

    def func_qualname(self) -> str:
        """Qualname of just the def chain (classes included) — matches
        the prewarm-registry key style."""
        return ".".join(self.scope) or "<module>"


def call_name(call: ast.Call) -> str | None:
    """Full dotted name of a call target, or None."""
    return attr_chain(call.func)


def contains_call_to(node: ast.AST, names: set[str]) -> ast.Call | None:
    """First descendant Call whose dotted or last name is in ``names``."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            dotted = call_name(sub)
            if dotted and (dotted in names or dotted.split(".")[-1] in names):
                return sub
    return None
