"""Declared prewarm registry — the static twin of ``cold_launches == 0``.

Every ``jax.jit`` / ``pmap`` / ``shard_map``-wrapped callable reachable
from the I/O-path modules (``osd/``, ``parallel/``,
``mgr/analytics.py``) must appear here, keyed ``module:qualname``, with
a note saying WHICH warmup path compiles it before the I/O path can
reach it.  The device-discipline rule (``device-prewarm``) fails the
lint when a reachable jit site is missing — so adding a new kernel
forces the author to either wire it into a warmup or consciously
register why it cannot compile mid-I/O.

Keep the runtime invariant in mind when editing: an entry here is a
*claim* that chaos' ``cold_launches`` gate stays green; the claim is
checked by ``tools/chaos_run.py`` and the batcher tests, not by ctlint.
"""

from __future__ import annotations

#: ``module:qualname`` of the jit/shard_map site -> which warmup covers
#: it (or why it is allowed to compile outside the I/O path).
PREWARMED: dict[str, str] = {
    "ceph_tpu.ops.rs_kernels:gf_bitmatmul":
        "decode/scrub batcher prewarm() + encode_service prewarm() "
        "compile every (signature, batch, bucket) shape at EC map-"
        "install warmup (osd/daemon.py _ec_warmup)",
    "ceph_tpu.ops.rs_kernels:gf_encode_compare":
        "scrub_batcher.prewarm() compiles the full bucket ladder at EC "
        "warmup; the scrub I/O path only ever launches warmed shapes",
    "ceph_tpu.ops.rs_kernels:gf_bitmatmul_pallas_grouped":
        "bench/experimental Pallas path; not dispatched by the I/O "
        "path (ec_benchmark + perf labs call it directly)",
    "ceph_tpu.ops.rs_kernels:gf_bitmatmul_pallas":
        "bench/experimental Pallas path; not dispatched by the I/O path",
    "ceph_tpu.ops.rs_kernels:gf_bitmatmul_pallas_acc":
        "bench/experimental Pallas path; not dispatched by the I/O path",
    "ceph_tpu.ops.hashing:_crc_kernel_jit.kern":
        "scrub_batcher.prewarm() compiles every (crc_lanes, bucket) "
        "shape at EC warmup; lru_cache(1) keeps one program per process",
    "ceph_tpu.mgr.analytics:AnalyticsEngine._build_jit":
        "AnalyticsEngine.prewarm() compiles the single fixed (D, M, W) "
        "shape at mgr start (mgr/daemon.py), before any digest pass",
    "ceph_tpu.crush.jaxmapper:BatchedRuleMapper._build":
        "compiled once per (map, rule) at mapper construction — remap "
        "builds mappers at map-install/peering, never per-op; the "
        "executable is reused across epochs (osd/remap.py)",
    "ceph_tpu.ec.plugins.clay_jit:ClayRepairProgram.__init__":
        "CLAY repair programs are staged per (profile, lost-node) at "
        "recovery planning time via stage(), outside the shard-read "
        "critical path; executables persist in the XLA disk cache",
    "ceph_tpu.parallel.encode_farm:batch_encode_dp._encode":
        "encode_service.prewarm() drives the farm over every warmed "
        "(bucket, batch) shape at EC map-install warmup",
    "ceph_tpu.parallel.encode_farm:sharded_encode_tp._encode":
        "encode_service.prewarm() covers the tensor-parallel path for "
        "the shapes the farm selects it for",
}

#: host-side entry points that dispatch straight into a jitted program:
#: the device-shape rule (``device-raw-shape``) flags call sites in
#: I/O-path modules that feed these a raw ``len()``/``.shape`` derived
#: dimension instead of a pow2-bucketed one.
JIT_ENTRYPOINTS: frozenset[str] = frozenset({
    "gf_bitmatmul",
    "gf_encode_compare",
    "gf_bitmatmul_pallas",
    "gf_bitmatmul_pallas_acc",
    "gf_bitmatmul_pallas_grouped",
    "batched_crc32c_device",
    "batch_encode_dp",
    "sharded_encode_tp",
})

#: the pow2-bucket helpers whose outputs are legitimate launch
#: dimensions (the shape-discipline allowlist)
BUCKET_HELPERS: frozenset[str] = frozenset({
    "pow2_bucket",
    "bucket_lanes",
})

#: donation declarations: ``module:qualname`` of a jitted callable ->
#: positional-arg indices whose buffers the launch may consume
#: (``donate_argnums`` / ``input_output_aliases``).  The transfer rule
#: ``device-nondonated-inout`` flags an in-place update pattern
#: (``x = kernel(..., x, ...)``) whose arg is NOT declared here: every
#: such launch silently allocates a second output buffer.  An entry is
#: a *claim* that the kernel really aliases the buffer (pallas
#: input_output_aliases or jit donate_argnums) — keep the two in sync.
DONATED: dict[str, tuple[int, ...]] = {
    # carry is aliased to the output (input_output_aliases={3: 0} on
    # the inner pallas_call; python-signature position 2)
    "ceph_tpu.ops.rs_kernels:gf_bitmatmul_pallas_acc": (2,),
}

#: declared analytics columns: the gauge names expected to occupy
#: metric slots of the mgr's fixed-shape (daemons x metrics x window)
#: time-series store.  The mgr RESERVES these slots at start
#: (TimeSeriesStore.reserve), so adding a column here both documents
#: it and guarantees it can never be overflow-dropped by transient
#: metrics racing for slots — the declaration the "fixed shape, never
#: resized" prewarm contract requires before a new column may feed
#: the digest (e.g. the progress module's degraded/misplaced EWMAs).
#: mgr_stats_max_metrics must stay >= len(ANALYTICS_COLUMNS).
ANALYTICS_COLUMNS: tuple[str, ...] = (
    "read_lat_us",
    "write_lat_us",
    "subop_w_lat_us",
    "num_pgs",
    "inflight_ops",
    "slow_ops",
    "slow_ops_inflight",
    # event-plane columns (PR 8): cluster-log/progress ETA inputs —
    # integer-exact EWMA of degraded/misplaced PG counts rides the
    # same ONE-launch digest
    "pgs_degraded",
    "pgs_misplaced",
    # load-harness column (loadgen/driver.py): the driver's interval-
    # mean op latency, ingested from its loadgen.* MgrClient session
    # and served back via `mgr digest` for the client-vs-mgr
    # cross-check — slot-reserved so transient metrics can never
    # overflow-drop the series the check depends on
    "load_lat_us",
)
