"""ctlint dataflow: project call graph + device-residency analysis.

ctlint's first generation (rules/device.py, rules/locks.py) was
intraprocedural: it could prove "no sync on THIS line under THIS
lock" but not "this helper, two calls away, materializes the device
buffer you just launched".  This module is the missing middle layer —
the program-shaped view of the package that XOR-schedule optimization
(arXiv 2108.02692) applies dynamically, applied statically:

- :class:`CallGraph` — resolves ``self.method``, module-level
  functions, ``from x import f`` aliases and ``module.func`` chains
  into ``module:qualname`` function ids (the prewarm-registry key
  style), on top of the import-graph reachability ``core.Project``
  already provides;
- **device-residency taint** — a forward abstract interpretation per
  function over the 4-value domain {HOST, DEVICE, DEVICE_FN, TOP}:
  sources are ``jnp.*`` constructors, ``jax.device_put``, calls of
  jit/pmap/shard_map-wrapped callables (the sites the prewarm
  registry declares) and calls of functions summarized as
  device-returning; the taint propagates through assignments, tuple
  unpacking, attribute stores, container packing and comprehensions;
- **interprocedural summaries** — per function: does it return a
  device value / a jit-compiled callable, which parameters flow
  through to the return, which parameters reach a host-materializing
  sink, does it (transitively) block the thread or force a device
  sync.  Summaries reach a fixpoint by bounded chaotic iteration
  (``CEPH_TPU_CTLINT_TRANSFER_MAX_DEPTH`` rounds — call chains longer
  than that widen to "unknown", keeping the pass fast and
  deterministic) with a per-function tainted-name cap
  (``CEPH_TPU_CTLINT_TRANSFER_MAX_STATES``) as the widening valve.

Everything is plain :mod:`ast`; the analyzer never imports the code
it reasons about.  The rule families consuming this engine live in
``rules/transfer.py`` (host-sink / redundant-put / non-donated in-out
/ implicit-sync) and the retrofitted ``rules/locks.py`` +
``rules/device.py`` (call-graph-deep blocking/sync under locks).
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field

from ceph_tpu.analysis.core import Project, SourceFile

# -- abstract values --------------------------------------------------------

HOST = "host"          #: definitely host data (numpy/bytes/scalars)
DEVICE = "device"      #: definitely a device-resident array
DEVICE_FN = "device_fn"  #: a callable whose call returns DEVICE (jit(f))
TOP = "top"            #: unknown


def join(a: str, b: str) -> str:
    """MAY-analysis join: agree -> keep, disagree -> TOP."""
    if a == b:
        return a
    return TOP


def taint_join(a: str, b: str) -> str:
    """Taint-biased join for flow-insensitive facts (attribute and
    container residency): device-ness wins, because the rules ask
    "MAY this be a device value" — a HOST assignment on another path
    must not launder the taint away."""
    if DEVICE in (a, b):
        return DEVICE
    if DEVICE_FN in (a, b):
        return DEVICE_FN
    return join(a, b)


#: bounded interprocedural propagation depth (summary fixpoint rounds);
#: call chains deeper than this conservatively widen to "unknown"
MAX_DEPTH = int(os.environ.get("CEPH_TPU_CTLINT_TRANSFER_MAX_DEPTH", "6"))
#: per-function tainted-name cap — the widening valve that keeps one
#: pathological function from dominating the whole lint pass
MAX_STATES = int(os.environ.get("CEPH_TPU_CTLINT_TRANSFER_MAX_STATES", "4096"))

#: call chains (dotted prefixes) whose result is a device array
_DEVICE_PREFIXES = ("jnp.", "jax.numpy.")
#: exact call names returning device arrays
_DEVICE_CALLS = {"jax.device_put", "device_put"}
#: wrappers producing a DEVICE_FN when *called with a function*
_JIT_WRAPPERS = {"jax.jit", "jit", "jax.pmap", "pmap", "pjit", "shard_map"}

#: host-materializing sinks: full dotted / trailing call names.  Every
#: one of these forces the device buffer back through the host —
#: ``device_get`` included: it is the *explicit, sanctioned* exit, but
#: an exit nonetheless, and every use must be a justified by-design
#: host boundary (baseline) or it is hiding a round-trip.
_SINK_CALLS = {
    "np.asarray": "materializes the device array on the host",
    "np.array": "copies the device array to the host",
    "np.ascontiguousarray": "copies the device array to the host",
    "numpy.asarray": "materializes the device array on the host",
    "jax.device_get": "is an explicit device->host transfer",
    "device_get": "is an explicit device->host transfer",
    "bytes": "serializes the device array through the host",
    "bytearray": "serializes the device array through the host",
    "memoryview": "exposes host memory of the device array",
}
#: container constructors that preserve their argument's residency
#: (list(tuple_of_device_arrays) repackages, it does not materialize —
#: .tolist() is the materializing spelling)
_IDENTITY_CALLS = {"list", "tuple", "sorted", "reversed"}
#: method names on a device receiver that materialize host-side
_SINK_METHODS = {
    "tobytes": "serializes the device array through the host",
    "tolist": "materializes the device array as host objects",
    "item": "synchronously fetches a device scalar",
}
#: builtins that force an implicit scalar sync on a device operand
_SCALAR_SYNCS = {"bool", "float", "int"}

#: thread-blocking calls (dotted or trailing names) and why — the
#: lock rules' seed set, propagated through the call graph
BLOCKING_CALLS = {
    "time.sleep": "sleeps",
    "os.fsync": "does disk I/O (fsync)",
    "os.fdatasync": "does disk I/O (fdatasync)",
    "subprocess.run": "spawns a process",
    "subprocess.check_call": "spawns a process",
    "subprocess.check_output": "spawns a process",
    "subprocess.Popen": "spawns a process",
    "importlib.import_module": "does a dynamic import (module-level "
                               "code + disk I/O)",
    "socket.create_connection": "does network I/O",
}
#: method names that block regardless of receiver
BLOCKING_METHODS = {
    "sendall": "does network I/O",
    "apply_transaction": "commits to the store",
    "queue_transaction": "commits to the store",
}
#: calls that force a device sync (or worse, a compile)
SYNC_CALLS = {"block_until_ready", "device_put"}


def attr_chain(node: ast.AST) -> str | None:
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


@dataclass
class FunctionInfo:
    """One def in the project, addressable as ``module:qualname``."""

    module: str
    qual: str                     # dotted scope incl. classes
    path: str
    node: ast.AST                 # FunctionDef | AsyncFunctionDef
    cls: str | None = None        # innermost enclosing class name
    params: list[str] = field(default_factory=list)

    @property
    def fid(self) -> str:
        return f"{self.module}:{self.qual}"

    @property
    def name(self) -> str:
        return self.qual.rsplit(".", 1)[-1]


@dataclass
class Summary:
    """Interprocedural facts about one function, reached by bounded
    fixpoint.  ``chain`` fields carry the call path that established a
    transitive fact, for actionable messages."""

    returns_device: bool = False
    returns_device_fn: bool = False
    #: param indices that may flow (residency-preserving) to the return
    passthrough: set[int] = field(default_factory=set)
    #: param index -> (sink op, why) when a param reaches a host sink
    sink_params: dict[int, tuple[str, str]] = field(default_factory=dict)
    #: (reason, chain-of-names) when the function may block the thread
    blocks: tuple[str, tuple[str, ...]] | None = None
    #: (sync call, chain-of-names) when it may force a device sync
    syncs: tuple[str, tuple[str, ...]] | None = None


class CallGraph:
    """Functions + call resolution over one :class:`Project`."""

    def __init__(self, project: Project):
        self.project = project
        self.functions: dict[str, FunctionInfo] = {}
        #: (module, bare name) -> fid for module-level defs
        self._module_funcs: dict[tuple[str, str], str] = {}
        #: (module, class, method) -> fid
        self._methods: dict[tuple[str, str, str], str] = {}
        #: module -> {local alias -> ("mod", modname) | ("obj", mod, name)}
        self._imports: dict[str, dict[str, tuple]] = {}
        #: module -> {class -> [base class names]}
        self._bases: dict[str, dict[str, list[str]]] = {}
        #: fids of jit/pmap/shard_map-wrapped defs (decorator form)
        self.jit_defs: set[str] = set()
        for sf in project.files:
            self._index_module(sf)

    # -- indexing ------------------------------------------------------

    def _index_module(self, sf: SourceFile) -> None:
        mod = sf.module
        imports: dict[str, tuple] = {}
        self._imports[mod] = imports
        self._bases[mod] = {}
        mods = {s.module for s in self.project.files}

        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    local = alias.asname or alias.name.split(".")[0]
                    if alias.name in mods:
                        imports[local] = ("mod", alias.name)
            elif isinstance(node, ast.ImportFrom) and node.module:
                for alias in node.names:
                    local = alias.asname or alias.name
                    sub = f"{node.module}.{alias.name}"
                    if sub in mods:
                        imports[local] = ("mod", sub)
                    elif node.module in mods:
                        imports[local] = ("obj", node.module, alias.name)

        scope: list[str] = []

        def walk(node: ast.AST) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    self._bases[mod][child.name] = [
                        b for b in (attr_chain(x) for x in child.bases) if b
                    ]
                    scope.append(child.name)
                    walk(child)
                    scope.pop()
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                    qual = ".".join(scope + [child.name])
                    cls = next(
                        (s for s in reversed(scope) if s[:1].isupper()),
                        None)
                    a = child.args
                    params = [x.arg for x in (
                        a.posonlyargs + a.args + a.kwonlyargs)]
                    info = FunctionInfo(
                        module=mod, qual=qual, path=sf.path, node=child,
                        cls=cls, params=params)
                    self.functions[info.fid] = info
                    if cls is None and not scope:
                        self._module_funcs[(mod, child.name)] = info.fid
                    elif cls is not None:
                        self._methods.setdefault(
                            (mod, cls, child.name), info.fid)
                    for dec in child.decorator_list:
                        dn = attr_chain(
                            dec.func if isinstance(dec, ast.Call) else dec)
                        if isinstance(dec, ast.Call) and dn in (
                                "functools.partial", "partial") and dec.args:
                            dn = attr_chain(dec.args[0])
                        if dn and (dn in _JIT_WRAPPERS
                                   or dn.endswith(".jit")
                                   or dn.split(".")[-1] in ("pjit", "pmap")):
                            self.jit_defs.add(info.fid)
                    scope.append(child.name)
                    walk(child)
                    scope.pop()
                else:
                    walk(child)

        walk(sf.tree)

    # -- resolution ----------------------------------------------------

    def _method_in(self, mod: str, cls: str, meth: str,
                   depth: int = 0) -> str | None:
        """Method lookup with same/imported-module base-class walking
        (bounded — diamond bases in this tree are shallow)."""
        hit = self._methods.get((mod, cls, meth))
        if hit is not None or depth >= 4:
            return hit
        for base in self._bases.get(mod, {}).get(cls, []):
            leaf = base.split(".")[-1]
            tgt = self._imports.get(mod, {}).get(leaf)
            if tgt and tgt[0] == "obj":
                hit = self._method_in(tgt[1], tgt[2], meth, depth + 1)
            else:
                hit = self._method_in(mod, leaf, meth, depth + 1)
            if hit is not None:
                return hit
        return None

    def resolve(self, caller: FunctionInfo, call: ast.Call) -> str | None:
        """fid of the call target, or None when it cannot be pinned to
        a project function (foreign call, dynamic dispatch)."""
        chain = attr_chain(call.func)
        if chain is None:
            return None
        parts = chain.split(".")
        mod, imports = caller.module, self._imports.get(caller.module, {})
        if len(parts) == 1:
            name = parts[0]
            hit = self._module_funcs.get((mod, name))
            if hit is not None:
                return hit
            tgt = imports.get(name)
            if tgt and tgt[0] == "obj":
                return self._module_funcs.get((tgt[1], tgt[2]))
            if caller.cls is not None:
                # bare call to a sibling function nested in the class
                return self._methods.get((mod, caller.cls, name))
            return None
        if len(parts) == 2:
            recv, meth = parts
            if recv in ("self", "cls") and caller.cls is not None:
                return self._method_in(mod, caller.cls, meth)
            tgt = imports.get(recv)
            if tgt is not None:
                if tgt[0] == "mod":
                    return self._module_funcs.get((tgt[1], meth))
                if tgt[0] == "obj":
                    # imported class: Class.method (static-ish call)
                    return self._methods.get((tgt[1], tgt[2], meth))
            # same-module class attribute call: Class.method
            hit = self._methods.get((mod, recv, meth))
            if hit is not None:
                return hit
            return None
        # a.b.meth: resolve the module prefix
        prefix, meth = ".".join(parts[:-1]), parts[-1]
        tgt = imports.get(parts[0])
        if tgt and tgt[0] == "mod" and len(parts) == 3:
            # alias.Class.method or package.module.func
            hit = self._methods.get((tgt[1], parts[1], meth))
            if hit is not None:
                return hit
            sub = f"{tgt[1]}.{parts[1]}"
            return self._module_funcs.get((sub, meth))
        mods = {s.module for s in self.project.files}
        if prefix in mods:
            return self._module_funcs.get((prefix, meth))
        return None


# -- per-function abstract interpretation -----------------------------------


def _blocking_reason(name: str | None) -> str | None:
    if not name:
        return None
    if name in BLOCKING_CALLS:
        return BLOCKING_CALLS[name]
    for dotted, why in BLOCKING_CALLS.items():
        if name.endswith("." + dotted):
            return why
    return BLOCKING_METHODS.get(name.split(".")[-1])


class _Interp(ast.NodeVisitor):
    """One forward pass over a function body.

    ``env`` maps local names to abstract values; ``attr_env`` maps
    ``self.x`` attribute names (per enclosing class, precomputed by
    the engine) to values.  The pass records sink/sync/blocking events
    into the engine-owned callbacks so rule modules stay thin."""

    def __init__(self, engine: "DataflowEngine", fn: FunctionInfo,
                 attr_env: dict[str, str], on_event=None):
        self.e = engine
        self.fn = fn
        self.attr_env = attr_env
        self.env: dict[str, str] = {}
        self.widened = False
        self.on_event = on_event   # (kind, node, payload) -> None
        self.returns: list[str] = []
        #: param name -> index, for summary updates
        self.param_ix = {p: i for i, p in enumerate(fn.params)}
        self.param_sinks: dict[int, tuple[str, str]] = {}
        self.param_passthrough: set[int] = set()

    # -- environment helpers ------------------------------------------

    def _set(self, name: str, val: str) -> None:
        if len(self.env) >= MAX_STATES:
            self.widened = True
            return
        old = self.env.get(name)
        self.env[name] = val if old is None else join(old, val)

    def _value(self, node: ast.AST) -> str:
        """Abstract value of an expression (also walks it for events)."""
        v = self._eval(node)
        return v

    def _eval(self, node: ast.AST) -> str:
        if isinstance(node, ast.Name):
            if node.id in self.env:
                return self.env[node.id]
            if node.id in self.param_ix:
                return TOP if node.id != "self" else HOST
            return HOST
        if isinstance(node, ast.Attribute):
            chain = attr_chain(node)
            if chain and chain.startswith("self."):
                return self.attr_env.get(chain[5:], HOST)
            return HOST
        if isinstance(node, ast.Constant):
            return HOST
        if isinstance(node, ast.Call):
            return self._eval_call(node)
        if isinstance(node, ast.Subscript):
            # element of a device container / slice of a device array
            base = self._eval(node.value)
            self._eval(node.slice)
            return DEVICE if base == DEVICE else base
        if isinstance(node, ast.BinOp):
            left, right = self._eval(node.left), self._eval(node.right)
            if DEVICE in (left, right):
                return DEVICE
            return join(left, right)
        if isinstance(node, ast.UnaryOp):
            return self._eval(node.operand)
        if isinstance(node, ast.BoolOp):
            vals = [self._eval(v) for v in node.values]
            out = vals[0]
            for v in vals[1:]:
                out = join(out, v)
            return out
        if isinstance(node, ast.Compare):
            ops = [self._eval(node.left)] + [
                self._eval(c) for c in node.comparators]
            # a comparison WITH a device operand yields a device bool
            return DEVICE if DEVICE in ops else HOST
        if isinstance(node, ast.IfExp):
            self._eval(node.test)
            return join(self._eval(node.body), self._eval(node.orelse))
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            vals = [self._eval(el) for el in node.elts]
            return DEVICE if DEVICE in vals else HOST
        if isinstance(node, ast.Dict):
            vals = [self._eval(v) for v in node.values if v is not None]
            for k in node.keys:
                if k is not None:
                    self._eval(k)
            return DEVICE if DEVICE in vals else HOST
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            return self._eval_comp(node, node.elt)
        if isinstance(node, ast.DictComp):
            return self._eval_comp(node, node.value)
        if isinstance(node, ast.Await):
            return self._eval(node.value)
        if isinstance(node, ast.Starred):
            return self._eval(node.value)
        if isinstance(node, ast.NamedExpr):
            v = self._eval(node.value)
            if isinstance(node.target, ast.Name):
                self._set(node.target.id, v)
            return v
        if isinstance(node, (ast.JoinedStr, ast.FormattedValue)):
            for sub in ast.iter_child_nodes(node):
                if isinstance(sub, ast.expr):
                    self._eval(sub)
            return HOST
        if isinstance(node, ast.Lambda):
            return HOST
        return HOST

    def _eval_comp(self, comp: ast.AST, result_expr: ast.AST) -> str:
        for gen in comp.generators:
            it = self._eval(gen.iter)
            self._bind_target(gen.target,
                              DEVICE if it == DEVICE else TOP
                              if it == TOP else HOST)
            for cond in gen.ifs:
                self._check_condition(cond)
        if isinstance(comp, ast.DictComp):
            self._eval(comp.key)
        return self._eval(result_expr)

    # -- calls ---------------------------------------------------------

    def _eval_call(self, call: ast.Call) -> str:
        chain = attr_chain(call.func)
        short = chain.split(".")[-1] if chain else None
        argvals = [self._eval(a) for a in call.args]
        kwvals = [self._eval(k.value) for k in call.keywords]

        # receiver method on a device value keeps residency
        # (x.astype/x.reshape/...) — checked before sink methods so
        # tobytes/tolist win below
        recv_val = None
        if isinstance(call.func, ast.Attribute):
            recv_val = self._eval(call.func.value)

        # -- events ----------------------------------------------------
        if chain:
            if short in _SINK_METHODS:
                if recv_val == DEVICE:
                    self._emit("host_sink", call,
                               (f".{short}()", _SINK_METHODS[short]))
                # a sink method on a bare parameter: record it so the
                # summary fires at device-valued call sites
                if isinstance(call.func, ast.Attribute):
                    self._note_param_sink(
                        call.func.value, f".{short}()",
                        _SINK_METHODS[short])
            elif (chain in _SINK_CALLS or short in (
                    "asarray", "array", "ascontiguousarray")
                    and chain.split(".")[0] in ("np", "numpy")) and argvals:
                why = _SINK_CALLS.get(chain) or _SINK_CALLS.get(
                    f"np.{short}", "materializes the device array on "
                    "the host")
                if argvals[0] == DEVICE:
                    self._emit("host_sink", call, (chain + "()", why))
                self._note_param_sink(call.args[0], chain + "()", why)
            elif chain in ("bytes", "bytearray", "memoryview") \
                    and argvals and argvals[0] == DEVICE:
                self._emit("host_sink", call,
                           (chain + "()", _SINK_CALLS[chain]))
            elif chain in _SCALAR_SYNCS and argvals \
                    and argvals[0] == DEVICE:
                self._emit("implicit_sync", call,
                           (chain + "()", "forces a blocking device "
                            "sync to fetch one scalar"))
            if short in ("device_put", "asarray", "array") and chain and (
                    chain in _DEVICE_CALLS
                    or chain.startswith(_DEVICE_PREFIXES)):
                if argvals and argvals[0] == DEVICE:
                    self._emit("redundant_put", call, (chain + "()",))

        # -- abstract result -------------------------------------------
        if chain:
            if chain in _JIT_WRAPPERS or chain.endswith(".jit") \
                    or short in ("pjit", "pmap"):
                return DEVICE_FN
            if chain in ("functools.partial", "partial") and call.args:
                inner = attr_chain(call.args[0])
                if inner and (inner in _JIT_WRAPPERS
                              or inner.endswith(".jit")):
                    return DEVICE_FN
            if chain in _DEVICE_CALLS or chain.startswith(_DEVICE_PREFIXES):
                return DEVICE
            if short in ("block_until_ready",) \
                    or chain in _IDENTITY_CALLS:
                # jax.block_until_ready(x) / list(x) return x-shaped
                return argvals[0] if argvals else HOST
            if chain in _SINK_CALLS or short in _SINK_METHODS \
                    or chain in _SCALAR_SYNCS:
                return HOST
        # call of a value known to be a compiled callable (x = jax.jit(f);
        # x(...) — or self._jit(...) via the class attr environment, or
        # factory()(args) where the factory returns a compiled callable)
        if isinstance(call.func, (ast.Name, ast.Attribute)) \
                and self._eval(call.func) == DEVICE_FN:
            return DEVICE
        if isinstance(call.func, ast.Call) \
                and self._eval_call(call.func) == DEVICE_FN:
            return DEVICE

        # project-resolved callee: use its summary
        fid = self.e.graph.resolve(self.fn, call)
        if fid is not None:
            self._emit("call", call, (fid, argvals))
            s = self.e.summaries.get(fid)
            if s is not None:
                # param sinks inside the callee fire at this call site
                for ix, (op, why) in sorted(s.sink_params.items()):
                    args = call.args
                    # account for the implicit self on method calls
                    info = self.e.graph.functions.get(fid)
                    shift = 1 if (info is not None and info.cls is not None
                                  and info.params[:1] == ["self"]) else 0
                    at = ix - shift
                    if 0 <= at < len(args) and argvals[at] == DEVICE:
                        self._emit("host_sink", call,
                                   (f"{info.name}() -> {op}", why))
                if s.returns_device:
                    return DEVICE
                if s.returns_device_fn:
                    return DEVICE_FN
                if s.passthrough:
                    info = self.e.graph.functions.get(fid)
                    shift = 1 if (info is not None and info.cls is not None
                                  and info.params[:1] == ["self"]) else 0
                    vals = [argvals[ix - shift]
                            for ix in s.passthrough
                            if 0 <= ix - shift < len(argvals)]
                    if DEVICE in vals:
                        return DEVICE
        if chain and short in self.e.jit_entrypoints:
            # registry-declared kernel entry point: its result is a
            # device array whatever the wrapper around the jit looks
            # like (lru_cached inner kerns, facades, re-exports)
            return DEVICE
        if fid is not None and fid in self.e.graph.jit_defs:
            return DEVICE
        # array methods preserve residency (x.astype/x.reshape/...)
        if recv_val == DEVICE and short not in _SINK_METHODS:
            return DEVICE
        return TOP if chain is None else HOST

    def _note_param_sink(self, arg: ast.AST, op: str, why: str) -> None:
        """A parameter fed straight into a host sink — recorded so the
        summary can fire the sink at device-valued call sites."""
        if isinstance(arg, ast.Name) and arg.id in self.param_ix:
            self.param_sinks.setdefault(
                self.param_ix[arg.id], (op, why))

    def _emit(self, kind: str, node: ast.AST, payload: tuple) -> None:
        if self.on_event is not None:
            self.on_event(kind, node, payload)

    # -- statements ----------------------------------------------------

    def _bind_target(self, target: ast.AST, val: str) -> None:
        if isinstance(target, ast.Name):
            self._set(target.id, val)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                self._bind_target(el, val)
        elif isinstance(target, ast.Starred):
            self._bind_target(target.value, val)
        elif isinstance(target, ast.Attribute):
            chain = attr_chain(target)
            if chain and chain.startswith("self."):
                name = chain[5:]
                old = self.attr_env.get(name)
                self.attr_env[name] = (
                    val if old is None else taint_join(old, val))
        elif isinstance(target, ast.Subscript):
            # storing a device value into a container taints the
            # container (MAY semantics)
            self._eval(target.slice)
            base = target.value
            if val == DEVICE:
                self._bind_target(base, DEVICE)

    def visit_Assign(self, node: ast.Assign) -> None:
        val = self._eval(node.value)
        for t in node.targets:
            self._bind_target(t, val)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._bind_target(node.target, self._eval(node.value))

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        val = self._eval(node.value)
        if isinstance(node.target, ast.Name):
            old = self.env.get(node.target.id, HOST)
            self._set(node.target.id,
                      DEVICE if DEVICE in (old, val) else join(old, val))

    def visit_Return(self, node: ast.Return) -> None:
        if node.value is None:
            self.returns.append(HOST)
            return
        v = self._eval(node.value)
        self.returns.append(v)
        # param -> return passthrough (residency-preserving)
        if isinstance(node.value, ast.Name) \
                and node.value.id in self.param_ix:
            self.param_passthrough.add(self.param_ix[node.value.id])

    def _check_condition(self, test: ast.AST) -> None:
        v = self._eval(test)
        if v == DEVICE:
            self._emit("implicit_sync", test,
                       ("branch condition",
                        "evaluating a device value for control flow "
                        "forces a blocking sync"))

    def visit_If(self, node: ast.If) -> None:
        self._check_condition(node.test)
        for s in node.body:
            self.visit(s)
        for s in node.orelse:
            self.visit(s)

    def visit_While(self, node: ast.While) -> None:
        self._check_condition(node.test)
        # two passes propagate loop-carried taint (bounded widening)
        for _ in range(2):
            for s in node.body:
                self.visit(s)
        for s in node.orelse:
            self.visit(s)

    def _visit_for(self, node) -> None:
        it = self._eval(node.iter)
        self._bind_target(
            node.target,
            DEVICE if it == DEVICE else TOP if it == TOP else HOST)
        for _ in range(2):
            for s in node.body:
                self.visit(s)
        for s in node.orelse:
            self.visit(s)

    visit_For = _visit_for
    visit_AsyncFor = _visit_for

    def _visit_with(self, node) -> None:
        for item in node.items:
            v = self._eval(item.context_expr)
            if item.optional_vars is not None:
                self._bind_target(item.optional_vars, v)
        for s in node.body:
            self.visit(s)

    visit_With = _visit_with
    visit_AsyncWith = _visit_with

    def visit_Expr(self, node: ast.Expr) -> None:
        self._eval(node.value)

    def visit_Assert(self, node: ast.Assert) -> None:
        self._check_condition(node.test)
        if node.msg is not None:
            self._eval(node.msg)

    def visit_Try(self, node: ast.Try) -> None:
        for s in node.body:
            self.visit(s)
        for h in node.handlers:
            for s in h.body:
                self.visit(s)
        for s in node.orelse:
            self.visit(s)
        for s in node.finalbody:
            self.visit(s)

    def visit_FunctionDef(self, node) -> None:
        # nested defs are separate functions in the graph; but a
        # jit-wrapped nested def BINDS a compiled callable locally
        # (the lru_cached-kernel-factory idiom: def f(): @jax.jit ...
        # return kern)
        for dec in node.decorator_list:
            dn = attr_chain(
                dec.func if isinstance(dec, ast.Call) else dec)
            if isinstance(dec, ast.Call) and dn in (
                    "functools.partial", "partial") and dec.args:
                dn = attr_chain(dec.args[0])
            if dn and (dn in _JIT_WRAPPERS
                       or dn.split(".")[-1] in ("pjit", "pmap")):
                self._set(node.name, DEVICE_FN)
                return

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_ClassDef = visit_FunctionDef

    def run(self) -> None:
        for stmt in self.fn.node.body:
            self.visit(stmt)


# -- the engine -------------------------------------------------------------


class DataflowEngine:
    """Builds the call graph, computes interprocedural summaries, and
    replays functions with an event callback for the rule modules.

    One engine instance is built per lint run and shared by every rule
    that needs value flow (transfer family, lock family, device
    family) — construction cost is paid once.
    """

    def __init__(self, project: Project,
                 jit_entrypoints: frozenset[str] | None = None):
        if jit_entrypoints is None:
            from ceph_tpu.analysis.prewarm_registry import JIT_ENTRYPOINTS

            jit_entrypoints = JIT_ENTRYPOINTS
        self.project = project
        self.graph = CallGraph(project)
        self.jit_entrypoints = jit_entrypoints
        self.summaries: dict[str, Summary] = {
            fid: Summary() for fid in self.graph.functions
        }
        #: (module, class) -> {attr -> abstract value} — attribute
        #: stores are flow-insensitive per class (a device attr
        #: anywhere taints reads everywhere in the class)
        self._attr_envs: dict[tuple[str, str | None], dict[str, str]] = {}
        self._fixpoint()

    # -- summaries -----------------------------------------------------

    def attr_env(self, fn: FunctionInfo) -> dict[str, str]:
        return self._attr_envs.setdefault((fn.module, fn.cls), {})

    def _fixpoint(self) -> None:
        # seed blocking/sync facts (direct calls only), then iterate
        # the whole summary lattice MAX_DEPTH times — each round
        # extends transitive facts by one call edge, so chains deeper
        # than MAX_DEPTH widen to "not proven" (deterministically)
        order = sorted(self.graph.functions)
        self._seed_block_sync(order)
        for _ in range(max(1, MAX_DEPTH)):
            changed = False
            for fid in order:
                if self._update(fid):
                    changed = True
            if not changed:
                break

    def _seed_block_sync(self, order: list[str]) -> None:
        for fid in order:
            fn = self.graph.functions[fid]
            s = self.summaries[fid]
            for node in ast.walk(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                name = attr_chain(node.func)
                why = _blocking_reason(name)
                if why is not None and s.blocks is None:
                    s.blocks = (why, (name or "?",))
                short = name.split(".")[-1] if name else None
                if short in SYNC_CALLS and s.syncs is None:
                    s.syncs = (short, (short,))

    def _update(self, fid: str) -> bool:
        fn = self.graph.functions[fid]
        s = self.summaries[fid]
        before = (s.returns_device, s.returns_device_fn,
                  tuple(sorted(s.passthrough)),
                  tuple(sorted(s.sink_params)), s.blocks, s.syncs)

        interp = _Interp(self, fn, dict(self.attr_env(fn)))
        calls: list[tuple[str, tuple[str, ...]]] = []

        def on_event(kind, node, payload):
            if kind == "call":
                calls.append(payload)

        interp.on_event = on_event
        interp.run()

        # merge attribute effects back into the class-wide env
        cls_env = self.attr_env(fn)
        for k, v in interp.attr_env.items():
            old = cls_env.get(k)
            cls_env[k] = v if old is None else taint_join(old, v)

        if DEVICE in interp.returns:
            s.returns_device = True
        if DEVICE_FN in interp.returns:
            s.returns_device_fn = True
        s.passthrough |= interp.param_passthrough
        for ix, hit in interp.param_sinks.items():
            s.sink_params.setdefault(ix, hit)

        # transitive blocking / sync through resolved callees
        if s.blocks is None or s.syncs is None:
            for node in ast.walk(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                callee = self.graph.resolve(fn, node)
                if callee is None or callee == fid:
                    continue
                cs = self.summaries.get(callee)
                if cs is None:
                    continue
                cname = self.graph.functions[callee].name
                if s.blocks is None and cs.blocks is not None \
                        and len(cs.blocks[1]) < MAX_DEPTH:
                    s.blocks = (cs.blocks[0], (cname,) + cs.blocks[1])
                if s.syncs is None and cs.syncs is not None \
                        and len(cs.syncs[1]) < MAX_DEPTH:
                    s.syncs = (cs.syncs[0], (cname,) + cs.syncs[1])

        after = (s.returns_device, s.returns_device_fn,
                 tuple(sorted(s.passthrough)),
                 tuple(sorted(s.sink_params)), s.blocks, s.syncs)
        return before != after

    # -- rule-facing API ----------------------------------------------

    def replay(self, fn: FunctionInfo, on_event) -> None:
        """Re-interpret one function with final summaries, streaming
        (kind, node, payload) events: ``host_sink``, ``implicit_sync``,
        ``redundant_put``, ``call``."""
        _Interp(self, fn, dict(self.attr_env(fn)), on_event).run()

    def functions_in(self, modules: set[str]) -> list[FunctionInfo]:
        return [f for fid, f in sorted(self.graph.functions.items())
                if f.module in modules]

    def may_block(self, fid: str) -> tuple[str, tuple[str, ...]] | None:
        s = self.summaries.get(fid)
        return s.blocks if s else None

    def may_sync(self, fid: str) -> tuple[str, tuple[str, ...]] | None:
        s = self.summaries.get(fid)
        return s.syncs if s else None


_ENGINE_CACHE: dict[int, DataflowEngine] = {}


def engine_for(project: Project) -> DataflowEngine:
    """One engine per Project instance per lint run (rules share it)."""
    key = id(project)
    eng = _ENGINE_CACHE.get(key)
    if eng is None:
        _ENGINE_CACHE.clear()   # previous projects are dead
        eng = _ENGINE_CACHE[key] = DataflowEngine(project)
    return eng
