"""ctlint — AST-based invariant analysis for the ceph_tpu tree.

The runtime already *proves* its hot-path invariants after the fact:
``cold_launches == 0`` counters show the device discipline held, chaos
trace hashes show schedules were deterministic, and tests show frames
and config keys stayed wired.  This package proves the same invariants
at lint time, before a cold code path ships a violation — the role a
race detector or clang-tidy pass plays for the C++ reference.

Six rule families (see :mod:`ceph_tpu.analysis.rules`):

- **device-discipline** — every jit/pmap/shard_map-wrapped callable
  reachable from the I/O-path modules must appear in the declared
  prewarm registry; shapes fed to jitted kernels must come from the
  pow2-bucket helpers; no device sync under a held lock (resolved
  through the call graph — a helper that syncs frames below the
  critical section is caught too).
- **lock-order** — cross-module lock-acquisition graph: cycles, and
  blocking calls (sleep, socket send, store commit) under held locks,
  resolved interprocedurally with the blocking chain named.
- **wire-protocol** — duplicate/unregistered frame ids and
  encode/decode field asymmetry in ``msg/messages.py``.
- **config-registry** — every config key read anywhere must have a
  registered default; dead registered options are reported.
- **determinism** — no wall clock, ``random``-module globals, or
  unordered-set iteration in pure-trace paths (``chaos/schedule.py``).
- **transfer** — device-residency dataflow
  (:mod:`ceph_tpu.analysis.dataflow`): no device value reaching a
  host-materializing op on the I/O path, no redundant device_put, no
  undeclared in-out launch buffers, no implicit scalar syncs; paired
  at runtime with ``common/transfer_guard.py`` (``host_transfers``
  counter) the way the prewarm registry pairs with
  ``cold_launches``.

Run via ``tools/lint.py`` (human / ``--json`` / ``--update-baseline``)
or through the tier-1 gate ``tests/test_static_analysis.py``.
Suppress a finding inline with ``# ctlint: disable=<rule>`` and
grandfather the remainder in ``ctlint_baseline.json``.
"""

from ceph_tpu.analysis.core import (  # noqa: F401
    Finding,
    Project,
    Rule,
    load_baseline,
    run_analysis,
    split_by_baseline,
)
from ceph_tpu.analysis.rules import ALL_RULES  # noqa: F401
