"""ctlint core: findings model, suppressions, baseline, project walker.

Everything here is plain :mod:`ast` — the analyzer never imports the
code it checks, so fixture files may contain deliberate violations
(duplicate frame ids, device sync under locks) that would assert or
deadlock if executed.
"""

from __future__ import annotations

import ast
import json
import re
from dataclasses import dataclass, field
from pathlib import Path

SEV_ERROR = "error"
SEV_WARNING = "warning"

#: inline suppression, honored on the flagged line or the line above:
#: ``# ctlint: disable=rule-a,rule-b`` (or ``disable=all``)
_SUPPRESS_RE = re.compile(r"#\s*ctlint:\s*disable=([a-z0-9_,\- ]+|all)")
#: whole-file suppression: ``# ctlint: disable-file=rule-a``
_SUPPRESS_FILE_RE = re.compile(
    r"#\s*ctlint:\s*disable-file=([a-z0-9_,\- ]+|all)")
#: opt a module into the pure-trace determinism scope (anchored to a
#: whole comment line so prose *mentioning* the marker doesn't opt in)
_PURE_TRACE_RE = re.compile(r"^\s*#\s*ctlint:\s*pure-trace\s*$", re.M)


@dataclass(frozen=True)
class Finding:
    """One rule violation at ``path:line``.

    The baseline key deliberately omits ``line`` so unrelated edits
    above a grandfathered finding do not un-baseline it."""

    rule: str
    severity: str
    path: str          # repo-relative, forward slashes
    line: int
    message: str

    def key(self) -> tuple[str, str, str]:
        return (self.rule, self.path, self.message)

    def to_json(self) -> dict:
        return {
            "rule": self.rule, "severity": self.severity,
            "file": self.path, "line": self.line, "message": self.message,
        }

    def render(self) -> str:
        return (f"{self.path}:{self.line}: [{self.rule}] "
                f"{self.severity}: {self.message}")


class SourceFile:
    """One parsed module: AST + per-line suppression sets."""

    def __init__(self, path: str, text: str):
        self.path = path
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=path)
        self.pure_trace = bool(_PURE_TRACE_RE.search(text))
        self._line_disable: dict[int, set[str]] = {}
        self._file_disable: set[str] = set()
        for i, ln in enumerate(self.lines, start=1):
            if "ctlint" not in ln:
                continue
            m = _SUPPRESS_RE.search(ln)
            if m:
                self._line_disable[i] = {
                    r.strip() for r in m.group(1).split(",") if r.strip()
                }
            m = _SUPPRESS_FILE_RE.search(ln)
            if m:
                self._file_disable |= {
                    r.strip() for r in m.group(1).split(",") if r.strip()
                }

    def suppressed(self, rule: str, line: int) -> bool:
        if self._file_disable & {rule, "all"}:
            return True
        for at in (line, line - 1):
            rules = self._line_disable.get(at)
            if rules and rules & {rule, "all"}:
                return True
        return False

    @property
    def module(self) -> str:
        """Dotted module name for a repo-relative path (best effort —
        fixture files outside a package just use their stem)."""
        p = self.path[:-3] if self.path.endswith(".py") else self.path
        parts = [x for x in p.split("/") if x]
        if parts and parts[-1] == "__init__":
            parts = parts[:-1]
        return ".".join(parts)


@dataclass
class Project:
    """The unit a rule runs over: parsed sources plus an auxiliary
    read-only set (tools/tests) that rules may mine for *evidence*
    (e.g. config-key reads) but never report findings against."""

    root: Path
    files: list[SourceFile] = field(default_factory=list)
    aux_files: list[SourceFile] = field(default_factory=list)

    @classmethod
    def load(cls, root: str | Path,
             include: tuple[str, ...] = ("ceph_tpu",),
             aux: tuple[str, ...] = ("tools", "tests", "bench.py"),
             ) -> "Project":
        root = Path(root)
        proj = cls(root=root)
        proj.files = _collect(root, include)
        proj.aux_files = _collect(root, aux)
        return proj

    # -- module/import helpers (device-discipline reachability) --------

    def by_module(self) -> dict[str, SourceFile]:
        return {sf.module: sf for sf in self.files}

    def import_graph(self) -> dict[str, set[str]]:
        """module -> imported project modules.  ``from pkg import x``
        resolves ``pkg.x`` when that is a project module, else ``pkg``
        — enough precision for reachability over absolute imports
        (the house style; relative imports are not used)."""
        mods = self.by_module()
        graph: dict[str, set[str]] = {m: set() for m in mods}
        for mod, sf in mods.items():
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.Import):
                    for alias in node.names:
                        tgt = _project_module(alias.name, mods)
                        if tgt:
                            graph[mod].add(tgt)
                elif isinstance(node, ast.ImportFrom) and node.module:
                    base = _project_module(node.module, mods)
                    for alias in node.names:
                        sub = _project_module(
                            f"{node.module}.{alias.name}", mods)
                        if sub:
                            graph[mod].add(sub)
                        elif base:
                            graph[mod].add(base)
        return graph

    def reachable_from(self, roots: set[str]) -> set[str]:
        graph = self.import_graph()
        seen: set[str] = set()
        stack = [r for r in roots if r in graph]
        while stack:
            m = stack.pop()
            if m in seen:
                continue
            seen.add(m)
            stack.extend(graph.get(m, ()) - seen)
        return seen


def _project_module(name: str, mods: dict[str, SourceFile]) -> str | None:
    if name in mods:
        return name
    # a package import maps to its __init__ module if present
    return None


def _collect(root: Path, names: tuple[str, ...]) -> list[SourceFile]:
    out: list[SourceFile] = []
    for name in names:
        p = root / name
        if p.is_file() and p.suffix == ".py":
            paths = [p]
        elif p.is_dir():
            paths = sorted(p.rglob("*.py"))
        else:
            continue
        for f in paths:
            if "__pycache__" in f.parts:
                continue
            rel = f.relative_to(root).as_posix()
            try:
                out.append(SourceFile(rel, f.read_text()))
            except (SyntaxError, UnicodeDecodeError):
                continue  # fixtures may hold non-module content
    return out


class Rule:
    """Base class: subclasses set ``name`` (the family), ``rules`` (the
    ids they can emit) and implement :meth:`run`."""

    name = "rule"
    rules: tuple[str, ...] = ()

    def run(self, project: Project) -> list[Finding]:
        raise NotImplementedError


def run_analysis(root: str | Path, rules=None,
                 project: Project | None = None) -> list[Finding]:
    """Run ``rules`` (default: all) over the tree at ``root``; returns
    findings with inline suppressions already filtered, sorted by
    (path, line, rule)."""
    from ceph_tpu.analysis.rules import ALL_RULES

    if project is None:
        project = Project.load(root)
    by_path = {sf.path: sf for sf in project.files}
    findings: list[Finding] = []
    for rule in (rules if rules is not None else
                 [cls() for cls in ALL_RULES]):
        for f in rule.run(project):
            sf = by_path.get(f.path)
            if sf is not None and sf.suppressed(f.rule, f.line):
                continue
            findings.append(f)
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule))


# -- baseline ---------------------------------------------------------------

BASELINE_VERSION = 1


def load_baseline(path: str | Path) -> dict[tuple[str, str, str], str]:
    """baseline key -> justification (empty dict when no file)."""
    p = Path(path)
    if not p.exists():
        return {}
    data = json.loads(p.read_text())
    out = {}
    for e in data.get("findings", []):
        out[(e["rule"], e["file"], e["message"])] = e.get(
            "justification", "")
    return out


def split_by_baseline(
    findings: list[Finding], baseline: dict[tuple[str, str, str], str],
) -> tuple[list[Finding], list[Finding], list[tuple]]:
    """(new, grandfathered, stale-baseline-entries)."""
    keys = {f.key() for f in findings}
    new = [f for f in findings if f.key() not in baseline]
    old = [f for f in findings if f.key() in baseline]
    stale = [k for k in baseline if k not in keys]
    return new, old, stale


def baseline_integrity(
    baseline: dict[tuple[str, str, str], str],
    project: Project,
    known_rules: set[str],
) -> list[tuple[tuple[str, str, str], str]]:
    """Entries that cannot possibly fire again: their rule id is gone
    from the catalog or their file is gone from the tree.  A normal
    stale entry (finding fixed, file still there) merely needs an
    ``--update-baseline``; these are harder rot — the (rule, file)
    pair no longer EXISTS — and the chaos/bench preflight fails on
    them so dead grandfather entries cannot mask a rename."""
    paths = {sf.path for sf in project.files} \
        | {sf.path for sf in project.aux_files}
    out: list[tuple[tuple[str, str, str], str]] = []
    for key in sorted(baseline):
        rule, path, _msg = key
        if rule not in known_rules:
            out.append((key, f"rule {rule!r} no longer exists"))
        elif path not in paths:
            out.append((key, f"file {path!r} no longer exists"))
    return out


def write_baseline(path: str | Path, findings: list[Finding],
                   previous: dict[tuple[str, str, str], str]) -> None:
    """Rewrite the baseline to exactly the current finding set, keeping
    each surviving entry's justification; new entries get a TODO
    placeholder the committer must replace."""
    entries = []
    for f in sorted(findings, key=lambda f: f.key()):
        entries.append({
            "rule": f.rule, "file": f.path, "message": f.message,
            "justification": previous.get(
                f.key(), "TODO: justify or fix before committing"),
        })
    Path(path).write_text(json.dumps(
        {"version": BASELINE_VERSION, "findings": entries},
        indent=2, sort_keys=False) + "\n")
