"""Concurrent replicated+EC workload with a recorded op history.

The ``ceph_test_rados`` role (src/test/osd/TestRados.cc +
RadosModel.h): drive writes/reads/snaps against live pools while the
thrasher runs, recording every operation with logical start/finish
timestamps so the invariant checkers (ceph_tpu/chaos/invariants.py)
can judge the run afterwards — no acked write lost, no stale or
corrupted read, snapshots frozen at their creation-time content.

Oracle design: every object has ONE writer task issuing versioned
payloads v1, v2, ... (writers to the same object would make the oracle
either-or; versioned single-writer sequences make it a total order —
the model RadosModel.h uses).  Payloads are self-describing
(``pool|oid|vN|`` header + version-derived fill), so a read can be
validated standalone: parse the version, regenerate the expected
bytes, compare exactly.  A blend of two writes, a torn stripe or a
bit-flip all fail the comparison.

Timestamps are a process-local logical clock (monotonic counter): the
runner is single-loop asyncio, so ``start < ack`` intervals order
exactly like the real submissions.
"""

from __future__ import annotations

import asyncio
import itertools
import logging

log = logging.getLogger("ceph_tpu.chaos")

_HEADER_SEP = b"|#|"


def payload_for(pool: str, oid: str, version: int, size: int) -> bytes:
    """Deterministic self-describing payload for (pool, oid, version)."""
    header = f"{pool}|{oid}|v{version}".encode() + _HEADER_SEP
    fill = bytes([(version * 31 + len(oid) * 7) % 251 + 1])
    if size < len(header):
        size = len(header)
    return header + fill * (size - len(header))


def parse_payload(data: bytes) -> tuple[str, str, int] | None:
    """Recover (pool, oid, version) from a read, or None when the
    bytes are not a whole, untampered payload of any version."""
    if not data or _HEADER_SEP not in data[:128]:
        return None
    header, _rest = data.split(_HEADER_SEP, 1)
    try:
        pool, oid, vtag = header.decode().split("|")
        version = int(vtag[1:])
    except (ValueError, UnicodeDecodeError):
        return None
    if payload_for(pool, oid, version, len(data)) != data:
        return None  # right shape, wrong bytes: blended/torn/corrupt
    return pool, oid, version


class History:
    """The recorded operation history one run produces."""

    def __init__(self):
        self._clock = itertools.count(1)
        self.writes: list[dict] = []
        self.reads: list[dict] = []
        self.snaps: list[dict] = []

    def now(self) -> int:
        return next(self._clock)

    def record_write(self, pool, oid, version, start, ack, error=None,
                     errno=None):
        self.writes.append({
            "pool": pool, "oid": oid, "version": version,
            "start": start, "ack": ack, "error": error,
            "errno": errno,
        })

    def record_read(self, pool, oid, start, end, version=None,
                    valid=False, error=None):
        self.reads.append({
            "pool": pool, "oid": oid, "start": start, "end": end,
            "version": version, "valid": valid, "error": error,
        })

    def record_snap(self, pool, oid, snapid, expect_version):
        self.snaps.append({
            "pool": pool, "oid": oid, "snapid": snapid,
            "expect_version": expect_version, "removed": False,
        })

    def mark_snap_removed(self, pool, oid, snapid):
        for s in self.snaps:
            if (s["pool"], s["oid"], s["snapid"]) == (pool, oid, snapid):
                s["removed"] = True

    def summary(self) -> dict:
        acked = sum(1 for w in self.writes if w["ack"] is not None)
        return {
            "writes": len(self.writes), "writes_acked": acked,
            "reads": len(self.reads),
            "reads_errored": sum(
                1 for r in self.reads if r["error"] is not None),
            "snaps": len(self.snaps),
        }


class Workload:
    """Drives the pools; owns the history.

    ``pools`` entries: {"name": str, "type": "replicated"|"erasure",
    "snaps": bool} — pools must already exist.  ``object_size`` should
    stay a multiple of one EC stripe so thrash-time recovery decodes
    hit the prewarmed batcher buckets (the cold_launches==0 invariant
    is part of the point)."""

    def __init__(
        self, client, pools: list[dict], *, objects: int = 4,
        rounds: int = 3, object_size: int = 8192,
        read_loops: int = 4, write_gap: float = 0.0,
    ):
        self.client = client
        self.pools = pools
        self.objects = objects
        self.rounds = rounds
        self.object_size = object_size
        self.read_loops = read_loops
        # pause between one writer's rounds: scenarios that need the
        # write stream to SPAN the whole thrash window (degraded-disk:
        # the mgr's detection pipeline observes live traffic) pace
        # their writers instead of bursting every round up front
        self.write_gap = write_gap
        self.history = History()
        self._done = asyncio.Event()

    def _oids(self, pool_name: str) -> list[str]:
        return [f"{pool_name}-obj{i}" for i in range(self.objects)]

    async def _writer(self, pool: dict, oid: str) -> None:
        h = self.history
        io = self.client.ioctx(pool["name"]).dup()
        # snaps run on EC pools too: snap-frozen-content under thrash
        # is exactly where EC COW clones (shard-granular) can diverge
        # from the replicated path (thrash-erasure-code + snaps role)
        snaps_on = pool.get("snaps")
        last_acked = 0
        snap_ids: list[int] = []
        snap_of: dict[int, int] = {}  # snapid -> round it froze
        for v in range(1, self.rounds + 1):
            data = payload_for(pool["name"], oid, v, self.object_size)
            start = h.now()
            try:
                await io.write_full(oid, data)
            except OSError as e:
                h.record_write(pool["name"], oid, v, start, None,
                               error=str(e),
                               errno=getattr(e, "errno", None))
                continue
            h.record_write(pool["name"], oid, v, start, h.now())
            last_acked = v
            if snaps_on and v == max(1, self.rounds // 2):
                # freeze the current content under a self-managed snap
                # mid-thrash; the final invariant replays the read
                try:
                    snapid = await io.selfmanaged_snap_create()
                    snap_ids.insert(0, snapid)
                    io.set_snap_context(snapid, list(snap_ids))
                    h.record_snap(pool["name"], oid, snapid, last_acked)
                    snap_of[snapid] = last_acked
                except OSError as e:
                    log.debug("chaos workload: snap failed: %s", e)
            await asyncio.sleep(self.write_gap)
        if snaps_on and snap_ids and self._snap_remove_for(oid):
            # snap REMOVE under thrash (half the objects, derived from
            # the oid): trim must reap the clone without disturbing the
            # head — the post-settle deep scrub judges the debris and
            # the removed snap leaves the frozen-content oracle
            victim_snap = snap_ids[-1]  # the oldest recorded snap
            try:
                await io.selfmanaged_snap_remove(victim_snap)
                snap_ids.remove(victim_snap)
                io.set_snap_context(
                    snap_ids[0] if snap_ids else victim_snap,
                    list(snap_ids))
                h.mark_snap_removed(pool["name"], oid, victim_snap)
            except OSError as e:
                log.debug("chaos workload: snap remove failed: %s", e)

    @staticmethod
    def _snap_remove_for(oid: str) -> bool:
        """Deterministic half of the objects exercise snap removal
        (the other half keeps its snap for the frozen-content read)."""
        return sum(oid.encode()) % 2 == 0

    async def _reader(self, pool: dict) -> None:
        h = self.history
        io = self.client.ioctx(pool["name"]).dup()
        oids = self._oids(pool["name"])
        for loop_i in range(self.read_loops):
            for oid in oids:
                if self._done.is_set():
                    return
                start = h.now()
                try:
                    data = await io.read(oid)
                except OSError as e:
                    # ENOENT after an acked write is judged by the
                    # checker; other errors are availability noise
                    h.record_read(
                        pool["name"], oid, start, h.now(),
                        error=f"errno={getattr(e, 'errno', None)}")
                    continue
                parsed = parse_payload(data)
                h.record_read(
                    pool["name"], oid, start, h.now(),
                    version=parsed[2] if parsed else None,
                    valid=parsed is not None
                    and parsed[0] == pool["name"] and parsed[1] == oid,
                )
                await asyncio.sleep(0.01)

    async def run(self) -> History:
        """Run writers and readers to completion; returns the history."""
        writers = [
            self._writer(pool, oid)
            for pool in self.pools for oid in self._oids(pool["name"])
        ]
        readers = [self._reader(pool) for pool in self.pools]

        async def _drive_writers():
            try:
                await asyncio.gather(*writers)
            finally:
                self._done.set()

        await asyncio.gather(_drive_writers(), *readers)
        return self.history

    # -- post-thrash verification reads --------------------------------

    async def final_reads(self) -> list[dict]:
        """Read back every object head (and every recorded snap) after
        the cluster settled; returns read records for the checker."""
        out: list[dict] = []
        for pool in self.pools:
            io = self.client.ioctx(pool["name"])
            for oid in self._oids(pool["name"]):
                rec = {"pool": pool["name"], "oid": oid, "kind": "final"}
                try:
                    data = await io.read(oid)
                    parsed = parse_payload(data)
                    rec["version"] = parsed[2] if parsed else None
                    rec["valid"] = (
                        parsed is not None and parsed[0] == pool["name"]
                        and parsed[1] == oid
                    )
                except OSError as e:
                    rec["error"] = f"errno={getattr(e, 'errno', None)}"
                out.append(rec)
        for snap in self.history.snaps:
            if snap.get("removed"):
                continue  # trimmed under thrash: no content to freeze
            io = self.client.ioctx(snap["pool"]).dup()
            io.snap_set_read(snap["snapid"])
            rec = {
                "pool": snap["pool"], "oid": snap["oid"], "kind": "snap",
                "snapid": snap["snapid"],
                "expect_version": snap["expect_version"],
            }
            try:
                data = await io.read(snap["oid"])
                parsed = parse_payload(data)
                rec["version"] = parsed[2] if parsed else None
                rec["valid"] = parsed is not None
            except OSError as e:
                rec["error"] = f"errno={getattr(e, 'errno', None)}"
            out.append(rec)
        return out
