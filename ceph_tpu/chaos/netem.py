"""Deterministic messenger-level network emulation.

The messenger already carries the reference's *probabilistic* fault
knobs (``ms_inject_socket_failures`` — every Nth send tears the
connection; ``ms_inject_delay`` — uniform latency).  Those are great
for soak tests and useless for replay: which message dies depends on
global send order.  This shim adds the *deterministic* verbs the
thrasher needs, keyed by peer identity:

- **partition(a, b)** — symmetric cut: every send on the a<->b link
  raises ``ConnectionError`` (the peers' failure detectors see a dead
  link and react: sub-op failure, MOSDFailure, mon election);
- **drop_oneway(src, dst)** — src's sends to dst vanish silently while
  dst's replies still flow (the half-dead-NIC case heartbeats exist
  to catch);
- **delay(src, dst, seconds)** — fixed per-send latency on one link;
- **reorder(src, dst, every, hold)** — bounded reordering: every Nth
  send on the link is held ``hold`` seconds *before* entering the
  connection's serialized writer, so later messages overtake it —
  real reordering at the frame level, bounded by the hold window.

Rules match entities exactly (``("osd", 3)``) or by kind wildcard
(``("osd", None)``).  Both endpoints of a mini-cluster attach the same
shim, so symmetric rules bite in both directions.  Every verdict
counts into the ``chaos`` perf collection.
"""

from __future__ import annotations

import asyncio

Entity = tuple  # ("osd", 3) / ("mon", 0) / ("osd", None) wildcard


def _match(rule_ent, ent) -> bool:
    return rule_ent[0] == ent[0] and (
        rule_ent[1] is None or rule_ent[1] == ent[1]
    )


def _norm(e) -> tuple:
    """Entities arrive as tuples or (from JSON traces) lists."""
    return (e[0], e[1])


class Netem:
    """One shim instance per cluster; attach to every messenger."""

    def __init__(self):
        self._partitions: list[tuple[Entity, Entity]] = []
        self._oneways: list[tuple[Entity, Entity]] = []
        self._delays: dict[tuple[Entity, Entity], float] = {}
        self._reorders: dict[tuple[Entity, Entity], tuple[int, float]] = {}
        self._reorder_count: dict[tuple, int] = {}
        self.stats = {
            "partitioned_sends": 0, "dropped_sends": 0,
            "delayed_sends": 0, "reordered_sends": 0,
            # client-link verdicts counted separately: the client-netem
            # oracle needs PROOF a partition/drop actually bit a
            # client send, not just that a rule was armed
            "client_partitioned_sends": 0, "client_dropped_sends": 0,
            "client_delayed_sends": 0,
        }

    def _counters(self):
        from ceph_tpu.chaos import chaos_counters

        return chaos_counters()

    # -- rule management (the schedule's netem verbs) -------------------

    def attach(self, messenger) -> None:
        messenger.netem = self

    def detach(self, messenger) -> None:
        if getattr(messenger, "netem", None) is self:
            messenger.netem = None

    def partition(self, a, b) -> None:
        a, b = _norm(a), _norm(b)
        if (a, b) not in self._partitions:
            self._partitions.append((a, b))

    def heal_partition(self, a, b) -> None:
        a, b = _norm(a), _norm(b)
        for cut in ((a, b), (b, a)):
            if cut in self._partitions:
                self._partitions.remove(cut)

    def drop_oneway(self, src, dst) -> None:
        link = (_norm(src), _norm(dst))
        if link not in self._oneways:
            self._oneways.append(link)

    def heal_oneway(self, src, dst) -> None:
        link = (_norm(src), _norm(dst))
        if link in self._oneways:
            self._oneways.remove(link)

    def delay(self, src, dst, seconds: float) -> None:
        self._delays[(_norm(src), _norm(dst))] = float(seconds)

    def heal_delay(self, src, dst) -> None:
        self._delays.pop((_norm(src), _norm(dst)), None)

    def reorder(self, src, dst, every: int = 3, hold: float = 0.01) -> None:
        link = (_norm(src), _norm(dst))
        self._reorders[link] = (max(2, int(every)), float(hold))
        self._reorder_count.setdefault(link, 0)

    def heal_reorder(self, src, dst) -> None:
        self._reorders.pop((_norm(src), _norm(dst)), None)

    def clear(self) -> None:
        self._partitions.clear()
        self._oneways.clear()
        self._delays.clear()
        self._reorders.clear()
        self._reorder_count.clear()

    def active_rules(self) -> dict:
        return {
            "partitions": [list(map(list, c)) for c in self._partitions],
            "oneways": [list(map(list, c)) for c in self._oneways],
            "delays": {
                f"{s}->{d}": v for (s, d), v in self._delays.items()
            },
            "reorders": {
                f"{s}->{d}": list(v) for (s, d), v in self._reorders.items()
            },
        }

    # -- the send-path hook (called by Connection.send_message) ---------

    async def on_send(self, src: Entity, dst: Entity) -> bool:
        """Apply the active rules to one send.  Returns False when the
        message must be silently dropped; raises ConnectionError on a
        partitioned link; sleeps for delay/reorder holds.  Runs BEFORE
        the connection's send lock, so a held message is genuinely
        overtaken by later sends on the same connection."""
        client_link = src[0] == "client" or dst[0] == "client"
        for a, b in self._partitions:
            if (_match(a, src) and _match(b, dst)) or (
                _match(b, src) and _match(a, dst)
            ):
                self.stats["partitioned_sends"] += 1
                if client_link:
                    self.stats["client_partitioned_sends"] += 1
                self._counters().inc("netem_partitioned_sends")
                raise ConnectionError(
                    f"netem: {src} -> {dst} partitioned")
        for s, d in self._oneways:
            if _match(s, src) and _match(d, dst):
                self.stats["dropped_sends"] += 1
                if client_link:
                    self.stats["client_dropped_sends"] += 1
                self._counters().inc("netem_dropped_sends")
                return False
        for (s, d), secs in list(self._delays.items()):
            if _match(s, src) and _match(d, dst):
                self.stats["delayed_sends"] += 1
                if client_link:
                    self.stats["client_delayed_sends"] += 1
                self._counters().inc("netem_delayed_sends")
                await asyncio.sleep(secs)
        for (s, d), (every, hold) in list(self._reorders.items()):
            if _match(s, src) and _match(d, dst):
                link = (s, d)
                self._reorder_count[link] = (
                    self._reorder_count.get(link, 0) + 1
                )
                if self._reorder_count[link] % every == 0:
                    self.stats["reordered_sends"] += 1
                    self._counters().inc("netem_reordered_sends")
                    await asyncio.sleep(hold)
        return True
