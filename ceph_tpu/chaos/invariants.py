"""Durability invariant checkers over a chaos run.

Pure functions over recorded state — each returns a list of violation
dicts (empty = invariant holds), so they are unit-testable on
hand-built violating histories without booting a cluster (the
``ceph_test_rados`` history-check role, src/test/osd/RadosModel.h
``update_object_version``/``check_ref``):

- :func:`check_history` — read-your-writes over the live run: every
  read returns a whole payload of a version between the newest write
  acked before the read began (no stale/lost reads) and the newest
  write started before it ended (no time travel);
- :func:`check_final_reads` — post-thrash: every head read returns the
  last acked version (or a later, indeterminate-fate write), every
  snap read returns exactly the version frozen at snap creation;
- :func:`check_converged` — the cluster reports every PG active+clean;
- :func:`check_quorum` — every monitor settled on the SAME leader and
  map epoch (split-brain detector — the seed-66 bug class);
- :func:`check_scrub_reports` — zero deep-scrub inconsistencies after
  the thrash;
- :func:`check_disk_faults` — at-rest fsck sweeps report zero bad
  blobs: every injected disk fault (EIO / bit rot / torn commit) was
  healed by the repair chain or its OSD re-placed;
- :func:`check_cold_launches` — the decode/scrub batchers minted ZERO
  cold XLA launches during chaos (recovery under failure must run on
  prewarmed shapes; a compile in the I/O path is a perf regression
  the thrash would otherwise hide);
- :func:`check_domains` — CRUSH actually separated shards across
  failure domains: pre-kill snapshots show no PG of a rack-domain
  pool mapped two shards into one rack, and whole-rack loss left
  every PG >= k data shards / >= 1 replica to serve from;
- :func:`check_backfill` — the soak run genuinely exercised the
  backfill path: the ``backfill_started``/``backfill_completed``
  perf-counter pair moved, and when an interrupt was scripted at
  least one pass was cut short and re-run to completion.
"""

from __future__ import annotations

import errno


def _write_bounds(writes: list[dict]) -> dict:
    """Per (pool, oid): sorted write records."""
    by_obj: dict[tuple, list[dict]] = {}
    for w in writes:
        by_obj.setdefault((w["pool"], w["oid"]), []).append(w)
    for recs in by_obj.values():
        recs.sort(key=lambda w: w["start"])
    return by_obj


def check_history(history) -> list[dict]:
    """Read-your-writes / no-lost-ack over the recorded live run."""
    out: list[dict] = []
    by_obj = _write_bounds(history.writes)
    for r in history.reads:
        key = (r["pool"], r["oid"])
        writes = by_obj.get(key, [])
        acked_before = [
            w["version"] for w in writes
            if w["ack"] is not None and w["ack"] < r["start"]
        ]
        started_before = [
            w["version"] for w in writes if w["start"] < r["end"]
        ]
        lo = max(acked_before, default=0)
        hi = max(started_before, default=0)
        if r.get("error") is not None:
            # availability errors are not durability violations —
            # EXCEPT ENOENT: an object with an acked write must exist
            if lo >= 1 and f"errno={errno.ENOENT}" in r["error"]:
                out.append({
                    "invariant": "acked_write_lost", **r,
                    "detail": f"ENOENT but v{lo} was acked before read",
                })
            continue
        if r["version"] is None or not r.get("valid"):
            out.append({
                "invariant": "corrupt_read", **r,
                "detail": "payload is not a whole write of any version",
            })
        elif r["version"] < lo:
            out.append({
                "invariant": "stale_read", **r,
                "detail": f"returned v{r['version']} < acked v{lo}",
            })
        elif r["version"] > hi:
            out.append({
                "invariant": "phantom_read", **r,
                "detail": f"returned v{r['version']} > newest started v{hi}",
            })
    return out


def check_final_reads(history, final_reads: list[dict]) -> list[dict]:
    """Post-thrash verification: last acked version (or newer
    indeterminate write) on every head; exact frozen version on every
    snap read."""
    out: list[dict] = []
    by_obj = _write_bounds(history.writes)
    for r in final_reads:
        key = (r["pool"], r["oid"])
        writes = by_obj.get(key, [])
        lo = max((w["version"] for w in writes if w["ack"] is not None),
                 default=0)
        hi = max((w["version"] for w in writes), default=0)
        if r.get("kind") == "snap":
            if r.get("error") is not None or r.get("version") is None:
                out.append({
                    "invariant": "snap_lost", **r,
                    "detail": "snap read failed or returned garbage",
                })
            elif r["version"] != r["expect_version"]:
                out.append({
                    "invariant": "snap_moved", **r,
                    "detail": (
                        f"snap {r['snapid']} froze v{r['expect_version']}"
                        f" but reads v{r['version']}"
                    ),
                })
            continue
        if r.get("error") is not None:
            if lo >= 1:
                out.append({
                    "invariant": "acked_write_lost", **r,
                    "detail": f"final read failed but v{lo} was acked",
                })
            continue
        if r.get("version") is None or not r.get("valid"):
            out.append({
                "invariant": "corrupt_read", **r,
                "detail": "final payload is not a whole write",
            })
        elif r["version"] < lo:
            out.append({
                "invariant": "acked_write_lost", **r,
                "detail": f"final v{r['version']} < last acked v{lo}",
            })
        elif r["version"] > hi:
            out.append({
                "invariant": "phantom_read", **r,
                "detail": f"final v{r['version']} > newest started v{hi}",
            })
    return out


def check_converged(status: dict) -> list[dict]:
    """The mon's aggregated pg summary must be all active+clean."""
    pgs = (status or {}).get("pgs", {})
    by_state = pgs.get("by_state", {})
    ok = (
        pgs.get("num_pgs", 0) > 0
        and pgs.get("num_reported", 0) >= pgs.get("num_pgs", 0)
        and set(by_state) == {"active+clean"}
    )
    if ok:
        return []
    return [{
        "invariant": "not_converged",
        "detail": f"pg summary {pgs!r} not all active+clean",
    }]


def check_quorum(mon_views: list[dict]) -> list[dict]:
    """``mon_views``: one snapshot per monitor — {"rank", "stable",
    "leader", "epoch"}.  All must be stable on ONE leader who claims
    leadership, at ONE osdmap epoch."""
    out: list[dict] = []
    unstable = [v["rank"] for v in mon_views if not v.get("stable")]
    if unstable:
        out.append({
            "invariant": "quorum_unstable",
            "detail": f"mons {unstable} not settled",
        })
        return out
    leaders = {v.get("leader") for v in mon_views}
    if len(leaders) != 1 or None in leaders:
        out.append({
            "invariant": "split_brain",
            "detail": "disagreeing leader views "
            + str({v['rank']: v.get('leader') for v in mon_views}),
        })
    else:
        leader = leaders.pop()
        if not any(
            v["rank"] == leader and v.get("leader") == leader
            for v in mon_views
        ):
            out.append({
                "invariant": "leaderless_quorum",
                "detail": f"agreed leader mon.{leader} view missing or "
                "doesn't claim leadership",
            })
    epochs = {v.get("epoch") for v in mon_views}
    if len(epochs) != 1:
        out.append({
            "invariant": "map_epoch_skew",
            "detail": "osdmap epochs "
            + str({v['rank']: v.get('epoch') for v in mon_views}),
        })
    return out


def check_scrub_reports(reports: list[dict]) -> list[dict]:
    """Post-thrash deep scrub must find nothing."""
    out: list[dict] = []
    for rep in reports:
        if rep.get("error"):
            out.append({
                "invariant": "scrub_failed", "pg": rep.get("pg"),
                "detail": str(rep["error"]),
            })
        elif rep.get("inconsistencies"):
            out.append({
                "invariant": "scrub_inconsistency", "pg": rep.get("pg"),
                "detail": rep["inconsistencies"],
            })
    return out


def check_cold_launches(before: dict, after: dict) -> list[dict]:
    """``before``/``after``: {counter_name: count} snapshots around
    the run (per-batcher cold_launches plus the transfer guard's
    host_transfers); any growth means chaos minted an XLA compile —
    or an implicit host<->device transfer — inside the I/O path."""
    out: list[dict] = []
    for name, b in before.items():
        a = after.get(name, b)
        if a > b:
            out.append({
                "invariant": "cold_launch", "batcher": name,
                "detail": f"{name} grew {b} -> {a} during chaos",
            })
    return out


def check_mgr(mgr_stat: dict, expected_daemons: list[str]) -> list[dict]:
    """``mgr_stat``: the mon's `mgr stat` blob after the cluster
    settled.  The mgr is never in the data path, so the only mgr
    invariants are (a) an ACTIVE mgr exists again after the thrash,
    (b) its report streams RESUMED — every expected live daemon shows
    in the digest's reporting set with a fresh digest — and (c) its
    analytics engine minted no cold XLA launches mid-chaos (checked
    separately via check_cold_launches over the mgr_analytics
    counters)."""
    out: list[dict] = []
    if not mgr_stat.get("active"):
        out.append({
            "invariant": "no_active_mgr",
            "detail": f"MgrMap has no active mgr: {mgr_stat!r}",
        })
        return out
    age = mgr_stat.get("digest_age")
    if age is None or age > 10.0:
        out.append({
            "invariant": "mgr_digest_stale",
            "detail": f"last digest {age!r}s old — report stream "
            "never resumed after failover",
        })
    reporting = set(mgr_stat.get("reporting") or [])
    missing = sorted(set(expected_daemons) - reporting)
    if missing:
        out.append({
            "invariant": "mgr_reports_missing",
            "detail": f"daemons {missing} never re-registered with "
            f"the active mgr (reporting: {sorted(reporting)})",
        })
    return out


def check_slow_osd(obs: dict) -> list[dict]:
    """``obs``: the degraded-disk watcher's observations —
    {"targets": [osd ids], "slow_ops_raised", "outlier_flagged",
    "scrub_deprioritized", "scrub_deferred", "slow_ops_cleared"}.

    The detection/feedback loop must have CLOSED end to end: slow
    commits raised the mon-visible SLOW_OPS warning, the mgr's
    analytics flagged the slowed OSD as an outlier, the OSD learned
    the verdict (MMgrConfigure scrub_deprioritize) and deferred at
    least one background scrub, and after the heal the warning
    CLEARED (a stuck warning is as bad as none)."""
    out: list[dict] = []
    if not obs.get("targets"):
        out.append({
            "invariant": "no_slow_disk_scheduled",
            "detail": "scenario expected a slow_disk event, trace has "
                      "none",
        })
        return out
    if not obs.get("slow_ops_raised"):
        out.append({
            "invariant": "slow_ops_never_raised",
            "detail": "SLOW_OPS never appeared in `ceph health` while "
                      f"osd(s) {obs['targets']} were slowed",
        })
    if not obs.get("outlier_flagged"):
        out.append({
            "invariant": "outlier_never_flagged",
            "detail": "mgr analytics never flagged the slowed osd as "
                      "a latency outlier",
        })
    if not obs.get("scrub_deprioritized"):
        out.append({
            "invariant": "scrub_never_deprioritized",
            "detail": "the slowed osd never received the mgr's "
                      "scrub_deprioritize verdict",
        })
    if not obs.get("scrub_deferred") and obs.get("target_leads_pg"):
        # only judged when the victim LED a pg (the scheduler only
        # schedules pgs this osd leads — no pg, nothing to defer)
        out.append({
            "invariant": "scrub_never_deferred",
            "detail": "the slowed osd led pgs but its scrub scheduler "
                      "never deferred a due scrub while flagged",
        })
    if not obs.get("slow_ops_cleared"):
        out.append({
            "invariant": "slow_ops_never_cleared",
            "detail": "SLOW_OPS still raised after the disk healed "
                      "and the cluster settled",
        })
    return out


def check_disk_faults(fsck_reports: list[dict]) -> list[dict]:
    """``fsck_reports``: per-OSD at-rest verification sweeps
    ({"osd": id, "bad": [...]}).  Any blob still failing its checksum
    after the run settled is injected damage the fault-tolerance chain
    (EIO-as-erasure decode-around, quarantine + background repair, pg
    repair) failed to heal."""
    out: list[dict] = []
    for rep in fsck_reports or []:
        if rep.get("bad"):
            out.append({
                "invariant": "unhealed_disk_damage", "osd": rep.get("osd"),
                "detail": rep["bad"],
            })
    return out


def check_events(obs: dict) -> list[dict]:
    """The cluster event plane under chaos (``obs`` is the runner's
    event-watcher record):

    - when the trace degraded the cluster (kills/outs/disk deaths),
      the mgr progress module must have OBSERVED it: at least one
      progress event, whose completion fraction is monotone
      non-decreasing, reaches 1.0, and is reaped post-settle;
    - every injected daemon death left a crash dump the crash module
      collected (``ceph crash ls``);
    - at settle — after the runner muted the EXPECTED codes
      (RECENT_CRASH for its own injected deaths) — zero UNMUTED
      unexpected health checks remain: chaos debris must not leave the
      operator staring at a warning nobody can explain.
    """
    out: list[dict] = []
    events: dict[str, dict] = obs.get("progress_events") or {}
    if obs.get("expect_progress") and not events:
        out.append({
            "invariant": "progress_never_observed",
            "detail": "the trace degraded the cluster but the mgr "
            "progress module never opened an event",
        })
    for eid, rec in sorted(events.items()):
        fr = rec.get("fractions") or []
        if any(b < a for a, b in zip(fr, fr[1:])):
            out.append({
                "invariant": "progress_regressed", "event": eid,
                "detail": f"completion fractions walked backwards: {fr}",
            })
        if rec.get("final", 0.0) < 1.0:
            out.append({
                "invariant": "progress_incomplete", "event": eid,
                "detail": f"never reached 1.0 (final "
                f"{rec.get('final')}, fractions {fr[-5:]})",
            })
        if not rec.get("reaped"):
            out.append({
                "invariant": "progress_not_reaped", "event": eid,
                "detail": "event still active after settle + grace",
            })
    crash_entities = obs.get("crash_entities") or set()
    for entity, n in sorted((obs.get("deaths") or {}).items()):
        if n > 0 and entity not in crash_entities:
            out.append({
                "invariant": "crash_missing", "entity": entity,
                "detail": f"{n} injected death(s) but no crash dump "
                "collected for it",
            })
    unexpected = sorted(
        set(obs.get("unmuted_checks") or [])
        - set(obs.get("allowed_checks") or []))
    if unexpected:
        out.append({
            "invariant": "unexpected_health_at_settle",
            "detail": f"unmuted health checks at settle: {unexpected}",
        })
    return out


#: write-error codes a partitioned client may legally observe: a
#: deadline firing (ETIMEDOUT), a transient bounce (EAGAIN), or the
#: resend budget exhausting (EIO).  Anything else — and any HANG,
#: which simply never records an error — is an objecter bug.
LEGAL_PARTITION_ERRNOS = frozenset(
    {errno.ETIMEDOUT, errno.EAGAIN, errno.EIO})


def check_client_netem(obs: dict) -> list[dict]:
    """The client-netem ack oracle (``obs`` is the runner's record):

    - the trace must have scheduled client-link faults AND at least
      one partition verdict must have actually BITTEN a client send
      (``client_partitioned_sends`` — an armed rule nothing hit proves
      nothing);
    - every write the objecter FAILED must carry a legal partition-
      facing errno (deadline ETIMEDOUT / EAGAIN / resend-budget EIO)
      — a silent hang records no error and no ack, and is caught by
      the workload never completing; an unexpected errno here is the
      driver misclassifying a partition;
    - zero lost/rolled-back ACKED writes is judged by check_history /
      check_final_reads over the same run (ETIMEDOUT and resend-
      duplicates are legal outcomes; silent loss is not).
    """
    out: list[dict] = []
    if not obs.get("client_events"):
        out.append({
            "invariant": "no_client_event_scheduled",
            "detail": "scenario expected client-link netem events, "
                      "trace has none",
        })
        return out
    stats = obs.get("netem") or {}
    if not stats.get("client_partitioned_sends"):
        out.append({
            "invariant": "client_partition_never_fired",
            "detail": "no client send ever hit an armed client-link "
                      f"partition (netem: {stats})",
        })
    for w in obs.get("errored_writes") or []:
        if w.get("errno") not in LEGAL_PARTITION_ERRNOS:
            out.append({
                "invariant": "illegal_client_error",
                "detail": f"write {w.get('pool')}/{w.get('oid')} "
                          f"v{w.get('version')} failed with errno="
                          f"{w.get('errno')} ({w.get('error')}); legal"
                          " under partition: ETIMEDOUT/EAGAIN/EIO",
            })
    return out


def check_fullness(obs: dict) -> list[dict]:
    """The fullness-pressure gating ladder (``obs`` is the fullness
    watcher's record).  Every rung must have been OBSERVED live and
    the whole ladder must clear after the drain:

    - OSD_NEARFULL and OSD_BACKFILLFULL health raised (mon statfs
      ingestion -> map bits -> health checks);
    - backfill actually PAUSED at backfillfull: a remote reservation
      answered REJECT_TOOFULL on the fullness branch
      (recovery.py ``backfill_reject_toofull`` counter grew);
    - OSD_FULL raised and a client write BOUNCED with ENOSPC while
      the map carried the FULL bit;
    - the local failsafe was never breached: no store's observed
      usage ratio reached osd_failsafe_full_ratio (the gate exists so
      the mon's full bit always engages first);
    - after the drain the entire ladder CLEARED and the cluster
      converged (convergence itself is check_converged's verdict).
    """
    out: list[dict] = []
    for key, name in (
        ("nearfull_raised", "OSD_NEARFULL"),
        ("backfillfull_raised", "OSD_BACKFILLFULL"),
        ("full_raised", "OSD_FULL"),
    ):
        if not obs.get(key):
            out.append({
                "invariant": "fullness_check_never_raised",
                "detail": f"{name} never appeared in `ceph health` "
                          "while the ladder was driven",
            })
    if not obs.get("backfill_rejects"):
        out.append({
            "invariant": "backfill_never_paused",
            "detail": "no REJECT_TOOFULL reservation was observed "
                      "while a backfillfull osd was a backfill target",
        })
    if not obs.get("enospc_bounced"):
        out.append({
            "invariant": "enospc_never_bounced",
            "detail": "no client write bounced ENOSPC while the map "
                      "carried a FULL bit",
        })
    peak = float(obs.get("failsafe_peak") or 0.0)
    failsafe = float(obs.get("failsafe_ratio") or 1.0)
    if peak >= failsafe:
        out.append({
            "invariant": "failsafe_breached",
            "detail": f"observed usage ratio {peak:.3f} >= "
                      f"osd_failsafe_full_ratio {failsafe:.3f}",
        })
    if not obs.get("ladder_cleared"):
        out.append({
            "invariant": "fullness_never_cleared",
            "detail": "fullness health checks still raised after the "
                      "drain and settle "
                      f"(remaining: {obs.get('checks_at_settle')})",
        })
    return out


def check_load(rec: dict, expected_tenants: list[str]) -> list[dict]:
    """The composed chaos x load verdict (``rec`` is the load
    harness's run record).  Production is thrash AND traffic at once,
    so the harness's whole gate set must hold THROUGH the thrash:

    - zero op errors and a fully-drained in-flight set (the objecter
      retried every op through the cuts/kills to completion);
    - the self-verifying payload sweep found zero lost/corrupt acked
      writes;
    - SLO percentiles present (client-side p50/p95/p99 computed over
      real completions);
    - the client-vs-mgr latency cross-check AGREES (the report plane
      survived the thrash too);
    - per-tenant ``qos_*`` fairness counters present for every
      profile tenant (the mClock gate differentiated under pressure);
    - cold_launches == 0 and host_transfers == 0 (also delta-checked
      cluster-wide by check_cold_launches).
    """
    out: list[dict] = []
    lat = (rec.get("latency") or {})
    if lat.get("errors"):
        out.append({
            "invariant": "load_op_errors",
            "detail": f"{lat['errors']} ops failed "
                      f"(samples: {rec.get('error_samples')})",
        })
    if rec.get("undrained"):
        out.append({
            "invariant": "load_undrained",
            "detail": f"{rec['undrained']} ops never completed",
        })
    v = rec.get("verify") or {}
    if v.get("mismatches") or v.get("lost"):
        out.append({
            "invariant": "load_acked_write_lost",
            "detail": f"payload sweep: {v}",
        })
    overall = lat.get("overall") or {}
    if not all(overall.get(k, 0) > 0
               for k in ("p50_us", "p95_us", "p99_us")):
        out.append({
            "invariant": "load_percentiles_missing",
            "detail": f"latency overall row: {overall}",
        })
    if not (rec.get("client_vs_mgr") or {}).get("agree"):
        out.append({
            "invariant": "load_mgr_crosscheck_failed",
            "detail": f"client_vs_mgr: {rec.get('client_vs_mgr')}",
        })
    qos = rec.get("qos") or {}
    missing = [t for t in expected_tenants
               if not (qos.get(t) or {}).get("admitted")]
    if missing:
        out.append({
            "invariant": "load_qos_rows_missing",
            "detail": f"tenants {missing} have no admitted ops in "
                      f"the qos fairness rows ({sorted(qos)})",
        })
    if rec.get("cold_launches"):
        out.append({
            "invariant": "load_cold_launches",
            "detail": f"{rec['cold_launches']} cold launches mid-load",
        })
    if rec.get("host_transfers"):
        out.append({
            "invariant": "load_host_transfers",
            "detail": f"{rec['host_transfers']} implicit transfers",
        })
    return out


def check_domains(obs: list[dict], expect_kill: bool = True) -> list[dict]:
    """Judge the failure-domain snapshots taken before correlated kills.

    Each record is a :meth:`ChaosCluster._domains_snapshot` — taken at
    the instant a rack/host kill fires, BEFORE the members die, so the
    placement it captures is the one the acked writes relied on.  Two
    claims per rack-domain pool:

    - separation: CRUSH put at most ONE shard/replica of any PG into
      any single rack (``max_shards_per_domain <= 1``) — otherwise a
      whole-rack loss could take out two shards of the same stripe and
      the durability story is fiction;
    - survivability: after deleting every OSD of the killed rack(s),
      every PG still holds >= ``need`` shards (k for EC, 1 replica for
      replicated), so every acked write stays readable through the
      correlated loss.
    """
    out: list[dict] = []
    if expect_kill and not obs:
        out.append({
            "invariant": "domains_no_kill_observed",
            "detail": "rack_script scenario recorded no rack/host kill "
                      "snapshots — the correlated-failure beat never fired",
        })
    for rec in obs:
        for name, p in (rec.get("pools") or {}).items():
            if p.get("max_shards_per_domain", 0) > 1:
                out.append({
                    "invariant": "domains_not_separated",
                    "detail": f"pool {name}: {p['max_shards_per_domain']} "
                              f"shards of one PG share a rack "
                              f"(kill={rec.get('killed_racks')})",
                })
            surv = p.get("min_surviving_shards")
            if surv is not None and surv < p.get("need", 1):
                out.append({
                    "invariant": "domains_insufficient_survivors",
                    "detail": f"pool {name}: only {surv} shard(s) survive "
                              f"rack loss {rec.get('killed_racks')}, "
                              f"need {p.get('need', 1)}",
                })
    return out


def check_backfill(obs: dict) -> list[dict]:
    """Judge a soak run's backfill evidence.

    ``obs`` is a cluster-wide delta of the ``backfill_started`` /
    ``backfill_completed`` perf counters across the run (the counters
    are process-global, so daemon restarts do not reset them).  A
    soak run exists to force the backfill path — trim pressure must
    have pushed the log tail past the revived member — so:

    - ``backfill_started > 0``: recovery actually took the backfill
      branch (if log-delta recovery sufficed, the trim pressure or
      outage length is miscalibrated and the scenario proves nothing);
    - ``backfill_completed > 0``: at least one pass converged;
    - with an interrupt scripted, ``started > completed``: the
      mid-transfer kill landed inside a pass (the cut-short pass
      starts but never completes; the re-run after revive does both).
    """
    out: list[dict] = []
    started = obs.get("backfill_started", 0)
    completed = obs.get("backfill_completed", 0)
    if started <= 0:
        out.append({
            "invariant": "backfill_never_ran",
            "detail": "backfill_started delta == 0: recovery never took "
                      "the backfill path despite soak trim pressure",
        })
    if completed <= 0:
        out.append({
            "invariant": "backfill_never_completed",
            "detail": f"backfill_completed delta == 0 "
                      f"(started={started}): no pass converged",
        })
    if obs.get("interrupt_scripted") and started <= completed:
        out.append({
            "invariant": "backfill_never_interrupted",
            "detail": f"started={started} <= completed={completed}: the "
                      f"scripted mid-transfer kill missed every pass",
        })
    return out


#: checker registry: name -> callable, for reporting
ALL_INVARIANTS = (
    "history", "final_reads", "converged", "quorum", "scrub",
    "disk_faults", "cold_launches", "mgr", "slow_osd", "events",
    "client_netem", "fullness", "load", "domains", "backfill",
)


def touched_checkers(result: dict) -> list[str]:
    """Which checkers a finished run gave NONZERO WORK — the fuzz
    plane's coverage signal (a checker that merely ran against an
    empty observation record proves nothing was exercised).  Judged
    from the run's result record alone so committed artifacts replay
    the same answer; a checker that was judged at all counts only
    when its domain shows evidence: writes for the history oracles,
    injected deltas for disk faults, observed sends for netem, raised
    rungs for fullness, and so on."""
    judged = set(result.get("invariants") or ())
    wl = result.get("workload") or {}
    cov = result.get("coverage") or {}
    deltas = cov.get("perf_deltas") or {}
    out: set[str] = set()
    if wl.get("writes", 0) or wl.get("load_ops", 0):
        out |= {"history", "final_reads"} & judged
    if result.get("events_applied", 0):
        out |= {"converged", "quorum", "scrub"} & judged
    if result.get("disk_faults"):
        out.add("disk_faults")
    if "cold_launches" in judged:
        out.add("cold_launches")
    if "mgr" in judged and (
            any(k.startswith("mgr.")
                for k in (cov.get("deaths") or {}))
            or any(k.startswith("mgr_analytics.") and v
                   for k, v in deltas.items())):
        # a failover the report plane absorbed, or analytics that
        # verifiably digested this run's reports
        out.add("mgr")
    slow = result.get("slow_osd_obs") or {}
    if slow.get("slow_ops_raised"):
        out.add("slow_osd")
    ev = result.get("events_obs") or {}
    if ev.get("events") or ev.get("deaths") or ev.get(
            "crash_entities"):
        out.add("events")
    cn = result.get("client_netem_obs") or {}
    if (cn.get("client_partitioned_sends")
            or cn.get("client_dropped_sends")
            or cn.get("client_delayed_sends")):
        out.add("client_netem")
    fl = result.get("fullness_obs") or {}
    if (fl.get("nearfull_raised") or fl.get("backfillfull_raised")
            or fl.get("full_raised")):
        out.add("fullness")
    if result.get("load"):
        out.add("load")
    if result.get("domains_obs"):
        out.add("domains")
    bf = result.get("backfill_obs") or {}
    if bf.get("backfill_started", 0) > 0:
        out.add("backfill")
    elif "backfill" not in judged and (
            deltas.get("backfill_started", 0) > 0):
        # cross-bred traces run backfill in scenarios that never
        # judged check_backfill: the counter movement IS the touch
        out.add("backfill")
    if any(k.startswith("tier_") and v for k, v in deltas.items()):
        # tier machinery moved: the history oracles judged redirects
        out.add("tier")
    return sorted(out)
