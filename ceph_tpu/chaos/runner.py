"""Chaos runner: replay a generated schedule against a live cluster.

The teuthology-thrasher role (qa/tasks/thrasher.py do_thrash loop),
inverted for determinism: the schedule is generated up front
(ceph_tpu/chaos/schedule.py), the runner boots a mini-cluster, starts
the recording workload, applies each event at its virtual time, then
settles the cluster and judges every durability invariant
(ceph_tpu/chaos/invariants.py):

1. workload history clean (no lost/stale/corrupt read),
2. final + snap reads return the acked content,
3. cluster converges back to active+clean within the bound,
4. every monitor agrees on one leader and one map epoch,
5. post-thrash deep scrub over every PG reports zero inconsistencies,
6. (disk-fault scenarios) every store's at-rest fsck sweep is clean —
   injected rot was healed or its OSD re-placed,
7. the decode/scrub batchers minted ZERO cold XLA launches — chaos
   must exercise the prewarmed recovery path, not compile mid-flight.

Every applied event opens a ``chaos`` tracer span and counts into the
``chaos`` perf collection (dumped by the daemons' ``dump_chaos``
admin-socket command).
"""

from __future__ import annotations

import asyncio
import logging
import time

from ceph_tpu.chaos import chaos_counters, chaos_tracer
from ceph_tpu.chaos.netem import Netem
from ceph_tpu.chaos.schedule import generate_schedule, trace_hash
from ceph_tpu.chaos.workload import Workload
from ceph_tpu.chaos import invariants as inv

log = logging.getLogger("ceph_tpu.chaos")


#: built-in scenario configs (the qa/suites role).  Each is a plain
#: dict so CLI users can ship their own as JSON.
SCENARIOS: dict[str, dict] = {
    # the classic OSDThrasher: kill/revive, out/in, reweight, repair
    # and balancer runs against replicated + EC pools.  A mgr rides
    # along so the EVENT-PLANE invariant (check_events) can watch
    # progress events open/complete/reap and crash dumps land for
    # every injected kill.  (n_mgrs/watch_events/conf do not feed the
    # schedule generator's draws — trace hashes are unchanged.)
    "osd_thrash": {
        "name": "osd_thrash",
        "n_osds": 5, "n_mons": 1, "n_mgrs": 1,
        "watch_events": True,
        "duration": 3.0, "n_events": 9,
        "mix": {"osd_kill": 3.0, "osd_out": 2.0, "reweight": 1.0,
                "scrub": 0.5, "repair": 0.5, "balance": 0.5},
        "conf": {
            # fast mgr cadences so short degraded windows are observed
            "mgr_report_interval": 0.2, "mgr_digest_interval": 0.2,
            "mgr_module_tick_interval": 0.15,
            "mgr_progress_complete_grace": 1.0,
        },
        "pools": [
            {"name": "rep", "type": "replicated", "pg_num": 4,
             "size": 2, "snaps": True},
            {"name": "ec", "type": "erasure", "pg_num": 2,
             "k": 2, "m": 1, "snaps": True},
        ],
        "workload": {"objects": 3, "rounds": 3, "object_size": 8192},
    },
    # deterministic network faults: partitions, one-way drops, delay,
    # bounded reordering — the netem shim's beat
    "netem_storm": {
        "name": "netem_storm",
        "n_osds": 4, "n_mons": 1,
        "duration": 3.0, "n_events": 10,
        "mix": {"partition": 2.0, "drop_oneway": 2.0, "delay": 2.0,
                "reorder": 2.0, "netem_clear": 0.5},
        "max_partitions": 1,
        "pools": [
            {"name": "rep", "type": "replicated", "pg_num": 4,
             "size": 2, "snaps": True},
            {"name": "ec", "type": "erasure", "pg_num": 2,
             "k": 2, "m": 1, "snaps": True},
        ],
        "workload": {"objects": 3, "rounds": 3, "object_size": 8192},
    },
    # disk-fault chaos: the store layer lies — one-shot EIOs, at-rest
    # bit flips, torn commits and a sticky-dead disk, against OSDs on
    # REAL BlockStore devices (checksum-at-rest + BlueFS-lite), so
    # injected rot surfaces exactly as production media errors do.
    # Exercises EIO-as-erasure decode-around, replicated read
    # failover, the read-error ledger's self-markdown escalation, and
    # quarantine + background repair; self_heal runs a repair sweep
    # before the deep-scrub verdict and fsck proves the platters are
    # clean at rest.
    "disk-fault": {
        "name": "disk-fault",
        "n_osds": 5, "n_mons": 1, "n_mgrs": 1,
        "watch_events": True,
        # ledger damage outlives the run on surviving daemons: the
        # devicehealth warning at settle is EXPECTED, not debris
        "settle_allowed_health": ["DEVICE_HEALTH"],
        "store": "blockstore",
        "self_heal": True,
        "duration": 3.0, "n_events": 10,
        "mix": {"eio": 2.5, "bitflip": 2.0, "torn_write": 1.5,
                "disk_dead": 0.5, "osd_kill": 0.5,
                "deep_scrub": 0.5, "repair": 0.5},
        "max_dead": 1,
        "conf": {
            "mgr_report_interval": 0.2, "mgr_digest_interval": 0.2,
            "mgr_module_tick_interval": 0.15,
            "mgr_progress_complete_grace": 1.0,
        },
        "pools": [
            {"name": "rep", "type": "replicated", "pg_num": 4,
             "size": 2, "snaps": True},
            {"name": "ec", "type": "erasure", "pg_num": 2,
             "k": 2, "m": 1},
        ],
        "workload": {"objects": 3, "rounds": 3, "object_size": 8192},
    },
    # mgr-plane chaos: kill/revive manager daemons (active AND
    # standby) under client load.  Invariants: report streams resume
    # after every failover (an active mgr exists, every live OSD
    # re-registers, the digest is fresh), the analytics engine mints
    # zero cold XLA launches, and — because the mgr is never in the
    # data path — the client workload invariants are untouched.
    "mgr-failover": {
        "name": "mgr-failover",
        "n_osds": 4, "n_mons": 1, "n_mgrs": 2,
        "duration": 3.0, "n_events": 8,
        "mix": {"mgr_kill": 3.0, "osd_kill": 1.0, "scrub": 0.5,
                "balance": 0.5},
        "pools": [
            {"name": "rep", "type": "replicated", "pg_num": 4,
             "size": 2, "snaps": True},
            {"name": "ec", "type": "erasure", "pg_num": 2,
             "k": 2, "m": 1},
        ],
        "workload": {"objects": 3, "rounds": 3, "object_size": 8192},
    },
    # degraded-disk chaos: one OSD's store goes SLOW (sticky injected
    # commit latency — the disk still answers, late), under client
    # load with a live mgr.  The detection/feedback chain under test:
    # slow commits -> op-tracker complaints -> SLOW_OPS health warning
    # (mgr digest -> `ceph health`); slow subop_w latency -> mgr
    # analytics outlier detection -> MMgrConfigure scrub_deprioritize
    # -> the victim's scrub scheduler defers background scrubs.  The
    # slow_osd invariant requires all of it observed AND the warning
    # CLEARED after the disk heals (the ROADMAP item-(e) loop).
    "degraded-disk": {
        "name": "degraded-disk",
        "n_osds": 5, "n_mons": 1, "n_mgrs": 1,
        "duration": 6.0, "n_events": 6,
        "slow_disk_at": 0.3, "slow_disk_delay": 0.5,
        "watch_slow_osd": True,
        "mix": {"scrub": 1.0, "deep_scrub": 0.5, "reweight": 0.5},
        "conf": {
            # complaint threshold under the injected delay so slow
            # writes COUNT, and short windows so raise/clear both fit
            # the run
            "osd_op_complaint_time": 0.25,
            "mgr_slow_ops_warn_window": 3.0,
            # frequent background scrubs so the deprioritization has
            # scheduling decisions to defer inside the run
            "osd_scrub_interval": 1.0,
            "osd_deep_scrub_interval": 3600.0,
            "osd_scrub_deprioritize_factor": 8.0,
        },
        "pools": [
            {"name": "rep", "type": "replicated", "pg_num": 4,
             "size": 2, "snaps": True},
            {"name": "ec", "type": "erasure", "pg_num": 2,
             "k": 2, "m": 1},
        ],
        # paced writers (write_gap) so the write stream SPANS the
        # slow window: complaints and latency samples must keep
        # flowing while the mgr's report/analytics/digest pipeline
        # observes the slow disk
        "workload": {"objects": 4, "rounds": 6, "object_size": 8192,
                     "write_gap": 0.7},
    },
    # client-plane netem: the async objecter (PR 10: per-op deadline/
    # backoff/map-wait drivers, coalesced bursts, bounded windows)
    # joins the blast radius for the first time — the workload client's
    # messenger wears the shim, and the schedule cuts/drops/delays
    # CLIENT<->OSD links (mon links stay up: the command plane is the
    # observer).  One early client partition is pinned per trace
    # (client_partition_at) so the ack oracle always has a partition
    # that verifiably fired; drops run in BOTH directions — vanished
    # requests drive the deadline/backoff beat, vanished ACKS of
    # applied writes drive resend-dedup-by-reqid.  check_client_netem
    # + the history/final-read oracles judge it: a partitioned client
    # may see ETIMEDOUT or resend-duplicates, never a lost or
    # rolled-back acked write.
    "client-netem": {
        "name": "client-netem",
        "n_osds": 4, "n_mons": 1,
        "client_netem": True,
        "client_partition_at": 0.3,
        "duration": 4.0, "n_events": 10,
        "max_client_cuts": 1,
        "mix": {"client_partition": 2.5, "client_drop": 2.0,
                "client_delay": 1.5, "osd_kill": 0.5, "scrub": 0.5},
        "pools": [
            {"name": "rep", "type": "replicated", "pg_num": 4,
             "size": 2, "snaps": True},
            {"name": "ec", "type": "erasure", "pg_num": 2,
             "k": 2, "m": 1, "snaps": True},
        ],
        # paced writers so the write stream SPANS the cut windows —
        # acks must be earned through partitions, not before them
        "workload": {"objects": 3, "rounds": 4, "object_size": 8192,
                     "write_gap": 0.3},
    },
    # fullness-pressure: small-capacity BlockStore OSDs driven up the
    # whole gating ladder WHILE recovery runs.  The scripted skeleton
    # (schedule.py fullness_script) fills to nearfull, then
    # backfillfull, THEN outs an osd so the triggered backfill meets
    # REJECT_TOOFULL live (recovery.py backfillfull gate), then fills
    # to full (client writes must bounce ENOSPC against the map's
    # FULL bit), then drains.  The ratios are widened via conf so the
    # ladder is robust to CRUSH imbalance on tiny stores — the
    # SEMANTICS under test (statfs -> mon bits -> health/gating ->
    # heal) are ratio-independent.  check_fullness demands every rung
    # observed, the failsafe never breached, and the ladder CLEARED.
    "fullness-pressure": {
        "name": "fullness-pressure",
        "n_osds": 5, "n_mons": 1,
        "store": "blockstore",
        "capacity_bytes": 4 << 20,
        "ballast_size": 128 * 1024,
        "ballast_pool": "rep",
        "fullness_script": True,
        "nearfull_fill": 0.50, "backfillfull_fill": 0.62,
        "full_fill": 0.82,
        "duration": 3.0, "n_events": 2,
        "mix": {"scrub": 1.0, "deep_scrub": 1.0},
        "conf": {
            "mon_osd_nearfull_ratio": 0.45,
            "mon_osd_backfillfull_ratio": 0.55,
            "mon_osd_full_ratio": 0.80,
            "osd_beacon_report_interval": 0.2,
        },
        "pools": [
            {"name": "rep", "type": "replicated", "pg_num": 8,
             "size": 2, "snaps": True},
            {"name": "ec", "type": "erasure", "pg_num": 2,
             "k": 2, "m": 1},
        ],
        "workload": {"objects": 2, "rounds": 2, "object_size": 8192},
    },
    # chaos x loadgen composition: a deterministic LOAD trace
    # (ceph_tpu/loadgen) replayed THROUGH a thrash trace in one run —
    # production is both at once.  The load harness attaches to the
    # chaos cluster in external mode (rados/ec planes), streams its
    # telemetry to the chaos mgr, and its full gate set — the
    # self-verifying payload sweep, per-tenant qos_* fairness
    # counters, SLO percentiles, client-vs-mgr cross-check,
    # cold_launches == 0 and host_transfers == 0 — is judged TOGETHER
    # with the chaos invariants (check_load + converged/quorum/scrub).
    "compose_load": {
        "name": "compose_load",
        "n_osds": 4, "n_mons": 1, "n_mgrs": 1,
        "duration": 4.0, "n_events": 8,
        "mix": {"osd_kill": 2.0, "osd_out": 1.0, "delay": 1.5,
                "reorder": 1.0, "scrub": 0.5, "balance": 0.5},
        "load_profile": {"profile": "compose_smoke"},
        "conf": {
            "mgr_report_interval": 0.25, "mgr_digest_interval": 0.25,
            "mgr_stats_max_metrics": 24,
            "osd_mclock_client_profiles": "gold:20.0,bronze:2.0",
        },
        # the harness's own pools, pre-created here so the thrash
        # events (scrub/repair/balance) target what the load hits
        "pools": [
            {"name": "lg-rep", "type": "replicated", "pg_num": 8,
             "size": 2},
            {"name": "lg-ec", "type": "erasure", "pg_num": 4,
             "k": 2, "m": 1},
        ],
    },
    # monitor-plane chaos: restarts + osd kills over a 3-mon quorum,
    # plus pg_num splitting mid-storm
    "quorum_thrash": {
        "name": "quorum_thrash",
        "n_osds": 4, "n_mons": 3,
        "duration": 3.0, "n_events": 8,
        "mix": {"mon_restart": 2.0, "osd_kill": 1.0, "pg_split": 1.0,
                "scrub": 0.5, "balance": 0.5},
        "max_splits": 1,
        "pools": [
            {"name": "rep", "type": "replicated", "pg_num": 2,
             "size": 2, "snaps": True},
            {"name": "ec", "type": "erasure", "pg_num": 2,
             "k": 2, "m": 1},
        ],
        "workload": {"objects": 3, "rounds": 3, "object_size": 8192},
    },
    # rack-scale correlated failure: a real CRUSH topology (4 racks x
    # 1 host x 2 osds) with rack failure-domain rules on BOTH pool
    # types — the replicated pool rides the pre-registered
    # chaos_rack_rule, the EC pool's profile carries
    # crush-failure-domain=rack — and the scripted skeleton kills a
    # WHOLE rack at once, dwells, revives, then kills one host in a
    # different rack.  check_domains proves (pre-kill) that CRUSH put
    # at most one shard of any PG in any rack and that every PG
    # retained >= k shards / >= 1 replica through whole-rack loss;
    # the history/final-read oracles prove every acked write survived.
    "rack-loss": {
        "name": "rack-loss",
        "n_osds": 8, "n_mons": 1, "n_mgrs": 1,
        "watch_events": True,
        "topology": {"racks": 4, "hosts_per_rack": 1,
                     "osds_per_host": 2},
        "rack_script": True,
        "host_kill_after": True,
        "rack_dwell": 1.6,
        "duration": 5.0, "n_events": 5,
        "mix": {"scrub": 1.0, "deep_scrub": 0.5, "delay": 0.5},
        "conf": {
            "mgr_report_interval": 0.2, "mgr_digest_interval": 0.2,
            "mgr_module_tick_interval": 0.15,
            "mgr_progress_complete_grace": 1.0,
        },
        "pools": [
            {"name": "rep", "type": "replicated", "pg_num": 4,
             "size": 3, "failure_domain": "rack", "snaps": True},
            {"name": "ec", "type": "erasure", "pg_num": 2,
             "k": 2, "m": 1, "failure_domain": "rack"},
        ],
        # paced writers so acks are earned THROUGH the rack outage,
        # not banked before it
        "workload": {"objects": 3, "rounds": 6, "object_size": 8192,
                     "write_gap": 0.5},
    },
    # control-plane blast radius: mon/mgr/mds links wear netem rules
    # (delay/partition/drop toward the osd plane) while the data-plane
    # workload runs.  The scripted skeleton guarantees one beat per
    # plane; the mix draws more.  The oracle: the data plane is
    # UNTOUCHED (history/final reads clean), the cluster converges,
    # and mgr report streams resume.
    "control-net": {
        "name": "control-net",
        "n_osds": 4, "n_mons": 3, "n_mgrs": 1,
        "control_netem": True,
        "duration": 4.0, "n_events": 8,
        "mix": {"mon_netem": 2.0, "mgr_netem": 1.5, "mds_netem": 0.5,
                "osd_kill": 0.5, "scrub": 0.5},
        "pools": [
            {"name": "rep", "type": "replicated", "pg_num": 4,
             "size": 2, "snaps": True},
            {"name": "ec", "type": "erasure", "pg_num": 2,
             "k": 2, "m": 1},
        ],
        "workload": {"objects": 3, "rounds": 4, "object_size": 8192,
                     "write_gap": 0.3},
    },
    # long-soak log-trim chaos: aggressive osd_min/max_pg_log_entries
    # keep every pg log tiny while paced writers churn well past the
    # trim horizon during a LONG scripted outage — so the revived
    # member genuinely predates every surviving log tail and recovery
    # MUST take the backfill path (not the log delta).  A second kill
    # then lands while that backfill runs (osd_recovery_sleep paces
    # the pass so the interrupt verifiably catches it mid-transfer);
    # check_backfill demands the backfill_started/backfill_completed
    # counter pair prove backfill ran, was interrupted, and still
    # converged — with zero lost/stale reads and cold_launches == 0.
    "soak-trim-backfill": {
        "name": "soak-trim-backfill",
        "n_osds": 5, "n_mons": 1, "n_mgrs": 1,
        "watch_events": True,
        "soak_script": True,
        "soak_interrupt": "target",
        "soak_outage": 5.0,
        "duration": 10.0, "n_events": 4,
        "mix": {"scrub": 1.0, "deep_scrub": 0.5},
        "conf": {
            "osd_min_pg_log_entries": 8,
            "osd_max_pg_log_entries": 16,
            # serialize reconciles and pace each one: pushes then land
            # every 0.3s across the pass, so the gated interrupt kill
            # reliably strikes BETWEEN pushes and fails the remainder
            # (max_active 4 would finish every push in the first few
            # ms and leave only sleeps for the kill to hit)
            "osd_recovery_sleep": 0.3,
            "osd_recovery_max_active": 1,
            "mgr_report_interval": 0.2, "mgr_digest_interval": 0.2,
            "mgr_module_tick_interval": 0.15,
            "mgr_progress_complete_grace": 1.0,
        },
        "pools": [
            {"name": "rep", "type": "replicated", "pg_num": 2,
             "size": 2, "snaps": True},
            {"name": "ec", "type": "erasure", "pg_num": 2,
             "k": 2, "m": 1},
        ],
        # many paced writers: the stream must SPAN the whole outage so
        # the trim horizon provably passes the down member's log
        "workload": {"objects": 8, "rounds": 24, "object_size": 4096,
                     "write_gap": 0.33},
    },
    # cache-tier chaos: a replicated writeback tier over an EC base
    # pool (osd tier add / cache-mode / set-overlay), with the trace
    # driving the PrimaryLogPG tier machinery live — CACHE_FLUSH and
    # CACHE_EVICT against the hot pool, promote-on-miss reads via the
    # base — while paced writers keep minting new dirty versions
    # through the overlay.  The oracle is the interleave-fuzz one
    # (tests/test_interleave_fuzz.py): last-write-wins must hold
    # through every redirect/flush/evict/promote interleaving, so the
    # versioned history/final-read checks judge it with no new
    # invariant.  Evicting a dirty object is EBUSY and a flush racing
    # a promote may bounce — refused events are chaos, recorded in
    # event_errors, never violations.
    "cache-tier": {
        "name": "cache-tier",
        "n_osds": 5, "n_mons": 1,
        "duration": 4.0, "n_events": 10,
        "tier": {"base": "base", "hot": "hot", "mode": "writeback"},
        "mix": {"tier_flush": 2.0, "tier_evict": 2.0,
                "tier_promote": 2.0, "osd_kill": 1.0, "scrub": 0.5,
                "delay": 0.5},
        "pools": [
            {"name": "base", "type": "erasure", "pg_num": 4,
             "k": 2, "m": 1},
            # the hot pool is the tier, not a workload target: the
            # workload reaches it THROUGH the base pool's overlay
            {"name": "hot", "type": "replicated", "pg_num": 4,
             "size": 2, "workload": False},
        ],
        # paced writers so flush/evict/promote events interleave a
        # LIVE dirty stream, not a settled corpus
        "workload": {"objects": 3, "rounds": 4, "object_size": 8192,
                     "write_gap": 0.3},
    },
}


def _cold_launch_snapshot() -> dict:
    """cold_launches on the process-wide batchers (delta-checked:
    the collections are process-global and other work may have warmed
    them before this run).  The mgr analytics engine follows the same
    discipline — its prewarm at mgr start cancels the counter, so any
    growth here is a compile on the digest path."""
    from ceph_tpu.common.metrics import get_perf_counters
    from ceph_tpu.common.transfer_guard import snapshot as tg_snapshot
    from ceph_tpu.parallel import decode_batcher, scrub_batcher

    return {
        "decode_batch": int(
            decode_batcher.shared().stats.get("cold_launches", 0)),
        "scrub_verify_batch": int(
            scrub_batcher.shared().stats.get("cold_launches", 0)),
        "mgr_analytics": int(get_perf_counters(
            "mgr_analytics").dump().get("cold_launches", 0)),
        # the transfer guard's violation counter rides the same
        # delta-checked snapshot: chaos that provokes an implicit
        # host<->device transfer inside a guarded steady-state launch
        # fails exactly like an in-path XLA compile would
        "transfer_guard_host_transfers": tg_snapshot()["host_transfers"],
    }


class ChaosCluster:
    """Mini-cluster under chaos: mons + OSDs + recording client, every
    messenger wearing one shared netem shim."""

    def __init__(self, scenario: dict, time_scale: float = 1.0):
        self.scenario = scenario
        self.time_scale = time_scale
        self.netem = Netem()
        self.mons: list = []
        self.monmap: list[tuple[str, int]] = []
        self.osds: list = []
        self.mgrs: list = []
        self.client = None
        self._crush_template = None
        self._heal_tasks: set = set()
        self.event_errors: list[dict] = []
        self.events_applied = 0
        self._store_dir: str | None = None
        self._stores: dict[int, object] = {}  # osd id -> mounted store
        # entity -> injected-death count (kills + self-escalations);
        # the check_events invariant demands a crash dump for each
        self.deaths: dict[str, int] = {}
        # composed-mode load harness (run_scenario sets it; teardown
        # must stop it before the daemons go away)
        self.load_harness = None
        # fullness-pressure state: ballast object names written by
        # fill events (drain deletes them) + the watcher/fill
        # observation record check_fullness judges
        self._ballast_names: list[str] = []
        # failure-domain placement snapshots: one record per
        # rack/host kill, taken BEFORE the kill lands (check_domains
        # judges that CRUSH separated shards across domains while the
        # doomed rack was still up, and that every PG retained enough
        # shards to survive whole-rack loss)
        self.domains_obs: list[dict] = []
        # baseline for the backfill-interrupt gate: perf counters are
        # process-global, so sweep runs sharing this process must
        # judge in-flight passes against a per-run snapshot
        self._backfill_gate_base: tuple[float, float] = (0.0, 0.0)
        self.fullness: dict = {
            "nearfull_raised": False, "backfillfull_raised": False,
            "full_raised": False, "enospc_bounced": False,
            "backfill_rejects": 0.0, "failsafe_peak": 0.0,
            "ladder_cleared": False,
        }
        import tempfile

        # run-scoped crash_dir: every daemon persists dumps here and
        # the mgr crash module collects them (`ceph crash ls`)
        self.crash_dir = tempfile.mkdtemp(prefix="chaos-crash-")

    def _conf(self):
        """Per-daemon ConfigProxy carrying the scenario's overrides +
        the run-scoped crash_dir (fresh per daemon: config observers
        must not cross daemons)."""
        from ceph_tpu.common import ConfigProxy

        overrides = dict(self.scenario.get("conf") or {})
        overrides.setdefault("crash_dir", self.crash_dir)
        return ConfigProxy(overrides)

    def _note_death(self, entity: str) -> None:
        self.deaths[entity] = self.deaths.get(entity, 0) + 1

    def _make_store(self, osd_id: int):
        """Per-scenario store engine: 'blockstore' puts each OSD on a
        real BlockStore device (checksum-at-rest + BlueFS-lite KV) in
        a run-scoped tempdir — the disk-fault scenario needs a store
        whose bit rot surfaces as EIO, like production media."""
        if self.scenario.get("store") != "blockstore":
            return None
        import os
        import tempfile

        from ceph_tpu.store.blockstore import BlockStore

        if self._store_dir is None:
            self._store_dir = tempfile.mkdtemp(prefix="chaos-disk-")
        store = BlockStore(
            os.path.join(self._store_dir, f"osd{osd_id}"),
            capacity_bytes=int(
                self.scenario.get("capacity_bytes", 1 << 40)))
        store.mount()
        self._stores[osd_id] = store
        return store

    # -- lifecycle -----------------------------------------------------

    async def start(self) -> None:
        from ceph_tpu.client import RadosClient
        from ceph_tpu.crush import builder as B
        from ceph_tpu.crush.types import CrushMap
        from ceph_tpu.mon import Monitor
        from ceph_tpu.osd.daemon import OSDDaemon

        sc = self.scenario
        self._backfill_gate_base = self._backfill_totals()
        crush = CrushMap()
        topo = sc.get("topology")
        if topo:
            # rack-scale failure domains: root -> rack -> host -> osd,
            # with a pre-registered rack-separated replicated rule the
            # mon's pool-create honors by name (EC pools get their
            # failure domain through the profile's
            # crush-failure-domain key instead)
            per_host = int(topo.get("osds_per_host", 1))
            hosts_per_rack = int(topo.get("hosts_per_rack", 1))
            n_racks = int(topo["racks"])
            if n_racks * hosts_per_rack * per_host != sc["n_osds"]:
                raise ValueError(
                    f"topology {topo} does not cover n_osds="
                    f"{sc['n_osds']}")
            root = B.build_rack_hierarchy(
                crush, osds_per_host=per_host,
                hosts_per_rack=hosts_per_rack, n_racks=n_racks)
            rid = B.add_simple_rule(
                crush, root.id, crush.type_id(
                    topo.get("failure_domain", "rack")))
            crush.rule_names["chaos_rack_rule"] = rid
        else:
            B.build_hierarchy(
                crush, osds_per_host=1, n_hosts=sc["n_osds"])
        self._crush_template = crush
        n_mons = sc.get("n_mons", 1)
        self.mons = [
            Monitor(crush=crush.copy(), rank=r, n_mons=n_mons,
                    conf=self._conf())
            for r in range(n_mons)
        ]
        for m in self.mons:
            self.netem.attach(m.messenger)
            await m.start()
        self.monmap = [m.addr for m in self.mons]
        if n_mons > 1:
            for m in self.mons:
                await m.open_quorum(list(self.monmap))
            for m in self.mons:
                await m.wait_stable()
        self.mgrs = []
        if sc.get("n_mgrs"):
            from ceph_tpu.mgr.daemon import MgrDaemon

            for i in range(sc["n_mgrs"]):
                mgr = MgrDaemon(self._mgr_name(i), list(self.monmap),
                                conf=self._conf())
                self.netem.attach(mgr.messenger)
                await mgr.start()
                self.mgrs.append(mgr)
        self.osds = []
        for i in range(sc["n_osds"]):
            osd = OSDDaemon(i, list(self.monmap),
                            store=self._make_store(i), conf=self._conf())
            self.netem.attach(osd.messenger)
            await osd.start()
            self.osds.append(osd)
        self.client = RadosClient(client_id=8080)
        # the workload's acks are the oracle.  Classically the client
        # stays OUTSIDE the blast radius; client-netem scenarios flip
        # that — the client messenger wears the shim too, and the
        # schedule's client_* verbs cut its OSD links (never its mon
        # links: the command plane stays the observer)
        if sc.get("client_netem"):
            self.netem.attach(self.client.messenger)
        await self.client.connect_multi(list(self.monmap))
        for pool in sc.get("pools", []):
            if pool.get("type") == "erasure":
                prof = f"chaos-{pool['name']}"
                profile = {
                    "plugin": "jax", "k": str(pool.get("k", 2)),
                    "m": str(pool.get("m", 1)),
                }
                if pool.get("failure_domain"):
                    # the profile drives create_ec_rule: one shard
                    # per rack/host, the rack-loss scenario's proof
                    profile["crush-failure-domain"] = (
                        pool["failure_domain"])
                await self.client.ec_profile_set(prof, profile)
                await self.client.pool_create(
                    pool["name"], pg_num=pool.get("pg_num", 2),
                    pool_type="erasure", erasure_code_profile=prof)
            elif pool.get("failure_domain"):
                # replicated pools ride the pre-registered rack rule
                await self.client.pool_create(
                    pool["name"], pg_num=pool.get("pg_num", 4),
                    size=pool.get("size", 2), rule="chaos_rack_rule")
            else:
                await self.client.pool_create(
                    pool["name"], pg_num=pool.get("pg_num", 4),
                    size=pool.get("size", 2))
        tier = sc.get("tier")
        if tier:
            # writeback cache tier: hot over base, overlay on — the
            # same mon verbs operators run (OSDMonitor tier commands)
            for cmd in (
                {"prefix": "osd tier add", "pool": tier["base"],
                 "tierpool": tier["hot"]},
                {"prefix": "osd tier cache-mode", "pool": tier["hot"],
                 "mode": tier.get("mode", "writeback")},
                {"prefix": "osd tier set-overlay",
                 "pool": tier["base"], "tierpool": tier["hot"]},
            ):
                code, rs, _ = await self.client.command(cmd)
                if code != 0:
                    raise RuntimeError(f"tier setup {cmd} -> {rs}")
            if tier.get("target_max_bytes"):
                await self.client.command({
                    "prefix": "osd pool set", "pool": tier["hot"],
                    "var": "target_max_bytes",
                    "val": str(tier["target_max_bytes"])})
            # the overlay must be IN the client's map before the
            # workload writes, or early writes skip the tier
            await self.client._wait_new_map(
                self.client.osdmap.epoch - 1, timeout=10)
        await self._await_warmup()

    async def _await_warmup(self, timeout: float = 30.0) -> None:
        """Wait for every daemon's EC-profile warmup to finish: the
        cold_launches==0 invariant judges the steady state, and a kill
        landing mid-compile would blame chaos for a boot-time cold
        launch."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if all(not osd._warm_tasks for osd in self.osds if osd) \
                    and all(m._warm_task is None or m._warm_task.done()
                            for m in self.mgrs if m):
                return
            await asyncio.sleep(0.05)

    async def stop(self) -> None:
        from ceph_tpu.common.fault_injector import FAULTS

        # disarm every store fault before teardown: umount/checkpoint
        # must not trip a leftover injection, and the next seed's run
        # must start clean (points are process-global)
        FAULTS.clear()
        for t in list(self._heal_tasks):
            t.cancel()
        if self.client is not None:
            await self.client.shutdown()
        for osd in self.osds:
            if osd is not None:
                await osd.stop()
        for g in self.mgrs:
            if g is not None:
                await g.stop()
        for m in self.mons:
            if m is not None:
                await m.stop()
        for store in self._stores.values():
            try:
                store.umount()
            except OSError:
                log.exception("chaos: store umount failed")
        import shutil

        if self._store_dir is not None:
            shutil.rmtree(self._store_dir, ignore_errors=True)
        shutil.rmtree(self.crash_dir, ignore_errors=True)

    # -- event application ---------------------------------------------

    async def apply_event(self, ev) -> None:
        counters = chaos_counters()
        counters.inc("events", kind=ev.kind)
        with chaos_tracer().span(
            "chaos_event", kind=ev.kind, t=ev.t,
            **{k: str(v) for k, v in ev.args.items()},
        ) as sp:
            try:
                await self._apply(ev)
                self.events_applied += 1
            except Exception as e:
                # a refused event (no primary mid-thrash, EAGAIN storm)
                # is part of chaos, not a failure of the harness — but
                # it is recorded and counted
                sp.tag(error=type(e).__name__)
                counters.inc("event_errors", kind=ev.kind)
                self.event_errors.append({
                    "kind": ev.kind, "args": dict(ev.args),
                    "error": f"{type(e).__name__}: {e}",
                })

    async def _kill_osd(self, osd_id: int) -> None:
        osd = self.osds[osd_id]
        if osd is not None:
            # an injected kill IS an unclean death: the daemon
            # persists a crash dump the way a SIGKILL'd reference
            # daemon leaves one for ceph-crash to post
            if not osd.stopping:
                osd.record_crash(
                    reason="chaos: injected daemon kill")
                self._note_death(f"osd.{osd_id}")
            # keep the store: revive is a daemon restart (the
            # reference thrasher's revive keeps the disk too).
            # Wiping here would let TWO sequential kills destroy
            # more shards than m — the second kill lands before the
            # first revive's rebuild finishes, and that is operator
            # data loss, not a cluster bug
            self._stashed_stores = getattr(self, "_stashed_stores", {})
            self._stashed_stores[osd_id] = osd.store
            await osd.stop()
            self.osds[osd_id] = None

    async def _revive_osd(self, osd_id: int) -> None:
        cur = self.osds[osd_id]
        if cur is not None and cur.stopping:
            # the daemon died on its own (read-error-ledger disk
            # escalation — its _escalate path already wrote the
            # crash dump): stash its store and treat it as killed
            # so the revive below restarts it
            self._note_death(f"osd.{osd_id}")
            self._stashed_stores = getattr(self, "_stashed_stores", {})
            self._stashed_stores[osd_id] = cur.store
            self.osds[osd_id] = None
        if self.osds[osd_id] is None:
            from ceph_tpu.osd.daemon import OSDDaemon

            store = getattr(self, "_stashed_stores", {}).pop(
                osd_id, None)
            osd = OSDDaemon(osd_id, list(self.monmap), store=store,
                            conf=self._conf())
            self.netem.attach(osd.messenger)
            await osd.start()
            self.osds[osd_id] = osd
            # missed-write catch-up recovery (log replay / decode
            # toward the restarted member) runs from the new map;
            # data-LOSS rebuilds are exercised by osd_out remaps
            # (backfill + EC decode onto fresh members)

    async def _apply(self, ev) -> None:
        a = ev.args
        kind = ev.kind
        if kind == "osd_kill":
            if a.get("await_backfill"):
                await self._await_backfill_inflight()
            await self._kill_osd(a["osd"])
        elif kind == "osd_revive":
            await self._revive_osd(a["osd"])
        elif kind in ("rack_kill", "host_kill"):
            # correlated loss: every member of one failure domain dies
            # in the same beat.  check_domains snapshots the acting
            # sets FIRST — the proof CRUSH separated shards across
            # domains must predate the kill it survives
            if self.scenario.get("topology"):
                self.domains_obs.append(self._domains_snapshot(
                    killed=list(a["osds"]), kind=kind))
            for o in a["osds"]:
                await self._kill_osd(o)
        elif kind == "rack_revive":
            for o in a["osds"]:
                await self._revive_osd(o)
        elif kind in ("mon_netem", "mgr_netem", "mds_netem"):
            ent = {
                "mon_netem": ("mon", a.get("rank", 0)),
                "mgr_netem": ("mgr", a.get("mgr", 0)),
                "mds_netem": ("mds", a.get("mds", 0)),
            }[kind]
            wild = ("osd", None)
            mode = a.get("mode", "delay")
            if mode == "partition":
                self.netem.partition(ent, wild)
                self._schedule_heal(
                    a.get("ttl"),
                    lambda: self.netem.heal_partition(ent, wild))
            elif mode == "drop":
                self.netem.drop_oneway(wild, ent)
                self._schedule_heal(
                    a.get("ttl"),
                    lambda: self.netem.heal_oneway(wild, ent))
            else:
                # both directions: slow outbound AND inbound links
                links = ((ent, wild), (wild, ent))
                for s_, d_ in links:
                    self.netem.delay(s_, d_, a.get("seconds", 0.02))
                self._schedule_heal(
                    a.get("ttl"),
                    lambda: [self.netem.heal_delay(s_, d_)
                             for s_, d_ in links])
        elif kind == "osd_out":
            await self._command({"prefix": "osd out", "id": str(a["osd"])})
        elif kind == "osd_in":
            await self._command({"prefix": "osd in", "id": str(a["osd"])})
        elif kind == "reweight":
            await self._command({
                "prefix": "osd crush reweight",
                "name": f"osd.{a['osd']}", "weight": str(a["weight"]),
            })
        elif kind == "mon_restart":
            await self._mon_restart(a["rank"])
        elif kind == "pg_split":
            om = self.client.osdmap
            pid = om.lookup_pg_pool_name(a["pool"])
            if pid >= 0:
                cur = om.pools[pid].pg_num
                await self._command({
                    "prefix": "osd pool set", "pool": a["pool"],
                    "var": "pg_num", "val": str(min(cur * 2, 16)),
                })
        elif kind in ("scrub", "deep_scrub", "repair"):
            om = self.client.osdmap
            pid = om.lookup_pg_pool_name(a["pool"])
            if pid >= 0:
                ps = int(ev.t * 1000) % max(1, om.pools[pid].pg_num)
                prefix = {
                    "scrub": "pg scrub", "deep_scrub": "pg deep-scrub",
                    "repair": "pg repair",
                }[kind]
                await self._command({
                    "prefix": prefix, "pgid": f"{pid}.{ps}"})
        elif kind == "balance":
            await self._command({
                "prefix": "osd balance",
                "max_swaps": str(a.get("max_swaps", 8)),
            })
        elif kind == "partition":
            self.netem.partition(tuple(a["a"]), tuple(a["b"]))
            self._schedule_heal(
                a.get("ttl"),
                lambda: self.netem.heal_partition(
                    tuple(a["a"]), tuple(a["b"])))
        elif kind == "heal_partition":
            self.netem.heal_partition(tuple(a["a"]), tuple(a["b"]))
        elif kind == "drop_oneway":
            self.netem.drop_oneway(tuple(a["src"]), tuple(a["dst"]))
            self._schedule_heal(
                a.get("ttl"),
                lambda: self.netem.heal_oneway(
                    tuple(a["src"]), tuple(a["dst"])))
        elif kind == "heal_oneway":
            self.netem.heal_oneway(tuple(a["src"]), tuple(a["dst"]))
        elif kind == "delay":
            self.netem.delay(
                tuple(a["src"]), tuple(a["dst"]), a["seconds"])
            self._schedule_heal(
                a.get("ttl"),
                lambda: self.netem.heal_delay(
                    tuple(a["src"]), tuple(a["dst"])))
        elif kind == "reorder":
            self.netem.reorder(
                tuple(a["src"]), tuple(a["dst"]),
                every=a.get("every", 3), hold=a.get("hold", 0.01))
            self._schedule_heal(
                a.get("ttl"),
                lambda: self.netem.heal_reorder(
                    tuple(a["src"]), tuple(a["dst"])))
        elif kind == "client_partition":
            peer = tuple(a["peer"])
            self.netem.partition(("client", None), peer)
            self._schedule_heal(
                a.get("ttl"),
                lambda: self.netem.heal_partition(
                    ("client", None), peer))
        elif kind == "heal_client_partition":
            self.netem.heal_partition(("client", None), tuple(a["peer"]))
        elif kind == "client_drop":
            src, dst = ("client", None), tuple(a["peer"])
            if a.get("to_client"):
                src, dst = dst, src
            self.netem.drop_oneway(src, dst)
            self._schedule_heal(
                a.get("ttl"),
                lambda: self.netem.heal_oneway(src, dst))
        elif kind == "heal_client_drop":
            src, dst = ("client", None), tuple(a["peer"])
            if a.get("to_client"):
                src, dst = dst, src
            self.netem.heal_oneway(src, dst)
        elif kind == "client_delay":
            # both directions: slow requests out AND slow acks back
            links = ((("client", None), tuple(a["peer"])),
                     (tuple(a["peer"]), ("client", None)))
            for s_, d_ in links:
                self.netem.delay(s_, d_, a["seconds"])
            self._schedule_heal(
                a.get("ttl"),
                lambda: [self.netem.heal_delay(s_, d_)
                         for s_, d_ in links])
        elif kind == "fill":
            await self._apply_fill(a["level"], float(a["ratio"]))
        elif kind == "drain":
            await self._apply_drain()
        elif kind == "netem_clear":
            self.netem.clear()
        elif kind in ("eio", "bitflip", "torn_write", "disk_dead",
                      "slow_disk", "disk_heal"):
            self._apply_disk_fault(kind, a["osd"],
                                   delay=a.get("delay"))
        elif kind == "mgr_kill":
            mgr = self.mgrs[a["mgr"]]
            if mgr is not None:
                mgr.record_crash(reason="chaos: injected mgr kill")
                self._note_death(f"mgr.{mgr.name}")
                await mgr.stop()
                self.mgrs[a["mgr"]] = None
        elif kind in ("tier_flush", "tier_evict", "tier_promote"):
            from ceph_tpu.client.rados import ObjectOperation

            if kind == "tier_promote":
                # a read via the BASE pool: overlay redirect, and if
                # the object was evicted, the promote-on-miss path
                await self.client.ioctx(a["base"]).read(a["oid"])
            else:
                op = ObjectOperation()
                if kind == "tier_flush":
                    op.cache_flush()
                else:
                    # evicting a dirty object is EBUSY by design —
                    # apply_event records the refusal as chaos
                    op.cache_evict()
                await self.client.ioctx(a["hot"]).operate(a["oid"], op)
        elif kind == "mgr_revive":
            if self.mgrs[a["mgr"]] is None:
                from ceph_tpu.mgr.daemon import MgrDaemon

                mgr = MgrDaemon(self._mgr_name(a["mgr"]),
                                list(self.monmap), conf=self._conf())
                self.netem.attach(mgr.messenger)
                await mgr.start()
                self.mgrs[a["mgr"]] = mgr
        else:
            raise ValueError(f"unknown chaos event kind {kind!r}")

    @staticmethod
    def _mgr_name(i: int) -> str:
        return chr(ord("x") + i)

    #: FAULTS keys a disk-fault event may arm on one osd's store
    _DISK_FAULT_OPS = ("read", "write", "commit", "mount", "latency")

    def _apply_disk_fault(self, kind: str, osd_id: int,
                          delay: float | None = None) -> None:
        """Arm (or clear) store-level FAULTS points for one OSD's
        disk.  One key per (op, osd); a later event on the same osd
        re-arms the key (latest fault wins — a disk does not queue its
        lies)."""
        import errno as _errno

        from ceph_tpu.common.fault_injector import FAULTS

        if kind == "eio":
            FAULTS.inject(
                f"store.read.osd.{osd_id}", error=_errno.EIO, count=1)
        elif kind == "bitflip":
            FAULTS.inject(f"store.read.osd.{osd_id}", bitflip=True, count=1)
        elif kind == "torn_write":
            FAULTS.inject(f"store.write.osd.{osd_id}", torn=True, count=1)
        elif kind == "disk_dead":
            # the dying-disk mode: EVERY read and commit fails until
            # healed; the victim's read-error ledger escalates it to
            # self-markdown and peering re-places its data
            FAULTS.inject(
                f"store.read.osd.{osd_id}", error=_errno.EIO, count=None)
            FAULTS.inject(
                f"store.write.osd.{osd_id}", error=_errno.EIO, count=None)
        elif kind == "slow_disk":
            # a disk that still works but has gone SLOW: sticky async
            # latency on every store commit of this osd (the OSD's
            # _store_latency_gate — an event-loop sleep, so ONE slow
            # disk slows only its own commits in-process)
            FAULTS.inject(
                f"store.latency.osd.{osd_id}",
                delay=float(delay or 0.5), count=None)
        elif kind == "disk_heal":
            for op in self._DISK_FAULT_OPS:
                FAULTS.clear(f"store.{op}.osd.{osd_id}")

    # -- backfill-interrupt machinery -----------------------------------

    def _backfill_totals(self) -> tuple[float, float]:
        """Cluster-wide (backfill_started, backfill_completed) sums.
        The counters are process-global, so a baseline snapshot is
        taken at cluster start and deltas are judged against it."""
        from ceph_tpu.common.metrics import get_perf_counters
        s = c = 0.0
        for i in range(self.scenario["n_osds"]):
            d = get_perf_counters(f"osd.{i}").dump()
            s += d.get("backfill_started", 0.0)
            c += d.get("backfill_completed", 0.0)
        return s, c

    async def _await_backfill_inflight(self, timeout: float = 10.0) -> None:
        """Hold a scripted interrupt kill until a backfill pass is
        verifiably in flight (started > completed, judged against the
        run's baseline) so the kill lands MID-TRANSFER instead of
        racing the revived member's boot.  Every completed pass bumps
        both counters equally, so a positive delta means a pass is
        running right now.  This gates DELIVERY of one trace event on
        cluster state — the trace itself (times, kinds, args, hash)
        stays pure in (seed, scenario).  On timeout the kill proceeds
        anyway and check_backfill reports the miss honestly."""
        base_s, base_c = self._backfill_gate_base
        loop = asyncio.get_running_loop()
        deadline = loop.time() + timeout
        while loop.time() < deadline:
            s, c = self._backfill_totals()
            if (s - base_s) > (c - base_c):
                return
            await asyncio.sleep(0.02)
        log.warning("await_backfill: no pass in flight after %.1fs — "
                    "killing anyway", timeout)

    # -- failure-domain machinery ---------------------------------------

    def _rack_of(self, osd_id: int) -> int:
        """Topology scenarios place osd ids densely: rack r holds
        osds [r*per_rack, (r+1)*per_rack)."""
        topo = self.scenario["topology"]
        per_rack = (int(topo.get("osds_per_host", 1))
                    * int(topo.get("hosts_per_rack", 1)))
        return osd_id // per_rack

    def _domains_snapshot(self, killed: list[int],
                          kind: str = "rack_kill") -> dict:
        """Pre-kill placement evidence for check_domains: for every
        rack-failure-domain pool, how CRUSH spread each PG's acting
        set across racks, and how many shards survive once the doomed
        rack goes dark."""
        from ceph_tpu.crush.types import CRUSH_ITEM_NONE
        from ceph_tpu.osd.types import pg_t

        om = self.client.osdmap
        killed_racks = sorted({self._rack_of(o) for o in killed})
        rec: dict = {
            "kind": kind, "killed_osds": sorted(killed),
            "killed_racks": killed_racks, "pools": {},
        }
        for pool in self.scenario.get("pools", []):
            if pool.get("failure_domain") != "rack":
                continue
            pid = om.lookup_pg_pool_name(pool["name"])
            if pid < 0:
                continue
            pl = om.pools[pid]
            need = (pool.get("k", 2)
                    if pool.get("type") == "erasure" else 1)
            worst = 0
            min_surviving = None
            for ps in range(pl.pg_num):
                _u, _up, acting, _pri = om.pg_to_up_acting_osds(
                    pg_t(pid, ps), folded=True)
                members = [o for o in acting if o != CRUSH_ITEM_NONE]
                per: dict[int, int] = {}
                for o in members:
                    r = self._rack_of(o)
                    per[r] = per.get(r, 0) + 1
                if per:
                    worst = max(worst, max(per.values()))
                surv = sum(1 for o in members
                           if self._rack_of(o) not in killed_racks)
                min_surviving = (surv if min_surviving is None
                                 else min(min_surviving, surv))
            rec["pools"][pool["name"]] = {
                "type": pool.get("type", "replicated"),
                "pg_num": pl.pg_num,
                "max_shards_per_domain": worst,
                "min_surviving_shards": min_surviving,
                "need": need,
            }
        return rec

    # -- fullness-pressure machinery -----------------------------------

    def _store_ratios(self, in_only: bool = False) -> dict[int, float]:
        """Live used/total per OSD store (dead daemons skipped; the
        scripted ladder never kills).  ``in_only`` restricts to up+in
        members — the set backfill reservations can target."""
        om = self.client.osdmap if self.client else None
        out: dict[int, float] = {}
        for osd in self.osds:
            if osd is None:
                continue
            if in_only and om is not None and (
                not om.is_up(osd.id) or om.is_out(osd.id)
            ):
                continue
            try:
                sf = osd.store.statfs()
            except (OSError, NotImplementedError):
                continue
            total = sf.get("total", 0)
            out[osd.id] = (sf.get("used", 0) / total) if total else 0.0
        return out

    async def _fullness_check_raised(self, check: str,
                                     timeout: float = 12.0) -> bool:
        """Poll `ceph health` until ``check`` appears (statfs beacons
        -> mon full bits -> health is an async chain)."""
        import json as _json

        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                code, _rs, data = await self.client.command(
                    {"prefix": "health"})
                if code == 0 and data:
                    if check in (_json.loads(data).get("checks") or {}):
                        return True
            except (OSError, ValueError, ConnectionError,
                    asyncio.TimeoutError):
                pass
            await asyncio.sleep(0.15)
        return False

    def _ballast_candidates(self, pool_name: str, target: int):
        """Yield unwritten ballast names whose PG acting set contains
        ``target`` (placement computed client-side — fills STEER, so
        tiny stores cross their thresholds without CRUSH-imbalance
        overshooting any one of them)."""
        from ceph_tpu.osd.daemon import object_to_pg

        om = self.client.osdmap
        pid = om.lookup_pg_pool_name(pool_name)
        pl = om.get_pg_pool(pid) if pid >= 0 else None
        if pl is None:
            return
        have = set(self._ballast_names)
        for i in range(4096):
            name = f"ballast-{i:05d}"
            if name in have:
                continue
            pg = object_to_pg(pl, name)
            _u, _up, acting, _pri = om.pg_to_up_acting_osds(pg)
            if target in acting:
                yield name

    async def _apply_fill(self, level: str, ratio: float) -> None:
        """Closed-loop ballast writer: push store usage until the
        level's target is observed.  nearfull/full push the MOST-full
        store over the line (one over-threshold osd raises the check
        and gates writes); backfillfull pushes the LEAST-full store
        up until EVERY up+in member is past the reservation gate.
        Each write is aimed at a PG holding the chosen osd, so the
        ladder is driven precisely — the TRACE stays pure, only this
        application loop is adaptive (like wait_clean)."""
        import errno as _errno

        sc = self.scenario
        pool = sc.get("ballast_pool", "rep")
        size = int(sc.get("ballast_size", 128 * 1024))
        # never push any store near the local failsafe: the ladder is
        # proven against the widened conf ratios, with the failsafe
        # margin held in reserve (check_fullness asserts the peak)
        cap = float(sc.get("full_fill", 0.82)) + 0.06
        io = self.client.ioctx(pool)
        obs = self.fullness
        for _ in range(400):
            ratios = self._store_ratios(in_only=True)
            if not ratios or max(ratios.values()) >= cap:
                break
            if level == "backfillfull":
                if min(ratios.values()) >= ratio:
                    break
                target = min(ratios, key=ratios.get)
            else:
                if max(ratios.values()) >= ratio:
                    break
                target = max(ratios, key=ratios.get)
            name = next(
                self._ballast_candidates(pool, target), None)
            if name is None:
                break  # namespace exhausted for this placement
            try:
                await io.write_full(name, b"\xba" * size)
                self._ballast_names.append(name)
            except OSError as e:
                if e.errno == _errno.ENOSPC:
                    obs["enospc_bounced"] = True
                    break
                raise
        check = {"nearfull": "OSD_NEARFULL",
                 "backfillfull": "OSD_BACKFILLFULL",
                 "full": "OSD_FULL"}[level]
        if await self._fullness_check_raised(check):
            obs[f"{level}_raised"] = True
        if level == "full" and not obs["enospc_bounced"]:
            await self._probe_enospc(io, pool, size)

    async def _probe_enospc(self, io, pool_name: str,
                            size: int) -> None:
        """The ENOSPC proof: aim writes at PGs whose acting set
        contains a map-FULL osd and require the bounce.  A write may
        race the bit onto an OSD whose map lags one beacon — retry
        over fresh candidates with a short grace."""
        import errno as _errno

        from ceph_tpu.osd.daemon import object_to_pg

        om = self.client.osdmap
        pid = om.lookup_pg_pool_name(pool_name)
        pl = om.get_pg_pool(pid) if pid >= 0 else None
        if pl is None:
            return
        full = {o for o in range(om.max_osd)
                if om.exists(o) and om.is_full(o)}
        if not full:
            return
        attempts = 0
        for i in range(512):
            name = f"ballast-probe-{i:03d}"
            pg = object_to_pg(pl, name)
            _u, _up, acting, _pri = om.pg_to_up_acting_osds(pg)
            if not (full & set(acting)):
                continue
            try:
                await io.write_full(name, b"\xbb" * size)
                # raced the bit on the OSD's older map: the write
                # landed — track it for the drain, grace, retry
                self._ballast_names.append(name)
            except OSError as e:
                if e.errno == _errno.ENOSPC:
                    self.fullness["enospc_bounced"] = True
                return
            attempts += 1
            if attempts >= 8:
                return
            await asyncio.sleep(0.25)

    async def _apply_drain(self) -> None:
        """Delete every ballast object (deletes pass the full gate —
        they are how an operator digs out) and let usage fall; the
        settle phase then requires the ladder to CLEAR."""
        sc = self.scenario
        io = self.client.ioctx(sc.get("ballast_pool", "rep"))
        import errno as _errno

        for name in self._ballast_names:
            try:
                await io.remove(name)
            except OSError as e:
                if e.errno != _errno.ENOENT:
                    log.warning("chaos: drain of %s failed: %s",
                                name, e)
        self._ballast_names = []

    def _schedule_heal(self, ttl, heal) -> None:
        if not ttl:
            return

        async def _later():
            await asyncio.sleep(ttl * self.time_scale)
            heal()

        t = asyncio.ensure_future(_later())
        self._heal_tasks.add(t)
        t.add_done_callback(self._heal_tasks.discard)

    async def _command(self, cmd: dict) -> tuple[int, str, bytes]:
        code, rs, data = await self.client.command(cmd)
        if code != 0:
            raise OSError(-code, f"{cmd.get('prefix')}: {rs}")
        return code, rs, data

    async def _mon_restart(self, rank: int) -> None:
        from ceph_tpu.mon import Monitor

        old = self.mons[rank]
        if old is None:
            return
        host, port = old.addr
        await old.stop()
        m = Monitor(
            crush=self._crush_template.copy(), rank=rank,
            n_mons=len(self.mons),
        )
        self.netem.attach(m.messenger)
        await m.start(host, port)
        self.mons[rank] = m
        await m.open_quorum(list(self.monmap))

    # -- post-thrash verification ---------------------------------------

    def mon_views(self) -> list[dict]:
        return [
            {
                "rank": m.rank,
                "stable": m.paxos.stable.is_set(),
                "leader": m.paxos.leader,
                "epoch": m.osdmap.epoch,
            }
            for m in self.mons if m is not None
        ]

    async def await_quorum_agreement(self, timeout: float = 30.0) -> list:
        """Poll until every mon agrees (one leader, one epoch); returns
        the surviving violations (empty = invariant holds)."""
        deadline = time.monotonic() + timeout
        views = self.mon_views()
        while time.monotonic() < deadline:
            views = self.mon_views()
            if not inv.check_quorum(views):
                return []
            await asyncio.sleep(0.2)
        return inv.check_quorum(views)

    async def await_mgr_reports(self, timeout: float = 30.0) -> list:
        """Poll `mgr stat` until the report plane has healed (an
        active mgr, every OSD re-registered, fresh digest); returns
        surviving check_mgr violations (empty = invariant holds).
        Scenario-trace end revives every killed daemon, so EVERY osd
        is expected to report."""
        import json as _json

        expected = [f"osd.{i}" for i in range(self.scenario["n_osds"])]
        deadline = time.monotonic() + timeout
        stat: dict = {}
        while time.monotonic() < deadline:
            try:
                code, _rs, data = await self.client.command(
                    {"prefix": "mgr stat"})
                stat = _json.loads(data) if code == 0 and data else {}
            except (OSError, ValueError):
                stat = {}
            if not inv.check_mgr(stat, expected):
                return []
            await asyncio.sleep(0.3)
        return inv.check_mgr(stat, expected)

    async def deep_scrub_sweep(self, retries: int = 6) -> list[dict]:
        """Deep scrub every PG of every scenario pool; returns reports."""
        import json as _json

        reports: list[dict] = []
        om = self.client.osdmap
        for pool in self.scenario.get("pools", []):
            pid = om.lookup_pg_pool_name(pool["name"])
            if pid < 0:
                continue
            for ps in range(om.pools[pid].pg_num):
                rep = None
                for attempt in range(retries):
                    code, _rs, data = await self.client.command({
                        "prefix": "pg deep-scrub",
                        "pgid": f"{pid}.{ps}",
                    })
                    if code == 0:
                        rep = _json.loads(data)
                        break
                    await asyncio.sleep(0.3 * (attempt + 1))
                reports.append(rep if rep is not None else {
                    "pg": f"{pid}.{ps}",
                    "error": "deep scrub never reached a primary",
                })
        return reports

    async def repair_sweep(self, retries: int = 6) -> None:
        """`pg repair` over every PG of every scenario pool — the
        disk-fault scenario's heal pass: scrub-detected damage (rotten
        shards quarantined to holes, divergent members of torn
        commits) is rebuilt from the authoritative copies before the
        deep-scrub verdict."""
        om = self.client.osdmap
        for pool in self.scenario.get("pools", []):
            pid = om.lookup_pg_pool_name(pool["name"])
            if pid < 0:
                continue
            for ps in range(om.pools[pid].pg_num):
                for attempt in range(retries):
                    code, _rs, _data = await self.client.command({
                        "prefix": "pg repair", "pgid": f"{pid}.{ps}",
                    })
                    if code == 0:
                        break
                    await asyncio.sleep(0.3 * (attempt + 1))

    def fsck_sweep(self) -> list[dict]:
        """At-rest verification of every OSD's store (live daemons and
        stashed stores of dead ones): any blob whose checksum no
        longer verifies is damage the run failed to heal.  Stores
        without an fsck (MemStore) contribute nothing."""
        out: list[dict] = []
        seen: set[int] = set()
        stores: list[tuple[int, object]] = []
        for osd in self.osds:
            if osd is not None:
                stores.append((osd.id, osd.store))
                seen.add(osd.id)
        for osd_id, store in getattr(self, "_stashed_stores", {}).items():
            if osd_id not in seen:
                stores.append((osd_id, store))
        for osd_id, store in stores:
            fsck = getattr(store, "fsck", None)
            if not callable(fsck):
                continue
            try:
                bad = fsck()
            except (OSError, ValueError) as e:
                bad = [{"error": f"{type(e).__name__}: {e}"}]
            out.append({"osd": osd_id, "bad": bad})
        return out


async def _watch_slow_osd(cluster, targets, obs, perf_base) -> None:
    """Degraded-disk observer: while the thrash runs, record whether
    the SLOW_OPS warning surfaced in `ceph health`, whether the mgr's
    outlier detection flagged a slowed osd, and whether the victim's
    scrub scheduler learned + acted on the deprioritization verdict."""
    import json as _json

    tnames = {f"osd.{t}" for t in targets}
    while True:
        try:
            code, _rs, data = await cluster.client.command(
                {"prefix": "health"})
            if code == 0 and data:
                h = _json.loads(data)
                if "SLOW_OPS" in (h.get("checks") or {}):
                    obs["slow_ops_raised"] = True
        except (OSError, ValueError, ConnectionError,
                asyncio.TimeoutError):
            pass
        for g in cluster.mgrs:
            if g is not None and g.active \
                    and tnames & g._outlier_daemons():
                obs["outlier_flagged"] = True
        om = cluster.client.osdmap
        for t in targets:
            osd = cluster.osds[t]
            if osd is None:
                continue
            if osd.mgr_client.scrub_deprioritized:
                obs["scrub_deprioritized"] = True
            deferred = (osd.perf.dump().get("scrub_deferred_slow", 0.0)
                        - perf_base.get(t, 0.0))
            if deferred > 0:
                obs["scrub_deferred"] = deferred
            if om is not None and not obs.get("target_leads_pg"):
                from ceph_tpu.osd.types import pg_t as _pg_t

                for pid, pool in om.pools.items():
                    for ps in range(pool.pg_num):
                        _u, _up, _a, pri = om.pg_to_up_acting_osds(
                            _pg_t(pid, ps), folded=True)
                        if pri == t:
                            obs["target_leads_pg"] = True
                            break
                    if obs.get("target_leads_pg"):
                        break
        await asyncio.sleep(0.25)


async def _watch_fullness(cluster, obs, perf_base) -> None:
    """Fullness observer: while the ladder is driven, record the
    peak usage ratio any store reaches (the failsafe-never-breached
    proof), the REJECT_TOOFULL reservation count growing on the
    backfillfull members (recovery.py backfill_reject_toofull — the
    backfill-actually-paused proof), and any health rung the fill
    handler's own bounded wait might have missed."""
    import json as _json

    while True:
        ratios = cluster._store_ratios()
        if ratios:
            obs["failsafe_peak"] = max(
                obs["failsafe_peak"], max(ratios.values()))
        rejects = 0.0
        for osd in cluster.osds:
            if osd is None:
                continue
            rejects += (
                osd.perf.dump().get("backfill_reject_toofull", 0.0)
                - perf_base.get(osd.id, 0.0))
        if rejects > obs["backfill_rejects"]:
            obs["backfill_rejects"] = rejects
        try:
            code, _rs, data = await cluster.client.command(
                {"prefix": "health"})
            if code == 0 and data:
                checks = _json.loads(data).get("checks") or {}
                for level, check in (
                    ("nearfull", "OSD_NEARFULL"),
                    ("backfillfull", "OSD_BACKFILLFULL"),
                    ("full", "OSD_FULL"),
                ):
                    if check in checks:
                        obs[f"{level}_raised"] = True
        except (OSError, ValueError, ConnectionError,
                asyncio.TimeoutError):
            pass
        await asyncio.sleep(0.15)


def _dump_wedge_state(cluster) -> None:
    """Convergence timed out: snapshot every live OSD's recovery-side
    state so a wedge is diagnosable from the run log alone — which pg
    each daemon still considers unclean, who holds reservation slots,
    and where the recovery task is parked (a silent reservation
    livelock leaves NO log lines; this is the only witness)."""
    from ceph_tpu.osd.pgutil import pg_t

    for osd in cluster.osds:
        if osd is None:
            continue
        task = getattr(osd, "_recovery_task", None)
        frames: list[str] = []
        state = "none"
        if task is not None:
            if not task.done():
                state = "running"
                for f in task.get_stack(limit=6):
                    frames.append(
                        f"{f.f_code.co_filename.rsplit('/', 1)[-1]}:"
                        f"{f.f_lineno}:{f.f_code.co_name}")
            elif task.cancelled():
                state = "cancelled"
            elif task.exception() is not None:
                state = f"raised:{task.exception()!r}"
            else:
                state = "done"
        prim: list[str] = []
        om = osd.osdmap
        if om is not None:
            for pid, pool in om.pools.items():
                for ps in range(pool.pg_num):
                    _, _, acting, p = om.pg_to_up_acting_osds(
                        pg_t(pid, ps), folded=True)
                    prim.append(f"{pid}.{ps}:p{p}a{acting}")
        log.error(
            "wedge osd.%d: epoch=%d recovering=%s clean_epoch=%s "
            "local_slots=%s remote_slots=%s remote_grants=%s "
            "recovery_task=%s stack=%s map=%s",
            osd.id, osd.epoch, sorted(osd._recovering_pgs),
            dict(osd._clean_epoch),
            getattr(osd.local_reserver, "in_use", "?"),
            getattr(osd.remote_reserver, "in_use", "?"),
            sorted(osd._remote_grants),
            state,
            " <- ".join(frames) or "-",
            " ".join(prim),
        )


async def _settle_fullness(cluster, obs, time_scale: float) -> None:
    """Post-drain verification: the whole ladder must CLEAR — no
    fullness health check may survive the drain and settle."""
    import json as _json

    fullness_checks = {"OSD_NEARFULL", "OSD_BACKFILLFULL", "OSD_FULL"}
    deadline = time.monotonic() + 30.0 * time_scale
    checks: list = []
    while time.monotonic() < deadline:
        try:
            code, _rs, data = await cluster.client.command(
                {"prefix": "health"})
            if code == 0 and data:
                checks = sorted(_json.loads(data).get("checks") or {})
                if not (set(checks) & fullness_checks):
                    obs["ladder_cleared"] = True
                    return
        except (OSError, ValueError, ConnectionError,
                asyncio.TimeoutError):
            pass
        await asyncio.sleep(0.3)
    obs["checks_at_settle"] = checks


async def _watch_events(cluster, obs) -> None:
    """Event-plane observer: sample the active mgr's progress module
    while the thrash runs, recording each event's fraction sequence
    (monotonicity is judged over THESE samples), final fraction, and
    whether it was reaped into the completed history."""
    while True:
        try:
            _sample_progress(cluster, obs)
        except Exception:  # a sampler must never die mid-thrash
            log.exception("chaos: event watcher sample failed")
        await asyncio.sleep(0.2)


def _sample_progress(cluster, obs) -> None:
    for g in cluster.mgrs:
        if g is None:
            continue
        prog = g.modules.get("progress")
        if prog is None:
            continue
        if g.active and prog.running:
            for ev in prog.public_events():
                rec = obs["progress_events"].setdefault(ev["id"], {
                    "kind": ev["kind"], "fractions": [],
                    "final": 0.0, "reaped": False,
                })
                fr = float(ev.get("fraction") or 0.0)
                if not rec["fractions"] or rec["fractions"][-1] != fr:
                    rec["fractions"].append(fr)
                rec["final"] = max(rec["final"], fr)
        # completed history is ground truth for reap/final even when
        # the sampler missed the active window (module state persists
        # on the daemon object)
        for done in prog.public_completed():
            rec = obs["progress_events"].setdefault(done["id"], {
                "kind": done["kind"], "fractions": [],
                "final": 0.0, "reaped": False,
            })
            rec["final"] = max(
                rec["final"], float(done.get("fraction") or 0.0))
            rec["reaped"] = True


async def _settle_events(cluster, obs, time_scale: float) -> None:
    """Post-settle event-plane verification: wait for active progress
    events to complete + reap, require a crash dump per injected
    death, mute the EXPECTED RECENT_CRASH, and record what health
    codes remain unmuted."""
    import json as _json

    # 1. progress events must finish and reap (completion grace +
    # slack for the module tick cadence)
    deadline = time.monotonic() + 20.0 * time_scale
    while time.monotonic() < deadline:
        live = [
            g for g in cluster.mgrs
            if g is not None and g.active
            and g.modules.get("progress") is not None
            and g.modules["progress"].running
        ]
        if live and all(not g.modules["progress"].events for g in live):
            break
        await asyncio.sleep(0.3)
    # final authoritative sample: the watcher is a 0.2s poller and can
    # race the module's reap; the module's own state cannot
    _sample_progress(cluster, obs)
    # 2. every injected death must have a collected crash dump —
    # judged through `ceph crash ls` (mon <- digest <- crash module),
    # proving the full collection chain, not just the files on disk
    expected = {e for e, n in cluster.deaths.items() if n > 0}
    deadline = time.monotonic() + 15.0
    seen: set = set()
    while time.monotonic() < deadline:
        try:
            code, _rs, data = await cluster.client.command(
                {"prefix": "crash ls"})
            if code == 0 and data:
                seen = {
                    m.get("entity")
                    for m in _json.loads(data).get("crashes", [])
                }
        except (OSError, ValueError, ConnectionError,
                asyncio.TimeoutError):
            pass
        if expected <= seen:
            break
        await asyncio.sleep(0.4)
    obs["crash_entities"] = seen
    obs["deaths"] = dict(cluster.deaths)
    # 3. mute the crash warning the runner itself caused, then the
    # remaining UNMUTED checks must be the allowed set only
    if expected:
        try:
            await cluster.client.command({
                "prefix": "health mute", "code": "RECENT_CRASH"})
        except (OSError, ConnectionError, asyncio.TimeoutError):
            pass
    allowed = set(obs.get("allowed_checks") or [])
    deadline = time.monotonic() + 12.0
    checks: list = []
    while time.monotonic() < deadline:
        try:
            code, _rs, data = await cluster.client.command(
                {"prefix": "health"})
            if code == 0 and data:
                checks = sorted(_json.loads(data).get("checks") or {})
        except (OSError, ValueError, ConnectionError,
                asyncio.TimeoutError):
            pass
        if not (set(checks) - allowed):
            break
        await asyncio.sleep(0.4)
    obs["unmuted_checks"] = checks


def _perf_totals(n_osds: int) -> dict:
    """Cluster-wide perf-counter sums (osd.* + mgr_analytics.*) for
    the per-run coverage export.  Counters are process-global and
    restart-proof (a revived daemon re-attaches), so before/after
    deltas attribute movement to THIS run."""
    from ceph_tpu.common.metrics import get_perf_counters

    tot: dict[str, float] = {}
    for i in range(n_osds):
        for k, v in get_perf_counters(f"osd.{i}").dump().items():
            if isinstance(v, (int, float)):
                tot[k] = tot.get(k, 0.0) + v
    for k, v in get_perf_counters("mgr_analytics").dump().items():
        if isinstance(v, (int, float)):
            key = f"mgr_analytics.{k}"
            tot[key] = tot.get(key, 0.0) + v
    return tot


async def run_scenario(
    scenario: dict | str, seed: int, *, time_scale: float = 1.0,
    settle_timeout: float = 90.0,
) -> dict:
    """One (scenario, seed) chaos run end to end; returns the result
    record that lands in the chaos artifact."""
    if isinstance(scenario, str):
        scenario = SCENARIOS[scenario]
    events = generate_schedule(seed, scenario)
    return await run_trace(
        scenario, events, seed=seed, time_scale=time_scale,
        settle_timeout=settle_timeout)


async def run_trace(
    scenario: dict, events: list, *, seed: int = 0,
    time_scale: float = 1.0, settle_timeout: float = 90.0,
) -> dict:
    """Replay a RAW event trace against a fresh cluster — the fuzz
    plane's entry point: :func:`run_scenario` is the (seed, scenario)
    special case, mutant traces come straight from the corpus.  The
    trace must pass ``schedule.validate_trace`` (mutants are repaired
    before they get here); the result record carries the same
    invariant verdicts as a scenario run plus a ``coverage`` block
    (which counter families moved, which event kinds fired, which
    daemons died) for the fingerprint."""
    th = trace_hash(events)
    counters = chaos_counters()
    counters.inc("runs")
    t_wall = time.monotonic()
    cluster = ChaosCluster(scenario, time_scale=time_scale)
    result: dict = {
        "scenario": scenario["name"], "seed": seed,
        "trace_hash": th, "n_events": len(events),
    }
    watch_task: asyncio.Task | None = None
    events_watch_task: asyncio.Task | None = None
    fullness_watch_task: asyncio.Task | None = None
    try:
        await cluster.start()
        cold_before = _cold_launch_snapshot()
        perf_before = _perf_totals(scenario["n_osds"])
        from ceph_tpu.common.fault_injector import disk_fault_counters

        df_before = dict(disk_fault_counters().dump())
        backfill_base: dict | None = None
        if scenario.get("soak_script"):
            # perf collections are process-global (a revived daemon
            # re-attaches to the same counters), so delta-checking
            # across the run is restart-proof
            from ceph_tpu.common.metrics import get_perf_counters

            backfill_base = {
                name: sum(
                    get_perf_counters(f"osd.{i}").dump().get(name, 0.0)
                    for i in range(scenario["n_osds"]))
                for name in ("backfill_started", "backfill_completed")
            }
        workload = None
        wl_task = None
        load_task = None
        if scenario.get("load_profile"):
            # chaos x loadgen composition: the deterministic LOAD
            # trace IS the workload — the harness attaches to this
            # cluster in external mode and the thrash replays through
            # its open-loop arrival process
            from ceph_tpu.loadgen.driver import LoadHarness
            from ceph_tpu.loadgen.schedule import resolve_profile

            lp = dict(scenario["load_profile"])
            profile = resolve_profile(
                lp.get("profile", "compose_smoke"),
                clients=lp.get("clients"),
                ops_per_client=lp.get("ops_per_client"))
            load_harness = cluster.load_harness = LoadHarness(
                profile, seed, time_scale=time_scale,
                monmap=list(cluster.monmap), conf=cluster._conf(),
                qos_osds=cluster.osds)
            await load_harness.start()
            load_task = asyncio.ensure_future(load_harness.run())
            # thrash begins once the namespaces are prefilled: setup
            # is not the production window under test
            await load_harness.prefill_done.wait()
        else:
            wl_conf = scenario.get("workload", {})
            # tiered scenarios exclude the hot pool from direct I/O:
            # the workload reaches it through the base pool's overlay
            workload = Workload(
                cluster.client,
                [p for p in scenario.get("pools", [])
                 if p.get("workload", True)],
                objects=wl_conf.get("objects", 3),
                rounds=wl_conf.get("rounds", 3),
                object_size=wl_conf.get("object_size", 8192),
                write_gap=wl_conf.get("write_gap", 0.0) * time_scale,
            )
            wl_task = asyncio.ensure_future(workload.run())

        if scenario.get("fullness_script"):
            perf_base = {
                osd.id: osd.perf.dump().get(
                    "backfill_reject_toofull", 0.0)
                for osd in cluster.osds if osd is not None
            }
            fullness_watch_task = asyncio.ensure_future(
                _watch_fullness(cluster, cluster.fullness, perf_base))

        slow_obs: dict | None = None
        if scenario.get("watch_slow_osd"):
            targets = [
                e.args["osd"] for e in events if e.kind == "slow_disk"]
            slow_obs = {
                "targets": targets, "slow_ops_raised": False,
                "outlier_flagged": False, "scrub_deprioritized": False,
                "scrub_deferred": 0.0, "slow_ops_cleared": False,
            }
            perf_base = {
                t: cluster.osds[t].perf.dump().get(
                    "scrub_deferred_slow", 0.0)
                for t in targets if cluster.osds[t] is not None
            }
            watch_task = asyncio.ensure_future(
                _watch_slow_osd(cluster, targets, slow_obs, perf_base))

        events_obs: dict | None = None
        if scenario.get("watch_events"):
            degrading = {"osd_kill", "osd_out", "disk_dead"}
            events_obs = {
                # only traces that actually degraded the cluster are
                # required to produce progress events (deterministic
                # per (seed, scenario) — it derives from the trace)
                "expect_progress": any(
                    e.kind in degrading for e in events),
                "progress_events": {},
                "allowed_checks": list(
                    scenario.get("settle_allowed_health", [])),
            }
            events_watch_task = asyncio.ensure_future(
                _watch_events(cluster, events_obs))

        loop = asyncio.get_running_loop()
        t0 = loop.time()
        for ev in events:
            delay = t0 + ev.t * time_scale - loop.time()
            if delay > 0:
                await asyncio.sleep(delay)
            await cluster.apply_event(ev)
        history = None
        load_rec = None
        if wl_task is not None:
            history = await wl_task
        if load_task is not None:
            load_rec = await load_task

        if scenario.get("self_heal"):
            # drain in-flight disk-fault escalations before capturing
            # the settle epoch: a self-markdown landing just AFTER the
            # capture would let pre-death active+clean reports satisfy
            # the convergence wait while re-peering is still running
            await asyncio.sleep(1.5 * time_scale)

        # settle: converge back to active+clean under the final map
        violations: dict[str, list] = {}
        settle_epoch = cluster.client.osdmap.epoch
        try:
            status = await cluster.client.wait_clean(
                timeout=settle_timeout, min_epoch=settle_epoch)
            violations["converged"] = inv.check_converged(status)
        except TimeoutError as e:
            violations["converged"] = [{
                "invariant": "not_converged", "detail": str(e)}]
            _dump_wedge_state(cluster)
        violations["quorum"] = await cluster.await_quorum_agreement()
        if workload is not None:
            violations["history"] = inv.check_history(history)
            final = await workload.final_reads()
            violations["final_reads"] = inv.check_final_reads(
                history, final)
        if load_rec is not None:
            expected_tenants = sorted(
                cluster.load_harness.profile.get("tenants") or {})
            violations["load"] = inv.check_load(
                load_rec, expected_tenants)
            result["load"] = load_rec
        reports = await cluster.deep_scrub_sweep()
        if scenario.get("self_heal") and inv.check_scrub_reports(reports):
            # disk-fault mode: injected rot the run hasn't absorbed yet
            # (e.g. a flipped shard nothing read) is healed by `pg
            # repair` — the same authoritative-copy machinery operators
            # invoke — then deep scrub must come back clean.  Bounded
            # retries give in-flight quarantine/repair tasks time.
            for _round in range(4):
                await cluster.repair_sweep()
                await asyncio.sleep(0.5 * time_scale)
                reports = await cluster.deep_scrub_sweep()
                if not inv.check_scrub_reports(reports):
                    break
        violations["scrub"] = inv.check_scrub_reports(reports)
        fsck_reports = []
        if scenario.get("store") == "blockstore":
            for _round in range(4):
                fsck_reports = cluster.fsck_sweep()
                if not inv.check_disk_faults(fsck_reports):
                    break
                # damage still referenced at rest: background repairs
                # may be in flight, or a clone needs one more pass
                await cluster.repair_sweep()
                if workload is not None:
                    await workload.final_reads()
                await asyncio.sleep(0.5 * time_scale)
                fsck_reports = cluster.fsck_sweep()
                if not inv.check_disk_faults(fsck_reports):
                    break
        violations["disk_faults"] = inv.check_disk_faults(fsck_reports)
        if scenario.get("n_mgrs"):
            # report streams must RESUME after mgr failover (the mgr
            # itself is never in the data path — every other invariant
            # above already judged the client workload untouched)
            violations["mgr"] = await cluster.await_mgr_reports()
        if slow_obs is not None:
            # the warning must CLEAR after the heal: poll `ceph
            # health` until the mgr's quiet window elapses and the
            # digest drops SLOW_OPS
            import json as _json

            deadline = time.monotonic() + 25.0
            while time.monotonic() < deadline:
                try:
                    code, _rs, data = await cluster.client.command(
                        {"prefix": "health"})
                    if code == 0 and data:
                        h = _json.loads(data)
                        if "SLOW_OPS" not in (h.get("checks") or {}):
                            slow_obs["slow_ops_cleared"] = True
                            break
                except (OSError, ValueError, ConnectionError,
                        asyncio.TimeoutError):
                    pass
                await asyncio.sleep(0.4)
            if watch_task is not None:
                watch_task.cancel()
            violations["slow_osd"] = inv.check_slow_osd(slow_obs)
            result["slow_osd_obs"] = dict(slow_obs)
        if events_obs is not None:
            # the event plane: progress completion/reap, crash dumps
            # per injected death, no unmuted debris at settle
            await _settle_events(cluster, events_obs, time_scale)
            if events_watch_task is not None:
                events_watch_task.cancel()
            violations["events"] = inv.check_events(events_obs)
            result["events_obs"] = {
                "expect_progress": events_obs["expect_progress"],
                "events": {
                    eid: {"kind": rec["kind"], "final": rec["final"],
                          "reaped": rec["reaped"],
                          "samples": len(rec["fractions"])}
                    for eid, rec in sorted(
                        events_obs["progress_events"].items())
                },
                "deaths": events_obs.get("deaths", {}),
                "crash_entities": sorted(
                    e for e in events_obs.get("crash_entities", ())
                    if e),
                "unmuted_checks": events_obs.get("unmuted_checks", []),
            }
        if scenario.get("client_netem"):
            # the client-netem ack oracle: a partition verifiably bit
            # a client send, every failed write carries a legal errno
            # — while check_history/check_final_reads above already
            # judged no acked write lost or rolled back
            client_kinds = ("client_partition", "client_drop",
                            "client_delay")
            errored = [w for w in history.writes
                       if w.get("error") is not None]
            violations["client_netem"] = inv.check_client_netem({
                "client_events": sum(
                    1 for e in events if e.kind in client_kinds),
                "netem": dict(cluster.netem.stats),
                "errored_writes": errored,
            })
            import errno as _errno

            result["client_netem_obs"] = {
                "client_partitioned_sends": cluster.netem.stats[
                    "client_partitioned_sends"],
                "client_dropped_sends": cluster.netem.stats[
                    "client_dropped_sends"],
                "client_delayed_sends": cluster.netem.stats[
                    "client_delayed_sends"],
                "errored_writes": len(errored),
                "timeouts": sum(
                    1 for w in errored
                    if w.get("errno") == _errno.ETIMEDOUT),
            }
        if scenario.get("fullness_script"):
            await _settle_fullness(cluster, cluster.fullness,
                                   time_scale)
            if fullness_watch_task is not None:
                fullness_watch_task.cancel()
            cluster.fullness["failsafe_ratio"] = cluster._conf()[
                "osd_failsafe_full_ratio"]
            violations["fullness"] = inv.check_fullness(
                cluster.fullness)
            result["fullness_obs"] = dict(cluster.fullness)
        if scenario.get("topology"):
            # rack-scale failure domains: the pre-kill placement
            # snapshots must prove CRUSH separated shards across
            # racks AND that every PG retained enough shards to
            # survive the whole-rack loss it was about to take
            violations["domains"] = inv.check_domains(
                cluster.domains_obs,
                expect_kill=bool(scenario.get("rack_script")))
            result["domains_obs"] = list(cluster.domains_obs)
        if backfill_base is not None:
            from ceph_tpu.common.metrics import get_perf_counters

            backfill_obs = {
                name: sum(
                    get_perf_counters(f"osd.{i}").dump().get(name, 0.0)
                    for i in range(scenario["n_osds"]))
                - backfill_base[name]
                for name in ("backfill_started", "backfill_completed")
            }
            backfill_obs["interrupt_scripted"] = bool(
                scenario.get("soak_interrupt", "target"))
            if events_obs is not None:
                backfill_obs["progress_events"] = len(
                    events_obs.get("progress_events") or {})
            violations["backfill"] = inv.check_backfill(backfill_obs)
            result["backfill_obs"] = dict(backfill_obs)
        violations["cold_launches"] = inv.check_cold_launches(
            cold_before, _cold_launch_snapshot())

        ok = not any(violations.values())
        counters.inc("runs_green" if ok else "runs_red")
        for name, vs in violations.items():
            if vs:
                counters.inc("violations", invariant=name, by=len(vs))
        df_after = disk_fault_counters().dump()
        perf_after = _perf_totals(scenario["n_osds"])
        result["coverage"] = {
            "event_kinds": sorted({e.kind for e in events}),
            "perf_deltas": {
                k: round(perf_after[k] - perf_before.get(k, 0.0), 6)
                for k in sorted(perf_after)
                if perf_after[k] - perf_before.get(k, 0.0)
            },
            "netem_moved": sorted(
                k for k, v in cluster.netem.stats.items() if v),
            "deaths": dict(sorted(cluster.deaths.items())),
        }
        result.update({
            "ok": ok,
            "events_applied": cluster.events_applied,
            "event_errors": len(cluster.event_errors),
            "workload": (
                history.summary() if history is not None else {
                    "load_ops": load_rec.get("ops_completed", 0)
                    if load_rec else 0,
                }),
            "netem": dict(cluster.netem.stats),
            "disk_faults": {
                k: v - df_before.get(k, 0)
                for k, v in df_after.items()
                if v - df_before.get(k, 0)
            },
            "invariants": {
                name: {"ok": not vs, "violations": vs}
                for name, vs in violations.items()
            },
            "wall_s": round(time.monotonic() - t_wall, 2),
        })
        return result
    finally:
        if watch_task is not None:
            watch_task.cancel()
        if events_watch_task is not None:
            events_watch_task.cancel()
        if fullness_watch_task is not None:
            fullness_watch_task.cancel()
        if cluster.load_harness is not None:
            try:
                await cluster.load_harness.stop()
            except Exception:
                log.exception("chaos: load harness teardown failed")
        await cluster.stop()


def run_sweep(
    scenario_names: list[str], seeds, *, time_scale: float = 1.0,
    scenarios: dict[str, dict] | None = None,
) -> dict:
    """Synchronous driver for CLI/tests: every scenario x every seed,
    each on a fresh event loop (daemon state never leaks across runs).
    Raises nothing — red runs land in the artifact with their
    violations."""
    book = scenarios or SCENARIOS
    runs: list[dict] = []
    for name in scenario_names:
        for seed in seeds:
            loop = asyncio.new_event_loop()
            try:
                runs.append(loop.run_until_complete(
                    run_scenario(book[name], seed, time_scale=time_scale)
                ))
            except Exception as e:  # harness crash: record, keep going
                log.exception("chaos run %s/%s crashed", name, seed)
                runs.append({
                    "scenario": name, "seed": seed, "ok": False,
                    "crash": f"{type(e).__name__}: {e}",
                })
            finally:
                loop.close()
    green = sum(1 for r in runs if r.get("ok"))
    return {
        "schema": "ceph_tpu.chaos/v1",
        "scenarios": list(scenario_names),
        "seeds": list(seeds),
        "runs": runs,
        "summary": {
            "total": len(runs), "green": green,
            "red": len(runs) - green,
            "all_green": green == len(runs),
        },
    }
