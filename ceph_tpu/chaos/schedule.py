"""Seeded deterministic event-schedule generation.

The OSDThrasher (qa/tasks/thrasher.py) draws its next action from a
live RNG while the cluster runs, so no two runs are alike and a failure
is unreproducible without the full teuthology log.  Here the WHOLE
event trace is generated up front as a pure function of ``(seed,
scenario)``: the runner then merely replays it against the cluster, so

- the same seed always yields the identical trace (asserted by
  :func:`trace_hash`, committed into the chaos artifact), and
- a failing seed replays the exact same thrash sequence for debugging
  (the ``ceph_test_rados --seed`` contract).

The generator is stateful *internally* — it tracks which OSDs its own
trace has killed/outed and which links it has partitioned, so traces
are always applicable (never reviving a live OSD, never exceeding the
down budget that would lose quorum/min_size) — but that state derives
only from the seed and scenario, never from the wall clock or the
cluster.
"""

from __future__ import annotations

import hashlib
import json
import random
from dataclasses import dataclass, field

#: every event kind a schedule may emit (the thrasher's action
#: vocabulary + the netem verbs)
EVENT_KINDS = (
    "osd_kill",       # stop the daemon (store survives for the revive)
    "osd_revive",     # restart a killed osd on its surviving store
    "osd_out",        # mon: mark out (remap + backfill away)
    "osd_in",         # mon: mark in again
    "reweight",       # crush reweight an osd
    "mon_restart",    # bounce a monitor (quorum re-forms, catch-up)
    "pg_split",       # double a pool's pg_num
    "scrub",          # shallow scrub a random pg
    "deep_scrub",     # deep scrub a random pg
    "repair",         # pg repair a random pg
    "balance",        # run the upmap balancer
    "partition",      # netem: symmetric partition between two entities
    "heal_partition",  # netem: heal one active partition
    "drop_oneway",    # netem: silently drop src->dst
    "heal_oneway",    # netem: heal one active one-way drop
    "delay",          # netem: fixed per-send latency on a link
    "reorder",        # netem: bounded reordering on a link
    "netem_clear",    # netem: drop every active rule
    # disk-fault verbs (armed through common/fault_injector FAULTS
    # store points, keyed store.<op>.osd.<id>)
    "eio",            # one-shot EIO on an osd's next store read
    "bitflip",        # flip one stored bit at rest on the next read
    "torn_write",     # tear the osd's next transaction commit
    "disk_dead",      # sticky EIO on every read+write (dying disk)
    "slow_disk",      # sticky injected store-commit latency (a disk
                      # that still works but has gone SLOW — the
                      # degraded-disk scenario's beat: SLOW_OPS health,
                      # mgr outlier detection, scrub deprioritization)
    "disk_heal",      # clear every armed store fault on an osd
    # mgr-plane verbs (the mgr is NEVER in the data path: killing it
    # may only cost observability — the workload invariants must be
    # untouched, and report streams must resume after failover)
    "mgr_kill",       # stop a manager daemon (active or standby)
    "mgr_revive",     # restart a killed manager (fresh gid)
    # client-link netem verbs (the PR-10 objecter's resend/backoff/
    # deadline/map-wait paths under REAL partitions — the workload
    # client joins the blast radius; its recorded completions are the
    # ack-aware oracle)
    "client_partition",       # symmetric cut client <-> peer entity
    "heal_client_partition",  # heal one active client cut
    "client_drop",    # one-way silent drop on a client link (either
                      # direction: vanished requests or vanished acks
                      # — the resend-dedup-by-reqid case)
    "heal_client_drop",       # heal one active client drop
    "client_delay",   # fixed per-send latency on a client link
    # fullness-pressure verbs (the nearfull->backfillfull->full->heal
    # ladder driven live against small-capacity stores; application is
    # closed-loop — the runner writes/deletes ballast until the target
    # ratio is observed — but the TRACE stays pure in (seed, scenario))
    "fill",           # write ballast until every up osd >= args[ratio]
    "drain",          # delete ballast until usage falls below nearfull
    # rack-scale correlated-failure verbs (CRUSH failure domains under
    # live fire: the trace kills a WHOLE rack or host at once — args
    # carry the member osd list so replay needs no topology lookup,
    # and the budget guard below guarantees surviving domains always
    # retain >= k shards / >= 1 replica)
    "rack_kill",      # kill every osd of one rack (correlated loss)
    "host_kill",      # kill every osd of one host
    "rack_revive",    # revive every osd of a killed rack
    # control-plane netem verbs: the mon/mgr/mds links join the blast
    # radius (mode: delay / partition / drop toward the osd plane) —
    # the data-plane ack oracle must come through untouched.  mds
    # rules have armed-rule semantics today: chaos clusters run no
    # MDS, so the rule verifiably arms + heals without a data-path
    # bite (the verb exists so traces cover the whole control plane)
    "mon_netem",      # degrade one monitor's links
    "mgr_netem",      # degrade one manager's links
    "mds_netem",      # degrade one mds's links (armed-rule semantics)
    # cache-tier verbs (writeback tier over a base pool: the chaos
    # plane drives the PrimaryLogPG tier machinery — flush dirty
    # objects to the base, evict clean copies, promote-on-miss reads
    # — while the workload's versioned oracle judges last-write-wins
    # through every redirect)
    "tier_flush",     # CACHE_FLUSH one object from the hot pool
    "tier_evict",     # CACHE_EVICT one object from the hot pool
    "tier_promote",   # read via the base pool (promote-on-miss path)
)


@dataclass(frozen=True)
class ChaosEvent:
    """One scheduled action.  ``t`` is the virtual time offset (seconds
    from chaos start; the runner scales it), ``kind`` one of
    EVENT_KINDS, ``args`` the kind-specific parameters."""

    t: float
    kind: str
    args: dict = field(default_factory=dict)

    def to_json(self) -> dict:
        return {"t": self.t, "kind": self.kind, "args": dict(self.args)}


def trace_hash(events: list[ChaosEvent]) -> str:
    """Canonical sha256 over the event trace — the replay assertion:
    regenerating a seed must reproduce this hash bit-identically."""
    blob = json.dumps(
        [e.to_json() for e in events], sort_keys=True,
        separators=(",", ":"),
    ).encode()
    return hashlib.sha256(blob).hexdigest()


class _TraceState:
    """What the generator must remember about its own trace so every
    drawn event is applicable when replayed in order."""

    def __init__(self, n_osds: int, n_mons: int, n_mgrs: int = 0):
        self.alive = set(range(n_osds))     # daemons running
        self.in_set = set(range(n_osds))    # marked in
        self.partitions: list[tuple] = []   # active symmetric cuts
        self.oneways: list[tuple] = []      # active one-way drops
        self.n_mons = n_mons
        self.splits = 0
        self.disk_dead: set[int] = set()    # osds with a sticky-dead disk
        self.slow_disks: set[int] = set()   # osds with injected latency
        self.disk_faulted: set[int] = set()  # osds with ANY store fault
        self.last_damage = -1e9  # t of the last AT-REST damage event
        self.mgr_alive = set(range(n_mgrs))  # manager daemons running
        self.client_cuts: list[tuple] = []   # active client partitions
        self.client_drops: list[tuple] = []  # active client one-way drops


def _entity_pool(rng: random.Random, scenario: dict) -> list[tuple]:
    """Link endpoints netem rules may target: osd<->osd and, in
    multi-mon scenarios, osd<->mon links (never client links — the
    workload oracle needs its acks)."""
    ents = [("osd", i) for i in range(scenario["n_osds"])]
    if scenario.get("n_mons", 1) > 1:
        ents += [("mon", r) for r in range(scenario["n_mons"])]
    return ents


def _client_peer(rng: random.Random, scenario: dict) -> tuple:
    """The far end of a client-link netem rule: one specific OSD, or
    — about a quarter of draws — the ("osd", None) wildcard cutting
    the client off from the WHOLE data plane at once (mon links stay
    up: the session/command plane is the observer, never the target —
    the oracle judges the objecter's data path)."""
    if rng.random() < 0.25:
        return ("osd", None)
    return ("osd", rng.randrange(scenario["n_osds"]))


def generate_schedule(seed: int, scenario: dict) -> list[ChaosEvent]:
    """Draw ``scenario['n_events']`` events over ``scenario
    ['duration']`` virtual seconds, honoring the scenario's event-mix
    weights and its safety budgets.  Pure in ``(seed, scenario)``."""
    rng = random.Random(f"chaos:{seed}:{scenario['name']}")
    n_osds = scenario["n_osds"]
    n_mons = scenario.get("n_mons", 1)
    n_events = scenario.get("n_events", 10)
    duration = float(scenario.get("duration", 5.0))
    mix = dict(scenario.get("mix", {"osd_kill": 1.0}))
    # revive/heal verbs are implied counterparts, not independent
    # draws: the generator emits them to keep its budgets
    for implied in ("osd_revive", "osd_in", "heal_partition",
                    "heal_oneway"):
        mix.pop(implied, None)
    # at most this many osds simultaneously dead+out: keeps a k+m EC
    # pool writable while the thrash runs (the OSDThrasher's
    # min_in/max_dead budget)
    max_dead = scenario_max_dead(scenario)
    max_cuts = scenario.get("max_partitions", 1)
    pg_pools = [p["name"] for p in scenario.get("pools", [])] or ["rep"]

    st = _TraceState(n_osds, n_mons, scenario.get("n_mgrs", 0))
    kinds = sorted(mix)
    weights = [float(mix[k]) for k in kinds]
    times = sorted(round(rng.uniform(0.05, duration), 3)
                   for _ in range(n_events))
    events: list[ChaosEvent] = []

    def emit(t: float, kind: str, **args) -> None:
        events.append(ChaosEvent(t=t, kind=kind, args=args))

    # degraded-disk scenarios pin ONE guaranteed early slow_disk so
    # the mgr pipeline (reports -> analytics -> outlier -> SLOW_OPS)
    # always has a full observation window; the victim still derives
    # from the seed (pure in (seed, scenario) like every other draw)
    lead_at = scenario.get("slow_disk_at")
    if lead_at is not None:
        victim = rng.randrange(n_osds)
        st.slow_disks.add(victim)
        st.disk_faulted.add(victim)
        emit(round(float(lead_at), 3), "slow_disk", osd=victim,
             delay=float(scenario.get("slow_disk_delay", 0.5)))

    # client-netem scenarios pin ONE guaranteed early client partition
    # (the acceptance oracle demands a partition that verifiably
    # FIRED in every trace): the pinned cut always takes the
    # ("osd", None) wildcard — a specific osd may lead no PG, and a
    # cut nothing sends through proves nothing.  Only the ttl derives
    # from the seed; mix-drawn cuts keep their seed-varied peers.
    # the pinned cut lives OUTSIDE the mix budget (its own slot,
    # healed by ttl + trace end): letting a mix-drawn cut budget-pop
    # it could heal it milliseconds after it armed, and the oracle
    # would rightly flag a partition that never bit a send
    lead_cut = scenario.get("client_partition_at")
    pinned_cut = None
    if lead_cut is not None:
        pinned_cut = ("osd", None)
        emit(round(float(lead_cut), 3), "client_partition",
             peer=list(pinned_cut),
             ttl=round(rng.uniform(0.4, 1.0), 3))

    # fullness-pressure scenarios pin the whole gating ladder as a
    # scripted skeleton (like slow_disk_at: the ladder must ALWAYS
    # progress, only its timing and the outed victim vary with the
    # seed).  Order is the invariant under test: nearfull first, then
    # backfillfull BEFORE the osd_out so the triggered backfill meets
    # REJECT_TOOFULL live (recovery.py backfillfull gate), then full
    # (client writes must bounce ENOSPC), then drain + heal.  The
    # fill/drain application is closed-loop in the runner; the trace —
    # order, targets, victim — is pure in (seed, scenario).
    if scenario.get("fullness_script"):
        t_f = round(0.2 + rng.uniform(0.0, 0.3), 3)
        emit(t_f, "fill", level="nearfull",
             ratio=float(scenario.get("nearfull_fill", 0.86)))
        t_f = round(t_f + 0.3 + rng.uniform(0.0, 0.3), 3)
        emit(t_f, "fill", level="backfillfull",
             ratio=float(scenario.get("backfillfull_fill", 0.91)))
        victim = rng.randrange(n_osds)
        t_f = round(t_f + 0.2 + rng.uniform(0.0, 0.2), 3)
        st.in_set.discard(victim)
        emit(t_f, "osd_out", osd=victim)
        t_f = round(t_f + 0.3 + rng.uniform(0.0, 0.3), 3)
        emit(t_f, "fill", level="full",
             ratio=float(scenario.get("full_fill", 0.955)))
        t_f = round(t_f + 0.4 + rng.uniform(0.0, 0.4), 3)
        emit(t_f, "drain")
        # the generic trace-end wholeness below emits the osd_in

    # rack-scale correlated-failure skeleton: kill ONE whole failure
    # domain — every osd of a seed-chosen rack — dwell, revive, and
    # optionally follow with a single-host kill in a DIFFERENT rack.
    # Budget: the surviving racks must retain >= k shards (EC) or
    # >= 1 replica, which one-shard-per-rack placement guarantees
    # exactly when racks - 1 >= max(k, 1); the guard refuses to emit
    # an unsurvivable trace rather than emit one that loses data by
    # construction.  Rack scenarios keep osd_kill/osd_out OUT of
    # their mix so a mix draw can never double-kill a scripted victim.
    if scenario.get("rack_script"):
        topo = scenario["topology"]
        per_host = int(topo.get("osds_per_host", 1))
        hosts_per_rack = int(topo.get("hosts_per_rack", 1))
        per_rack = per_host * hosts_per_rack
        n_racks = int(topo["racks"])
        need = max(
            (p.get("k", p.get("size", 2))
             for p in scenario.get("pools", [])), default=1)
        if n_racks - 1 >= need:
            rack = rng.randrange(n_racks)
            osds = list(range(rack * per_rack, (rack + 1) * per_rack))
            t_k = round(0.4 + rng.uniform(0.0, 0.4), 3)
            st.alive.difference_update(osds)
            emit(t_k, "rack_kill", rack=rack, osds=osds)
            dwell = float(scenario.get(
                "rack_dwell", max(0.8, duration * 0.3)))
            t_r = round(t_k + dwell + rng.uniform(0.0, 0.3), 3)
            st.alive.update(osds)
            emit(t_r, "rack_revive", rack=rack, osds=osds)
            if scenario.get("host_kill_after"):
                # a second, smaller correlated loss after the rack
                # revives: one whole host in a different rack (its
                # members stay dead until trace-end wholeness)
                other = rng.choice(
                    [r for r in range(n_racks) if r != rack])
                host = (other * hosts_per_rack
                        + rng.randrange(hosts_per_rack))
                hosds = list(range(
                    host * per_host, (host + 1) * per_host))
                t_h = round(t_r + 0.3 + rng.uniform(0.0, 0.3), 3)
                st.alive.difference_update(hosds)
                emit(t_h, "host_kill", host=host, osds=hosds)

    # long-soak skeleton: ONE victim goes down early and stays down
    # for most of the trace while the paced workload churns every pg
    # log past the trim horizon (the scenario's conf pins tiny
    # osd_min/max_pg_log_entries), so the revived member PREDATES
    # every surviving log tail and recovery MUST take the backfill
    # path — the runner's check_backfill invariant demands the
    # backfill_started/backfill_completed counters prove it.  A
    # second, shorter kill lands while that backfill runs (the
    # backfill TARGET itself, or a seed-chosen live source member) to
    # prove the persisted cursor resumes an interrupted pass.
    if scenario.get("soak_script"):
        victim = rng.randrange(n_osds)
        t_k = round(0.3 + rng.uniform(0.0, 0.2), 3)
        st.alive.discard(victim)
        emit(t_k, "osd_kill", osd=victim)
        dwell = float(scenario.get("soak_outage", duration * 0.55))
        t_r = round(t_k + dwell + rng.uniform(0.0, 0.2), 3)
        st.alive.add(victim)
        emit(t_r, "osd_revive", osd=victim)
        mode = scenario.get("soak_interrupt", "target")
        if mode:
            if mode == "target":
                v2 = victim
            else:
                v2 = rng.choice(sorted(st.alive - {victim}))
            # fire just after the revive: the runner holds THIS kill
            # (await_backfill) until a backfill pass is verifiably in
            # flight, so the interrupt lands mid-transfer instead of
            # racing the revived member's boot — arming the gate
            # BEFORE the first pass can start is what makes the
            # mid-transfer hit deterministic (the trace itself stays
            # pure — the gate shifts delivery, not the event)
            t_k2 = round(t_r + 0.1 + rng.uniform(0.0, 0.1), 3)
            st.alive.discard(v2)
            emit(t_k2, "osd_kill", osd=v2, await_backfill=True)
            t_r2 = round(t_k2 + 0.4 + rng.uniform(0.0, 0.3), 3)
            st.alive.add(v2)
            emit(t_r2, "osd_revive", osd=v2)

    # control-plane blast-radius skeleton: one guaranteed beat per
    # plane — a mon link degradation (delay when the quorum cannot
    # spare a member, else partition), a mgr link fault, and an mds
    # rule (armed-rule semantics) — so every trace provably put the
    # control plane in the blast radius while the data-plane oracle
    # earned its acks.
    if scenario.get("control_netem"):
        t_c = round(0.3 + rng.uniform(0.0, 0.3), 3)
        emit(t_c, "mon_netem", rank=rng.randrange(n_mons),
             mode="partition" if n_mons >= 3 else "delay",
             seconds=round(rng.uniform(0.01, 0.04), 4),
             ttl=round(rng.uniform(0.5, 1.2), 3))
        if scenario.get("n_mgrs", 0) > 0:
            t_c = round(t_c + 0.3 + rng.uniform(0.0, 0.3), 3)
            emit(t_c, "mgr_netem",
                 mgr=rng.randrange(scenario["n_mgrs"]),
                 mode=rng.choice(["partition", "drop", "delay"]),
                 seconds=round(rng.uniform(0.01, 0.04), 4),
                 ttl=round(rng.uniform(0.5, 1.2), 3))
        t_c = round(t_c + 0.3 + rng.uniform(0.0, 0.3), 3)
        emit(t_c, "mds_netem", mds=0, mode="delay",
             seconds=round(rng.uniform(0.01, 0.04), 4),
             ttl=round(rng.uniform(0.5, 1.0), 3))

    for t in times:
        kind = rng.choices(kinds, weights=weights)[0]
        dead = sorted(set(range(n_osds)) - st.alive)
        outed = sorted(set(range(n_osds)) - st.in_set)
        down_ish = len(dead) + len(set(outed) - set(dead))
        if kind == "osd_kill":
            if down_ish >= max_dead:
                # budget spent: revive the longest-dead instead
                if dead:
                    emit(t, "osd_revive", osd=dead[0])
                    st.alive.add(dead[0])
                elif outed:
                    emit(t, "osd_in", osd=outed[0])
                    st.in_set.add(outed[0])
                continue
            victim = rng.choice(sorted(st.alive))
            st.alive.discard(victim)
            emit(t, "osd_kill", osd=victim)
        elif kind == "osd_out":
            if down_ish >= max_dead or len(st.in_set) <= 2:
                if outed:
                    emit(t, "osd_in", osd=outed[0])
                    st.in_set.add(outed[0])
                continue
            victim = rng.choice(sorted(st.in_set))
            st.in_set.discard(victim)
            emit(t, "osd_out", osd=victim)
        elif kind == "reweight":
            emit(t, "reweight", osd=rng.randrange(n_osds),
                 weight=round(rng.choice([0.25, 0.5, 0.75, 1.0]), 2))
        elif kind == "mon_restart":
            if n_mons < 2:
                continue  # single-mon cluster: a restart is an outage
            emit(t, "mon_restart", rank=rng.randrange(n_mons))
        elif kind == "pg_split":
            if st.splits >= scenario.get("max_splits", 1):
                continue
            st.splits += 1
            emit(t, "pg_split", pool=rng.choice(pg_pools))
        elif kind in ("scrub", "deep_scrub", "repair"):
            emit(t, kind, pool=rng.choice(pg_pools))
        elif kind in ("eio", "bitflip", "torn_write", "disk_dead"):
            # store faults against a LIVE osd (arming a dead daemon's
            # store exercises nothing).  AT-REST damage (bitflip,
            # disk_dead) respects a redundancy budget the way kills
            # respect max_dead: at most ONE outstanding dying disk,
            # and consecutive damage events at least damage_gap apart
            # so quarantine + background repair can restore
            # reconstructibility between hits — two unhealed rotten
            # copies of the same object is operator data loss
            # (exceeding m), not a cluster bug.  Over-budget draws
            # DOWNGRADE to a transient one-shot eio.
            victims = sorted(st.alive - st.disk_dead)
            if not victims:
                continue
            victim = rng.choice(victims)
            gap = float(scenario.get("damage_gap", 1.0))
            damaging = kind in ("bitflip", "disk_dead")
            if damaging and (
                st.disk_dead or t - st.last_damage < gap
            ):
                kind = "eio"
            elif kind == "disk_dead" and down_ish >= max_dead:
                kind = "eio"  # the victim will suicide: kill budget
            if kind == "disk_dead":
                st.alive.discard(victim)
                st.disk_dead.add(victim)
            if kind in ("bitflip", "disk_dead"):
                st.last_damage = t
            st.disk_faulted.add(victim)
            emit(t, kind, osd=victim)
        elif kind == "slow_disk":
            # one slow disk at a time: two simultaneously-slow members
            # of a size-2/k+1 pool is an availability study, not the
            # degraded-disk scenario's detection beat
            victims = sorted(st.alive - st.disk_dead - st.slow_disks)
            if st.slow_disks or not victims:
                continue
            victim = rng.choice(victims)
            st.slow_disks.add(victim)
            st.disk_faulted.add(victim)
            emit(t, "slow_disk", osd=victim,
                 delay=float(scenario.get("slow_disk_delay", 0.5)))
        elif kind == "mgr_kill":
            # no down-budget: losing EVERY mgr is legal (observability
            # gap, not data loss) — but a dead set yields the revive
            # instead so the trace keeps exercising failovers
            if not st.mgr_alive:
                dead_mgrs = sorted(
                    set(range(scenario.get("n_mgrs", 0))) - st.mgr_alive)
                if dead_mgrs:
                    emit(t, "mgr_revive", mgr=dead_mgrs[0])
                    st.mgr_alive.add(dead_mgrs[0])
                continue
            victim = rng.choice(sorted(st.mgr_alive))
            st.mgr_alive.discard(victim)
            emit(t, "mgr_kill", mgr=victim)
        elif kind == "balance":
            emit(t, "balance", max_swaps=8)
        elif kind == "partition":
            if len(st.partitions) >= max_cuts:
                cut = st.partitions.pop(rng.randrange(len(st.partitions)))
                emit(t, "heal_partition", a=list(cut[0]), b=list(cut[1]))
                continue
            ents = _entity_pool(rng, scenario)
            a, b = rng.sample(ents, 2)
            st.partitions.append((a, b))
            emit(t, "partition", a=list(a), b=list(b),
                 ttl=round(rng.uniform(0.3, 1.2), 3))
        elif kind == "drop_oneway":
            if len(st.oneways) >= max_cuts:
                link = st.oneways.pop(rng.randrange(len(st.oneways)))
                emit(t, "heal_oneway", src=list(link[0]), dst=list(link[1]))
                continue
            ents = _entity_pool(rng, scenario)
            a, b = rng.sample(ents, 2)
            st.oneways.append((a, b))
            emit(t, "drop_oneway", src=list(a), dst=list(b),
                 ttl=round(rng.uniform(0.3, 1.0), 3))
        elif kind == "delay":
            ents = _entity_pool(rng, scenario)
            a, b = rng.sample(ents, 2)
            emit(t, "delay", src=list(a), dst=list(b),
                 seconds=round(rng.uniform(0.005, 0.04), 4),
                 ttl=round(rng.uniform(0.3, 1.5), 3))
        elif kind == "reorder":
            ents = _entity_pool(rng, scenario)
            a, b = rng.sample(ents, 2)
            emit(t, "reorder", src=list(a), dst=list(b),
                 every=rng.choice([2, 3, 5]),
                 hold=round(rng.uniform(0.005, 0.03), 4),
                 ttl=round(rng.uniform(0.3, 1.5), 3))
        elif kind == "client_partition":
            max_client = scenario.get("max_client_cuts", 1)
            if len(st.client_cuts) >= max_client:
                cut = st.client_cuts.pop(
                    rng.randrange(len(st.client_cuts)))
                emit(t, "heal_client_partition", peer=list(cut))
                continue
            peer = _client_peer(rng, scenario)
            st.client_cuts.append(peer)
            emit(t, "client_partition", peer=list(peer),
                 ttl=round(rng.uniform(0.3, 1.0), 3))
        elif kind == "client_drop":
            max_client = scenario.get("max_client_cuts", 1)
            if len(st.client_drops) >= max_client:
                link = st.client_drops.pop(
                    rng.randrange(len(st.client_drops)))
                emit(t, "heal_client_drop", peer=list(link[0]),
                     to_client=link[1])
                continue
            peer = _client_peer(rng, scenario)
            # direction matters: dropping client->osd loses requests
            # (deadline/backoff beat); dropping osd->client loses ACKS
            # of APPLIED writes (the resend must dedup by reqid)
            to_client = rng.random() < 0.5
            st.client_drops.append((peer, to_client))
            emit(t, "client_drop", peer=list(peer), to_client=to_client,
                 ttl=round(rng.uniform(0.3, 0.8), 3))
        elif kind == "client_delay":
            peer = _client_peer(rng, scenario)
            emit(t, "client_delay", peer=list(peer),
                 seconds=round(rng.uniform(0.005, 0.05), 4),
                 ttl=round(rng.uniform(0.3, 1.5), 3))
        elif kind in ("mon_netem", "mgr_netem", "mds_netem"):
            # control-plane link faults self-heal by ttl (plus the
            # trace-end netem_clear), so they carry no trace state
            if kind == "mon_netem":
                who = {"rank": rng.randrange(n_mons)}
                mode = rng.choice(["delay", "partition", "drop"])
                if n_mons < 3 and mode == "partition":
                    # a quorum that cannot spare a member only gets
                    # its links SLOWED, never cut
                    mode = "delay"
            elif kind == "mgr_netem":
                n_mgrs = scenario.get("n_mgrs", 0)
                if n_mgrs < 1:
                    continue
                who = {"mgr": rng.randrange(n_mgrs)}
                mode = rng.choice(["delay", "partition", "drop"])
            else:
                who = {"mds": 0}
                mode = "delay"
            emit(t, kind, mode=mode,
                 seconds=round(rng.uniform(0.005, 0.04), 4),
                 ttl=round(rng.uniform(0.3, 1.0), 3), **who)
        elif kind in ("tier_flush", "tier_evict", "tier_promote"):
            tier = scenario.get("tier")
            if not tier:
                continue
            n_obj = int(scenario.get("workload", {}).get("objects", 3))
            emit(t, kind, base=tier["base"], hot=tier["hot"],
                 oid=f"{tier['base']}-obj{rng.randrange(n_obj)}")
        elif kind == "netem_clear":
            st.partitions.clear()
            st.oneways.clear()
            st.client_cuts.clear()
            st.client_drops.clear()
            emit(t, "netem_clear")
    # the trace always ends whole: every dead osd revives, every outed
    # osd returns, every cut heals — the runner's convergence invariant
    # judges a complete cluster, not a half-thrashed one
    t_end = round(duration + 0.05, 3)
    for cut in st.partitions:
        emit(t_end, "heal_partition", a=list(cut[0]), b=list(cut[1]))
    for link in st.oneways:
        emit(t_end, "heal_oneway", src=list(link[0]), dst=list(link[1]))
    for peer in st.client_cuts:
        emit(t_end, "heal_client_partition", peer=list(peer))
    if pinned_cut is not None:
        emit(t_end, "heal_client_partition", peer=list(pinned_cut))
    for peer, to_client in st.client_drops:
        emit(t_end, "heal_client_drop", peer=list(peer),
             to_client=to_client)
    emit(t_end, "netem_clear")
    for osd in sorted(st.disk_faulted):
        # every fault-touched disk heals at trace end: sticky-dead
        # disks must heal BEFORE the revive below (a restarted daemon
        # must not boot onto a store still returning EIO), and an
        # armed-but-unfired one-shot fault must not fire later, inside
        # the runner's post-thrash verification sweeps
        emit(t_end, "disk_heal", osd=osd)
    for osd in sorted(set(range(n_osds)) - st.alive):
        emit(t_end, "osd_revive", osd=osd)
    for osd in sorted(set(range(n_osds)) - st.in_set):
        emit(t_end, "osd_in", osd=osd)
    for mgr in sorted(set(range(scenario.get("n_mgrs", 0)))
                      - st.mgr_alive):
        emit(t_end, "mgr_revive", mgr=mgr)
    # scripted-ladder scenarios interleave pinned events with mix
    # draws: a STABLE sort restores replay order.  Gated — legacy
    # scenarios' committed trace hashes encode their emission order
    # (e.g. the degraded-disk slow_disk lead precedes earlier-t mix
    # draws) and must replay bit-identically forever.
    if (scenario.get("fullness_script") or scenario.get("rack_script")
            or scenario.get("soak_script")
            or scenario.get("control_netem")):
        events.sort(key=lambda e: e.t)
    return events


# -- trace schema + applicability (the fuzz plane's contract) ---------------
#
# The mutation engine (ceph_tpu/fuzz/mutate.py) edits raw event lists;
# everything below is what keeps its output runnable: a per-kind arg
# schema, per-scenario verb applicability, a validator that refuses a
# trace the runner could not replay, and a deterministic repair pass
# that normalizes an arbitrary edit back into a legal trace.  All of
# it is pure — no clock, no shared RNG — because mutant traces carry
# the same committed-hash contract as generated ones.

_INT = (int,)
_NUM = (int, float)

#: required args per event kind (optional args — ttl, await_backfill —
#: are not listed; extra keys are allowed)
EVENT_ARG_SCHEMA: dict[str, dict[str, tuple | type]] = {
    "osd_kill": {"osd": _INT}, "osd_revive": {"osd": _INT},
    "osd_out": {"osd": _INT}, "osd_in": {"osd": _INT},
    "reweight": {"osd": _INT, "weight": _NUM},
    "mon_restart": {"rank": _INT},
    "pg_split": {"pool": str},
    "scrub": {"pool": str}, "deep_scrub": {"pool": str},
    "repair": {"pool": str},
    "balance": {},
    "partition": {"a": list, "b": list},
    "heal_partition": {"a": list, "b": list},
    "drop_oneway": {"src": list, "dst": list},
    "heal_oneway": {"src": list, "dst": list},
    "delay": {"src": list, "dst": list, "seconds": _NUM},
    "reorder": {"src": list, "dst": list, "every": _INT, "hold": _NUM},
    "netem_clear": {},
    "eio": {"osd": _INT}, "bitflip": {"osd": _INT},
    "torn_write": {"osd": _INT}, "disk_dead": {"osd": _INT},
    "slow_disk": {"osd": _INT, "delay": _NUM},
    "disk_heal": {"osd": _INT},
    "mgr_kill": {"mgr": _INT}, "mgr_revive": {"mgr": _INT},
    "client_partition": {"peer": list},
    "heal_client_partition": {"peer": list},
    "client_drop": {"peer": list, "to_client": bool},
    "heal_client_drop": {"peer": list, "to_client": bool},
    "client_delay": {"peer": list, "seconds": _NUM},
    "fill": {"level": str, "ratio": _NUM}, "drain": {},
    "rack_kill": {"rack": _INT, "osds": list},
    "host_kill": {"host": _INT, "osds": list},
    "rack_revive": {"rack": _INT, "osds": list},
    "mon_netem": {"rank": _INT, "mode": str, "seconds": _NUM},
    "mgr_netem": {"mgr": _INT, "mode": str, "seconds": _NUM},
    "mds_netem": {"mds": _INT, "mode": str, "seconds": _NUM},
    "tier_flush": {"base": str, "hot": str, "oid": str},
    "tier_evict": {"base": str, "hot": str, "oid": str},
    "tier_promote": {"base": str, "hot": str, "oid": str},
}


def scenario_max_dead(scenario: dict) -> int:
    """The scenario's simultaneous dead+out budget: keeps a k+m EC
    pool writable while the thrash runs (the OSDThrasher's
    min_in/max_dead budget)."""
    n_osds = scenario["n_osds"]
    max_dead = scenario.get("max_dead", max(1, n_osds - 1 - max(
        p.get("k", p.get("size", 2)) + p.get("m", 0)
        for p in scenario.get("pools", [{"size": 2}])
    )))
    return max(1, min(max_dead, n_osds - 2))


def scenario_verbs(scenario: dict) -> tuple[str, ...]:
    """Every verb a LEGAL trace for this scenario may contain — the
    validator's vocabulary.  Scenario-dependent gates mirror the
    generator's own refusals (a verb the generator would never draw
    here is a verb the runner cannot meaningfully replay here)."""
    out = set(EVENT_KINDS)
    if scenario.get("n_mons", 1) < 2:
        out.discard("mon_restart")
    if not scenario.get("n_mgrs"):
        out -= {"mgr_kill", "mgr_revive", "mgr_netem"}
    if not scenario.get("client_netem"):
        out -= {"client_partition", "heal_client_partition",
                "client_drop", "heal_client_drop", "client_delay"}
    if not scenario.get("topology"):
        out -= {"rack_kill", "host_kill", "rack_revive"}
    if not scenario.get("tier"):
        out -= {"tier_flush", "tier_evict", "tier_promote"}
    if scenario.get("store") != "blockstore":
        # at-rest disk faults need a store whose lies surface like
        # real media errors (MemStore has no at-rest bytes to rot);
        # slow_disk/disk_heal stay — injected commit latency works on
        # any store and every fault-touched disk heals at trace end
        out -= {"eio", "bitflip", "torn_write", "disk_dead"}
    if not scenario.get("capacity_bytes"):
        # the fullness ladder needs small-capacity stores the
        # closed-loop ballast writer can actually push over a ratio
        out -= {"fill", "drain"}
    return tuple(sorted(out))


def applicable_verbs(scenario: dict) -> tuple[str, ...]:
    """The CROSS-BREEDING pool: verbs a mutant may inject into this
    scenario's traces and still be expected to run green.  Stricter
    than :func:`scenario_verbs` — the fuzzer's job is to find bugs,
    not to manufacture reds out of oracle preconditions:

    - fill/drain stay out everywhere (the application is closed-loop
      against store capacity; injected mid-trace they starve or stall
      foreign workloads);
    - rack verbs stay out (args carry topology member lists; only the
      scripted skeleton knows a survivable one);
    - kills/outs stay out of topology and fullness scenarios (their
      scripted ladders budget the failure pattern themselves — the
      same reason their mixes exclude them);
    - at-rest damage (bitflip/disk_dead) stays out — the generator
      meters damage with a redundancy budget (damage_gap, one dying
      disk); a mutant splicing a second hit is operator data loss,
      not a found bug.  Transient eio/torn_write join only self_heal
      scenarios (the repair sweep is the heal path for their debris);
    - slow_disk stays out of watch_events scenarios (a late SLOW_OPS
      clear reads as settle debris to check_events).
    """
    out = {
        "reweight", "scrub", "deep_scrub", "repair", "balance",
        "partition", "drop_oneway", "delay", "reorder", "netem_clear",
        "pg_split", "mon_netem", "mds_netem", "osd_kill", "osd_out",
    }
    if scenario.get("n_mons", 1) >= 2:
        out.add("mon_restart")
    if scenario.get("n_mgrs"):
        out |= {"mgr_kill", "mgr_netem"}
    if scenario.get("client_netem"):
        out |= {"client_partition", "client_drop", "client_delay"}
    if scenario.get("tier"):
        out |= {"tier_flush", "tier_evict", "tier_promote"}
    if scenario.get("store") == "blockstore" and scenario.get(
            "self_heal"):
        out |= {"eio", "torn_write"}
    if scenario.get("watch_events"):
        out.discard("slow_disk")
    if scenario.get("topology") or scenario.get("fullness_script"):
        out -= {"osd_kill", "osd_out"}
    return tuple(sorted(out))


def events_to_json(events: list[ChaosEvent]) -> list[dict]:
    return [e.to_json() for e in events]


def events_from_json(recs: list[dict]) -> list[ChaosEvent]:
    return [
        ChaosEvent(t=float(r["t"]), kind=r["kind"],
                   args=dict(r.get("args") or {}))
        for r in recs
    ]


class _ReplayState:
    """The validator/repairer's legality simulation — the same state
    discipline the generator keeps internally, replayed over an
    arbitrary event list."""

    def __init__(self, scenario: dict):
        n = scenario["n_osds"]
        self.n_osds = n
        self.n_mons = scenario.get("n_mons", 1)
        self.n_mgrs = scenario.get("n_mgrs", 0)
        self.alive = set(range(n))
        self.in_set = set(range(n))
        self.mgr_alive = set(range(self.n_mgrs))
        self.partitions: list[tuple] = []
        self.oneways: list[tuple] = []
        self.client_cuts: list[tuple] = []
        self.client_drops: list[tuple] = []
        self.faulted: set[int] = set()
        self.rack_dead: set[int] = set()  # dead via rack/host kills
        self.splits = 0
        self.max_dead = scenario_max_dead(scenario)
        self.max_cuts = scenario.get("max_partitions", 1)
        # the generator's pinned client cut (client_partition_at)
        # lives OUTSIDE the mix budget — its own slot
        self.max_client = scenario.get("max_client_cuts", 1) + (
            1 if scenario.get("client_partition_at") is not None
            else 0)
        self.max_splits = scenario.get("max_splits", 1)

    def down_budget_used(self) -> int:
        """Mix-killed/outed osds counted against max_dead (rack-script
        correlated kills run their own survivability budget)."""
        dead = (set(range(self.n_osds)) - self.alive) - self.rack_dead
        outed = set(range(self.n_osds)) - self.in_set
        return len(dead) + len(outed - dead)

    def whole(self) -> bool:
        return (self.alive == set(range(self.n_osds))
                and self.in_set == set(range(self.n_osds))
                and self.mgr_alive == set(range(self.n_mgrs))
                and not self.partitions and not self.oneways
                and not self.client_cuts and not self.client_drops
                and not self.faulted)


def _check_args(e: ChaosEvent) -> str | None:
    """Schema check one event; returns a violation string or None."""
    schema = EVENT_ARG_SCHEMA.get(e.kind)
    if schema is None:
        return f"unknown event kind {e.kind!r}"
    if not isinstance(e.args, dict):
        return f"{e.kind}: args is not a dict"
    for key, typ in sorted(schema.items()):
        if key not in e.args:
            return f"{e.kind}: missing arg {key!r}"
        if not isinstance(e.args[key], typ):
            return (f"{e.kind}: arg {key!r}={e.args[key]!r} is not "
                    f"{typ!r}")
    if not isinstance(e.t, _NUM):
        return f"{e.kind}: t={e.t!r} is not a number"
    return None


def _step(st: _ReplayState, e: ChaosEvent,
          scenario: dict) -> str | None:
    """Advance the legality simulation by one event; returns a
    violation string (state unchanged) or None (state advanced).
    Shared by validate_trace (reject) and repair_trace (drop)."""
    a = e.args
    k = e.kind

    def _osd_ok(o) -> bool:
        return 0 <= o < st.n_osds

    if k == "osd_kill":
        if not _osd_ok(a["osd"]) or a["osd"] not in st.alive:
            return f"osd_kill {a['osd']}: not alive"
        if (a["osd"] not in (set(range(st.n_osds)) - st.in_set)
                and st.down_budget_used() >= st.max_dead):
            return f"osd_kill {a['osd']}: max_dead budget spent"
        st.alive.discard(a["osd"])
    elif k == "osd_revive":
        if not _osd_ok(a["osd"]) or a["osd"] in st.alive:
            return f"osd_revive {a['osd']}: already alive"
        st.alive.add(a["osd"])
        st.rack_dead.discard(a["osd"])
    elif k == "osd_out":
        if not _osd_ok(a["osd"]) or a["osd"] not in st.in_set:
            return f"osd_out {a['osd']}: already out"
        if len(st.in_set) <= 2:
            return f"osd_out {a['osd']}: would leave < 2 in"
        if (a["osd"] in st.alive
                and st.down_budget_used() >= st.max_dead):
            return f"osd_out {a['osd']}: max_dead budget spent"
        st.in_set.discard(a["osd"])
    elif k == "osd_in":
        if not _osd_ok(a["osd"]) or a["osd"] in st.in_set:
            return f"osd_in {a['osd']}: already in"
        st.in_set.add(a["osd"])
    elif k in ("reweight", "eio", "bitflip", "torn_write",
               "slow_disk", "disk_dead"):
        if not _osd_ok(a["osd"]):
            return f"{k}: osd {a['osd']} out of range"
        if k != "reweight":
            if a["osd"] not in st.alive:
                return f"{k} {a['osd']}: arming a dead osd's store"
            st.faulted.add(a["osd"])
            if k == "disk_dead":
                if st.down_budget_used() >= st.max_dead:
                    return f"disk_dead {a['osd']}: max_dead budget"
                st.alive.discard(a["osd"])
    elif k == "disk_heal":
        if not _osd_ok(a["osd"]):
            return f"disk_heal: osd {a['osd']} out of range"
        st.faulted.discard(a["osd"])
    elif k == "mon_restart":
        if st.n_mons < 2:
            return "mon_restart: single-mon cluster"
        if not 0 <= a["rank"] < st.n_mons:
            return f"mon_restart: rank {a['rank']} out of range"
    elif k == "pg_split":
        if st.splits >= st.max_splits:
            return "pg_split: max_splits budget spent"
        st.splits += 1
    elif k in ("mgr_kill", "mgr_revive"):
        if not 0 <= a["mgr"] < st.n_mgrs:
            return f"{k}: mgr {a['mgr']} out of range"
        if k == "mgr_kill":
            if a["mgr"] not in st.mgr_alive:
                return f"mgr_kill {a['mgr']}: already dead"
            st.mgr_alive.discard(a["mgr"])
        else:
            if a["mgr"] in st.mgr_alive:
                return f"mgr_revive {a['mgr']}: already alive"
            st.mgr_alive.add(a["mgr"])
    elif k == "partition":
        if len(st.partitions) >= st.max_cuts:
            return "partition: max_partitions budget spent"
        st.partitions.append((tuple(a["a"]), tuple(a["b"])))
    elif k == "heal_partition":
        cut = (tuple(a["a"]), tuple(a["b"]))
        rcut = (cut[1], cut[0])
        if cut in st.partitions:
            st.partitions.remove(cut)
        elif rcut in st.partitions:
            st.partitions.remove(rcut)
    elif k == "drop_oneway":
        if len(st.oneways) >= st.max_cuts:
            return "drop_oneway: max_partitions budget spent"
        st.oneways.append((tuple(a["src"]), tuple(a["dst"])))
    elif k == "heal_oneway":
        link = (tuple(a["src"]), tuple(a["dst"]))
        if link in st.oneways:
            st.oneways.remove(link)
    elif k == "client_partition":
        if len(st.client_cuts) >= st.max_client:
            return "client_partition: max_client_cuts budget spent"
        st.client_cuts.append(tuple(a["peer"]))
    elif k == "heal_client_partition":
        peer = tuple(a["peer"])
        if peer in st.client_cuts:
            st.client_cuts.remove(peer)
    elif k == "client_drop":
        if len(st.client_drops) >= st.max_client:
            return "client_drop: max_client_cuts budget spent"
        st.client_drops.append((tuple(a["peer"]), a["to_client"]))
    elif k == "heal_client_drop":
        link = (tuple(a["peer"]), a["to_client"])
        if link in st.client_drops:
            st.client_drops.remove(link)
    elif k == "netem_clear":
        st.partitions.clear()
        st.oneways.clear()
        st.client_cuts.clear()
        st.client_drops.clear()
    elif k in ("rack_kill", "host_kill"):
        osds = set(a["osds"])
        if not osds <= st.alive:
            return f"{k}: members {sorted(osds - st.alive)} not alive"
        st.alive -= osds
        st.rack_dead |= osds
    elif k == "rack_revive":
        osds = set(a["osds"])
        if osds & st.alive:
            return (f"rack_revive: members "
                    f"{sorted(osds & st.alive)} already alive")
        st.alive |= osds
        st.rack_dead -= osds
    elif k == "mon_netem":
        if not 0 <= a["rank"] < st.n_mons:
            return f"mon_netem: rank {a['rank']} out of range"
        if a["mode"] == "partition" and st.n_mons < 3:
            return ("mon_netem: a quorum that cannot spare a member "
                    "only gets its links slowed, never cut")
    elif k == "mgr_netem":
        if not 0 <= a["mgr"] < st.n_mgrs:
            return f"mgr_netem: mgr {a['mgr']} out of range"
    # delay/reorder/scrub/deep_scrub/repair/balance/mds_netem/
    # client_delay/fill/drain/tier_*: stateless (or closed-loop in the
    # runner); schema + scenario_verbs gating is the whole contract
    return None


def validate_trace(events: list[ChaosEvent],
                   scenario: dict) -> list[str]:
    """Refuse a trace the runner could not replay: schema violations,
    out-of-vocabulary verbs, unsorted times, legality/budget breaks,
    or a trace that does not end whole.  Returns violation strings
    (empty = valid).  Every generated trace validates; every repaired
    mutant must too."""
    out: list[str] = []
    vocab = set(scenario_verbs(scenario))
    duration = float(scenario.get("duration", 5.0))
    st = _ReplayState(scenario)
    for i, e in enumerate(events):
        err = _check_args(e)
        if err is not None:
            out.append(f"event[{i}]: {err}")
            continue
        if e.kind not in vocab:
            out.append(f"event[{i}]: {e.kind} not applicable to "
                       f"scenario {scenario.get('name')!r}")
            continue
        if e.t < 0 or e.t > duration + 1.0:
            out.append(f"event[{i}]: t={e.t} outside "
                       f"[0, {duration + 1.0}]")
        # NOTE: list order IS replay order (the runner fires each
        # event after max(0, t - now)) — an out-of-order t is legal
        # and some legacy scenarios' committed traces rely on it, so
        # the legality simulation walks the list, not sorted times
        err = _step(st, e, scenario)
        if err is not None:
            out.append(f"event[{i}]: {err}")
    if not st.whole():
        out.append(
            "trace does not end whole: "
            f"dead={sorted(set(range(st.n_osds)) - st.alive)} "
            f"out={sorted(set(range(st.n_osds)) - st.in_set)} "
            f"dead_mgrs={sorted(set(range(st.n_mgrs)) - st.mgr_alive)} "
            f"cuts={len(st.partitions) + len(st.oneways)} "
            f"client_cuts={len(st.client_cuts) + len(st.client_drops)} "
            f"faulted={sorted(st.faulted)}")
    return out


def repair_trace(events: list[ChaosEvent],
                 scenario: dict) -> list[ChaosEvent]:
    """Deterministically normalize an arbitrary event-list edit into a
    legal trace: clamp times into the scenario window, stable-sort,
    drop events that are out of schema/vocabulary or that the legality
    simulation refuses, then append the canonical trace-end wholeness
    block (heal every cut, clear every fault, revive every body).  The
    output always passes :func:`validate_trace` — mutants never crash
    the runner on malformed input."""
    duration = float(scenario.get("duration", 5.0))
    vocab = set(scenario_verbs(scenario))
    clamped = [
        ChaosEvent(t=round(min(max(float(e.t), 0.05), duration), 3),
                   kind=e.kind, args=dict(e.args))
        for e in events
        if isinstance(e.t, _NUM)
    ]
    clamped.sort(key=lambda e: e.t)  # stable: equal-t order preserved
    st = _ReplayState(scenario)
    kept: list[ChaosEvent] = []
    for e in clamped:
        if _check_args(e) is not None or e.kind not in vocab:
            continue
        if _step(st, e, scenario) is None:
            kept.append(e)
    t_end = round(duration + 0.05, 3)
    for cut in st.partitions:
        kept.append(ChaosEvent(t_end, "heal_partition",
                               {"a": list(cut[0]), "b": list(cut[1])}))
    for link in st.oneways:
        kept.append(ChaosEvent(
            t_end, "heal_oneway",
            {"src": list(link[0]), "dst": list(link[1])}))
    for peer in st.client_cuts:
        kept.append(ChaosEvent(t_end, "heal_client_partition",
                               {"peer": list(peer)}))
    for peer, to_client in st.client_drops:
        kept.append(ChaosEvent(
            t_end, "heal_client_drop",
            {"peer": list(peer), "to_client": to_client}))
    kept.append(ChaosEvent(t_end, "netem_clear", {}))
    for osd in sorted(st.faulted):
        kept.append(ChaosEvent(t_end, "disk_heal", {"osd": osd}))
    for osd in sorted(set(range(st.n_osds)) - st.alive):
        kept.append(ChaosEvent(t_end, "osd_revive", {"osd": osd}))
    for osd in sorted(set(range(st.n_osds)) - st.in_set):
        kept.append(ChaosEvent(t_end, "osd_in", {"osd": osd}))
    for mgr in sorted(set(range(st.n_mgrs)) - st.mgr_alive):
        kept.append(ChaosEvent(t_end, "mgr_revive", {"mgr": mgr}))
    return kept
