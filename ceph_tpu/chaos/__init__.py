"""Chaos engine — the OSDThrasher / ``ceph_test_rados`` twin.

The reference ships an entire thrashing and model-checking apparatus
(qa/tasks/thrasher.py OSDThrasher: kill/revive/out/in/reweight/split
under load; src/test/osd/TestRados.cc recording an operation history
and checking every read against it).  This package is that layer for
the mini-cluster:

- :mod:`schedule` — a seeded, deterministic event-schedule generator:
  the same ``(seed, scenario)`` always yields the same event trace,
  hashable for replay assertions;
- :mod:`netem` — a messenger-level network shim with deterministic
  per-peer partitions, one-way drops, fixed delays and bounded
  reordering (the deterministic complement of the probabilistic
  ``ms_inject_socket_failures``/``ms_inject_delay`` knobs);
- :mod:`workload` — a concurrent replicated+EC read/write/snap
  workload that records an operation history;
- :mod:`invariants` — durability checkers run during and after each
  run: no acked write lost or corrupted, convergence to active+clean,
  one agreed mon quorum, zero post-thrash deep-scrub inconsistencies,
  and zero cold XLA launches on the decode/scrub batchers;
- :mod:`runner` — drives scenario configs over seed sweeps against a
  live mini-cluster (the ``tools/chaos_run.py`` CLI's engine).

Chaos events flow into ``common/tracing`` spans (tracer ``"chaos"``)
and a ``BucketCounters("chaos")`` perf collection, dumped via the
daemons' ``dump_chaos`` admin-socket command.
"""

from __future__ import annotations

from ceph_tpu.chaos.schedule import (  # noqa: F401
    ChaosEvent,
    EVENT_KINDS,
    generate_schedule,
    trace_hash,
)


def chaos_counters():
    """The process-wide chaos perf collection (BucketCounters role):
    every applied event, netem verdict and invariant outcome counts
    here, labelled by kind."""
    from ceph_tpu.common.metrics import BucketCounters

    return BucketCounters("chaos")


def chaos_tracer():
    """The process-wide chaos span ring (blkin/otel role for thrash
    events): each applied event opens a span tagged with its kind,
    target and virtual time."""
    from ceph_tpu.common.tracing import get_tracer

    return get_tracer("chaos")


def dump_chaos() -> dict:
    """The ``dump_chaos`` admin-socket payload: chaos perf counters +
    the most recent event spans (registered on every daemon; the
    collection is process-global, like the batchers')."""
    return {
        "counters": chaos_counters().dump(),
        "recent_events": chaos_tracer().dump(limit=100),
    }
