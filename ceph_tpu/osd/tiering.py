"""Cache tiering: HitSet recency + TierAgent flush/evict (reference
src/osd/PrimaryLogPG.cc TierAgent machinery, src/osd/HitSet.h),
split out of the daemon per the PGBackend seam layout."""

from __future__ import annotations

import asyncio
import errno
import logging
import time


from ceph_tpu.osd.pglog import (
    PGMETA_OID,
)
from ceph_tpu.osd.types import pg_t
from ceph_tpu.store import coll_t, ghobject_t

from ceph_tpu.msg.messages import (
    OP_DELETE,
    OP_READ,
    OP_SETXATTR,
    OP_WRITE_FULL,
    MOSDOp,
    MOSDOpReply,
)
from ceph_tpu.osd.pgutil import (
    NO_SHARD,
    object_to_pg,
)

log = logging.getLogger("ceph_tpu.osd")


class TieringMixin:
    """Cache-tier admission, promotion, flush and eviction — mixed
    into OSDDaemon; state lives in the daemon's __init__."""

    # -- cache tiering (PrimaryLogPG HitSet/TierAgent, src/osd/HitSet.h)

    def _hitset(self, pool_id: int) -> "OrderedDict":
        from collections import OrderedDict as _OD

        hs = getattr(self, "_hitsets", None)
        if hs is None:
            hs = self._hitsets = {}
        if pool_id not in hs:
            hs[pool_id] = _OD()
        return hs[pool_id]

    def _hitset_touch(self, pool_id: int, oid: str) -> None:
        """Approximate recency (the reference's HitSet stack reduced to
        one explicit-object window, src/osd/HitSet.h ExplicitHashHitSet):
        most-recent at the end, bounded."""
        hs = self._hitset(pool_id)
        hs[oid] = time.monotonic()
        hs.move_to_end(oid)
        while len(hs) > 4096:
            hs.popitem(last=False)

    async def _pool_op(self, pool_id: int, oid: str, ops: list) -> "MOSDOpReply":
        """The daemon as a CLIENT of another pool (the tiering
        flush/promote I/O, PrimaryLogPG::start_copy using the
        objecter).  Minimal resend-on-EAGAIN."""
        import errno as _errno

        for _try in range(8):
            om = self.osdmap
            pool = om.get_pg_pool(pool_id)
            if pool is None:
                return MOSDOpReply(result=-_errno.ENOENT, epoch=self.epoch)
            pg = object_to_pg(pool, oid)
            _, primary = self._acting(pool, pg)
            addr = om.osd_addrs.get(primary)
            if primary < 0 or addr is None:
                await asyncio.sleep(0.2)
                continue
            tid = next(self._tids)
            m = MOSDOp(pool=pool_id, oid=oid, ops=list(ops), tid=tid,
                       epoch=om.epoch)
            if m.is_write():
                m.reqid = f"osd.{self.id}:{tid}"
            fut: asyncio.Future = asyncio.get_running_loop().create_future()
            self._waiters[tid] = fut
            try:
                conn = await self.messenger.connect_to(
                    ("osd", primary), *addr)
                await conn.send_message(m)
                reply = await asyncio.wait_for(fut, 30.0)
            except (ConnectionError, OSError, asyncio.TimeoutError):
                await asyncio.sleep(0.2)
                continue
            finally:
                self._waiters.pop(tid, None)
            if reply.result == -_errno.EAGAIN:
                await asyncio.sleep(0.1 * (_try + 1))
                continue
            return reply
        return MOSDOpReply(result=-_errno.ETIMEDOUT, epoch=self.epoch)

    async def _tier_internal_op(
        self, pool, oid: str, ops: list, *, have_lock: bool = False,
    ) -> int:
        """Run a replicated write vector on OUR pool as an internal op
        (agent flush/evict, promote): full primary pipeline, replicas
        included, marked so the tier hook doesn't recurse.
        ``have_lock``: the caller already holds the object lock."""
        m = MOSDOp(pool=pool.id, oid=oid, ops=list(ops),
                   tid=next(self._tids), epoch=self.epoch)
        m._tier_internal = True
        m._have_obj_lock = have_lock
        m.reqid = f"osd.{self.id}:{m.tid}"
        reply = await self._execute_op(m)
        return reply.result

    async def _tier_prepare(self, pool, pg, msg) -> "MOSDOpReply | None":
        """The cache-pool op admission (PrimaryLogPG::maybe_handle_cache
        + do_cache_redirect/promote_object, writeback mode):

        - CACHE_FLUSH / CACHE_EVICT / COPY_FROM vectors are handled
          here entirely;
        - an op whose object misses the cache promotes it from the
          base pool first (whole-object, data only — documented lite
          scope vs the reference's omap/xattr copy);
        - deletes propagate to the base synchronously (the reference
          whiteouts + flushes; same visible result);
        - writes mark the object dirty (xattr), reads/writes record
          hits.  Returns a reply to short-circuit, or None to continue
          with the (possibly rewritten) vector."""
        import errno as _errno

        from ceph_tpu.msg.messages import (
            OP_CACHE_EVICT,
            OP_CACHE_FLUSH,
            OP_COPY_FROM,
            OSDOp,
        )

        base_pid = int(pool.extra["tier_of"])
        c = self._shard_coll(pool, pg, NO_SHARD)
        o = ghobject_t(msg.oid)
        present = self.store.exists(c, o) and not self._is_whiteout(c, o)

        kinds = {op.op for op in msg.ops}
        if OP_CACHE_FLUSH in kinds:
            if not present:
                return MOSDOpReply(tid=msg.tid, result=-_errno.ENOENT,
                                   epoch=self.epoch)
            rc = await self._tier_flush(pool, base_pid, c, o, msg.oid,
                                        have_lock=True)
            return MOSDOpReply(tid=msg.tid, result=rc, epoch=self.epoch)
        if OP_CACHE_EVICT in kinds:
            if not present:
                return MOSDOpReply(tid=msg.tid, result=-_errno.ENOENT,
                                   epoch=self.epoch)
            if self._tier_dirty(c, o):
                return MOSDOpReply(tid=msg.tid, result=-_errno.EBUSY,
                                   epoch=self.epoch)
            rc = await self._tier_internal_op(
                pool, msg.oid, [OSDOp(OP_DELETE)], have_lock=True)
            self._hitset(pool.id).pop(msg.oid, None)
            self.perf.inc("tier_evict")
            return MOSDOpReply(tid=msg.tid, result=rc, epoch=self.epoch)
        if OP_COPY_FROM in kinds:
            op = next(op for op in msg.ops if op.op == OP_COPY_FROM)
            spool, _, soid = (op.name or "").partition(":")
            reply = await self._pool_op(
                int(spool), soid, [OSDOp(OP_READ)])
            if reply.result != 0:
                return MOSDOpReply(tid=msg.tid, result=reply.result,
                                   epoch=self.epoch)
            # the copy is DIRTY (writeback: it exists only here until
            # flushed — an unflushed-evictable copy would be lost)
            msg.ops = [
                OSDOp(OP_WRITE_FULL, data=reply.data),
                OSDOp(OP_SETXATTR, name="cache.dirty", data=b"1"),
            ]
            return None  # continue as a normal replicated write

        self._hitset_touch(pool.id, msg.oid)
        if present:
            self.perf.inc("tier_hit")
        else:
            self.perf.inc("tier_miss")
            # promote-on-miss (reads AND writes: writeback promotes
            # before mutating, PrimaryLogPG::promote_object)
            reply = await self._pool_op(base_pid, msg.oid, [OSDOp(OP_READ)])
            if reply.result == 0:
                rc = await self._tier_internal_op(pool, msg.oid, [
                    OSDOp(OP_WRITE_FULL, data=reply.data),
                ], have_lock=True)
                if rc != 0:
                    return MOSDOpReply(tid=msg.tid, result=rc,
                                       epoch=self.epoch)
                self.perf.inc("tier_promote")
            elif reply.result != -_errno.ENOENT:
                return MOSDOpReply(tid=msg.tid, result=reply.result,
                                   epoch=self.epoch)

        if msg.is_write():
            if any(op.op == OP_DELETE for op in msg.ops):
                # propagate the delete to the base FIRST (lite
                # stand-in for whiteout + flush): if the base refuses,
                # the op fails — a cache-only delete would resurrect
                # on the next promote
                reply = await self._pool_op(
                    base_pid, msg.oid, [OSDOp(OP_DELETE)])
                if reply.result not in (0, -_errno.ENOENT):
                    return MOSDOpReply(tid=msg.tid, result=reply.result,
                                       epoch=self.epoch)
            else:
                msg.ops = list(msg.ops) + [
                    OSDOp(OP_SETXATTR, name="cache.dirty", data=b"1")]
        return None

    def _tier_dirty(self, c: coll_t, o: ghobject_t) -> bool:
        try:
            return self.store.getattr(c, o, "u_cache.dirty") == b"1"
        except (KeyError, FileNotFoundError, OSError):
            return False

    async def _tier_flush(self, pool, base_pid: int, c, o, oid: str,
                          *, have_lock: bool = False) -> int:
        """Write a dirty cache object back to the base pool, then mark
        it clean (CEPH_OSD_OP_CACHE_FLUSH, PrimaryLogPG::start_flush)."""
        from ceph_tpu.msg.messages import OP_RMXATTR, OSDOp

        try:
            data = self.store.read(c, o)
        except (FileNotFoundError, OSError):
            return -errno.ENOENT
        if self._tier_dirty(c, o):
            reply = await self._pool_op(
                base_pid, oid, [OSDOp(OP_WRITE_FULL, data=bytes(data))])
            if reply.result != 0:
                return reply.result
            rc = await self._tier_internal_op(
                pool, oid, [OSDOp(OP_RMXATTR, name="cache.dirty")],
                have_lock=have_lock)
            if rc != 0:
                return rc
        self.perf.inc("tier_flush")
        return 0

    async def _tier_agent(self) -> None:
        """The TierAgent loop (PrimaryLogPG::agent_work): under
        target_max_bytes pressure, flush dirty objects then evict cold
        clean ones, per cache pool, for the PGs this OSD leads."""
        interval = self.conf["osd_tier_agent_interval"]
        while not self.stopping:
            await asyncio.sleep(interval)
            om = self.osdmap
            if om is None:
                continue
            for pool in list(om.pools.values()):
                try:
                    target = int(pool.extra.get("target_max_bytes", "0"))
                except (TypeError, ValueError):
                    continue
                if (
                    not target
                    or not pool.extra.get("tier_of")
                    or pool.extra.get("cache_mode") != "writeback"
                ):
                    continue
                try:
                    await self._tier_agent_pool(pool, target)
                except Exception:
                    log.exception("osd.%d: tier agent failed", self.id)

    async def _tier_agent_pool(self, pool, target: int) -> None:
        from ceph_tpu.msg.messages import OSDOp

        base_pid = int(pool.extra["tier_of"])
        mine: list[tuple[str, int, coll_t, ghobject_t]] = []
        total = 0
        for ps in range(pool.pg_num):
            pg = pg_t(pool.id, ps)
            _a, primary = self._acting(pool, pg)
            if primary != self.id:
                continue
            c = coll_t(pool.id, ps, NO_SHARD)
            if not self.store.collection_exists(c):
                continue
            for o in self.store.collection_list(c):
                if o.name == PGMETA_OID or o.snap >= 0:
                    continue
                if self._is_whiteout(c, o):
                    continue
                try:
                    size = self.store.stat(c, o)
                except (FileNotFoundError, OSError):
                    continue
                mine.append((o.name, size, c, o))
                total += size
        if total <= target:
            return
        # coldest first: hitset order is recency (absent = coldest)
        hs = self._hitset(pool.id)
        rank = {oid: i for i, oid in enumerate(hs)}
        mine.sort(key=lambda t: rank.get(t[0], -1))
        for oid, size, c, o in mine:
            if total <= target * 0.8:
                break
            # flush-then-evict is ATOMIC vs client ops on this object:
            # the object lock spans both, so a write can't land between
            # the flush and the delete and be silently dropped
            async with self._obj_lock(pool.id, oid):
                if self._tier_dirty(c, o):
                    if await self._tier_flush(pool, base_pid, c, o, oid,
                                              have_lock=True) != 0:
                        continue
                if await self._tier_internal_op(
                        pool, oid, [OSDOp(OP_DELETE)],
                        have_lock=True) == 0:
                    self.perf.inc("tier_evict")
                    hs.pop(oid, None)
                    total -= size
