"""OSDMap / CrushMap wire encoding + incremental deltas.

The reference versions every map struct (OSDMap::encode
src/osd/OSDMap.cc, CrushWrapper::encode src/crush/CrushWrapper.cc) so
maps can ship between daemons and persist in the mon store.  Same
contract here over the denc module: ``encode_osdmap``/``decode_osdmap``
round-trip the full cluster map — crush buckets/rules/tunables/
choose_args, pools, osd states/weights/affinity/addresses, upmap and
temp exception tables, EC profiles.

Epoch churn ships as :class:`Incremental` deltas (the reference's
``OSDMap::Incremental``, src/osd/OSDMap.h; applied by
``OSDMap::apply_incremental``, src/osd/OSDMap.cc): the monitor diffs
consecutive epochs (:func:`diff_osdmap`) and publishes the delta;
subscribers land bit-identical to the full map
(:func:`apply_incremental`, pinned by tests/test_osdmap_incremental.py).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ceph_tpu.crush.types import (
    Bucket,
    BucketAlg,
    ChooseArg,
    CrushMap,
    Rule,
    RuleOp,
    RuleStep,
    Tunables,
)
from ceph_tpu.msg.denc import Decoder, Encoder
from ceph_tpu.osd.osdmap import OSDMap
from ceph_tpu.osd.types import PgPool, pg_t


# -- choose_args (shared by crush + osdmap sections) ------------------------

def _enc_choose_args(enc: Encoder, table: dict[int, ChooseArg]) -> None:
    enc.u32(len(table))
    for bid in sorted(table):
        arg = table[bid]
        enc.i32(bid)
        ws = arg.weight_set or []
        enc.u32(len(ws))
        for pos in ws:
            enc.u32(len(pos))
            for w in pos:
                enc.u64(w)
        ids = arg.ids
        enc.bool_(ids is not None)
        if ids is not None:
            enc.u32(len(ids))
            for i in ids:
                enc.i32(i)


def _dec_choose_args(dec: Decoder) -> dict[int, ChooseArg]:
    out: dict[int, ChooseArg] = {}
    for _ in range(dec.u32()):
        bid = dec.i32()
        nws = dec.u32()
        ws = [[dec.u64() for _ in range(dec.u32())] for _ in range(nws)]
        ids = None
        if dec.bool_():
            ids = [dec.i32() for _ in range(dec.u32())]
        out[bid] = ChooseArg(bid, weight_set=ws or None, ids=ids)
    return out


# -- crush ------------------------------------------------------------------

def encode_crush(enc: Encoder, m: CrushMap) -> None:
    # v2 appends the MSR tunables (crush.h msr_descents/collision_tries)
    with enc.versioned(2, 1):
        enc.u32(m.max_devices)
        enc.u32(len(m.buckets))
        for bid in sorted(m.buckets):
            b = m.buckets[bid]
            enc.i32(b.id)
            enc.i32(b.type)
            enc.u8(int(b.alg))
            enc.u8(b.hash)
            enc.u32(b.size)
            for it in b.items:
                enc.i32(it)
            for w in b.item_weights:
                enc.u32(w)
            for name, arr in (
                ("sum", b.sum_weights),
                ("node", b.node_weights),
                ("straw", b.straws),
            ):
                enc.u32(len(arr))
                for v in arr:
                    enc.u64(v)
        enc.u32(len(m.rules))
        for rid in sorted(m.rules):
            r = m.rules[rid]
            enc.u32(rid)
            enc.u32(r.rule_type)
            enc.bool_(r.device_class is not None)
            if r.device_class is not None:
                enc.str_(r.device_class)
            enc.u32(len(r.steps))
            for s in r.steps:
                enc.u32(int(s.op))
                enc.i32(s.arg1)
                enc.i32(s.arg2)
        enc.u32(len(m.types))
        for tid in sorted(m.types):
            enc.i32(tid)
            enc.str_(m.types[tid])
        t = m.tunables
        for v in (
            t.choose_local_tries, t.choose_local_fallback_tries,
            t.choose_total_tries, t.chooseleaf_descend_once,
            t.chooseleaf_vary_r, t.chooseleaf_stable,
            t.msr_descents, t.msr_collision_tries,
        ):
            enc.u32(v)
        _enc_choose_args(enc, m.choose_args)
        enc.u32(len(m.bucket_names))
        for name in sorted(m.bucket_names):
            enc.str_(name)
            enc.i32(m.bucket_names[name])
        enc.u32(len(m.rule_names))
        for name in sorted(m.rule_names):
            enc.str_(name)
            enc.i32(m.rule_names[name])
        enc.u32(len(m.device_classes))
        for osd in sorted(m.device_classes):
            enc.i32(osd)
            enc.str_(m.device_classes[osd])


def decode_crush(dec: Decoder) -> CrushMap:
    m = CrushMap(types={})
    with dec.versioned() as _crush_v:
        m.max_devices = dec.u32()
        for _ in range(dec.u32()):
            bid = dec.i32()
            btype = dec.i32()
            alg = BucketAlg(dec.u8())
            hash_ = dec.u8()
            size = dec.u32()
            items = [dec.i32() for _ in range(size)]
            weights = [dec.u32() for _ in range(size)]
            b = Bucket(
                id=bid, type=btype, alg=alg, hash=hash_,
                items=items, item_weights=weights,
            )
            b.sum_weights = [dec.u64() for _ in range(dec.u32())]
            b.node_weights = [dec.u64() for _ in range(dec.u32())]
            b.straws = [dec.u64() for _ in range(dec.u32())]
            m.buckets[bid] = b
        for _ in range(dec.u32()):
            rid = dec.u32()
            rtype = dec.u32()
            device_class = dec.str_() if dec.bool_() else None
            steps = [
                RuleStep(RuleOp(dec.u32()), dec.i32(), dec.i32())
                for _ in range(dec.u32())
            ]
            m.rules[rid] = Rule(
                rule_type=rtype, steps=steps, device_class=device_class
            )
        for _ in range(dec.u32()):
            tid = dec.i32()
            m.types[tid] = dec.str_()
        m.tunables = Tunables(
            choose_local_tries=dec.u32(),
            choose_local_fallback_tries=dec.u32(),
            choose_total_tries=dec.u32(),
            chooseleaf_descend_once=dec.u32(),
            chooseleaf_vary_r=dec.u32(),
            chooseleaf_stable=dec.u32(),
        )
        if _crush_v >= 2:
            m.tunables.msr_descents = dec.u32()
            m.tunables.msr_collision_tries = dec.u32()
        m.choose_args = _dec_choose_args(dec)
        for _ in range(dec.u32()):
            name = dec.str_()
            m.bucket_names[name] = dec.i32()
        for _ in range(dec.u32()):
            name = dec.str_()
            m.rule_names[name] = dec.i32()
        for _ in range(dec.u32()):
            osd = dec.i32()
            m.device_classes[osd] = dec.str_()
    return m


# -- pools ------------------------------------------------------------------

def _encode_pool(enc: Encoder, p: PgPool) -> None:
    # v2 appends snapshot state (pg_pool_t snap fields)
    with enc.versioned(2, 1):
        enc.i64(p.id)
        enc.u8(p.type)
        enc.u32(p.size)
        enc.u32(p.min_size)
        enc.i32(p.crush_rule)
        enc.u32(p.pg_num)
        enc.u32(p.pgp_num)
        enc.u32(p.flags)
        enc.str_(p.erasure_code_profile)
        enc.u32(len(p.extra))
        for k in sorted(p.extra):
            v = p.extra[k]
            if not isinstance(v, str):
                from ceph_tpu.msg.denc import EncodingError

                raise EncodingError(
                    f"pool {p.id} extra[{k!r}] must be str, got {type(v).__name__}"
                )
            enc.str_(k)
            enc.str_(v)
        enc.u64(p.snap_seq)
        enc.u32(len(p.removed_snaps))
        for s in sorted(p.removed_snaps):
            enc.u64(s)
        enc.u32(len(p.pool_snaps))
        for name in sorted(p.pool_snaps):
            enc.str_(name)
            enc.u64(p.pool_snaps[name])


def _decode_pool(dec: Decoder) -> PgPool:
    with dec.versioned() as v:
        p = PgPool(
            id=dec.i64(), type=dec.u8(), size=dec.u32(), min_size=dec.u32(),
            crush_rule=dec.i32(), pg_num=dec.u32(), pgp_num=dec.u32(),
            flags=dec.u32(), erasure_code_profile=dec.str_(),
        )
        for _ in range(dec.u32()):
            k = dec.str_()
            p.extra[k] = dec.str_()
        if v >= 2:
            p.snap_seq = dec.u64()
            p.removed_snaps = {dec.u64() for _ in range(dec.u32())}
            p.pool_snaps = {dec.str_(): dec.u64() for _ in range(dec.u32())}
    return p


# -- osdmap -----------------------------------------------------------------

def _encode_pg_table(enc: Encoder, table: dict, value_enc) -> None:
    enc.u32(len(table))
    for pg in sorted(table, key=lambda g: (g.pool, g.ps)):
        enc.i64(pg.pool)
        enc.u32(pg.ps)
        value_enc(table[pg])


def _decode_pg_table(dec: Decoder, value_dec) -> dict:
    out = {}
    for _ in range(dec.u32()):
        pool = dec.i64()
        ps = dec.u32()
        out[pg_t(pool, ps)] = value_dec()
    return out


def encode_osdmap(m: OSDMap) -> bytes:
    enc = Encoder()
    with enc.versioned(1, 1):
        enc.u32(m.epoch)
        enc.u32(m.max_osd)
        for s in m.osd_state:
            enc.u8(s)
        for w in m.osd_weight:
            enc.u32(w)
        enc.bool_(m.osd_primary_affinity is not None)
        if m.osd_primary_affinity is not None:
            for a in m.osd_primary_affinity:
                enc.u32(a)
        enc.u32(len(m.pools))
        for pid in sorted(m.pools):
            _encode_pool(enc, m.pools[pid])
        _encode_pg_table(
            enc, m.pg_upmap,
            lambda v: (enc.u32(len(v)), [enc.i32(o) for o in v]),
        )
        _encode_pg_table(
            enc, m.pg_upmap_items,
            lambda v: (
                enc.u32(len(v)),
                [(enc.i32(a), enc.i32(b)) for a, b in v],
            ),
        )
        _encode_pg_table(enc, m.pg_upmap_primaries, lambda v: enc.i32(v))
        _encode_pg_table(
            enc, m.pg_temp,
            lambda v: (enc.u32(len(v)), [enc.i32(o) for o in v]),
        )
        _encode_pg_table(enc, m.primary_temp, lambda v: enc.i32(v))
        enc.u32(len(m.erasure_code_profiles))
        for name in sorted(m.erasure_code_profiles):
            enc.str_(name)
            prof = m.erasure_code_profiles[name]
            enc.u32(len(prof))
            for k in sorted(prof):
                enc.str_(k)
                enc.str_(prof[k])
        enc.u32(len(m.osd_addrs))
        for osd in sorted(m.osd_addrs):
            host, port = m.osd_addrs[osd]
            enc.i32(osd)
            enc.str_(host)
            enc.u32(port)
        enc.u32(len(m.pool_names))
        for pid in sorted(m.pool_names):
            enc.i64(pid)
            enc.str_(m.pool_names[pid])
        # the mapping pipeline consumes OSDMap.choose_args (balancer
        # overrides), which is distinct from the crush map's own table
        enc.bool_(m.choose_args is not None)
        if m.choose_args is not None:
            _enc_choose_args(enc, m.choose_args)
        encode_crush(enc, m.crush)
    return enc.bytes()


def decode_osdmap(data: bytes) -> OSDMap:
    dec = Decoder(data)
    with dec.versioned():
        epoch = dec.u32()
        max_osd = dec.u32()
        osd_state = [dec.u8() for _ in range(max_osd)]
        osd_weight = [dec.u32() for _ in range(max_osd)]
        affinity = None
        if dec.bool_():
            affinity = [dec.u32() for _ in range(max_osd)]
        pools = {}
        for _ in range(dec.u32()):
            p = _decode_pool(dec)
            pools[p.id] = p
        pg_upmap = _decode_pg_table(
            dec, lambda: [dec.i32() for _ in range(dec.u32())]
        )
        pg_upmap_items = _decode_pg_table(
            dec,
            lambda: [(dec.i32(), dec.i32()) for _ in range(dec.u32())],
        )
        pg_upmap_primaries = _decode_pg_table(dec, dec.i32)
        pg_temp = _decode_pg_table(
            dec, lambda: [dec.i32() for _ in range(dec.u32())]
        )
        primary_temp = _decode_pg_table(dec, dec.i32)
        profiles = {}
        for _ in range(dec.u32()):
            name = dec.str_()
            profiles[name] = {
                dec.str_(): dec.str_() for _ in range(dec.u32())
            }
        addrs = {}
        for _ in range(dec.u32()):
            osd = dec.i32()
            host = dec.str_()
            addrs[osd] = (host, dec.u32())
        pool_names = {}
        for _ in range(dec.u32()):
            pid = dec.i64()
            pool_names[pid] = dec.str_()
        choose_args = _dec_choose_args(dec) if dec.bool_() else None
        crush = decode_crush(dec)
    om = OSDMap(
        crush=crush, epoch=epoch, max_osd=max_osd,
        osd_state=osd_state, osd_weight=osd_weight,
        osd_primary_affinity=affinity, pools=pools,
        pg_upmap=pg_upmap, pg_upmap_items=pg_upmap_items,
        pg_upmap_primaries=pg_upmap_primaries,
        pg_temp=pg_temp, primary_temp=primary_temp,
        erasure_code_profiles=profiles, osd_addrs=addrs,
        pool_names=pool_names, choose_args=choose_args,
    )
    return om


# -- incrementals -----------------------------------------------------------

@dataclass
class Incremental:
    """Delta from epoch-1 to ``epoch`` (reference OSDMap::Incremental).

    Values are absolute (new state byte, new weight, full new pool
    struct, ...) rather than xor-deltas; removals are explicit lists.
    ``new_crush`` ships the whole crush encode when any crush field
    changed — crush churn is rare and the blob is small, mirroring the
    reference's choice to embed a full crush bufferlist.
    """

    epoch: int = 0
    new_max_osd: int | None = None
    new_state: dict[int, int] = field(default_factory=dict)
    new_weight: dict[int, int] = field(default_factory=dict)
    new_primary_affinity: dict[int, int] = field(default_factory=dict)
    affinity_present: bool | None = None  # None->list / list->None flips
    new_addrs: dict[int, tuple[str, int]] = field(default_factory=dict)
    removed_addrs: list[int] = field(default_factory=list)
    new_pools: dict[int, PgPool] = field(default_factory=dict)
    removed_pools: list[int] = field(default_factory=list)
    new_pool_names: dict[int, str] = field(default_factory=dict)
    removed_pool_names: list[int] = field(default_factory=list)
    new_profiles: dict[str, dict[str, str]] = field(default_factory=dict)
    removed_profiles: list[str] = field(default_factory=list)
    new_pg_upmap: dict[pg_t, list[int]] = field(default_factory=dict)
    removed_pg_upmap: list[pg_t] = field(default_factory=list)
    new_pg_upmap_items: dict[pg_t, list[tuple[int, int]]] = field(default_factory=dict)
    removed_pg_upmap_items: list[pg_t] = field(default_factory=list)
    new_pg_upmap_primaries: dict[pg_t, int] = field(default_factory=dict)
    removed_pg_upmap_primaries: list[pg_t] = field(default_factory=list)
    new_pg_temp: dict[pg_t, list[int]] = field(default_factory=dict)
    removed_pg_temp: list[pg_t] = field(default_factory=list)
    new_primary_temp: dict[pg_t, int] = field(default_factory=dict)
    removed_primary_temp: list[pg_t] = field(default_factory=list)
    new_choose_args: bytes | None = None  # encoded table (or b"" = clear)
    new_crush: bytes | None = None        # full crush encode


def _enc_pg_list(enc: Encoder, pgs: list[pg_t]) -> None:
    enc.u32(len(pgs))
    for pg in sorted(pgs, key=lambda g: (g.pool, g.ps)):
        enc.i64(pg.pool)
        enc.u32(pg.ps)


def _dec_pg_list(dec: Decoder) -> list[pg_t]:
    return [pg_t(dec.i64(), dec.u32()) for _ in range(dec.u32())]


def encode_incremental(inc: Incremental) -> bytes:
    enc = Encoder()
    with enc.versioned(1, 1):
        enc.u32(inc.epoch)
        enc.bool_(inc.new_max_osd is not None)
        if inc.new_max_osd is not None:
            enc.u32(inc.new_max_osd)
        for table in (inc.new_state, inc.new_weight, inc.new_primary_affinity):
            enc.u32(len(table))
            for osd in sorted(table):
                enc.i32(osd)
                enc.u32(table[osd])
        enc.u8({None: 0, False: 1, True: 2}[inc.affinity_present])
        enc.u32(len(inc.new_addrs))
        for osd in sorted(inc.new_addrs):
            host, port = inc.new_addrs[osd]
            enc.i32(osd)
            enc.str_(host)
            enc.u32(port)
        enc.u32(len(inc.removed_addrs))
        for osd in sorted(inc.removed_addrs):
            enc.i32(osd)
        enc.u32(len(inc.new_pools))
        for pid in sorted(inc.new_pools):
            _encode_pool(enc, inc.new_pools[pid])
        enc.u32(len(inc.removed_pools))
        for pid in sorted(inc.removed_pools):
            enc.i64(pid)
        enc.u32(len(inc.new_pool_names))
        for pid in sorted(inc.new_pool_names):
            enc.i64(pid)
            enc.str_(inc.new_pool_names[pid])
        enc.u32(len(inc.removed_pool_names))
        for pid in sorted(inc.removed_pool_names):
            enc.i64(pid)
        enc.u32(len(inc.new_profiles))
        for name in sorted(inc.new_profiles):
            enc.str_(name)
            prof = inc.new_profiles[name]
            enc.u32(len(prof))
            for k in sorted(prof):
                enc.str_(k)
                enc.str_(prof[k])
        enc.u32(len(inc.removed_profiles))
        for name in sorted(inc.removed_profiles):
            enc.str_(name)
        _encode_pg_table(
            enc, inc.new_pg_upmap,
            lambda v: (enc.u32(len(v)), [enc.i32(o) for o in v]),
        )
        _enc_pg_list(enc, inc.removed_pg_upmap)
        _encode_pg_table(
            enc, inc.new_pg_upmap_items,
            lambda v: (enc.u32(len(v)), [(enc.i32(a), enc.i32(b)) for a, b in v]),
        )
        _enc_pg_list(enc, inc.removed_pg_upmap_items)
        _encode_pg_table(enc, inc.new_pg_upmap_primaries, lambda v: enc.i32(v))
        _enc_pg_list(enc, inc.removed_pg_upmap_primaries)
        _encode_pg_table(
            enc, inc.new_pg_temp,
            lambda v: (enc.u32(len(v)), [enc.i32(o) for o in v]),
        )
        _enc_pg_list(enc, inc.removed_pg_temp)
        _encode_pg_table(enc, inc.new_primary_temp, lambda v: enc.i32(v))
        _enc_pg_list(enc, inc.removed_primary_temp)
        enc.bool_(inc.new_choose_args is not None)
        if inc.new_choose_args is not None:
            enc.bytes_(inc.new_choose_args)
        enc.bool_(inc.new_crush is not None)
        if inc.new_crush is not None:
            enc.bytes_(inc.new_crush)
    return enc.bytes()


def decode_incremental(data: bytes) -> Incremental:
    dec = Decoder(data)
    inc = Incremental()
    with dec.versioned():
        inc.epoch = dec.u32()
        if dec.bool_():
            inc.new_max_osd = dec.u32()
        for table in (inc.new_state, inc.new_weight, inc.new_primary_affinity):
            for _ in range(dec.u32()):
                osd = dec.i32()
                table[osd] = dec.u32()
        inc.affinity_present = {0: None, 1: False, 2: True}[dec.u8()]
        for _ in range(dec.u32()):
            osd = dec.i32()
            host = dec.str_()
            inc.new_addrs[osd] = (host, dec.u32())
        inc.removed_addrs = [dec.i32() for _ in range(dec.u32())]
        for _ in range(dec.u32()):
            p = _decode_pool(dec)
            inc.new_pools[p.id] = p
        inc.removed_pools = [dec.i64() for _ in range(dec.u32())]
        for _ in range(dec.u32()):
            pid = dec.i64()
            inc.new_pool_names[pid] = dec.str_()
        inc.removed_pool_names = [dec.i64() for _ in range(dec.u32())]
        for _ in range(dec.u32()):
            name = dec.str_()
            inc.new_profiles[name] = {
                dec.str_(): dec.str_() for _ in range(dec.u32())
            }
        inc.removed_profiles = [dec.str_() for _ in range(dec.u32())]
        inc.new_pg_upmap = _decode_pg_table(
            dec, lambda: [dec.i32() for _ in range(dec.u32())]
        )
        inc.removed_pg_upmap = _dec_pg_list(dec)
        inc.new_pg_upmap_items = _decode_pg_table(
            dec, lambda: [(dec.i32(), dec.i32()) for _ in range(dec.u32())]
        )
        inc.removed_pg_upmap_items = _dec_pg_list(dec)
        inc.new_pg_upmap_primaries = _decode_pg_table(dec, dec.i32)
        inc.removed_pg_upmap_primaries = _dec_pg_list(dec)
        inc.new_pg_temp = _decode_pg_table(
            dec, lambda: [dec.i32() for _ in range(dec.u32())]
        )
        inc.removed_pg_temp = _dec_pg_list(dec)
        inc.new_primary_temp = _decode_pg_table(dec, dec.i32)
        inc.removed_primary_temp = _dec_pg_list(dec)
        if dec.bool_():
            inc.new_choose_args = dec.bytes_()
        if dec.bool_():
            inc.new_crush = dec.bytes_()
    return inc


def _diff_dict(old: dict, new: dict, added: dict, removed: list) -> None:
    for k, v in new.items():
        if k not in old or old[k] != v:
            added[k] = v
    removed.extend(k for k in old if k not in new)


def diff_osdmap(
    old: OSDMap,
    new: OSDMap,
    old_sections: tuple[bytes | None, bytes] | None = None,
    new_sections: tuple[bytes | None, bytes] | None = None,
) -> Incremental:
    """Delta such that apply_incremental(old, delta) == new, verified
    bit-identical through encode_osdmap.  ``*_sections`` are optional
    pre-computed :func:`crush_sections` results."""
    inc = Incremental(epoch=new.epoch)
    if new.max_osd != old.max_osd:
        inc.new_max_osd = new.max_osd
    for osd in range(new.max_osd):
        olds = old.osd_state[osd] if osd < old.max_osd else None
        if olds != new.osd_state[osd]:
            inc.new_state[osd] = new.osd_state[osd]
        oldw = old.osd_weight[osd] if osd < old.max_osd else None
        if oldw != new.osd_weight[osd]:
            inc.new_weight[osd] = new.osd_weight[osd]
    if (new.osd_primary_affinity is None) != (old.osd_primary_affinity is None):
        inc.affinity_present = new.osd_primary_affinity is not None
    if new.osd_primary_affinity is not None:
        oldaff = old.osd_primary_affinity or []
        for osd in range(new.max_osd):
            o = oldaff[osd] if osd < len(oldaff) else None
            if o != new.osd_primary_affinity[osd]:
                inc.new_primary_affinity[osd] = new.osd_primary_affinity[osd]
    _diff_dict(old.osd_addrs, new.osd_addrs, inc.new_addrs, inc.removed_addrs)
    _diff_dict(old.pools, new.pools, inc.new_pools, inc.removed_pools)
    _diff_dict(
        old.pool_names, new.pool_names,
        inc.new_pool_names, inc.removed_pool_names,
    )
    _diff_dict(
        old.erasure_code_profiles, new.erasure_code_profiles,
        inc.new_profiles, inc.removed_profiles,
    )
    _diff_dict(old.pg_upmap, new.pg_upmap, inc.new_pg_upmap, inc.removed_pg_upmap)
    _diff_dict(
        old.pg_upmap_items, new.pg_upmap_items,
        inc.new_pg_upmap_items, inc.removed_pg_upmap_items,
    )
    _diff_dict(
        old.pg_upmap_primaries, new.pg_upmap_primaries,
        inc.new_pg_upmap_primaries, inc.removed_pg_upmap_primaries,
    )
    _diff_dict(old.pg_temp, new.pg_temp, inc.new_pg_temp, inc.removed_pg_temp)
    _diff_dict(
        old.primary_temp, new.primary_temp,
        inc.new_primary_temp, inc.removed_primary_temp,
    )

    oca, ocr = old_sections if old_sections is not None else crush_sections(old)
    nca, ncr = new_sections if new_sections is not None else crush_sections(new)
    if oca != nca:
        inc.new_choose_args = nca if nca is not None else b""
    if ocr != ncr:
        inc.new_crush = ncr
    return inc


def crush_sections(m: OSDMap) -> tuple[bytes | None, bytes]:
    """(choose_args blob | None, crush blob) — the two expensive
    encodes of diff_osdmap, exposed so a publisher that diffs every
    epoch can cache them instead of re-encoding both sides each time."""
    ca = None
    if m.choose_args is not None:
        e = Encoder()
        _enc_choose_args(e, m.choose_args)
        ca = e.bytes()
    e = Encoder()
    encode_crush(e, m.crush)
    return ca, e.bytes()


def apply_incremental(m: OSDMap, inc: Incremental) -> None:
    """Mutate ``m`` (at epoch N-1) into epoch N.  Raises ValueError on
    an epoch gap — callers then fetch a full map (the reference OSD
    requests the missing range, OSDMap.cc apply_incremental asserts)."""
    if inc.epoch != m.epoch + 1:
        raise ValueError(f"incremental {inc.epoch} onto map {m.epoch}")
    if inc.new_max_osd is not None:
        m.set_max_osd(inc.new_max_osd)
    for osd, s in inc.new_state.items():
        m.osd_state[osd] = s
    for osd, w in inc.new_weight.items():
        m.osd_weight[osd] = w
    if inc.affinity_present is False:
        m.osd_primary_affinity = None
    elif inc.affinity_present is True and m.osd_primary_affinity is None:
        from ceph_tpu.osd.osdmap import CEPH_OSD_DEFAULT_PRIMARY_AFFINITY

        m.osd_primary_affinity = (
            [CEPH_OSD_DEFAULT_PRIMARY_AFFINITY] * m.max_osd
        )
    for osd, a in inc.new_primary_affinity.items():
        m.osd_primary_affinity[osd] = a
    m.osd_addrs.update(inc.new_addrs)
    for osd in inc.removed_addrs:
        m.osd_addrs.pop(osd, None)
    m.pools.update(inc.new_pools)
    for pid in inc.removed_pools:
        m.pools.pop(pid, None)
        m.pool_names.pop(pid, None)
    m.pool_names.update(inc.new_pool_names)
    for pid in inc.removed_pool_names:
        m.pool_names.pop(pid, None)
    m.erasure_code_profiles.update(inc.new_profiles)
    for name in inc.removed_profiles:
        m.erasure_code_profiles.pop(name, None)
    for table, new_t, rem in (
        (m.pg_upmap, inc.new_pg_upmap, inc.removed_pg_upmap),
        (m.pg_upmap_items, inc.new_pg_upmap_items, inc.removed_pg_upmap_items),
        (m.pg_upmap_primaries, inc.new_pg_upmap_primaries,
         inc.removed_pg_upmap_primaries),
        (m.pg_temp, inc.new_pg_temp, inc.removed_pg_temp),
        (m.primary_temp, inc.new_primary_temp, inc.removed_primary_temp),
    ):
        table.update(new_t)
        for pg in rem:
            table.pop(pg, None)
    if inc.new_choose_args is not None:
        if inc.new_choose_args == b"":
            m.choose_args = None
        else:
            m.choose_args = _dec_choose_args(Decoder(inc.new_choose_args))
    if inc.new_crush is not None:
        m.crush = decode_crush(Decoder(inc.new_crush))
    m.epoch = inc.epoch


def apply_map_message(osdmap: OSDMap | None, maps: dict[int, bytes],
                      incs: dict[int, bytes]) -> tuple[OSDMap | None, bool]:
    """Shared MOSDMap consumption for the OSD daemon and the client.

    Returns ``(new_map, gap)``.  ``new_map`` is always a NEW object
    when anything changed (copy-on-write swap): callers that captured
    ``self.osdmap`` mid-operation keep a stable snapshot, matching the
    replace-on-decode semantics full maps always had.  ``gap`` is True
    when an incremental didn't connect to our epoch — the caller should
    re-subscribe with its current epoch to get the missing range.
    """
    m = osdmap
    for epoch in sorted(maps):
        if m is None or epoch > m.epoch:
            m = decode_osdmap(maps[epoch])
    for epoch in sorted(incs):
        if m is None or epoch > m.epoch + 1:
            return m, True
        if epoch == m.epoch + 1:
            if m is osdmap:
                # copy before first mutation; later incs in this batch
                # mutate the same fresh copy
                m = decode_osdmap(encode_osdmap(m))
            apply_incremental(m, decode_incremental(incs[epoch]))
    return m, False
