"""OSDMap / CrushMap wire encoding.

The reference versions every map struct (OSDMap::encode
src/osd/OSDMap.cc, CrushWrapper::encode src/crush/CrushWrapper.cc) so
maps can ship between daemons and persist in the mon store.  Same
contract here over the denc module: ``encode_osdmap``/``decode_osdmap``
round-trip the full cluster map — crush buckets/rules/tunables/
choose_args, pools, osd states/weights/affinity/addresses, upmap and
temp exception tables, EC profiles.
"""

from __future__ import annotations

from ceph_tpu.crush.types import (
    Bucket,
    BucketAlg,
    ChooseArg,
    CrushMap,
    Rule,
    RuleOp,
    RuleStep,
    Tunables,
)
from ceph_tpu.msg.denc import Decoder, Encoder
from ceph_tpu.osd.osdmap import OSDMap
from ceph_tpu.osd.types import PgPool, pg_t


# -- choose_args (shared by crush + osdmap sections) ------------------------

def _enc_choose_args(enc: Encoder, table: dict[int, ChooseArg]) -> None:
    enc.u32(len(table))
    for bid in sorted(table):
        arg = table[bid]
        enc.i32(bid)
        ws = arg.weight_set or []
        enc.u32(len(ws))
        for pos in ws:
            enc.u32(len(pos))
            for w in pos:
                enc.u64(w)
        ids = arg.ids
        enc.bool_(ids is not None)
        if ids is not None:
            enc.u32(len(ids))
            for i in ids:
                enc.i32(i)


def _dec_choose_args(dec: Decoder) -> dict[int, ChooseArg]:
    out: dict[int, ChooseArg] = {}
    for _ in range(dec.u32()):
        bid = dec.i32()
        nws = dec.u32()
        ws = [[dec.u64() for _ in range(dec.u32())] for _ in range(nws)]
        ids = None
        if dec.bool_():
            ids = [dec.i32() for _ in range(dec.u32())]
        out[bid] = ChooseArg(bid, weight_set=ws or None, ids=ids)
    return out


# -- crush ------------------------------------------------------------------

def encode_crush(enc: Encoder, m: CrushMap) -> None:
    with enc.versioned(1, 1):
        enc.u32(m.max_devices)
        enc.u32(len(m.buckets))
        for bid in sorted(m.buckets):
            b = m.buckets[bid]
            enc.i32(b.id)
            enc.i32(b.type)
            enc.u8(int(b.alg))
            enc.u8(b.hash)
            enc.u32(b.size)
            for it in b.items:
                enc.i32(it)
            for w in b.item_weights:
                enc.u32(w)
            for name, arr in (
                ("sum", b.sum_weights),
                ("node", b.node_weights),
                ("straw", b.straws),
            ):
                enc.u32(len(arr))
                for v in arr:
                    enc.u64(v)
        enc.u32(len(m.rules))
        for rid in sorted(m.rules):
            r = m.rules[rid]
            enc.u32(rid)
            enc.u32(r.rule_type)
            enc.bool_(r.device_class is not None)
            if r.device_class is not None:
                enc.str_(r.device_class)
            enc.u32(len(r.steps))
            for s in r.steps:
                enc.u32(int(s.op))
                enc.i32(s.arg1)
                enc.i32(s.arg2)
        enc.u32(len(m.types))
        for tid in sorted(m.types):
            enc.i32(tid)
            enc.str_(m.types[tid])
        t = m.tunables
        for v in (
            t.choose_local_tries, t.choose_local_fallback_tries,
            t.choose_total_tries, t.chooseleaf_descend_once,
            t.chooseleaf_vary_r, t.chooseleaf_stable,
        ):
            enc.u32(v)
        _enc_choose_args(enc, m.choose_args)
        enc.u32(len(m.bucket_names))
        for name in sorted(m.bucket_names):
            enc.str_(name)
            enc.i32(m.bucket_names[name])
        enc.u32(len(m.rule_names))
        for name in sorted(m.rule_names):
            enc.str_(name)
            enc.i32(m.rule_names[name])
        enc.u32(len(m.device_classes))
        for osd in sorted(m.device_classes):
            enc.i32(osd)
            enc.str_(m.device_classes[osd])


def decode_crush(dec: Decoder) -> CrushMap:
    m = CrushMap(types={})
    with dec.versioned():
        m.max_devices = dec.u32()
        for _ in range(dec.u32()):
            bid = dec.i32()
            btype = dec.i32()
            alg = BucketAlg(dec.u8())
            hash_ = dec.u8()
            size = dec.u32()
            items = [dec.i32() for _ in range(size)]
            weights = [dec.u32() for _ in range(size)]
            b = Bucket(
                id=bid, type=btype, alg=alg, hash=hash_,
                items=items, item_weights=weights,
            )
            b.sum_weights = [dec.u64() for _ in range(dec.u32())]
            b.node_weights = [dec.u64() for _ in range(dec.u32())]
            b.straws = [dec.u64() for _ in range(dec.u32())]
            m.buckets[bid] = b
        for _ in range(dec.u32()):
            rid = dec.u32()
            rtype = dec.u32()
            device_class = dec.str_() if dec.bool_() else None
            steps = [
                RuleStep(RuleOp(dec.u32()), dec.i32(), dec.i32())
                for _ in range(dec.u32())
            ]
            m.rules[rid] = Rule(
                rule_type=rtype, steps=steps, device_class=device_class
            )
        for _ in range(dec.u32()):
            tid = dec.i32()
            m.types[tid] = dec.str_()
        m.tunables = Tunables(
            choose_local_tries=dec.u32(),
            choose_local_fallback_tries=dec.u32(),
            choose_total_tries=dec.u32(),
            chooseleaf_descend_once=dec.u32(),
            chooseleaf_vary_r=dec.u32(),
            chooseleaf_stable=dec.u32(),
        )
        m.choose_args = _dec_choose_args(dec)
        for _ in range(dec.u32()):
            name = dec.str_()
            m.bucket_names[name] = dec.i32()
        for _ in range(dec.u32()):
            name = dec.str_()
            m.rule_names[name] = dec.i32()
        for _ in range(dec.u32()):
            osd = dec.i32()
            m.device_classes[osd] = dec.str_()
    return m


# -- pools ------------------------------------------------------------------

def _encode_pool(enc: Encoder, p: PgPool) -> None:
    with enc.versioned(1, 1):
        enc.i64(p.id)
        enc.u8(p.type)
        enc.u32(p.size)
        enc.u32(p.min_size)
        enc.i32(p.crush_rule)
        enc.u32(p.pg_num)
        enc.u32(p.pgp_num)
        enc.u32(p.flags)
        enc.str_(p.erasure_code_profile)
        enc.u32(len(p.extra))
        for k in sorted(p.extra):
            v = p.extra[k]
            if not isinstance(v, str):
                from ceph_tpu.msg.denc import EncodingError

                raise EncodingError(
                    f"pool {p.id} extra[{k!r}] must be str, got {type(v).__name__}"
                )
            enc.str_(k)
            enc.str_(v)


def _decode_pool(dec: Decoder) -> PgPool:
    with dec.versioned():
        p = PgPool(
            id=dec.i64(), type=dec.u8(), size=dec.u32(), min_size=dec.u32(),
            crush_rule=dec.i32(), pg_num=dec.u32(), pgp_num=dec.u32(),
            flags=dec.u32(), erasure_code_profile=dec.str_(),
        )
        for _ in range(dec.u32()):
            k = dec.str_()
            p.extra[k] = dec.str_()
    return p


# -- osdmap -----------------------------------------------------------------

def _encode_pg_table(enc: Encoder, table: dict, value_enc) -> None:
    enc.u32(len(table))
    for pg in sorted(table, key=lambda g: (g.pool, g.ps)):
        enc.i64(pg.pool)
        enc.u32(pg.ps)
        value_enc(table[pg])


def _decode_pg_table(dec: Decoder, value_dec) -> dict:
    out = {}
    for _ in range(dec.u32()):
        pool = dec.i64()
        ps = dec.u32()
        out[pg_t(pool, ps)] = value_dec()
    return out


def encode_osdmap(m: OSDMap) -> bytes:
    enc = Encoder()
    with enc.versioned(1, 1):
        enc.u32(m.epoch)
        enc.u32(m.max_osd)
        for s in m.osd_state:
            enc.u8(s)
        for w in m.osd_weight:
            enc.u32(w)
        enc.bool_(m.osd_primary_affinity is not None)
        if m.osd_primary_affinity is not None:
            for a in m.osd_primary_affinity:
                enc.u32(a)
        enc.u32(len(m.pools))
        for pid in sorted(m.pools):
            _encode_pool(enc, m.pools[pid])
        _encode_pg_table(
            enc, m.pg_upmap,
            lambda v: (enc.u32(len(v)), [enc.i32(o) for o in v]),
        )
        _encode_pg_table(
            enc, m.pg_upmap_items,
            lambda v: (
                enc.u32(len(v)),
                [(enc.i32(a), enc.i32(b)) for a, b in v],
            ),
        )
        _encode_pg_table(enc, m.pg_upmap_primaries, lambda v: enc.i32(v))
        _encode_pg_table(
            enc, m.pg_temp,
            lambda v: (enc.u32(len(v)), [enc.i32(o) for o in v]),
        )
        _encode_pg_table(enc, m.primary_temp, lambda v: enc.i32(v))
        enc.u32(len(m.erasure_code_profiles))
        for name in sorted(m.erasure_code_profiles):
            enc.str_(name)
            prof = m.erasure_code_profiles[name]
            enc.u32(len(prof))
            for k in sorted(prof):
                enc.str_(k)
                enc.str_(prof[k])
        enc.u32(len(m.osd_addrs))
        for osd in sorted(m.osd_addrs):
            host, port = m.osd_addrs[osd]
            enc.i32(osd)
            enc.str_(host)
            enc.u32(port)
        enc.u32(len(m.pool_names))
        for pid in sorted(m.pool_names):
            enc.i64(pid)
            enc.str_(m.pool_names[pid])
        # the mapping pipeline consumes OSDMap.choose_args (balancer
        # overrides), which is distinct from the crush map's own table
        enc.bool_(m.choose_args is not None)
        if m.choose_args is not None:
            _enc_choose_args(enc, m.choose_args)
        encode_crush(enc, m.crush)
    return enc.bytes()


def decode_osdmap(data: bytes) -> OSDMap:
    dec = Decoder(data)
    with dec.versioned():
        epoch = dec.u32()
        max_osd = dec.u32()
        osd_state = [dec.u8() for _ in range(max_osd)]
        osd_weight = [dec.u32() for _ in range(max_osd)]
        affinity = None
        if dec.bool_():
            affinity = [dec.u32() for _ in range(max_osd)]
        pools = {}
        for _ in range(dec.u32()):
            p = _decode_pool(dec)
            pools[p.id] = p
        pg_upmap = _decode_pg_table(
            dec, lambda: [dec.i32() for _ in range(dec.u32())]
        )
        pg_upmap_items = _decode_pg_table(
            dec,
            lambda: [(dec.i32(), dec.i32()) for _ in range(dec.u32())],
        )
        pg_upmap_primaries = _decode_pg_table(dec, dec.i32)
        pg_temp = _decode_pg_table(
            dec, lambda: [dec.i32() for _ in range(dec.u32())]
        )
        primary_temp = _decode_pg_table(dec, dec.i32)
        profiles = {}
        for _ in range(dec.u32()):
            name = dec.str_()
            profiles[name] = {
                dec.str_(): dec.str_() for _ in range(dec.u32())
            }
        addrs = {}
        for _ in range(dec.u32()):
            osd = dec.i32()
            host = dec.str_()
            addrs[osd] = (host, dec.u32())
        pool_names = {}
        for _ in range(dec.u32()):
            pid = dec.i64()
            pool_names[pid] = dec.str_()
        choose_args = _dec_choose_args(dec) if dec.bool_() else None
        crush = decode_crush(dec)
    om = OSDMap(
        crush=crush, epoch=epoch, max_osd=max_osd,
        osd_state=osd_state, osd_weight=osd_weight,
        osd_primary_affinity=affinity, pools=pools,
        pg_upmap=pg_upmap, pg_upmap_items=pg_upmap_items,
        pg_upmap_primaries=pg_upmap_primaries,
        pg_temp=pg_temp, primary_temp=primary_temp,
        erasure_code_profiles=profiles, osd_addrs=addrs,
        pool_names=pool_names, choose_args=choose_args,
    )
    return om
