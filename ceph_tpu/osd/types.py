"""Pool and placement-group types.

Behavioral twin of the reference pool model (src/osd/osd_types.h
``pg_pool_t``, src/include/rados.h ``ceph_stable_mod``): the stable-mod
PG folding that lets pg_num grow without reshuffling every object, the
pool-salted placement seed (``raw_pg_to_pps``,
src/osd/osd_types.cc:1805-1827), and the replicated/erasure split that
decides whether holes may shift left (``can_shift_osds``,
src/osd/osd_types.h:1762).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ceph_tpu.ops.hashing import crush_hash32_2

CEPH_OSD_IN = 0x10000
CEPH_OSD_OUT = 0
CEPH_OSD_MAX_PRIMARY_AFFINITY = 0x10000
CEPH_OSD_DEFAULT_PRIMARY_AFFINITY = 0x10000


def ceph_stable_mod(x: int, b: int, bmask: int) -> int:
    """src/include/rados.h:96 — fold x into [0,b) such that growing b
    moves as few values as possible."""
    if (x & bmask) < b:
        return x & bmask
    return x & (bmask >> 1)


def _pg_mask(n: int) -> int:
    """(1 << cbits(n-1)) - 1: smallest all-ones mask covering [0, n)."""
    return (1 << max(n - 1, 0).bit_length()) - 1


@dataclass(frozen=True)
class pg_t:
    """Placement group id: (pool, ps).  Mirrors src/osd/osd_types.h pg_t."""

    pool: int
    ps: int


class PoolType:
    REPLICATED = 1
    ERASURE = 3


FLAG_HASHPSPOOL = 1


@dataclass
class PgPool:
    """Twin of pg_pool_t (src/osd/osd_types.h:1472+): the per-pool
    placement parameters the mapping pipeline consumes."""

    id: int
    type: int = PoolType.REPLICATED
    size: int = 3
    min_size: int = 2
    crush_rule: int = 0
    pg_num: int = 32
    pgp_num: int = 32
    flags: int = FLAG_HASHPSPOOL
    # erasure pools record their profile name; the profile itself lives
    # in the cluster map (OSDMonitor semantics)
    erasure_code_profile: str = ""
    # snapshot state (pg_pool_t snap_seq / removed_snaps / snaps):
    # snap_seq is the newest snap id ever allocated in this pool;
    # removed_snaps feeds the OSD snap trimmer; pool_snaps maps
    # ``osd pool mksnap`` names to their ids (self-managed snaps don't
    # appear here)
    snap_seq: int = 0
    removed_snaps: set = field(default_factory=set)
    pool_snaps: dict = field(default_factory=dict)
    # peering_crush_bucket_* / tiering fields intentionally omitted
    # until those subsystems exist.
    extra: dict = field(default_factory=dict)

    @property
    def pg_num_mask(self) -> int:
        return _pg_mask(self.pg_num)

    @property
    def pgp_num_mask(self) -> int:
        return _pg_mask(self.pgp_num)

    def can_shift_osds(self) -> bool:
        """Replicated sets compact over holes; EC sets are positional
        (src/osd/osd_types.h:1762-1771)."""
        if self.type == PoolType.REPLICATED:
            return True
        if self.type == PoolType.ERASURE:
            return False
        raise ValueError(f"unhandled pool type {self.type}")

    def raw_pg_to_pg(self, pg: pg_t) -> pg_t:
        """Fold a raw ps into the current pg_num (osd_types.cc:1805)."""
        return pg_t(pg.pool, ceph_stable_mod(pg.ps, self.pg_num, self.pg_num_mask))

    def raw_pg_to_pps(self, pg: pg_t) -> int:
        """Placement seed fed to CRUSH (osd_types.cc:1816-1827); the
        HASHPSPOOL salt keeps per-pool PG placements decorrelated."""
        if self.flags & FLAG_HASHPSPOOL:
            return int(
                crush_hash32_2(
                    ceph_stable_mod(pg.ps, self.pgp_num, self.pgp_num_mask),
                    pg.pool,
                )
            )
        return ceph_stable_mod(pg.ps, self.pgp_num, self.pgp_num_mask) + pg.pool

    @property
    def fast_read(self) -> bool:
        """Read every available shard and decode from the first k to
        answer (pool fast_read flag; reference ECCommon.cc:531)."""
        return self.extra.get("fast_read") == "1"

    def get_snap_context(self):
        """Pool-snap SnapContext (pg_pool_t::get_snap_context): used for
        writes from clients that did not set a self-managed context."""
        from ceph_tpu.osd.snaps import SnapContext

        live = sorted(
            (s for s in self.pool_snaps.values()
             if s not in self.removed_snaps),
            reverse=True,
        )
        return SnapContext(seq=self.snap_seq if live else 0, snaps=live)

    def is_erasure(self) -> bool:
        return self.type == PoolType.ERASURE

    def is_replicated(self) -> bool:
        return self.type == PoolType.REPLICATED
