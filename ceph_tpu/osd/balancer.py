"""Upmap balancer: even out PG placement with pg_upmap_items.

Behavioral twin of the reference's upmap optimizer
(OSDMap::calc_pg_upmaps, src/osd/OSDMap.h:1519, driven by the mgr
balancer module in upmap mode): compute every PG's mapping, find
overfull/underfull OSDs against their weight-proportional targets, and
emit pg_upmap_items entries (per-PG [from, to] swaps) that move PGs
from the fullest devices to the emptiest ones without breaking
placement constraints.

The whole-cluster placement census runs through the batched TPU engine
(BatchedClusterMapper) — the reference iterates pg-by-pg on the CPU;
here each pool's full mapping is one device program, and the greedy
swap selection is cheap host work over the resulting arrays.

Constraint checking: a candidate swap is valid only if the destination
OSD is up/in, not already in the PG's set, and lives in a different
failure domain than every *other* member (same-or-better isolation than
the mapping it replaces — the reference validates candidates by
re-running crush; we validate structurally against the bucket tree).
"""

from __future__ import annotations

from collections import defaultdict

from ceph_tpu.crush.types import CRUSH_ITEM_NONE
from ceph_tpu.osd.osdmap import OSDMap
from ceph_tpu.osd.remap import BatchedClusterMapper
from ceph_tpu.osd.types import pg_t


class UpmapBalancer:
    def __init__(self, osdmap: OSDMap, failure_domain_type: int = 1):
        self.om = osdmap
        self.domain_type = failure_domain_type
        crush = osdmap.crush
        self._parent: dict[int, int] = {}
        for b in crush.buckets.values():
            for it in b.items:
                self._parent[it] = b.id

    def _domain(self, osd: int) -> int:
        cur = osd
        while cur in self._parent:
            cur = self._parent[cur]
            b = self.om.crush.buckets.get(cur)
            if b is not None and b.type == self.domain_type:
                return cur
        return osd  # degenerate maps: the osd is its own domain

    def census(self) -> tuple[dict[int, int], dict[pg_t, list[int]]]:
        """Whole-cluster placement: per-OSD PG counts + per-PG up sets
        (one batched remap)."""
        bcm = BatchedClusterMapper(self.om)
        counts: dict[int, int] = defaultdict(int)
        pgs: dict[pg_t, list[int]] = {}
        for pid, pm in bcm.map_cluster().items():
            for ps in range(self.om.pools[pid].pg_num):
                row = [
                    int(o) for o in pm.up[ps, : pm.up_cnt[ps]]
                    if o != CRUSH_ITEM_NONE
                ]
                pgs[pg_t(pid, ps)] = row
                for o in row:
                    counts[o] += 1
        return dict(counts), pgs

    def targets(self, total_slots: int) -> dict[int, float]:
        """Weight-proportional PG-count target per up+in OSD."""
        om = self.om
        weights = {
            o: om.osd_weight[o]
            for o in range(om.max_osd)
            if om.is_up(o) and not om.is_out(o)
        }
        wsum = sum(weights.values()) or 1
        return {o: total_slots * w / wsum for o, w in weights.items()}

    def optimize(
        self, max_swaps: int = 64, max_deviation: float = 1.0
    ) -> dict[pg_t, list[tuple[int, int]]]:
        """Greedy calc_pg_upmaps: repeatedly move one PG slot from the
        most-overfull OSD to the most-underfull valid OSD.  Returns the
        new pg_upmap_items entries (not yet applied to the map)."""
        om = self.om
        new_items: dict[pg_t, list[tuple[int, int]]] = {}
        counts, pgs = self.census()
        total = sum(counts.values())
        target = self.targets(total)
        for o in target:
            counts.setdefault(o, 0)

        for _ in range(max_swaps):
            over = max(target, key=lambda o: counts[o] - target[o])
            under = min(target, key=lambda o: counts[o] - target[o])
            if (
                counts[over] - target[over] <= max_deviation
                and target[under] - counts[under] <= max_deviation
            ):
                break  # balanced enough
            moved = False
            for pg, row in pgs.items():
                if over not in row or under in row:
                    continue
                if pg in new_items or pg in om.pg_upmap_items:
                    continue  # one adjustment per pg keeps this simple
                others = [o for o in row if o != over]
                udom = self._domain(under)
                if any(self._domain(o) == udom for o in others):
                    continue  # would stack two members in one domain
                new_items[pg] = [(over, under)]
                row[row.index(over)] = under
                counts[over] -= 1
                counts[under] += 1
                moved = True
                break
            if not moved:
                break  # no legal move improves the worst pair
        return new_items

    def apply(self, items: dict[pg_t, list[tuple[int, int]]]) -> None:
        """Install the computed exception-table entries (what the mgr
        balancer sends as 'osd pg-upmap-items' commands)."""
        for pg, pairs in items.items():
            self.om.pg_upmap_items[pg] = list(pairs)


def balance(osdmap: OSDMap, max_swaps: int = 64) -> int:
    """One balancer round: optimize + apply; returns swaps installed."""
    try:
        fd = osdmap.crush.type_id("host")
    except KeyError:
        fd = 1
    b = UpmapBalancer(osdmap, failure_domain_type=fd)
    items = b.optimize(max_swaps=max_swaps)
    b.apply(items)
    return len(items)
