"""Scrub: chunked background consistency scans, deep crc verification
and pg repair (the src/osd/scrubber/ seam), split out of the daemon
per the PGBackend seam layout."""

from __future__ import annotations

import asyncio
import errno
import logging
import time


from ceph_tpu.osd import ecutil
from ceph_tpu.osd.pglog import (
    ZERO,
)
from ceph_tpu.osd.types import pg_t

from ceph_tpu.msg.messages import (
    MOSDScrub,
    MOSDScrubReply,
)
from ceph_tpu.osd.pgutil import (
    HINFO_ATTR,
    VERSION_ATTR,
)

log = logging.getLogger("ceph_tpu.osd")


class ScrubMixin:
    """Chunked scrub + repair — mixed into OSDDaemon; state lives in
    the daemon's __init__."""

    # -- scrub (src/osd/scrubber/, simplified to one pass) -------------

    async def _handle_scrub(self, msg: MOSDScrub) -> None:
        import json

        try:
            report = await self.scrub_pg(
                msg.pool, msg.ps, deep=msg.deep,
                repair=getattr(msg, "repair", False))
            reply = MOSDScrubReply(
                tid=msg.tid, result=0, report=json.dumps(report).encode()
            )
        except Exception as e:
            log.exception("osd.%d: scrub failed", self.id)
            reply = MOSDScrubReply(
                tid=msg.tid, result=-errno.EIO, report=str(e).encode()
            )
        try:
            await msg.conn.send_message(reply)
        except ConnectionError:
            pass

    async def scrub_pg(
        self, pool_id: int, ps: int, deep: bool = False,
        repair: bool = False,
    ) -> dict:
        """Consistency check of one PG across its acting set, CHUNKED so
        client I/O interleaves (reference src/osd/scrubber/: chunked
        scrubs that block writes only on the objects in the current
        chunk).  Shallow compares object sets and versions; ``deep``
        additionally verifies every shard payload's crc32c against the
        stored HashInfo chain (or the parity equations for RMW'd
        objects).  ``repair`` reconstructs bad shards from the
        surviving ones afterwards — the `ceph pg repair` verb
        (scrub_backend authoritative-copy repair role)."""
        pool = self.osdmap.get_pg_pool(pool_id)
        if pool is None:
            return {"error": f"no pool {pool_id}"}
        pg = pg_t(pool_id, ps)
        _, _, acting, primary = self.osdmap.pg_to_up_acting_osds(pg, folded=True)
        if primary != self.id:
            return {"error": f"osd.{self.id} is not primary for {pool_id}.{ps}"}
        pairs = self._pg_members(pool, acting)

        # enumerate the object set (bulk; per-object state is probed
        # fresh under the object lock as each chunk is scrubbed)
        names: set[str] = set()
        for s_, o_ in pairs:
            if o_ == self.id:
                names.update(self._local_objects(pool, pg, s_))
            else:
                try:
                    info = await self._pg_query(
                        pool, pg, s_, o_, since=ZERO, want_objects=True
                    )
                    names.update(n for n, _v in info.objects)
                except (OSError, asyncio.TimeoutError, ConnectionError):
                    pass
        all_oids = sorted(names)

        chunk_max = self.conf["osd_scrub_chunk_max"]
        chunk_sleep = self.conf["osd_scrub_sleep"]
        inconsistencies: list[dict] = []

        async def _one(oid: str) -> list[dict]:
            async with self._obj_lock(pool.id, oid):
                return await self._scrub_object(pool, pg, pairs, oid, deep)

        for base in range(0, len(all_oids), chunk_max):
            # one gate admission per chunk at best-effort weight:
            # saturated client I/O outranks the scan (admission before
            # the object locks, per the opqueue deadlock rule).  The
            # chunk's objects run CONCURRENTLY (each under its own
            # object lock) so their verification work lands in the
            # scrub verifier's coalescing window as one batch instead
            # of one launch per object.
            async with self.op_gate.admit("best_effort"):
                for incs in await asyncio.gather(*(
                    _one(oid) for oid in all_oids[base : base + chunk_max]
                )):
                    inconsistencies.extend(incs)
            await asyncio.sleep(chunk_sleep)

        repaired: list[str] = []
        if repair and inconsistencies:
            bad_oids = sorted({i["object"] for i in inconsistencies})
            for oid in bad_oids:
                # hold the object lock across re-verify + repair so a
                # concurrent client write can neither be torn by the
                # force-pushes nor produce a false inconsistency
                async with self._obj_lock(pool.id, oid):
                    incs = await self._scrub_object(
                        pool, pg, pairs, oid, deep)
                    if not incs:
                        continue  # fixed itself (e.g. write raced scan)
                    try:
                        await self._repair_object(pool, pg, pairs, oid, incs)
                        repaired.append(oid)
                    except Exception:
                        log.exception(
                            "osd.%d: repair of %s/%s failed",
                            self.id, pg, oid)
            # re-verify: the report carries what survived repair
            remaining: list[dict] = []
            for oid in bad_oids:
                async with self._obj_lock(pool.id, oid):
                    remaining.extend(
                        await self._scrub_object(pool, pg, pairs, oid, deep)
                    )
            inconsistencies = remaining
        self._scrub_stamps[(pool_id, ps)] = (
            time.monotonic(),
            time.monotonic() if deep else
            self._scrub_stamps.get((pool_id, ps), (0.0, 0.0))[1],
        )
        return {
            "pg": f"{pool_id}.{ps}",
            "acting": [o for _, o in pairs],
            "objects": len(all_oids),
            "deep": deep,
            "repaired": repaired,
            "inconsistencies": inconsistencies,
        }

    async def _scrub_object(
        self, pool, pg, pairs, oid: str, deep: bool
    ) -> list[dict]:
        """One object's scrub checks (caller holds the object lock)."""
        out: list[dict] = []
        versions: dict[str, bytes | None] = {}
        payloads: dict[int, bytes] = {}
        member_payloads: dict[str, bytes] = {}
        hinfos: dict[int, bytes | None] = {}
        crcs: dict[str, int] = {}
        present = 0
        for s, o in pairs:
            key = f"{s}@osd.{o}"
            if deep:
                payload, attrs, _e = await self._read_shard_quiet(
                    pool, pg, s, o, oid)
            else:
                try:
                    payload, attrs = await self._probe_shard(
                        pool, pg, s, o, oid)
                except (OSError, asyncio.TimeoutError, ConnectionError):
                    payload, attrs = None, None
            if payload is None:
                versions[key] = None
                continue
            present += 1
            versions[key] = (attrs or {}).get(VERSION_ATTR, b"")
            if deep:
                payloads[s] = payload
                member_payloads[key] = payload
                hinfos[s] = (attrs or {}).get(HINFO_ATTR)
        if present == 0:
            return out  # deleted everywhere between listing and scrub
        parity_bad = None
        if deep and member_payloads:
            if pool.is_erasure():
                # EC: shard ids are distinct per member, so per-shard
                # verification (batched when the verifier is attached)
                # covers every member
                shard_crcs, parity_bad = await self._verify_payloads(
                    pool, payloads)
                for s, o in pairs:
                    if s in shard_crcs:
                        crcs[f"{s}@osd.{o}"] = shard_crcs[s]
            else:
                # replicated: every member shares shard NO_SHARD — crc
                # each member's copy individually
                from ceph_tpu.native import crc32c as _crc32c

                crcs = {
                    k: _crc32c(p) for k, p in member_payloads.items()
                }
        have = {k: v for k, v in versions.items() if v is not None}
        if len(have) != len(pairs) or len(set(have.values())) > 1:
            out.append({
                "object": oid, "kind": "shallow",
                "versions": {
                    k: (v.decode() if v else None)
                    for k, v in versions.items()
                },
            })
            return out
        if not deep:
            return out
        # deep: payload crc vs the stored HashInfo chain; RMW'd objects
        # have no hinfo (the overwrite broke the append chain) — verify
        # the parity equations instead by re-encoding the data shards
        hinfo_raw = None
        if pool.is_erasure() and hinfos:
            chains = {h for h in hinfos.values() if h is not None}
            if len(chains) == 1 and all(
                h is not None for h in hinfos.values()
            ):
                hinfo_raw = chains.pop()
                hi = ecutil.HashInfo.from_bytes(hinfo_raw)
                for s, o in pairs:
                    key = f"{s}@osd.{o}"
                    if key not in crcs:
                        continue
                    want = hi.get_chunk_hash(s)
                    if want != crcs[key]:
                        out.append({
                            "object": oid, "kind": "deep-crc",
                            "member": key, "shard": s,
                            "stored": want, "computed": crcs[key],
                        })
            elif chains:
                out.append({
                    "object": oid, "kind": "deep-hinfo-mismatch",
                    "members": sorted(
                        f"{s}" for s, h in hinfos.items() if h is not None
                    ),
                })
        if pool.is_erasure() and hinfo_raw is None and payloads:
            if parity_bad is not None:
                # the batched verifier already re-encoded the data
                # shards on device and compared parity there
                for s in sorted(parity_bad):
                    out.append({
                        "object": oid, "kind": "deep-parity",
                        "member": f"{s}", "shard": s,
                    })
            else:
                ec = self._ec_for(pool)
                sinfo = self._sinfo(ec)
                k = ec.get_data_chunk_count()
                import numpy as _np

                if all(s in payloads for s in range(k)) and len(payloads[0]):
                    chunks = {
                        s: _np.frombuffer(payloads[s], _np.uint8)
                        for s in range(k)
                    }
                    logical = ecutil.decode_concat(sinfo, ec, chunks)
                    expect = ecutil.encode(sinfo, ec, logical)
                    for s, payload in payloads.items():
                        if s in expect and expect[s].tobytes() != payload:
                            out.append({
                                "object": oid, "kind": "deep-parity",
                                "member": f"{s}", "shard": s,
                            })
        if not pool.is_erasure() and len(set(crcs.values())) > 1:
            out.append({
                "object": oid, "kind": "deep-replica-crc", "crcs": crcs,
            })
        return out

    async def _verify_payloads(
        self, pool, payloads
    ) -> tuple[dict[int, int], frozenset[int] | None]:
        """Per-shard crc32c (+ parity re-encode check for eligible EC
        objects) of one object's shard payloads.

        EC payloads go through the process-wide ScrubVerifier
        (parallel/scrub_batcher.py): concurrent scrub chunks — across
        objects and PGs — coalesce into fixed-shape batched device
        launches, bit-identical to the host loop.  Anything the
        verifier declines (or any failure) answers from the host path,
        so scrub behavior never depends on the batching layer.

        Returns ``(shard -> crc32c, parity_bad)`` where ``parity_bad``
        is the set of parity shards whose stored payload disagrees
        with a re-encode of the data shards, or None when the parity
        equations were not checked here."""
        verifier = self.scrub_verifier if pool.is_erasure() else None
        if verifier is not None:
            try:
                ec = self._ec_for(pool)
            except Exception:
                ec = None
            check = await verifier.verify_object(ec, payloads)
            if check is not None:
                return check.crcs, check.parity_bad
        from ceph_tpu.native import crc32c

        return {s: crc32c(p) for s, p in payloads.items()}, None

    async def _repair_object(self, pool, pg, pairs, oid, incs) -> None:
        """`pg repair`: rebuild the authoritative copy of a damaged
        object and push it over the bad members (reference
        scrub_backend authoritative-copy selection + repair_object)."""
        kinds = {i["kind"] for i in incs}
        if pool.is_erasure():
            bad_shards = {
                i["shard"] for i in incs if "shard" in i
            }
            if bad_shards and not kinds - {"deep-crc", "deep-parity"}:
                # corrupt shard payloads at a consistent version:
                # reconstruct from the k+ clean shards and push over
                ec = self._ec_for(pool)
                sinfo = self._sinfo(ec)
                good = {}
                src_attrs = None
                for s, o in pairs:
                    if s in bad_shards:
                        continue
                    payload, attrs, _e = await self._read_shard_quiet(
                        pool, pg, s, o, oid)
                    if payload is not None:
                        import numpy as _np

                        good[s] = _np.frombuffer(payload, _np.uint8)
                        src_attrs = src_attrs or attrs
                _t0 = time.perf_counter()
                rebuilt = await ecutil.decode_shards_async(
                    sinfo, ec, good, bad_shards,
                    service=self.encode_service,
                    aggregator=self.decode_aggregator,
                )
                self.perf.inc("recovery_decode_seconds",
                              time.perf_counter() - _t0)
                self.perf.inc("recovery_decode_bytes",
                              sum(v.nbytes for v in rebuilt.values()))
                osd_of = dict(pairs)
                await asyncio.gather(*(
                    self._push(pool, pg, s, osd_of[s], oid,
                               rebuilt[s].tobytes(), src_attrs or {},
                               force=True)
                    for s in bad_shards
                ))
                return
        if "deep-replica-crc" in kinds:
            # replicated payload divergence at one version: the
            # majority crc wins (primary breaks ties) and is pushed
            # over the minority — authoritative-copy selection
            crcs = next(
                i["crcs"] for i in incs if i["kind"] == "deep-replica-crc")
            from collections import Counter

            winner_crc, _n = Counter(crcs.values()).most_common(1)[0]
            winner_key = next(
                k for k, v in sorted(crcs.items()) if v == winner_crc)
            ws, wo = winner_key.split("@osd.")
            payload, attrs, _e = await self._read_shard_quiet(
                pool, pg, int(ws), int(wo), oid)
            if payload is None:
                return
            await asyncio.gather(*(
                self._push(pool, pg, s, o, oid, payload, attrs or {},
                           force=True)
                for s, o in pairs
                if crcs.get(f"{s}@osd.{o}") != winner_crc
            ))
            return
        # version-level divergence (shallow / hinfo mismatch): the
        # recovery reconciliation machinery is the repair (caller holds
        # the object lock)
        await self._reconcile_object(pool, pg, pairs, oid, have_lock=True)

    async def _scrub_scheduler(self) -> None:
        """Background scrub scheduling (reference
        src/osd/scrubber/osd_scrub_sched.cc role): periodically scrub
        the PG this OSD leads with the stalest stamp; deep scrubs on
        their own (longer) cadence."""
        interval = self.conf["osd_scrub_interval"]
        deep_interval = self.conf["osd_deep_scrub_interval"]
        if interval <= 0:
            return
        tick = max(0.05, min(interval, deep_interval or interval) / 4)
        while not self.stopping:
            await asyncio.sleep(tick)
            try:
                om = self.osdmap
                if om is None:
                    continue
                now = time.monotonic()
                # slow-OSD-aware deprioritization (the mgr analytics
                # loop): while the active mgr's outlier detection
                # flags this OSD slow (MMgrConfigure
                # scrub_deprioritize), background scrubs wait a
                # multiple of the normal interval — client I/O on a
                # struggling disk outranks housekeeping
                factor = 1.0
                if self.mgr_client.scrub_deprioritized:
                    factor = self.conf["osd_scrub_deprioritize_factor"]
                due: list[tuple[float, int, int, bool]] = []
                for pid, pool in om.pools.items():
                    for ps in range(pool.pg_num):
                        _u, _up, _a, primary = om.pg_to_up_acting_osds(
                            pg_t(pid, ps), folded=True)
                        if primary != self.id:
                            continue
                        if (pid, ps) not in self._scrub_stamps:
                            # stamps are in-RAM (the reference persists
                            # them in pg info): seed at first sight so a
                            # restart doesn't deep-scrub everything at
                            # once — first scrub lands one interval out
                            self._scrub_stamps[(pid, ps)] = (now, now)
                            continue
                        last, last_deep = self._scrub_stamps[(pid, ps)]
                        if deep_interval and now - last_deep > deep_interval:
                            if now - last_deep <= deep_interval * factor:
                                self.perf.inc("scrub_deferred_slow")
                                continue
                            due.append((last_deep, pid, ps, True))
                        elif now - last > interval:
                            if now - last <= interval * factor:
                                self.perf.inc("scrub_deferred_slow")
                                continue
                            due.append((last, pid, ps, False))
                # drain everything due this tick CONCURRENTLY (stalest
                # first for launch order): chunked admission through
                # the op gate still paces each scan, and co-scheduled
                # deep scrubs land their verification chunks in the
                # shared scrub verifier's window — cross-PG batching
                if due and not self.stopping:
                    results = await asyncio.gather(*(
                        self.scrub_pg(pid, ps, deep=deep)
                        for _stamp, pid, ps, deep in sorted(due)
                    ), return_exceptions=True)
                    for r in results:
                        if isinstance(r, BaseException):
                            log.error("osd.%d: scheduled scrub failed: %r",
                                      self.id, r)
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("osd.%d: scheduled scrub failed", self.id)
