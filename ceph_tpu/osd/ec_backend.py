"""EC backend: chunk fan-out writes, RMW overwrites, version-guarded
reads, fast_read reconstruction, sub-op service (the
src/osd/ECBackend.cc + ECTransaction.cc seam), split out of the
daemon per the PGBackend seam layout."""

from __future__ import annotations

import asyncio
import errno
import logging

import numpy as np

from ceph_tpu.crush.types import CRUSH_ITEM_NONE
from ceph_tpu.osd import ecutil
from ceph_tpu.osd.pglog import (
    DELETE,
    MODIFY,
    ZERO,
    eversion_t,
    pg_log_entry_t,
)
from ceph_tpu.osd.snaps import (
    NOSNAP,
    SNAPS_ATTR,
    SS_ATTR,
    WHITEOUT_ATTR,
    SnapSet,
    encode_snaps,
)
from ceph_tpu.osd.types import PgPool, pg_t
from ceph_tpu.store import Transaction, coll_t, ghobject_t

from ceph_tpu.msg.messages import (
    OP_APPEND,
    OP_CREATE,
    OP_DELETE,
    OP_GETXATTR,
    OP_GETXATTRS,
    OP_LIST_SNAPS,
    OP_OMAP_CLEAR,
    OP_OMAP_RMKEYS,
    OP_OMAP_SETKEYS,
    OP_READ,
    OP_RMXATTR,
    OP_ROLLBACK,
    OP_SETXATTR,
    OP_STAT,
    OP_TRUNCATE,
    OP_WRITE,
    OP_WRITE_FULL,
    OP_ZERO,
    MOSDECSubOpRead,
    MOSDECSubOpReadReply,
    MOSDECSubOpWrite,
    MOSDECSubOpWriteReply,
    MOSDOpReply,
)
from ceph_tpu.osd.pgutil import (
    ECConnErrors,
    ECFetchError,
    HINFO_ATTR,
    RB_SNAP,
    SIZE_ATTR,
    USER_XATTR_PREFIX,
    VERSION_ATTR,
    _read_extents,
    _v_bytes,
    _v_parse,
)

log = logging.getLogger("ceph_tpu.osd")


class ECBackendMixin:
    """The erasure-coded PGBackend — mixed into OSDDaemon; state lives
    in the daemon's __init__."""

    # -- EC backend ----------------------------------------------------

    def _shard_coll(self, pool: PgPool, pg: pg_t, shard: int) -> coll_t:
        return coll_t(pool.id, pool.raw_pg_to_pg(pg).ps, shard)

    def _ensure_coll(self, t: Transaction, c: coll_t) -> None:
        if not self.store.collection_exists(c):
            t.create_collection(c)

    def _ec_live(self, pool, acting) -> tuple[list, int | None] | None:
        """(live shard pairs, my_shard) or None when the op must bounce."""
        live = [
            (shard, osd)
            for shard, osd in enumerate(acting)
            if osd != CRUSH_ITEM_NONE
        ]
        if len(live) < pool.min_size:
            return None
        my_shard = next((s for s, o in live if o == self.id), None)
        if my_shard is None:
            # a primary that holds no shard of the live set would mint
            # versions from a PG log it never writes, defeating the
            # stale-shard guards — bounce the op instead
            return None
        return live, my_shard

    async def _ec_fan_out_write(
        self, pool, pg, live, oid, shard_payloads, attrs, version,
        *, off: int = 0, truncate: int = -1, rmattrs: list[str] | None = None,
        reqid: str = "", prev_version=None, _retried: bool = False,
        clone_snap: int = 0, clone_snaps: bytes = b"",
    ) -> int:
        """Fan one versioned shard write out to the live set; returns 0
        or the first failing shard's errno (the ECBackend ECSubWrite
        fan-out, src/osd/ECBackend.cc:943).

        ``prev_version`` (None = unguarded) is the base version this
        write was computed against: every shard must be AT that version
        or the write is refused with ESTALE — a shard that missed
        earlier writes is reconciled (recovery roll-forward) and the
        fan-out retried once, mirroring the reference's write-blocks-on-
        missing-object rule (PrimaryLogPG::is_missing_object wait)."""
        from ceph_tpu.common.fault_injector import FAULTS

        await FAULTS.check("osd.ec_fan_out")
        guarded = prev_version is not None
        parent_sp = self._op_span.get()
        waits = []
        local: list[tuple[int, bytes]] = []
        estale = False
        for shard, osd in live:
            payload = shard_payloads.get(shard, b"")
            if not isinstance(payload, bytes):
                payload = payload.tobytes()
            if osd == self.id:
                c = self._shard_coll(pool, pg, shard)
                o = ghobject_t(oid, shard=shard)
                if guarded and self._object_version(c, o) != prev_version:
                    estale = True
                    continue
                local.append((shard, payload))
            else:
                tid = next(self._tids)
                waits.append(self._traced_sub_op(
                    "ec_sub_write", parent_sp, shard, osd, reqid,
                    MOSDECSubOpWrite(
                        tid=tid, pg=pg, shard=shard, from_osd=self.id,
                        oid=oid, off=off, data=payload, attrs=attrs,
                        epoch=self.epoch, truncate=truncate,
                        version=version,
                        rmattrs=rmattrs or [], reqid=reqid,
                        prev_version=prev_version, guarded=guarded,
                        clone_snap=clone_snap, clone_snaps=clone_snaps,
                    ), tid))
        first_err = 0
        if waits:
            reps = await asyncio.gather(*waits, return_exceptions=True)
            lost = False
            for rep in reps:
                if isinstance(rep, asyncio.CancelledError):
                    raise rep
                if isinstance(rep, ECConnErrors + (OSError,)):
                    lost = True
                elif isinstance(rep, BaseException):
                    raise rep
                elif rep.result == -errno.ESTALE:
                    estale = True
                elif rep.result != 0 and first_err == 0:
                    first_err = rep.result
                if getattr(rep, "floored", False):
                    # the replica just pinned its contiguity floor: it
                    # rejoined mid-traffic and its EARLIER objects are
                    # stale with no map change left to trigger a pass
                    self._queue_pg_pass(pool, pg)
            if lost:
                # PARTIAL fan-out: some shard never confirmed while
                # others may already hold this version.  Repair NOW,
                # under the object lock, while the previous version
                # still has >= k holders — deferring to the next map
                # change lets a second partial write destroy the last
                # reconstructible version (chaos-engine-found: a
                # one-way drop + dup-acked retry left an object with
                # no version on >= k shards, wedging recovery forever)
                repaired = False
                try:
                    repaired = await self._reconcile_object(
                        pool, pg, list(live), oid, have_lock=True)
                except Exception:
                    log.exception(
                        "osd.%d: post-partial-fan-out reconcile of %s "
                        "failed", self.id, oid)
                if not repaired:
                    # links still cut: keep repairing in the background
                    # until the object reconciles (a partial write
                    # after the last map epoch has no other trigger)
                    self._queue_object_repair(pool, pg, oid)
                return -errno.EAGAIN
        if first_err:
            return first_err
        if not estale:
            # the primary's OWN shard applies only after every remote
            # accepted: a demoted primary whose fan-out the cluster
            # rejects must not poison its local shard with a write
            # nobody else has (that one divergent shard would cost the
            # pg its availability margin)
            for shard, payload in local:
                await self._store_latency_gate()
                with self._maybe_span(
                    "store_commit", parent=parent_sp, stage="store",
                    shard=shard, oid=oid,
                ):
                    await self._apply_shard_write_async(
                        pool, pg, shard, oid, payload, attrs,
                        version=version, off=off, truncate=truncate,
                        rmattrs=rmattrs, reqid=reqid,
                        clone_snap=clone_snap, clone_snaps=clone_snaps,
                    )
        if estale:
            if _retried:
                return -errno.EAGAIN
            # roll the lagging shard(s) forward, then retry once; if the
            # object state moved past our base meanwhile, the client
            # must redo the RMW from the new base
            pairs = [(s, o) for s, o in live]
            try:
                await self._reconcile_object(
                    pool, pg, pairs, oid, have_lock=True)
            except Exception:
                log.exception(
                    "osd.%d: pre-write reconcile of %s failed", self.id, oid)
                return -errno.EAGAIN
            acting_like = [CRUSH_ITEM_NONE] * pool.size
            for s, o in live:
                acting_like[s] = o
            served = await self._ec_served_version(
                pool, pg, acting_like, oid)
            if served != prev_version:
                return -errno.EAGAIN
            return await self._ec_fan_out_write(
                pool, pg, live, oid, shard_payloads, attrs, version,
                off=off, truncate=truncate, rmattrs=rmattrs, reqid=reqid,
                prev_version=prev_version, _retried=True,
                clone_snap=clone_snap, clone_snaps=clone_snaps,
            )
        return 0

    async def _ec_write_vector(
        self, pool, pg, acting, msg, ec, sinfo, admit_epoch: int | None = None
    ) -> MOSDOpReply:
        """EC write-class op vector: full writes encode directly; partial
        writes (write/append/zero/truncate) run the read-modify-write
        pipeline over the dirty stripe range — the ECCommon RMW pipeline
        (reference src/osd/ECCommon.cc:623-707 start_rmw/try_state_to_reads
        + ExtentCache) re-designed as a single batched read → mutate →
        re-encode → fan-out pass."""
        ops = msg.ops
        snapc = self._effective_snapc(pool, msg)
        if snapc.snaps and not snapc.valid():
            return MOSDOpReply(tid=msg.tid, result=-errno.EINVAL, epoch=self.epoch)
        if any(o.op == OP_DELETE for o in ops):
            if len(ops) != 1:
                return MOSDOpReply(tid=msg.tid, result=-errno.EINVAL, epoch=self.epoch)
            return await self._ec_delete(
                pool, pg, acting, msg, snapc, admit_epoch)
        lv = self._ec_live(pool, acting)
        if lv is None:
            return MOSDOpReply(tid=msg.tid, result=-errno.EAGAIN, epoch=self.epoch)
        live, my_shard = lv
        # duplicate-op detection: a resend of an already-applied
        # non-idempotent vector is answered, not re-applied (reference:
        # pg-log reqid dup lookup in PrimaryLogPG::do_op)
        lg = self._pg_log(self._shard_coll(pool, pg, my_shard))
        if msg.reqid and msg.reqid in lg.reqids:
            # the log claims this op already applied — but a fan-out
            # that died mid-write may have reached fewer than k shards
            # (the retry exists BECAUSE something failed).  Verify the
            # logged version is actually served before vouching for it;
            # if not, reconcile (roll forward if >= k shards carry it,
            # else divergent-rollback) and re-apply when rolled back.
            logged_v = lg.reqids[msg.reqid]
            served = await self._ec_served_version(
                pool, pg, acting, msg.oid, lg)
            if served is not None and served >= logged_v:
                return MOSDOpReply(tid=msg.tid, result=0, epoch=self.epoch)
            pairs = self._pg_members(pool, acting)
            try:
                await self._reconcile_object(
                    pool, pg, pairs, msg.oid, have_lock=True)
            except Exception:
                log.exception(
                    "osd.%d: dup-retry reconcile of %s failed", self.id,
                    msg.oid)
            served = await self._ec_served_version(
                pool, pg, acting, msg.oid, lg)
            if served is not None and served >= logged_v:
                return MOSDOpReply(tid=msg.tid, result=0, epoch=self.epoch)
            if served is None:
                # the cluster state is UNREADABLE right now (links cut
                # mid-thrash, shards unreachable): absence of evidence
                # is not divergence.  Rolling back on a failed probe
                # rewound the log to ZERO and re-applied this op's old
                # payload as a fresh low version — clobbering newer
                # acked writes shard by shard (chaos-engine-found
                # time-travel corruption).  Bounce and let the client
                # retry once the cluster is observable again.
                self._queue_object_repair(pool, pg, msg.oid)
                return MOSDOpReply(
                    tid=msg.tid, result=-errno.EAGAIN, epoch=self.epoch)
            if msg.reqid in lg.reqids:
                # reconcile did not strip it (e.g. zombie entry adopted
                # from a peer log): drop it here so the op re-applies
                t0 = Transaction()
                self._ensure_coll(t0, self._shard_coll(pool, pg, my_shard))
                lg.rollback_divergent(t0, msg.oid, served or ZERO)
                if t0.ops:
                    if getattr(self.store, "blocking_commit", False):
                        await asyncio.to_thread(
                            self.store.queue_transaction, t0)
                    else:
                        self.store.queue_transaction(t0)
            # fall through: apply the vector afresh
        for o in ops:
            if o.op in (OP_OMAP_SETKEYS, OP_OMAP_RMKEYS, OP_OMAP_CLEAR):
                # EC pools have no omap (reference restriction:
                # pool_requires_alignment / MODE_EC forbids omap ops)
                return MOSDOpReply(tid=msg.tid, result=-errno.EOPNOTSUPP, epoch=self.epoch)

        # -- current object state (skipped for a leading WRITE_FULL
        # when no snapshots are in play) ----
        exists, cur_size = False, 0
        cur_v = ZERO  # stale-shard write guard base (see _ec_fan_out_write)
        ss = SnapSet()
        local_ss_raw = self._getattr_quiet(
            self._shard_coll(pool, pg, my_shard),
            ghobject_t(msg.oid, shard=my_shard), SS_ATTR)
        if ops[0].op != OP_WRITE_FULL or snapc.snaps or local_ss_raw:
            try:
                exists, _wo, cur_size, cur_v, ss, _attrs = \
                    await self._ec_head_state(pool, pg, acting, msg.oid)
            except ECFetchError as e:
                return MOSDOpReply(
                    tid=msg.tid, result=-e.errno, epoch=self.epoch)
        else:
            # whole-object replace: the primary's own shard version is
            # the guard base; a mismatch on any shard reconciles first
            cur_v = self._object_version(
                self._shard_coll(pool, pg, my_shard),
                ghobject_t(msg.oid, shard=my_shard))

        # make_writeable: clone-on-write under a newer SnapContext
        clone_snap_arg, clone_snaps_arg = 0, b""
        if exists and ss.needs_cow(snapc):
            cl = ss.make_clone(snapc, cur_size)
            clone_snap_arg = cl.id
            clone_snaps_arg = encode_snaps(cl.snaps)
        else:
            ss.advance_seq(snapc)

        # -- fold the vector into (full | edits) + size + attr deltas ---
        full: np.ndarray | None = None
        edits: list[tuple] = []   # (off, np.ndarray) | ("zfill", off)
        size = cur_size
        attr_sets: dict[str, bytes] = {}
        attr_rms: list[str] = []
        touched = False
        for o in ops:
            if o.op == OP_CREATE:
                if o.off and exists:  # off=1 -> exclusive
                    return MOSDOpReply(tid=msg.tid, result=-errno.EEXIST, epoch=self.epoch)
                touched = True
            elif o.op == OP_WRITE_FULL:
                full = np.frombuffer(o.data, np.uint8)
                edits, size = [], len(o.data)
                touched = exists = True
            elif o.op == OP_WRITE:
                edits.append((o.off, np.frombuffer(o.data, np.uint8)))
                size = max(size, o.off + len(o.data))
                touched = exists = True
            elif o.op == OP_APPEND:
                edits.append((size, np.frombuffer(o.data, np.uint8)))
                size += len(o.data)
                touched = exists = True
            elif o.op == OP_ZERO:
                end = min(size, o.off + o.length)
                if o.off < end:
                    edits.append((o.off, np.zeros(end - o.off, np.uint8)))
                touched = exists = True
            elif o.op == OP_TRUNCATE:
                if o.off < size:
                    # bytes past the cut must read as zero if the object
                    # regrows later in this vector
                    edits.append(("zfill", o.off))
                size = o.off
                touched = exists = True
            elif o.op == OP_SETXATTR:
                attr_sets[USER_XATTR_PREFIX + o.name] = bytes(o.data)
            elif o.op == OP_RMXATTR:
                attr_rms.append(USER_XATTR_PREFIX + o.name)
            elif o.op == OP_ROLLBACK:
                # restore head from the clone serving o.off
                # (PrimaryLogPG::_rollback_to, EC flavor)
                target = ss.resolve(o.off)
                if target is None or (target == NOSNAP and not exists):
                    return MOSDOpReply(
                        tid=msg.tid, result=-errno.ENOENT,
                        epoch=self.epoch)
                if target == NOSNAP:
                    continue  # head already serves that snap
                try:
                    csz, cattrs, cchunks = await self._ec_fetch(
                        pool, pg, acting, msg.oid, ec, snap=target)
                except ECFetchError as e:
                    return MOSDOpReply(
                        tid=msg.tid, result=-e.errno, epoch=self.epoch)
                logical = await self._ecu_decode_concat(sinfo, ec, cchunks)
                full = np.asarray(logical[:csz], np.uint8)
                edits, size = [], csz
                for name, v in (cattrs or {}).items():
                    if name.startswith(USER_XATTR_PREFIX):
                        attr_sets[name] = v
                touched = exists = True
            else:
                return MOSDOpReply(tid=msg.tid, result=-errno.EOPNOTSUPP, epoch=self.epoch)

        version = self._next_version(
            self._shard_coll(pool, pg, my_shard), admit_epoch)
        if version is None:
            return MOSDOpReply(
                tid=msg.tid, result=-errno.EAGAIN, epoch=self.epoch)
        base_attrs = {
            SIZE_ATTR: str(size).encode(),
            VERSION_ATTR: _v_bytes(version),
            **attr_sets,
        }
        if ss.seq or ss.clones:
            base_attrs[SS_ATTR] = ss.to_bytes()
        base_attrs[WHITEOUT_ATTR] = b"0"

        # -- xattr-only vector: metadata write, no data churn -----------
        if not touched and full is None and not edits:
            if not exists:
                base_attrs[SIZE_ATTR] = b"0"
            r = await self._ec_fan_out_write(
                pool, pg, live, msg.oid, {}, base_attrs, version,
                rmattrs=attr_rms, reqid=msg.reqid, prev_version=cur_v,
                clone_snap=clone_snap_arg, clone_snaps=clone_snaps_arg,
            )
            return MOSDOpReply(tid=msg.tid, result=r, epoch=self.epoch)

        cs, sw = sinfo.chunk_size, sinfo.stripe_width
        new_shard_len = sinfo.logical_to_next_chunk_offset(size)

        if full is not None:
            # whole-object replace: no read needed; edits (if any) land
            # on the known content
            padded = np.zeros(sinfo.logical_to_next_stripe_offset(size), np.uint8)
            padded[: len(full)] = full
            for e in edits:
                if e[0] == "zfill":
                    padded[e[1]:] = 0
                else:
                    off, buf = e
                    padded[off : off + len(buf)] = buf
            if len(padded):
                shards = await self._ecu_encode(sinfo, ec, padded)
            else:
                shards = {s: np.zeros(0, np.uint8) for s in range(ec.get_chunk_count())}
            hinfo = ecutil.HashInfo(ec.get_chunk_count())
            hinfo.append(0, shards)
            base_attrs[HINFO_ATTR] = hinfo.to_bytes()
            r = await self._ec_fan_out_write(
                pool, pg, live, msg.oid, shards, base_attrs, version,
                off=0, truncate=new_shard_len, rmattrs=attr_rms,
                reqid=msg.reqid, prev_version=cur_v,
                clone_snap=clone_snap_arg, clone_snaps=clone_snaps_arg,
            )
            if r == 0:
                self._extent_cache_put(pool.id, msg.oid, version, 0, padded)
            else:
                self._extent_cache_drop(pool.id, msg.oid)
            return MOSDOpReply(tid=msg.tid, result=r, epoch=self.epoch)

        # -- RMW over the dirty stripe range ----------------------------
        real_edits: list[tuple[int, np.ndarray]] = []
        for e in edits:
            if e[0] == "zfill":
                # zero through the stripe boundary, not just to the
                # final size: a truncate-down must scrub the stale tail
                # of its last stripe, or a later extension (which relies
                # on the "bytes past size are zero" invariant) would
                # resurrect old bytes
                hi = max(size, sinfo.logical_to_next_stripe_offset(e[1]))
                if e[1] < hi:
                    real_edits.append((e[1], np.zeros(hi - e[1], np.uint8)))
            else:
                real_edits.append(e)
        # truncate/create never dirty stripes by themselves: shard-level
        # truncate keeps whole stripes, and store gap/extend writes
        # zero-fill — the parity of all-zero data is all zeros, so holes
        # stay consistent without re-encoding
        dirty = [
            (sinfo.logical_to_prev_stripe_offset(off),
             sinfo.logical_to_next_stripe_offset(off + len(buf)))
            for off, buf in real_edits if len(buf)
        ]
        if not dirty:
            # pure truncate / create / zero-beyond-end
            r = await self._ec_fan_out_write(
                pool, pg, live, msg.oid, {}, base_attrs, version,
                truncate=new_shard_len,
                rmattrs=attr_rms + (
                    [HINFO_ATTR] if exists and size != cur_size else []
                ),
                reqid=msg.reqid, prev_version=cur_v,
                clone_snap=clone_snap_arg, clone_snaps=clone_snaps_arg,
            )
            return MOSDOpReply(tid=msg.tid, result=r, epoch=self.epoch)
        d_lo = min(d[0] for d in dirty)
        d_hi = max(d[1] for d in dirty)
        old_end = sinfo.logical_to_next_stripe_offset(cur_size) if exists else 0
        buf = np.zeros(d_hi - d_lo, np.uint8)
        read_hi = min(d_hi, old_end)
        if exists and d_lo < read_hi:
            cached = self._extent_cache_get(
                pool.id, msg.oid, cur_v, d_lo, read_hi)
            if cached is not None:
                # hot stripe: the bytes we last wrote at cur_v ARE the
                # on-disk content — skip the shard read entirely
                buf[: read_hi - d_lo] = cached
            else:
                c_lo = sinfo.logical_to_prev_chunk_offset(d_lo)
                c_len = sinfo.logical_to_prev_chunk_offset(read_hi) - c_lo
                try:
                    _sz, _a, chunks = await self._ec_fetch(
                        pool, pg, acting, msg.oid, ec,
                        chunk_off=c_lo, chunk_len=c_len,
                        fast_read=pool.fast_read,
                    )
                except ECFetchError as e:
                    return MOSDOpReply(tid=msg.tid, result=-e.errno, epoch=self.epoch)
                old_logical = await self._ecu_decode_concat(sinfo, ec, chunks)
                buf[: len(old_logical)] = old_logical
        for off, data in real_edits:
            lo = max(off, d_lo)
            hi = min(off + len(data), d_hi)
            if lo < hi:
                buf[lo - d_lo : hi - d_lo] = data[lo - off : hi - off]
        shards = await self._ecu_encode(sinfo, ec, buf)
        # the cumulative-append crc chain cannot survive an overwrite;
        # deep scrub falls back to the parity-equation check (the
        # reference's ec_overwrites pools drop hinfo the same way)
        r = await self._ec_fan_out_write(
            pool, pg, live, msg.oid, shards, base_attrs, version,
            off=sinfo.logical_to_prev_chunk_offset(d_lo),
            truncate=new_shard_len,
            rmattrs=attr_rms + [HINFO_ATTR], reqid=msg.reqid,
            prev_version=cur_v,
            clone_snap=clone_snap_arg, clone_snaps=clone_snaps_arg,
        )
        if r == 0:
            self._extent_cache_put(pool.id, msg.oid, version, d_lo, buf)
        else:
            self._extent_cache_drop(pool.id, msg.oid)
        return MOSDOpReply(tid=msg.tid, result=r, epoch=self.epoch)

    def _apply_shard_write(
        self, pool, pg, shard, oid, payload: bytes, attrs,
        delete=False, version: eversion_t = ZERO,
        off: int = 0, truncate: int | None = None,
        rmattrs: list[str] | None = None, reqid: str = "",
    ) -> None:
        """Apply a shard write + (when versioned) its pg-log entry in
        ONE transaction — the reference couples data and log the same
        way (ECTransaction appends log entries to the shard txn)."""
        self.store.queue_transaction(
            self._shard_write_txn(pool, pg, shard, oid, payload, attrs,
                                  delete, version, off, truncate, rmattrs,
                                  reqid)
        )

    async def _apply_shard_write_async(
        self, pool, pg, shard, oid, payload: bytes, attrs,
        delete=False, version: eversion_t = ZERO,
        off: int = 0, truncate: int | None = None,
        rmattrs: list[str] | None = None, reqid: str = "",
        clone_snap: int = 0, clone_snaps: bytes = b"",
    ) -> None:
        """Same, but journaling stores fsync: run their commit on a
        worker thread so one OSD's disk flush never stalls the whole
        event loop (the reference's journaling happens on dedicated
        finisher threads for the same reason)."""
        t = self._shard_write_txn(
            pool, pg, shard, oid, payload, attrs, delete, version,
            off, truncate, rmattrs, reqid, clone_snap, clone_snaps,
        )
        try:
            if getattr(self.store, "blocking_commit", False):
                await asyncio.to_thread(self.store.queue_transaction, t)
            else:
                self.store.queue_transaction(t)
        except OSError as e:
            # a failed/torn commit is a medium error too: it feeds the
            # same ledger so a disk that can no longer write escalates
            # to self-markdown like one that can no longer read
            if (e.errno or errno.EIO) == errno.EIO:
                self._note_medium_error(pool, pg, shard, oid, op="write")
            raise

    def _shard_write_txn(
        self, pool, pg, shard, oid, payload, attrs, delete, version,
        off: int = 0, truncate: int | None = None,
        rmattrs: list[str] | None = None, reqid: str = "",
        clone_snap: int = 0, clone_snaps: bytes = b"",
    ) -> Transaction:
        """``truncate`` semantics: None keeps legacy whole-replace
        (truncate to len(payload)); -1 leaves the length alone (ranged
        RMW writes and metadata-only writes); >= 0 sets the exact shard
        length after the write (store truncate zero-fills on extend).
        ``clone_snap`` != 0 snapshots the local head shard into
        (oid, snap=clone_snap) before applying (make_writeable COW)."""
        c = self._shard_coll(pool, pg, shard)
        o = ghobject_t(oid, shard=shard)
        t = Transaction()
        self._ensure_coll(t, c)
        if clone_snap:
            cl = ghobject_t(oid, snap=clone_snap, shard=shard)
            if self.store.exists(c, o) and not self.store.exists(c, cl):
                t.clone(c, o, cl)
                t.setattrs(c, cl, {SNAPS_ATTR: clone_snaps})
        if pool.is_erasure() and (version > ZERO or delete):
            # rollback sidecar (the reference ECTransaction keeps
            # roll-backward info until the write commits cluster-wide):
            # preserve this shard's pre-write state so a PARTIAL
            # fan-out can restore the member to the previous version —
            # without it, an in-place partial overwrite destroys the
            # old version's shard quorum and the object wedges unfound
            rb = ghobject_t(oid, snap=RB_SNAP, shard=shard)
            if self.store.exists(c, rb):
                t.remove(c, rb)
            if not delete and self.store.exists(c, o):
                t.clone(c, o, rb)
        if delete:
            if self.store.exists(c, o):
                t.remove(c, o)
        else:
            t.touch(c, o)
            if payload:
                t.write(c, o, off, payload)
            if truncate is None:
                if off == 0:
                    t.truncate(c, o, len(payload))
            elif truncate >= 0:
                t.truncate(c, o, truncate)
            if attrs:
                t.setattrs(c, o, attrs)
            for name in rmattrs or ():
                t.rmattr(c, o, name)
        if version > ZERO:
            lg = self._pg_log(c)
            prior = self._object_version(c, o)
            entry = pg_log_entry_t(
                DELETE if delete else MODIFY, oid, version, prior,
                reqid,
            )
            if version > lg.info.last_update:
                lg.append(t, entry)
            else:
                # OUT-OF-ORDER commit: concurrent ops to different
                # objects race their store commits, and a later-minted
                # version can land first.  The entry must still be
                # RECORDED (fill, not append): silently dropping it
                # left the object with no log evidence — invisible to
                # missing_from() on every future pass, the last root
                # of the stale-shard flake (chaos x load found: a
                # replica that missed exactly such a write could never
                # be scoped for it).
                lg.fill(t, entry)
            self._pg_log_trim(t, lg)
        return t

    async def _ec_head_state(self, pool, pg, acting, oid):
        """Probe the EC head object: (exists, whiteout, size, version,
        SnapSet, attrs).  exists is False for a whiteout head (data-
        plane absent) but the SnapSet still anchors its clones."""
        ec = self._ec_for(pool)
        try:
            sz, attrs, _ = await self._ec_fetch(
                pool, pg, acting, oid, ec, want_data=False)
        except ECFetchError as e:
            if e.errno != errno.ENOENT:
                raise  # degraded, not absent: callers surface the errno
            return False, False, 0, ZERO, SnapSet(), {}
        ss = SnapSet.from_bytes(attrs.get(SS_ATTR))
        wo = attrs.get(WHITEOUT_ATTR) == b"1"
        v = _v_parse(attrs.get(VERSION_ATTR))
        return (not wo), wo, (0 if wo else sz), v, ss, attrs

    async def _ec_served_version(
        self, pool, pg, acting, oid, lg=None
    ) -> "eversion_t | None":
        """The object version a consistent k-shard subset currently
        serves (None = nothing decodable right now).  An absent object
        whose newest log entry is a DELETE counts as served at the
        delete's version (the write wasn't lost — it was superseded)."""
        ec = self._ec_for(pool)
        try:
            _sz, attrs, _ = await self._ec_fetch(
                pool, pg, acting, oid, ec, want_data=False)
        except ECFetchError as e:
            if e.errno != errno.ENOENT:
                return None
            if lg is not None:
                for v in sorted(lg.entries, reverse=True):
                    if lg.entries[v].oid == oid:
                        if lg.entries[v].op == DELETE:
                            return v
                        break
            return ZERO
        return _v_parse(attrs.get(VERSION_ATTR))

    async def _traced_sub_op(self, name, parent, shard, osd, reqid, msg, tid):
        """Child span per shard sub-op (the reference opens jaeger
        child spans per ECSubRead/Write, ECCommon.cc:440-445) — and the
        context-injection point: the sub-op message carries this span's
        TraceContext, so the replica's apply/commit spans join the same
        cluster-wide tree.  Untraced callers (recovery, background
        repair) pass ``parent=None`` and ride the wire context-free."""
        if parent is None:
            return await self._sub_op(osd, msg, tid)
        with self.tracer.span(
            name, parent=parent, shard=shard, osd=osd, reqid=reqid,
            stage="net",
        ) as sp:
            msg.trace = self.tracer.ctx_for(sp)
            return await self._sub_op(osd, msg, tid)

    def _ec_avail(self, acting) -> dict[int, int]:
        """shard -> osd for the currently usable members of an acting
        set (shared by the normal and fast_read fetch paths)."""
        return {
            shard: osd for shard, osd in enumerate(acting)
            if osd != CRUSH_ITEM_NONE and self.osdmap.is_up(osd)
        }

    async def _ec_fetch_fast(
        self, pool, pg, acting, oid, ec, *,
        chunk_off: int = 0, chunk_len: int = 0, snap: int = NOSNAP,
    ):
        """fast_read flavor (reference ECCommon.cc:531 + the fast_read
        pool option): fan the ranged read to EVERY available shard at
        once and complete from the first k version-consistent replies —
        latency is the fastest k of n shards instead of a fixed-k read
        plus retry rounds."""
        import numpy as np

        k = ec.get_data_chunk_count()
        avail = {
            shard: osd for shard, osd in enumerate(acting)
            if osd != CRUSH_ITEM_NONE and self.osdmap.is_up(osd)
        }
        if len(avail) < k:
            # not enough UP members to read right now: transient — the
            # client retries through the remap, never a medium error
            raise ECFetchError(errno.EAGAIN)
        async def read_one(s, o):
            return s, await self._read_shard_quiet(
                pool, pg, s, o, oid, off=chunk_off, length=chunk_len,
                snap=snap,
            )

        tasks = [
            asyncio.ensure_future(read_one(s, o)) for s, o in avail.items()
        ]
        got: dict[int, tuple] = {}
        enoent = 0
        saw_eio = False
        saw_transient = False
        try:
            for fut in asyncio.as_completed(tasks):
                shard, (payload, attrs, eno) = await fut
                if payload is None:
                    if eno == errno.ENOENT:
                        enoent += 1
                    elif eno == errno.EIO:
                        saw_eio = True
                    elif eno == errno.EHOSTUNREACH:
                        saw_transient = True
                    continue
                got[shard] = (payload, attrs or {})
                # complete as soon as k shards agree on the newest
                # version seen so far
                versions = {
                    s2: _v_parse(a.get(VERSION_ATTR))
                    for s2, (_p, a) in got.items()
                }
                vmax = max(versions.values())
                fresh = [s2 for s2, v in versions.items() if v == vmax]
                if len(fresh) >= k:
                    self.perf.inc("ec_fast_read")
                    attrs = got[fresh[0]][1]
                    chunks = {
                        s2: np.frombuffer(got[s2][0], np.uint8)
                        for s2 in fresh[:k]
                    }
                    if SIZE_ATTR not in attrs:
                        raise ECFetchError(errno.ENOENT)
                    return int(attrs[SIZE_ATTR]), attrs, chunks
        finally:
            for t in tasks:
                if not t.done():
                    t.cancel()
            if saw_eio:
                # fast read completed (or failed) past a medium-error
                # shard: background-repair it (EIO-as-erasure)
                self.perf.inc("ec_eio_decode_around")
                self._queue_object_repair(pool, pg, oid)
        if enoent and enoent == len(tasks) - len(got):
            raise ECFetchError(errno.ENOENT)
        if saw_transient:
            raise ECFetchError(errno.EAGAIN)
        raise ECFetchError(errno.EIO)

    async def _ec_fetch(
        self, pool, pg, acting, oid, ec, *,
        chunk_off: int = 0, chunk_len: int = 0, want_data: bool = True,
        snap: int = NOSNAP, fast_read: bool = False,
    ):
        """Version-consistent EC shard fetch — the ECCommon read
        pipeline (reference src/osd/ECCommon.cc:440-445 fans ECSubRead
        to all shards concurrently; stale shards are excluded and the
        read retried with a different shard set).

        Returns ``(size, attrs, chunks)``; ``chunks`` maps shard id to
        the requested chunk byte range (empty when ``want_data`` is
        False — a probe).  ``chunk_len == 0`` reads to the shard end.
        Raises :class:`ECFetchError` with ENOENT for a fully-absent
        object, EIO otherwise.
        """
        if (
            fast_read and want_data
            and getattr(ec, "mds_any_k", False)
            and ec.get_sub_chunk_count() == 1
        ):
            # decode-from-any-k is only sound for MDS codes; non-MDS
            # plugins (shec/lrc) and sub-chunk codes take the
            # minimum_to_decode-driven path below
            try:
                return await self._ec_fetch_fast(
                    pool, pg, acting, oid, ec,
                    chunk_off=chunk_off, chunk_len=chunk_len, snap=snap,
                )
            except ECFetchError:
                raise
            except Exception:
                log.exception(
                    "osd.%d: fast_read fetch failed; normal path", self.id)
        k = ec.get_data_chunk_count()
        avail = self._ec_avail(acting)
        excluded: dict[int, int] = {}  # shard -> errno seen
        for _attempt in range(len(acting) + 1):
            usable = {s: o for s, o in avail.items() if s not in excluded}
            want = set(range(k))
            try:
                minimum = ec.minimum_to_decode(want, set(usable))
            except Exception:
                break  # not enough shards left to decode
            need_shards = sorted(set(minimum))
            if want_data:
                reads = (
                    self._read_shard_quiet(
                        pool, pg, s, usable[s], oid,
                        off=chunk_off, length=chunk_len, snap=snap,
                    )
                    for s in need_shards
                )
            else:
                reads = (
                    self._read_shard_quiet(
                        pool, pg, s, usable[s], oid, off=0, length=1,
                        snap=snap,
                    )
                    for s in need_shards
                )
            results = await asyncio.gather(*reads)
            chunks: dict[int, np.ndarray] = {}
            shard_attrs: dict[int, dict[str, bytes]] = {}
            failed = False
            for shard, (payload, a, eno) in zip(need_shards, results):
                if payload is None:
                    excluded[shard] = eno
                    failed = True
                else:
                    chunks[shard] = np.frombuffer(payload, np.uint8)
                    shard_attrs[shard] = a or {}
            if failed:
                continue
            # a revived OSD may hold a STALE chunk from before it went
            # down: all chunks used in one decode must carry the same
            # object version (object_info consistency; the reference
            # reaches this via peering/recovery before serving)
            versions = {
                s: _v_parse(a.get(VERSION_ATTR)) for s, a in shard_attrs.items()
            }
            vmax = max(versions.values(), default=ZERO)
            stale = [s for s, v in versions.items() if v < vmax]
            if stale:
                for s in stale:
                    excluded[s] = errno.ESTALE
                continue
            attrs = next(iter(shard_attrs.values()), {})
            if not attrs or SIZE_ATTR not in attrs:
                raise ECFetchError(errno.ENOENT)
            if any(e == errno.EIO for e in excluded.values()):
                # the read completed by decoding AROUND a medium-error
                # shard: background-repair the bad shard now so the
                # degraded window closes (the reference requeues the
                # object for recovery on shard EIO the same way)
                self.perf.inc("ec_eio_decode_around")
                self._queue_object_repair(pool, pg, oid)
            return int(attrs[SIZE_ATTR]), attrs, (chunks if want_data else {})
        if excluded and all(e == errno.ENOENT for e in excluded.values()):
            raise ECFetchError(errno.ENOENT)
        if any(e == errno.EIO for e in excluded.values()):
            self._queue_object_repair(pool, pg, oid)
        if any(e in (errno.EHOSTUNREACH, errno.ESTALE)
               for e in excluded.values()):
            # unreachable or stale-mid-recovery shards made the object
            # unreadable RIGHT NOW — a transient the client retries
            # (reference primaries park such ops on waiting_for_degraded
            # instead of failing them), not a verified medium error
            raise ECFetchError(errno.EAGAIN)
        raise ECFetchError(errno.EIO)

    async def _ec_read_vector(
        self, pool, pg, acting, msg, ec, sinfo
    ) -> MOSDOpReply:
        """EC read-class op vector served from ONE version-consistent
        shard snapshot: ranged reads fetch only the covering stripes
        (objecter-style extent math) and xattrs ride the same attrs."""
        ops = msg.ops
        try:
            if any(o.op == OP_LIST_SNAPS for o in ops):
                _ex, _wo, _sz, _v, ss, _a = await self._ec_head_state(
                    pool, pg, acting, msg.oid)
                return MOSDOpReply(
                    tid=msg.tid, result=0, epoch=self.epoch,
                    data=ss.to_bytes())
            read_snap = NOSNAP
            if msg.snapid != NOSNAP:
                # find_object_context: route the read at a clone
                _ex, _wo, _sz, _v, ss, _a = await self._ec_head_state(
                    pool, pg, acting, msg.oid)
                target = ss.resolve(msg.snapid)
                if target is None or (target == NOSNAP and (
                        msg.snapid <= ss.seq or not _ex)):
                    return MOSDOpReply(
                        tid=msg.tid, result=-errno.ENOENT, epoch=self.epoch)
                if target != NOSNAP:
                    read_snap = target
        except ECFetchError as e:
            return MOSDOpReply(
                tid=msg.tid, result=-e.errno, epoch=self.epoch)
        reads = [o for o in ops if o.op == OP_READ]
        chunk_off = chunk_len = 0
        if reads:
            lo = min(o.off for o in reads)
            chunk_off = sinfo.logical_to_prev_chunk_offset(lo)
            if not any(o.length == 0 for o in reads):
                hi = max(o.off + o.length for o in reads)
                chunk_len = sinfo.logical_to_next_chunk_offset(hi) - chunk_off
        try:
            size, attrs, chunks = await self._ec_fetch(
                pool, pg, acting, msg.oid, ec,
                chunk_off=chunk_off, chunk_len=chunk_len,
                want_data=bool(reads), snap=read_snap,
                fast_read=pool.fast_read,
            )
        except ECFetchError as e:
            return MOSDOpReply(tid=msg.tid, result=-e.errno, epoch=self.epoch)
        if read_snap == NOSNAP and attrs.get(WHITEOUT_ATTR) == b"1":
            return MOSDOpReply(
                tid=msg.tid, result=-errno.ENOENT, epoch=self.epoch)
        logical = None
        base = 0
        if reads and chunks and any(len(v) for v in chunks.values()):
            logical = await self._ecu_decode_concat(sinfo, ec, chunks)
            base = sinfo.aligned_chunk_offset_to_logical_offset(chunk_off)
        outs: list[tuple[int, bytes, dict[str, bytes]]] = []
        first_read: bytes | None = None
        for o in ops:
            r, d, kv = 0, b"", {}
            if o.op == OP_READ:
                end = size if o.length == 0 else min(o.off + o.length, size)
                if logical is not None and o.off < end:
                    d = logical[o.off - base : end - base].tobytes()
                if first_read is None:  # summarize the FIRST read op,
                    first_read = d      # even when it returned 0 bytes
            elif o.op == OP_STAT:
                pass
            elif o.op == OP_GETXATTR:
                v = attrs.get(USER_XATTR_PREFIX + o.name)
                if v is None:
                    r = -errno.ENODATA
                else:
                    d = v
            elif o.op == OP_GETXATTRS:
                kv = {
                    name[len(USER_XATTR_PREFIX):]: v
                    for name, v in attrs.items()
                    if name.startswith(USER_XATTR_PREFIX)
                }
            else:
                # omap reads: EC pools have no omap (reference restriction)
                r = -errno.EOPNOTSUPP
            outs.append((r, d, kv))
        result = next((r for r, _d, _kv in outs if r != 0), 0)
        return MOSDOpReply(
            tid=msg.tid, result=result, epoch=self.epoch, size=size,
            data=first_read or b"", outs=outs,
        )

    async def _read_shard_quiet(
        self, pool, pg, shard, osd, oid, *, off: int = 0, length: int = 0,
        extents: list[tuple[int, int]] | None = None, snap: int = NOSNAP,
    ):
        """_read_shard with transport failures mapped to EHOSTUNREACH
        — DISTINCT from a medium-error EIO: a dead/cut peer is a
        transient the client should retry (EAGAIN at the op layer),
        not verified damage to decode around and background-repair."""
        try:
            return await self._read_shard(
                pool, pg, shard, osd, oid, off=off, length=length,
                extents=extents, snap=snap,
            )
        except (OSError, asyncio.TimeoutError, ConnectionError):
            return None, None, errno.EHOSTUNREACH

    async def _read_shard(
        self, pool, pg, shard, osd, oid, *, off: int = 0, length: int = 0,
        extents: list[tuple[int, int]] | None = None, snap: int = NOSNAP,
    ):
        """Ranged chunk read of one shard: (payload, attrs, errno).
        ``length == 0`` reads to the shard end.  ``extents`` returns
        the concatenation of multiple byte runs (sub-chunk repair).
        ``snap`` != NOSNAP reads the clone shard object instead."""
        if osd == self.id:
            c = self._shard_coll(pool, pg, shard)
            o = (ghobject_t(oid, shard=shard) if snap == NOSNAP
                 else ghobject_t(oid, snap=snap, shard=shard))
            if not self.store.exists(c, o):
                return None, None, errno.ENOENT
            try:
                if extents:
                    data = _read_extents(self.store, c, o, extents)
                else:
                    data = self.store.read(
                        c, o, off, None if length == 0 else length
                    )
                return data, self.store.getattrs(c, o), 0
            except FileNotFoundError:
                return None, None, errno.ENOENT
            except OSError as e:
                # local medium error (checksum-at-rest EIO): this shard
                # becomes an ERASURE for the caller — _ec_fetch decodes
                # around it — while the ledger/quarantine machinery
                # repairs it in the background (EIO-as-erasure, the
                # reference's ECBackend shard-EIO handling)
                eno = e.errno or errno.EIO
                if eno == errno.EIO:
                    self._note_medium_error(
                        pool, pg, shard, oid, snap=snap)
                return None, None, eno
        tid = next(self._tids)
        rep = await self._traced_sub_op(
            "ec_sub_read", self._op_span.get(), shard, osd,
            "", MOSDECSubOpRead(
                tid=tid, pg=pg, shard=shard, from_osd=self.id, oid=oid,
                off=off, length=length, want_attrs=True, epoch=self.epoch,
                extents=extents or [], snap=snap,
            ), tid)
        if rep.result != 0:
            return None, None, -rep.result
        return rep.data, rep.attrs, 0

    async def _ec_delete(self, pool, pg, acting, msg, snapc=None,
                         admit_epoch: int | None = None) -> MOSDOpReply:
        my_shard = next(
            (s for s, o in enumerate(acting) if o == self.id), None
        )
        if my_shard is None:
            # same guard as _ec_write_full: never mint versions from a
            # shard log this OSD doesn't own
            return MOSDOpReply(tid=msg.tid, result=-errno.EAGAIN, epoch=self.epoch)
        lg = self._pg_log(self._shard_coll(pool, pg, my_shard))
        if msg.reqid and msg.reqid in lg.reqids:
            return MOSDOpReply(tid=msg.tid, result=0, epoch=self.epoch)
        # snapshots: a delete under a newer SnapContext clones first;
        # if clones anchor to this name, leave a whiteout head (the
        # snapdir role) instead of removing the shard objects
        if snapc is not None and (snapc.snaps or self._getattr_quiet(
                self._shard_coll(pool, pg, my_shard),
                ghobject_t(msg.oid, shard=my_shard), SS_ATTR)):
            try:
                exists, _wo, cur_size, cur_v, ss, _ = \
                    await self._ec_head_state(pool, pg, acting, msg.oid)
            except ECFetchError as e:
                return MOSDOpReply(
                    tid=msg.tid, result=-e.errno, epoch=self.epoch)
            if not exists and ss.clones:
                # already a whiteout (or absent) but clones anchor here:
                # a second DELETE must not remove the snapdir head
                return MOSDOpReply(
                    tid=msg.tid, result=-errno.ENOENT, epoch=self.epoch)
            clone_snap_arg, clone_snaps_arg = 0, b""
            if exists and ss.needs_cow(snapc):
                cl = ss.make_clone(snapc, cur_size)
                clone_snap_arg = cl.id
                clone_snaps_arg = encode_snaps(cl.snaps)
            else:
                ss.advance_seq(snapc)
            if ss.clones and exists:
                lv = self._ec_live(pool, acting)
                if lv is None:
                    return MOSDOpReply(
                        tid=msg.tid, result=-errno.EAGAIN, epoch=self.epoch)
                live, _ = lv
                version = self._next_version(
                    self._shard_coll(pool, pg, my_shard), admit_epoch)
                if version is None:
                    return MOSDOpReply(
                        tid=msg.tid, result=-errno.EAGAIN,
                        epoch=self.epoch)
                wo_attrs = {
                    SIZE_ATTR: b"0",
                    VERSION_ATTR: _v_bytes(version),
                    WHITEOUT_ATTR: b"1",
                    SS_ATTR: ss.to_bytes(),
                }
                r = await self._ec_fan_out_write(
                    pool, pg, live, msg.oid, {}, wo_attrs, version,
                    truncate=0, reqid=msg.reqid, prev_version=cur_v,
                    clone_snap=clone_snap_arg, clone_snaps=clone_snaps_arg,
                )
                return MOSDOpReply(tid=msg.tid, result=r, epoch=self.epoch)
        self._extent_cache_drop(pool.id, msg.oid)
        version = self._next_version(
            self._shard_coll(pool, pg, my_shard), admit_epoch)
        if version is None:
            return MOSDOpReply(
                tid=msg.tid, result=-errno.EAGAIN, epoch=self.epoch)
        waits = []
        for shard, osd in enumerate(acting):
            if osd == CRUSH_ITEM_NONE:
                continue
            if osd == self.id:
                await self._apply_shard_write_async(
                    pool, pg, shard, msg.oid, b"", {}, delete=True,
                    version=version, reqid=msg.reqid,
                )
            else:
                tid = next(self._tids)
                waits.append(self._sub_op(osd, MOSDECSubOpWrite(
                    tid=tid, pg=pg, shard=shard, from_osd=self.id,
                    oid=msg.oid, off=0, data=b"", attrs={},
                    epoch=self.epoch, delete=True, version=version,
                    reqid=msg.reqid,
                ), tid))
        if waits:
            await asyncio.gather(*waits)
        return MOSDOpReply(tid=msg.tid, result=0, epoch=self.epoch)

    async def _handle_sub_write(self, msg: MOSDECSubOpWrite) -> None:
        from ceph_tpu.common.fault_injector import FAULTS

        pool = self.osdmap.get_pg_pool(msg.pg.pool)
        result = 0
        try:
            await FAULTS.check("osd.ec_sub_write_apply")
            # injected store latency (degraded-disk chaos) models the
            # slow disk's SERVICE-QUEUE delay: it runs BEFORE the
            # epoch/primacy/version guards below, so a map interval
            # that changed while the op sat in the slow queue still
            # fences it (a post-guard sleep would let a demoted
            # primary's fan-out land after the new primary's
            # reconcile already rolled the object — an acked-write
            # time-travel the chaos engine caught on this scenario)
            await self._store_latency_gate()
            if msg.version > ZERO and msg.version.epoch < self.epoch:
                # a sub-write minted under an older map (the version
                # carries the sender's ADMISSION epoch): accept it only
                # if the sender still leads this pg in OUR map — a
                # demoted primary's in-flight fan-out must not land
                # (the reference's require_same_or_newer_map gate)
                _u, _up, _a, cur_primary = self.osdmap.pg_to_up_acting_osds(
                    pg_t(msg.pg.pool, msg.pg.ps), folded=True)
                if msg.from_osd != cur_primary:
                    result = -errno.ESTALE
            skip = False
            if msg.guard > ZERO:
                c = self._shard_coll(pool, msg.pg, msg.shard)
                o = ghobject_t(msg.oid, shard=msg.shard)
                skip = self._object_version(c, o) > msg.guard
            if msg.guarded and not skip and result == 0:
                c = self._shard_coll(pool, msg.pg, msg.shard)
                o = ghobject_t(msg.oid, shard=msg.shard)
                if self._object_version(c, o) != msg.prev_version:
                    # this shard missed earlier writes (or holds a
                    # divergent newer one): recovery must reconcile it
                    # before it may accept new versions, or a partial
                    # write would stamp stale data current
                    result = -errno.ESTALE
            if not skip and result == 0:
                # the replica leg of the cluster trace: joined to the
                # primary's ec_sub_write span via the wire context
                with self._maybe_span(
                    "store_commit", ctx=msg.trace, stage="store",
                    shard=msg.shard, oid=msg.oid,
                ):
                    await self._apply_shard_write_async(
                        pool, msg.pg, msg.shard, msg.oid, msg.data,
                        msg.attrs, delete=msg.delete, version=msg.version,
                        off=msg.off, truncate=msg.truncate,
                        rmattrs=msg.rmattrs, reqid=msg.reqid,
                        clone_snap=msg.clone_snap,
                        clone_snaps=msg.clone_snaps,
                    )
        except OSError as e:
            result = -(e.errno or errno.EIO)
        # did THIS apply pin the contiguity floor?  (this member
        # rejoined mid-traffic and skipped a version window) — tell
        # the primary in the reply so it queues a recovery pass NOW:
        # without a later map change nothing else would scope the
        # member's stale objects before scrub finds them
        floored = False
        if result == 0 and msg.version > ZERO:
            lg = self._pg_log(self._shard_coll(pool, msg.pg, msg.shard))
            floored = (lg.contig_floor is not None
                       and lg.info.last_update == msg.version)
        await msg.conn.send_message(MOSDECSubOpWriteReply(
            tid=msg.tid, pg=msg.pg, shard=msg.shard, from_osd=self.id,
            result=result, epoch=self.epoch, floored=floored,
        ))

    async def _handle_sub_read(self, msg: MOSDECSubOpRead) -> None:
        pool = self.osdmap.get_pg_pool(msg.pg.pool)
        c = self._shard_coll(pool, msg.pg, msg.shard)
        o = (ghobject_t(msg.oid, shard=msg.shard) if msg.snap == NOSNAP
             else ghobject_t(msg.oid, snap=msg.snap, shard=msg.shard))
        if not self.store.exists(c, o):
            rep = MOSDECSubOpReadReply(
                tid=msg.tid, pg=msg.pg, shard=msg.shard, from_osd=self.id,
                result=-errno.ENOENT, epoch=self.epoch,
            )
        else:
            try:
                if msg.extents:
                    data = _read_extents(self.store, c, o, msg.extents)
                else:
                    data = self.store.read(
                        c, o, msg.off, None if msg.length == 0 else msg.length
                    )
                self.perf.inc("subop_read_bytes", len(data))
                attrs = self.store.getattrs(c, o) if msg.want_attrs else {}
                rep = MOSDECSubOpReadReply(
                    tid=msg.tid, pg=msg.pg, shard=msg.shard,
                    from_osd=self.id, result=0, data=data, attrs=attrs,
                    epoch=self.epoch,
                )
            except OSError as e:
                # e.g. a checksum-at-rest failure (BlockStore EIO): the
                # primary excludes this shard and reconstructs from the
                # others (the reference's shard-EIO path,
                # ECBackend::handle_sub_read error handling).  Locally
                # the error feeds the read-error ledger: quarantine +
                # escalation run on the osd that OWNS the dying disk.
                if (e.errno or errno.EIO) == errno.EIO:
                    self._note_medium_error(
                        pool, msg.pg, msg.shard, msg.oid, snap=msg.snap)
                rep = MOSDECSubOpReadReply(
                    tid=msg.tid, pg=msg.pg, shard=msg.shard,
                    from_osd=self.id, result=-(e.errno or 5),
                    epoch=self.epoch,
                )
        await msg.conn.send_message(rep)
