"""Whole-cluster batched PG remap — the ParallelPGMapper twin on TPU.

The reference computes every PG's (up, acting) by sharding pools over a
host ThreadPool (ParallelPGMapper, src/osd/OSDMapMapping.h:18-114;
consumers: mon, balancer, osdmaptool --test-map-pgs).  Here the whole
cluster maps as a handful of batched XLA programs: one
``BatchedRuleMapper`` launch per pool covers all its PGs' CRUSH
placements at once (ceph_tpu/crush/jaxmapper.py), and the rest of the
reference pipeline (src/osd/OSDMap.cc:2646-2971) — nonexistent-OSD
filtering, upmap exception tables, down filtering with EC positional
holes, hashed primary affinity, pg_temp overrides — runs as vectorized
numpy over the result arrays, with the sparse exception tables applied
through the scalar OSDMap methods so semantics stay bit-identical.

Pools whose map/rule fall outside the batched engine's surface (legacy
bucket algs, local_fallback tunables) transparently fall back to the
scalar pipeline.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

from ceph_tpu.crush.jaxmapper import (
    BatchedRuleMapper,
    UnsupportedMap,
    compile_map,
)
from ceph_tpu.crush.types import CRUSH_ITEM_NONE
from ceph_tpu.ops.hashing import crush_hash32_2
from ceph_tpu.osd.osdmap import CEPH_OSD_EXISTS, CEPH_OSD_UP, OSDMap
from ceph_tpu.osd.types import (
    CEPH_OSD_DEFAULT_PRIMARY_AFFINITY,
    CEPH_OSD_MAX_PRIMARY_AFFINITY,
    FLAG_HASHPSPOOL,
    PgPool,
    pg_t,
)

_NONE = np.int32(CRUSH_ITEM_NONE)


class PoolMapping(NamedTuple):
    """All PGs of one pool.  Rows are CRUSH_ITEM_NONE-padded; the valid
    prefix length is in the *_cnt vectors (EC rows keep positional NONE
    holes inside the prefix)."""

    up: np.ndarray             # [pg_num, width] int32
    up_cnt: np.ndarray         # [pg_num] int32
    up_primary: np.ndarray     # [pg_num] int32 (-1 if none)
    acting: np.ndarray         # [pg_num, width] int32
    acting_cnt: np.ndarray     # [pg_num] int32
    acting_primary: np.ndarray # [pg_num] int32

    def rows(self, i: int) -> tuple[list[int], int, list[int], int]:
        """(up, up_primary, acting, acting_primary) as the scalar
        pipeline would return them."""
        return (
            [int(v) for v in self.up[i, : self.up_cnt[i]]],
            int(self.up_primary[i]),
            [int(v) for v in self.acting[i, : self.acting_cnt[i]]],
            int(self.acting_primary[i]),
        )


def _stable_mod_vec(x: np.ndarray, b: int, bmask: int) -> np.ndarray:
    """ceph_stable_mod over a vector (src/include/rados.h:96)."""
    return np.where((x & bmask) < b, x & bmask, x & (bmask >> 1))


def _crush_fingerprint(crush, choose_args) -> int:
    """Content hash over exactly the inputs compile_map consumes: maps
    with identical CRUSH content (across epochs!) share one compiled
    program.  Weights/upmap/pg_temp/osd-state changes are runtime
    inputs, NOT part of the program — the common case (osd down, osd
    out, reweight, upmap) therefore reuses the XLA executable and only
    pool/rule/bucket topology changes recompile."""
    parts = [repr(crush.tunables), repr(crush.max_devices)]
    for bid in sorted(crush.buckets):
        b = crush.buckets[bid]
        parts.append(repr((
            bid, int(b.alg), b.hash, b.type, tuple(b.items),
            tuple(b.item_weights),
        )))
    for rid in sorted(crush.rules):
        r = crush.rules[rid]
        parts.append(repr((
            rid, r.rule_type, r.device_class,
            tuple((s.op, s.arg1, s.arg2) for s in r.steps),
        )))
    parts.append(repr(sorted(crush.device_classes.items())))
    if choose_args:
        parts.append(repr(sorted(
            (k, tuple(tuple(p) for p in (a.weight_set or ())),
             tuple(a.ids or ()))
            for k, a in choose_args.items()
        )))
    return hash("\n".join(parts))


# fingerprint -> (CompiledCrush | None, shared mapper dict); one slot —
# the control plane holds one live topology at a time
_PROGRAM_CACHE: dict[int, tuple] = {}


class BatchedClusterMapper:
    """Caches compiled per-pool rule programs — the OSDMapMapping
    analogue.  Compiled XLA programs persist across OSDMap epochs via
    a CRUSH-content fingerprint (see _crush_fingerprint)."""

    def __init__(self, osdmap: OSDMap):
        self.osdmap = osdmap
        try:
            fp = _crush_fingerprint(osdmap.crush, osdmap.choose_args)
        except Exception:
            fp = None
        if fp is not None and fp in _PROGRAM_CACHE:
            self.cc, self._mappers = _PROGRAM_CACHE[fp]
            return
        try:
            self.cc = compile_map(
                osdmap.crush, choose_args=osdmap.choose_args
            )
        except UnsupportedMap:
            self.cc = None
        self._mappers: dict[tuple[int, int], BatchedRuleMapper] = {}
        if fp is not None:
            _PROGRAM_CACHE.clear()  # one live topology; drop the old
            _PROGRAM_CACHE[fp] = (self.cc, self._mappers)

    def _rule_mapper(self, ruleno: int, size: int) -> BatchedRuleMapper | None:
        if self.cc is None:
            return None
        key = (ruleno, size)
        if key not in self._mappers:
            try:
                self._mappers[key] = BatchedRuleMapper(self.cc, ruleno, size)
            except (UnsupportedMap, KeyError):
                return None
        return self._mappers[key]

    # -- the batched pipeline -----------------------------------------

    def map_pool(self, poolid: int) -> PoolMapping:
        om = self.osdmap
        pool = om.get_pg_pool(poolid)
        if pool is None:
            raise KeyError(f"no pool {poolid}")
        b = pool.pg_num
        # rows must hold the widest legal result: CRUSH output is
        # pool.size wide, but explicit pg_upmap vectors and pg_temp
        # acting sets may legally be longer (the scalar pipeline returns
        # them whole)
        width = pool.size
        for pg, vec in om.pg_upmap.items():
            if pg.pool == poolid:
                width = max(width, len(vec))
        for pg, vec in om.pg_temp.items():
            if pg.pool == poolid:
                width = max(width, len(vec))

        ps = np.arange(b, dtype=np.uint32)
        pgp = _stable_mod_vec(ps, pool.pgp_num, pool.pgp_num_mask)
        if pool.flags & FLAG_HASHPSPOOL:
            pps = crush_hash32_2(pgp, np.uint32(poolid)).astype(np.uint32)
        else:
            pps = (pgp + np.uint32(poolid)).astype(np.uint32)

        mapper = (
            self._rule_mapper(pool.crush_rule, pool.size)
            if pool.crush_rule in om.crush.rules
            else None
        )
        if mapper is not None:
            try:
                raw0, cnt = mapper(pps, om.osd_weight)
            except Exception:
                # jax backend unavailable/broken (e.g. a misconfigured
                # JAX_PLATFORMS in a daemon environment): the placement
                # answer must not depend on the accelerator being there
                import logging

                logging.getLogger("ceph_tpu.remap").warning(
                    "batched remap unavailable; using scalar pipeline",
                    exc_info=True,
                )
                mapper = None
        if mapper is not None:
            cnt = cnt.astype(np.int32).copy()
            raw = np.full((b, width), _NONE, np.int32)
            raw[:, : raw0.shape[1]] = raw0
        elif pool.crush_rule in om.crush.rules:
            # scalar fallback (unsupported map features)
            raw = np.full((b, width), _NONE, np.int32)
            cnt = np.zeros(b, np.int32)
            from ceph_tpu.crush.mapper import crush_do_rule

            for i in range(b):
                r = crush_do_rule(
                    om.crush, pool.crush_rule, int(pps[i]), pool.size,
                    om.osd_weight, om.choose_args,
                )
                cnt[i] = min(len(r), width)
                raw[i, : cnt[i]] = r[: cnt[i]]
        else:
            raw = np.full((b, width), _NONE, np.int32)
            cnt = np.zeros(b, np.int32)

        max_osd = om.max_osd
        state = np.asarray(om.osd_state + [0], np.int64)  # +pad for max_osd==0
        if max_osd:
            exists = (state[:-1] & CEPH_OSD_EXISTS).astype(bool)
            up_ok = (state[:-1] & CEPH_OSD_UP).astype(bool) & exists
        else:
            exists = up_ok = np.zeros(0, bool)

        in_prefix = np.arange(width)[None, :] < cnt[:, None]
        valid = in_prefix & (raw != _NONE)

        def _alive(mask_per_osd: np.ndarray) -> np.ndarray:
            idx = np.clip(raw, 0, max(max_osd - 1, 0))
            ok = (raw >= 0) & (raw < max_osd)
            if max_osd:
                ok &= mask_per_osd[idx]
            else:
                ok[:] = False
            return ok

        # 1. _remove_nonexistent_osds (OSDMap.cc:2646-2668): shiftable
        # pools drop every non-existent entry INCLUDING holes (the
        # scalar keeps only exists(o)); EC pools hole them out in place
        keep = _alive(exists)
        if pool.can_shift_osds():
            raw, cnt = self._compact(raw, cnt, keep, in_prefix)
        else:
            raw = np.where(valid & ~keep, _NONE, raw)

        # 2. _apply_upmap — sparse exception tables (OSDMap.cc:2699-2765)
        affected = set()
        for table in (om.pg_upmap, om.pg_upmap_items, om.pg_upmap_primaries):
            for pg in table:
                if pg.pool == poolid and pg.ps < b:
                    affected.add(pg.ps)
        for psv in affected:
            row = [int(v) for v in raw[psv, : cnt[psv]]]
            om._apply_upmap(pool, pg_t(poolid, psv), row)
            assert len(row) <= width, (len(row), width)
            raw[psv, :] = _NONE
            raw[psv, : len(row)] = row
            cnt[psv] = len(row)

        # 3. _raw_to_up_osds (OSDMap.cc:2767-2791)
        in_prefix = np.arange(width)[None, :] < cnt[:, None]
        valid = in_prefix & (raw != _NONE)
        alive = _alive(up_ok)
        if pool.can_shift_osds():
            up, up_cnt = self._compact(raw, cnt, alive, in_prefix)
        else:
            up = np.where(in_prefix & ~alive, _NONE, raw)
            up_cnt = cnt.copy()

        # 4. primary + 5. _apply_primary_affinity (OSDMap.cc:2793-2846)
        up_primary = self._pick_primary(up, up_cnt)
        up, up_primary = self._apply_affinity(pool, pps, up, up_cnt, up_primary)

        # 6. pg_temp / primary_temp (OSDMap.cc:2848-2881) — sparse
        acting = up.copy()
        acting_cnt = up_cnt.copy()
        acting_primary = up_primary.copy()
        temp_ps = {
            pg.ps for pg in om.pg_temp if pg.pool == poolid and pg.ps < b
        } | {
            pg.ps for pg in om.primary_temp if pg.pool == poolid and pg.ps < b
        }
        for psv in temp_ps:
            temp_pg, temp_primary = om._get_temp_osds(pool, pg_t(poolid, psv))
            if temp_pg:
                n = len(temp_pg)
                assert n <= width, (n, width)
                acting[psv, :] = _NONE
                acting[psv, :n] = temp_pg
                acting_cnt[psv] = n
                acting_primary[psv] = temp_primary
            elif temp_primary != -1:
                acting_primary[psv] = temp_primary

        return PoolMapping(up, up_cnt, up_primary, acting, acting_cnt, acting_primary)

    def map_cluster(self) -> dict[int, PoolMapping]:
        """Map every pool — the whole-cluster remap."""
        return {pid: self.map_pool(pid) for pid in self.osdmap.pools}

    # -- vectorized pieces --------------------------------------------

    @staticmethod
    def _compact(
        raw: np.ndarray, cnt: np.ndarray, keep: np.ndarray, in_prefix: np.ndarray
    ) -> tuple[np.ndarray, np.ndarray]:
        """Drop masked-out entries, left-shifting survivors (replicated
        pools compact over holes)."""
        drop = in_prefix & ~keep
        order = np.argsort(drop, axis=1, kind="stable")
        out = np.take_along_axis(raw, order, axis=1)
        new_cnt = (in_prefix & keep).sum(axis=1).astype(np.int32)
        out = np.where(np.arange(raw.shape[1])[None, :] < new_cnt[:, None], out, _NONE)
        return out, new_cnt

    @staticmethod
    def _pick_primary(rows: np.ndarray, cnt: np.ndarray) -> np.ndarray:
        """First non-hole in the prefix (OSDMap.cc:2690-2697)."""
        width = rows.shape[1]
        valid = (np.arange(width)[None, :] < cnt[:, None]) & (rows != _NONE)
        anyv = valid.any(axis=1)
        first = valid.argmax(axis=1)
        prim = np.where(anyv, rows[np.arange(rows.shape[0]), first], -1)
        return prim.astype(np.int32)

    def _apply_affinity(
        self,
        pool: PgPool,
        pps: np.ndarray,
        rows: np.ndarray,
        cnt: np.ndarray,
        primary: np.ndarray,
    ) -> tuple[np.ndarray, np.ndarray]:
        """Vectorized _apply_primary_affinity: hashed proportional
        rejection; first accepted slot wins, else first valid slot."""
        om = self.osdmap
        aff_l = om.osd_primary_affinity
        if aff_l is None:
            return rows, primary
        nb, width = rows.shape
        max_osd = max(om.max_osd, 1)
        aff = np.zeros(max_osd, np.int64)
        aff[: len(aff_l)] = aff_l
        valid = (np.arange(width)[None, :] < cnt[:, None]) & (rows != _NONE)
        a = aff[np.clip(rows, 0, max_osd - 1)]
        a = np.where(valid, a, CEPH_OSD_MAX_PRIMARY_AFFINITY)
        nondefault = valid & (a != CEPH_OSD_DEFAULT_PRIMARY_AFFINITY)
        rowmask = nondefault.any(axis=1)
        if not rowmask.any():
            return rows, primary
        h = crush_hash32_2(pps[:, None], rows.astype(np.uint32)).astype(np.int64)
        accept = valid & (
            (a >= CEPH_OSD_MAX_PRIMARY_AFFINITY) | ((h >> 16) < a)
        )
        any_acc = accept.any(axis=1)
        first_acc = accept.argmax(axis=1)
        any_valid = valid.any(axis=1)
        first_valid = valid.argmax(axis=1)
        pos = np.where(any_acc, first_acc, np.where(any_valid, first_valid, -1))
        apply = rowmask & (pos >= 0)
        ar = np.arange(nb)
        new_primary = np.where(
            apply, rows[ar, np.clip(pos, 0, width - 1)], primary
        ).astype(np.int32)
        if pool.can_shift_osds():
            idx = np.tile(np.arange(width)[None, :], (nb, 1))
            p = pos[:, None]
            newidx = np.where(idx == 0, np.clip(p, 0, width - 1),
                              np.where(idx <= p, idx - 1, idx))
            rot = np.take_along_axis(rows, newidx, axis=1)
            doit = (apply & (pos > 0))[:, None]
            rows = np.where(doit, rot, rows)
        return rows, new_primary
