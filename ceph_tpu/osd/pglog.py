"""Per-PG operation log: versions, missing sets, delta recovery.

Behavioral twin of the reference's log-based consistency core
(src/osd/PGLog.{h,cc}, src/osd/osd_types.h pg_log_entry_t /
eversion_t / pg_missing_t; doc/dev/osd_internals/log_based_pg.rst):
every write the primary orders gets an eversion (epoch, seq); the
entry is persisted by every acting member in the same transaction as
the data; after a map change peers compare ``last_update`` and the
primary computes per-peer missing sets from the log delta — full
backfill only when a peer's state predates the log tail.

The log lives in the PG meta object's omap (reference: pg log keys in
the pgmeta object), one key per entry, plus an ``info`` key carrying
pg_info (last_update, log_tail).
"""

from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass, field

from ceph_tpu.msg.denc import Decoder, Encoder
from ceph_tpu.store import ObjectStore, Transaction, coll_t, ghobject_t

PGMETA_OID = "_pgmeta_"
INFO_KEY = "info"
LOG_KEY_PREFIX = "log."
FLOOR_KEY = "contig_floor"

MODIFY = 1
DELETE = 2


@dataclass(frozen=True, order=True)
class eversion_t:
    """(epoch, version) — reference src/osd/osd_types.h eversion_t;
    totally ordered, (0, 0) is 'nothing'."""

    epoch: int = 0
    version: int = 0

    def key(self) -> str:
        # zero-padded so omap string order == version order
        return f"{self.epoch:010d}.{self.version:012d}"

    def __str__(self) -> str:
        return f"{self.epoch}'{self.version}"


ZERO = eversion_t(0, 0)


@dataclass(frozen=True)
class pg_log_entry_t:
    """One ordered op (reference pg_log_entry_t: op, soid, version,
    prior_version, reqid — the reqid feeds duplicate-op detection so a
    client resend of a non-idempotent op is answered, not re-applied)."""

    op: int
    oid: str
    version: eversion_t
    prior_version: eversion_t = ZERO
    reqid: str = ""

    def encode(self) -> bytes:
        enc = Encoder()
        with enc.versioned(2, 1):
            enc.u8(self.op)
            enc.str_(self.oid)
            enc.u32(self.version.epoch)
            enc.u64(self.version.version)
            enc.u32(self.prior_version.epoch)
            enc.u64(self.prior_version.version)
            enc.str_(self.reqid)
        return enc.bytes()

    @classmethod
    def decode(cls, raw: bytes) -> "pg_log_entry_t":
        dec = Decoder(raw)
        with dec.versioned() as v:
            op = dec.u8()
            oid = dec.str_()
            ver = eversion_t(dec.u32(), dec.u64())
            pv = eversion_t(dec.u32(), dec.u64())
            reqid = dec.str_() if v >= 2 else ""
        return cls(op, oid, ver, pv, reqid)


@dataclass
class pg_info_t:
    """The slice of reference pg_info_t peering compares."""

    last_update: eversion_t = ZERO
    log_tail: eversion_t = ZERO

    def encode(self) -> bytes:
        enc = Encoder()
        with enc.versioned(1, 1):
            enc.u32(self.last_update.epoch)
            enc.u64(self.last_update.version)
            enc.u32(self.log_tail.epoch)
            enc.u64(self.log_tail.version)
        return enc.bytes()

    @classmethod
    def decode(cls, raw: bytes) -> "pg_info_t":
        dec = Decoder(raw)
        with dec.versioned():
            lu = eversion_t(dec.u32(), dec.u64())
            lt = eversion_t(dec.u32(), dec.u64())
        return cls(lu, lt)


@dataclass
class MissingSet:
    """oid -> (need, have): versions a peer must recover
    (reference pg_missing_t)."""

    items: dict[str, tuple[eversion_t, eversion_t]] = field(default_factory=dict)

    def add(self, oid: str, need: eversion_t, have: eversion_t = ZERO) -> None:
        prev = self.items.get(oid)
        if prev is None or need > prev[0]:
            have = prev[1] if prev is not None else have
            self.items[oid] = (need, have)

    def __bool__(self) -> bool:
        return bool(self.items)

    def __len__(self) -> int:
        return len(self.items)


class PGLog:
    """In-memory log + its persistence into the pgmeta omap."""

    #: duplicate-detection window kept past trim (the reference's
    #: osd_pg_log_dups_tracked analogue)
    REQID_WINDOW = 2000

    def __init__(self, cid: coll_t):
        self.cid = cid
        self.meta = ghobject_t(PGMETA_OID, shard=cid.shard)
        self.info = pg_info_t()
        self.entries: dict[eversion_t, pg_log_entry_t] = {}
        # reqid -> version of already-applied client ops; survives log
        # trim in RAM (rebuilt from surviving entries on load, so the
        # window shrinks to the log length across a restart — the same
        # bounded-dup contract the reference's dups list provides)
        self.reqids: "OrderedDict[str, eversion_t]" = OrderedDict()
        # highest version counter handed out by _next_version but not
        # yet appended (IN-MEMORY: an in-flight mint dies with the
        # daemon and its counter is simply skipped — a detectable gap).
        # Without the reservation, two concurrent ops to DIFFERENT
        # objects both read last_update before either append lands
        # (the fan-out round-trip sits in between) and mint the SAME
        # eversion — the loser's log entry is silently swallowed by
        # the winner's, leaving its object with no log evidence
        # (chaos x load composition-found version-mint collision).
        self.reserved_version: eversion_t = ZERO
        # contiguity floor (PERSISTED): the last_update this log held
        # when a NON-CONTIGUOUS entry was first appended (pg version
        # counters are dense, so a skipped counter means ops this
        # member never saw — a member revived mid-traffic starts
        # applying new sub-ops and its last_update leapfrogs the
        # missed window).  While set, last_update must NOT be trusted
        # as "has everything up to here": peering scopes this member
        # from the floor instead.  None = contiguous (normal).
        self.contig_floor: eversion_t | None = None

    # -- mutation ------------------------------------------------------

    def _track_reqid(self, entry: pg_log_entry_t) -> None:
        if entry.reqid:
            self.reqids[entry.reqid] = entry.version
            self.reqids.move_to_end(entry.reqid)
            while len(self.reqids) > self.REQID_WINDOW:
                self.reqids.popitem(last=False)

    def append(self, t: Transaction, entry: pg_log_entry_t) -> None:
        """Record one op; caller folds ``t`` into the data transaction
        so log and data commit atomically.

        A non-contiguous append (version counter skips — this member
        missed ops while the pg moved on) pins the contiguity floor at
        the pre-append last_update: the missed window's entries will
        never arrive (appends are forward-only), so last_update alone
        would silently vouch for state this member does not hold —
        the stale-shard scrub flake's root mechanism."""
        assert entry.version > self.info.last_update, (
            entry.version, self.info.last_update,
        )
        kv = {
            LOG_KEY_PREFIX + entry.version.key(): entry.encode(),
        }
        if (entry.version.version > self.info.last_update.version + 1
                and self.contig_floor is None):
            self.contig_floor = self.info.last_update
            kv[FLOOR_KEY] = self.contig_floor.key().encode()
        self.entries[entry.version] = entry
        self.info.last_update = entry.version
        self._track_reqid(entry)
        kv[INFO_KEY] = self.info.encode()
        t.touch(self.cid, self.meta)
        t.omap_setkeys(self.cid, self.meta, kv)

    def fill(self, t: Transaction, entry: pg_log_entry_t) -> None:
        """Insert a history entry a gapped log missed (post-recovery
        log sync): unlike append, versions at or below last_update
        are accepted — they fill CONTENT holes, so if this member is
        ever primary its missing_from() computations see the whole
        history instead of silently skipping the window it missed."""
        if entry.version in self.entries:
            return
        self.entries[entry.version] = entry
        self._track_reqid(entry)
        kv = {LOG_KEY_PREFIX + entry.version.key(): entry.encode()}
        if entry.version > self.info.last_update:
            self.info.last_update = entry.version
            kv[INFO_KEY] = self.info.encode()
        t.touch(self.cid, self.meta)
        t.omap_setkeys(self.cid, self.meta, kv)

    def effective_last_update(self) -> eversion_t:
        """What this log can VOUCH for: last_update, unless a
        contiguity gap pinned the floor lower."""
        if self.contig_floor is not None:
            return min(self.contig_floor, self.info.last_update)
        return self.info.last_update

    def clear_contig_floor(self, t: Transaction) -> None:
        """Primary-verified: every object through the gap was
        reconciled (a full recovery pass completed), so last_update
        may be trusted again."""
        if self.contig_floor is None:
            return
        self.contig_floor = None
        t.touch(self.cid, self.meta)
        t.omap_rmkeys(self.cid, self.meta, [FLOOR_KEY])

    def rollback_divergent(
        self, t: Transaction, oid: str, to: "eversion_t"
    ) -> None:
        """Drop this object's entries newer than ``to`` — the writes
        they recorded did not survive into the authoritative state
        (reference PGLog divergent-entry handling in merge_log /
        _merge_divergent_entries).  Their reqids must stop answering
        dup detection so a client retry re-applies the op.
        ``last_update`` is left alone: versions stay monotonic."""
        drop = [
            v for v, e in self.entries.items() if e.oid == oid and v > to
        ]
        for v in drop:
            e = self.entries.pop(v)
            if e.reqid:
                self.reqids.pop(e.reqid, None)
            t.touch(self.cid, self.meta)
            t.omap_rmkeys(self.cid, self.meta, [LOG_KEY_PREFIX + v.key()])

    def trim(self, t: Transaction, keep: int) -> None:
        """Drop oldest entries beyond ``keep`` (osd_min_pg_log_entries
        semantics); log_tail advances to the oldest kept version."""
        if len(self.entries) <= keep:
            return
        versions = sorted(self.entries)
        drop = versions[: len(versions) - keep]
        for v in drop:
            del self.entries[v]
        self.info.log_tail = drop[-1]
        t.touch(self.cid, self.meta)
        t.omap_rmkeys(
            self.cid, self.meta, [LOG_KEY_PREFIX + v.key() for v in drop]
        )
        t.omap_setkeys(self.cid, self.meta, {INFO_KEY: self.info.encode()})

    def set_tail(self, t: Transaction, tail: eversion_t) -> None:
        """Adopt a sender's log_tail after backfill: entries at or below
        it are dropped (the local log has a gap there)."""
        if tail <= self.info.log_tail:
            return
        drop = [v for v in self.entries if v <= tail]
        for v in drop:
            del self.entries[v]
        self.info.log_tail = tail
        if self.info.last_update < tail:
            self.info.last_update = tail
        t.touch(self.cid, self.meta)
        if drop:
            t.omap_rmkeys(
                self.cid, self.meta, [LOG_KEY_PREFIX + v.key() for v in drop]
            )
        t.omap_setkeys(self.cid, self.meta, {INFO_KEY: self.info.encode()})

    def adopt_tail(
        self,
        t: Transaction,
        tail: eversion_t,
        entries: "list[pg_log_entry_t] | tuple[pg_log_entry_t, ...]" = (),
        verified: bool = False,
    ) -> None:
        """Adopt an authoritative peer's (log_tail, entries-above-tail)
        after backfill — set_tail + fill as ONE step that keeps the
        log's evidence consistent:

        - dup detection: every adopted entry's reqid enters the window
          (via fill -> _track_reqid), so a client resend of an op this
          member ADOPTED rather than executed still dedups exactly-once;
        - contiguity: when adoption RAISES last_update past state this
          member never held (tail > pre-adoption last_update) and the
          transfer is not yet object-verified (``verified=False``), the
          contiguity floor pins at the pre-adoption effective
          last_update — otherwise an INTERRUPTED backfill leaves a log
          whose last_update silently vouches for the adopted window and
          the restart would wrongly take the cheap log-delta path.
          ``verified=True`` (the sender reconciled every object through
          the window) clears the floor instead."""
        pre_eff = self.effective_last_update()
        gapped = tail > self.info.last_update
        self.set_tail(t, tail)
        for e in entries:
            if e.version > tail:
                self.fill(t, e)
        if verified:
            self.clear_contig_floor(t)
        elif gapped and self.contig_floor is None:
            self.contig_floor = pre_eff
            t.touch(self.cid, self.meta)
            t.omap_setkeys(
                self.cid, self.meta, {FLOOR_KEY: pre_eff.key().encode()}
            )

    def split_into(self, t: Transaction, child: "PGLog", belongs) -> None:
        """PGLog::split_into twin (reference src/osd/PGLog.h split_into,
        called from PG::split_into on pg_num growth): entries whose
        object now folds into the child pg MOVE to the child's log;
        BOTH logs keep the parent's version bounds (last_update /
        log_tail continue the parent's eversion sequence), so
        post-split authority comparisons between members remain
        meaningful — without this, children born with empty logs make
        an empty member look authoritative and refiled objects get
        reaped as strays."""
        moved = [e for e in self.entries.values() if belongs(e.oid)]
        child.info.last_update = self.info.last_update
        child.info.log_tail = self.info.log_tail
        t.touch(child.cid, child.meta)
        kv = {INFO_KEY: child.info.encode()}
        for e in moved:
            child.entries[e.version] = e
            child._track_reqid(e)
            kv[LOG_KEY_PREFIX + e.version.key()] = e.encode()
        t.omap_setkeys(child.cid, child.meta, kv)
        if moved:
            for e in moved:
                del self.entries[e.version]
            t.touch(self.cid, self.meta)
            t.omap_rmkeys(self.cid, self.meta, [
                LOG_KEY_PREFIX + e.version.key() for e in moved
            ])
        t.omap_setkeys(self.cid, self.meta, {INFO_KEY: self.info.encode()})

    def merge_from(self, t: Transaction, child: "PGLog") -> None:
        """PG::merge_from twin (reference src/osd/PG.cc:563, called on
        pg_num shrink): the dissolving child pg's log folds into this
        (target) log.  Entries move wholesale; version bounds take the
        elementwise max so neither side's completeness claim widens —
        a peer whose state predates the merged tail must backfill,
        matching the reference's conservative stance on merge (it
        forces backfill when either side's history is short).  The
        child's on-disk meta dies with its collection in the same
        transaction (caller removes it).

        Version keys can COLLIDE across the two logs: child and target
        ran independent per-PG version counters, so the same
        (epoch, version) may name different ops in each.  Folding a
        colliding child entry in directly would silently overwrite the
        target's entry and its omap record — losing a log entry AND
        its reqid dedup vouch.  On collision, the child's entries are
        rewritten into a disjoint version range just past both logs'
        heads (order preserved, reqids intact); the rewritten versions
        only feed peering deltas and dup detection, and the post-merge
        reconcile pass (merge_pending) re-verifies objects by their
        stored attrs, so authority is unaffected — the reference's
        don't-trust-merged-logs stance at entry granularity."""
        t.touch(self.cid, self.meta)
        kv: dict[str, bytes] = {}
        child_entries = [child.entries[v] for v in sorted(child.entries)]
        if any(e.version in self.entries for e in child_entries):
            base = max(self.info.last_update, child.info.last_update)
            remapped = []
            for i, e in enumerate(child_entries):
                nv = eversion_t(base.epoch, base.version + 1 + i)
                remapped.append(pg_log_entry_t(
                    e.op, e.oid, nv, e.prior_version, e.reqid))
            child_entries = remapped
        for e in child_entries:
            self.entries[e.version] = e
            self._track_reqid(e)
            kv[LOG_KEY_PREFIX + e.version.key()] = e.encode()
        if child_entries and child_entries[-1].version > self.info.last_update:
            self.info.last_update = child_entries[-1].version
        if child.info.last_update > self.info.last_update:
            self.info.last_update = child.info.last_update
        if child.info.log_tail > self.info.log_tail:
            self.info.log_tail = child.info.log_tail
        kv[INFO_KEY] = self.info.encode()
        t.omap_setkeys(self.cid, self.meta, kv)

    # -- persistence ---------------------------------------------------

    def load(self, store: ObjectStore) -> None:
        if not store.collection_exists(self.cid) or not store.exists(
            self.cid, self.meta
        ):
            return
        omap = store.omap_get(self.cid, self.meta)
        if INFO_KEY in omap:
            self.info = pg_info_t.decode(omap[INFO_KEY])
        if FLOOR_KEY in omap:
            try:
                ep, _, ver = omap[FLOOR_KEY].decode().partition(".")
                self.contig_floor = eversion_t(int(ep), int(ver))
            except ValueError:
                self.contig_floor = ZERO  # unreadable: trust nothing
        self.entries = {}
        for key, raw in omap.items():
            if key.startswith(LOG_KEY_PREFIX):
                e = pg_log_entry_t.decode(raw)
                self.entries[e.version] = e
        for v in sorted(self.entries):
            self._track_reqid(self.entries[v])

    # -- peering math --------------------------------------------------

    def entries_after(self, v: eversion_t) -> list[pg_log_entry_t]:
        return [self.entries[k] for k in sorted(self.entries) if k > v]

    def covers(self, v: eversion_t) -> bool:
        """True when the log can produce an exact delta from state
        ``v`` (v >= log_tail)."""
        return v >= self.info.log_tail

    def missing_from(self, peer_last_update: eversion_t) -> MissingSet | None:
        """Missing set for a peer at ``peer_last_update``; None means
        the log was trimmed past it and backfill is required
        (PGLog::proc_replica_log semantics, simplified: no divergent
        branches because the primary serializes all writes)."""
        if peer_last_update == self.info.last_update:
            return MissingSet()
        if not self.covers(peer_last_update):
            return None
        missing = MissingSet()
        latest: dict[str, pg_log_entry_t] = {}
        first: dict[str, pg_log_entry_t] = {}
        for e in self.entries_after(peer_last_update):
            latest[e.oid] = e
            first.setdefault(e.oid, e)
        for oid, e in latest.items():
            if e.op == DELETE:
                # deletion replays as a delete during recovery
                missing.add(oid, e.version)
            else:
                # ``have`` = the version the peer actually holds: the
                # prior_version of the FIRST entry past its last_update
                # (later entries' prior_versions are intermediates the
                # peer never saw)
                missing.add(oid, e.version, first[oid].prior_version)
        return missing
