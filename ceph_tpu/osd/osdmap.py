"""Cluster map and the pg -> up/acting placement pipeline.

Behavioral twin of the reference OSDMap mapping path
(src/osd/OSDMap.cc:2670-2971): CRUSH raw placement, upmap exception
tables (explicit ``pg_upmap``, item swaps ``pg_upmap_items``, primary
pins ``pg_upmap_primaries``), down/dne filtering with EC positional
holes, hashed primary-affinity rejection, and pg_temp/primary_temp
recovery overrides — composed exactly as ``_pg_to_up_acting_osds``
(OSDMap.cc:2923-2971) does.

This is the scalar host pipeline; the batched whole-cluster remap
(ParallelPGMapper's job, src/osd/OSDMapMapping.h:18-114) runs on TPU via
ceph_tpu.osd.remap.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ceph_tpu.crush.mapper import crush_do_rule
from ceph_tpu.crush.types import CRUSH_ITEM_NONE, ChooseArg, CrushMap
from ceph_tpu.ops.hashing import crush_hash32_2
from ceph_tpu.osd.types import (
    CEPH_OSD_DEFAULT_PRIMARY_AFFINITY,
    CEPH_OSD_MAX_PRIMARY_AFFINITY,
    PgPool,
    pg_t,
)

CEPH_OSD_EXISTS = 1
CEPH_OSD_UP = 2
# fullness states, mon-committed from beacon statfs (the reference
# keeps these per-osd in the map too: CEPH_OSD_NEARFULL/.../FULL,
# src/mon/OSDMonitor.cc:669-671); they ride the existing per-osd u8
# state byte on the wire
CEPH_OSD_NEARFULL = 4
CEPH_OSD_BACKFILLFULL = 8
CEPH_OSD_FULL = 16
CEPH_OSD_FULL_MASK = (
    CEPH_OSD_NEARFULL | CEPH_OSD_BACKFILLFULL | CEPH_OSD_FULL)


class _InvalidatingDict(dict):
    """An exception-table dict (pg_temp/upmap/...) that drops its
    OSDMap's mapping memo on every mutation — callers write these
    tables directly (mon _apply_op, balancer, tests), so method-level
    invalidation alone would miss them."""

    __slots__ = ("_owner",)

    def __init__(self, owner: "OSDMap", *a, **kw):
        super().__init__(*a, **kw)
        self._owner = owner

    def _inv(self) -> None:
        self._owner._mapping_cache = None

    def __setitem__(self, k, v):
        self._inv()
        super().__setitem__(k, v)

    def __delitem__(self, k):
        self._inv()
        super().__delitem__(k)

    def pop(self, *a):
        self._inv()
        return super().pop(*a)

    def popitem(self):
        self._inv()
        return super().popitem()

    def clear(self):
        self._inv()
        super().clear()

    def update(self, *a, **kw):
        self._inv()
        super().update(*a, **kw)

    def setdefault(self, k, d=None):
        if k not in self:
            self._inv()
        return super().setdefault(k, d)


class _InvalidatingList(list):
    """osd_state/osd_weight/affinity twin of :class:`_InvalidatingDict`
    — index writes like ``om.osd_state[o] = 0`` must drop the memo."""

    _owner: "OSDMap"

    def _inv(self) -> None:
        self._owner._mapping_cache = None

    def __setitem__(self, i, v):
        self._inv()
        super().__setitem__(i, v)

    def __delitem__(self, i):
        self._inv()
        super().__delitem__(i)

    def __iadd__(self, other):
        self._inv()
        return super().__iadd__(other)

    def append(self, v):
        self._inv()
        super().append(v)

    def extend(self, it):
        self._inv()
        super().extend(it)

    def insert(self, i, v):
        self._inv()
        super().insert(i, v)

    def pop(self, i=-1):
        self._inv()
        return super().pop(i)

    def remove(self, v):
        self._inv()
        super().remove(v)

    def clear(self):
        self._inv()
        super().clear()


def _wrap_list(owner: "OSDMap", cur: list) -> "_InvalidatingList":
    out = _InvalidatingList(cur)
    out._owner = owner
    return out


@dataclass
class OSDMap:
    """Mutable cluster map (an epoch's worth of state).

    ``osd_weight`` is the *out* weight (16.16; 0 = out, 0x10000 = in) —
    distinct from CRUSH bucket weights, it drives probabilistic
    rejection inside CRUSH (mapper.c is_out) and upmap validity.
    """

    crush: CrushMap
    epoch: int = 1
    max_osd: int = 0
    osd_state: list[int] = field(default_factory=list)
    osd_weight: list[int] = field(default_factory=list)
    osd_primary_affinity: list[int] | None = None
    pools: dict[int, PgPool] = field(default_factory=dict)
    # exception tables, all keyed by *folded* pg (raw_pg_to_pg applied):
    pg_upmap: dict[pg_t, list[int]] = field(default_factory=dict)
    pg_upmap_items: dict[pg_t, list[tuple[int, int]]] = field(default_factory=dict)
    pg_upmap_primaries: dict[pg_t, int] = field(default_factory=dict)
    pg_temp: dict[pg_t, list[int]] = field(default_factory=dict)
    primary_temp: dict[pg_t, int] = field(default_factory=dict)
    erasure_code_profiles: dict[str, dict[str, str]] = field(default_factory=dict)
    choose_args: dict[int, ChooseArg] | None = None
    # entity addresses (reference OSDMap osd_addrs): osd -> (host, port)
    osd_addrs: dict[int, tuple[str, int]] = field(default_factory=dict)
    # pool id -> name (reference OSDMap pool_name map)
    pool_names: dict[int, str] = field(default_factory=dict)
    # per-epoch memo of pg_to_up_acting_osds (see its docstring);
    # (epoch, {(pg, folded): (up, upp, acting, actp)}) — never encoded
    _mapping_cache: tuple | None = field(
        default=None, repr=False, compare=False)

    def __post_init__(self) -> None:
        # exception tables invalidate the mapping memo on direct writes
        for name in ("pg_upmap", "pg_upmap_items", "pg_upmap_primaries",
                     "pg_temp", "primary_temp"):
            cur = getattr(self, name)
            if not isinstance(cur, _InvalidatingDict):
                setattr(self, name, _InvalidatingDict(self, cur))
        for name in ("osd_state", "osd_weight", "osd_primary_affinity"):
            cur = getattr(self, name)
            if isinstance(cur, list) and not isinstance(
                    cur, _InvalidatingList):
                setattr(self, name, _wrap_list(self, cur))

    def invalidate_mapping_cache(self) -> None:
        """Drop the per-epoch mapping memo.  Mutator methods and the
        exception-table dicts call this; remaining direct-field writes
        (osd_weight[i] in mon _apply_op / apply_incremental, CRUSH
        structural edits via builder) are covered by the epoch bump
        that lands with every committed mutation — call this by hand
        when mutating those outside a map commit."""
        self._mapping_cache = None

    def lookup_pg_pool_name(self, name: str) -> int:
        for pid, n in self.pool_names.items():
            if n == name:
                return pid
        return -1

    # -- osd state ---------------------------------------------------

    def set_max_osd(self, n: int) -> None:
        self.max_osd = n
        self.osd_state += [0] * (n - len(self.osd_state))
        self.osd_weight += [0] * (n - len(self.osd_weight))
        if self.osd_primary_affinity is not None:
            self.osd_primary_affinity += [CEPH_OSD_DEFAULT_PRIMARY_AFFINITY] * (
                n - len(self.osd_primary_affinity)
            )
        del self.osd_state[n:]
        del self.osd_weight[n:]

    def new_osd(self, osd: int, weight: int = 0x10000, up: bool = True) -> None:
        self.invalidate_mapping_cache()
        if osd >= self.max_osd:
            self.set_max_osd(osd + 1)
        self.osd_state[osd] = CEPH_OSD_EXISTS | (CEPH_OSD_UP if up else 0)
        self.osd_weight[osd] = weight

    def exists(self, osd: int) -> bool:
        return (
            0 <= osd < self.max_osd
            and bool(self.osd_state[osd] & CEPH_OSD_EXISTS)
        )

    def is_up(self, osd: int) -> bool:
        return self.exists(osd) and bool(self.osd_state[osd] & CEPH_OSD_UP)

    def is_down(self, osd: int) -> bool:
        return not self.is_up(osd)

    def is_out(self, osd: int) -> bool:
        return not self.exists(osd) or self.osd_weight[osd] == 0

    def is_full(self, osd: int) -> bool:
        return self.exists(osd) and bool(
            self.osd_state[osd] & CEPH_OSD_FULL)

    def is_backfillfull(self, osd: int) -> bool:
        # FULL implies backfillfull (ratios are ordered)
        return self.exists(osd) and bool(
            self.osd_state[osd] & (CEPH_OSD_BACKFILLFULL | CEPH_OSD_FULL))

    def is_nearfull(self, osd: int) -> bool:
        return self.exists(osd) and bool(
            self.osd_state[osd] & CEPH_OSD_FULL_MASK)

    def mark_down(self, osd: int) -> None:
        self.invalidate_mapping_cache()
        self.osd_state[osd] &= ~CEPH_OSD_UP

    def mark_up(self, osd: int) -> None:
        self.invalidate_mapping_cache()
        self.osd_state[osd] |= CEPH_OSD_UP | CEPH_OSD_EXISTS

    def mark_out(self, osd: int) -> None:
        self.invalidate_mapping_cache()
        self.osd_weight[osd] = 0

    def set_primary_affinity(self, osd: int, aff: int) -> None:
        self.invalidate_mapping_cache()
        if self.osd_primary_affinity is None:
            self.osd_primary_affinity = _wrap_list(self, [
                CEPH_OSD_DEFAULT_PRIMARY_AFFINITY
            ] * self.max_osd)
        self.osd_primary_affinity[osd] = aff

    def get_pg_pool(self, poolid: int) -> PgPool | None:
        return self.pools.get(poolid)

    # -- the pipeline (OSDMap.cc:2670-2971) --------------------------

    def _remove_nonexistent_osds(self, pool: PgPool, osds: list[int]) -> None:
        """OSDMap.cc:2646-2668: dne OSDs vanish (replicated) or become
        positional holes (EC)."""
        if pool.can_shift_osds():
            osds[:] = [o for o in osds if self.exists(o)]
        else:
            for i, o in enumerate(osds):
                if not self.exists(o):
                    osds[i] = CRUSH_ITEM_NONE

    def _pg_to_raw_osds(self, pool: PgPool, pg: pg_t) -> tuple[list[int], int]:
        """OSDMap.cc:2670-2688."""
        pps = pool.raw_pg_to_pps(pg)
        osds: list[int] = []
        if pool.crush_rule >= 0 and pool.crush_rule in self.crush.rules:
            osds = crush_do_rule(
                self.crush, pool.crush_rule, pps, pool.size,
                self.osd_weight, self.choose_args,
            )
        self._remove_nonexistent_osds(pool, osds)
        return osds, pps

    @staticmethod
    def _pick_primary(osds: list[int]) -> int:
        """OSDMap.cc:2690-2697: first non-hole."""
        for o in osds:
            if o != CRUSH_ITEM_NONE:
                return o
        return -1

    def _upmap_target_invalid(self, osd: int) -> bool:
        """A target is unusable if it is marked out or an invalid id."""
        return not (
            osd != CRUSH_ITEM_NONE
            and 0 <= osd < self.max_osd
            and self.osd_weight[osd] != 0
        )

    def _apply_upmap(self, pool: PgPool, raw_pg: pg_t, raw: list[int]) -> None:
        """OSDMap.cc:2699-2765."""
        pg = pool.raw_pg_to_pg(raw_pg)
        explicit = self.pg_upmap.get(pg)
        if explicit is not None:
            for osd in explicit:
                if (
                    osd != CRUSH_ITEM_NONE
                    and 0 <= osd < self.max_osd
                    and self.osd_weight[osd] == 0
                ):
                    return  # reject the whole explicit mapping
            raw[:] = list(explicit)
            # fall through: pg_upmap_items still applies
        for osd_from, osd_to in self.pg_upmap_items.get(pg, []):
            exists = False
            pos = -1
            # skip only when osd_to is a *valid* id that is marked out
            # (OSDMap.cc:2736-2740); invalid ids are applied and later
            # filtered into holes by _raw_to_up_osds
            to_valid_but_out = (
                osd_to != CRUSH_ITEM_NONE
                and 0 <= osd_to < self.max_osd
                and self.osd_weight[osd_to] == 0
            )
            for i, osd in enumerate(raw):
                if osd == osd_to:
                    exists = True
                    break
                if osd == osd_from and pos < 0 and not to_valid_but_out:
                    pos = i
            if not exists and pos >= 0:
                raw[pos] = osd_to
        new_prim = self.pg_upmap_primaries.get(pg)
        if new_prim is not None and not self._upmap_target_invalid(new_prim):
            new_prim_idx = 0
            for i in range(1, len(raw)):  # start from 1 on purpose
                if raw[i] == new_prim:
                    new_prim_idx = i
                    break
            if new_prim_idx > 0:
                raw[new_prim_idx] = raw[0]
                raw[0] = new_prim

    def _raw_to_up_osds(self, pool: PgPool, raw: list[int]) -> list[int]:
        """OSDMap.cc:2767-2791: drop (replicated) or hole-out (EC) the
        down/dne members."""
        if pool.can_shift_osds():
            return [o for o in raw if self.exists(o) and not self.is_down(o)]
        return [
            CRUSH_ITEM_NONE if (not self.exists(o) or self.is_down(o)) else o
            for o in raw
        ]

    def _apply_primary_affinity(
        self, seed: int, pool: PgPool, osds: list[int], primary: int
    ) -> int:
        """OSDMap.cc:2793-2846: hashed proportional rejection so an OSD
        with affinity a primaries only a/0x10000 of its PGs."""
        aff = self.osd_primary_affinity
        if aff is None:
            return primary
        if not any(
            o != CRUSH_ITEM_NONE and aff[o] != CEPH_OSD_DEFAULT_PRIMARY_AFFINITY
            for o in osds
        ):
            return primary
        pos = -1
        for i, o in enumerate(osds):
            if o == CRUSH_ITEM_NONE:
                continue
            a = aff[o]
            if a < CEPH_OSD_MAX_PRIMARY_AFFINITY and (
                int(crush_hash32_2(seed, o)) >> 16
            ) >= a:
                if pos < 0:
                    pos = i  # fallback, keep looking
            else:
                pos = i
                break
        if pos < 0:
            return primary
        primary = osds[pos]
        if pool.can_shift_osds() and pos > 0:
            # move the new primary to the front
            for i in range(pos, 0, -1):
                osds[i] = osds[i - 1]
            osds[0] = primary
        return primary

    def _get_temp_osds(self, pool: PgPool, raw_pg: pg_t) -> tuple[list[int], int]:
        """OSDMap.cc:2848-2881: recovery-time acting-set overrides."""
        pg = pool.raw_pg_to_pg(raw_pg)
        temp_pg: list[int] = []
        for o in self.pg_temp.get(pg, []):
            if not self.exists(o) or self.is_down(o):
                if pool.can_shift_osds():
                    continue
                temp_pg.append(CRUSH_ITEM_NONE)
            else:
                temp_pg.append(o)
        temp_primary = self.primary_temp.get(pg, -1)
        if temp_primary == -1 and temp_pg:
            temp_primary = self._pick_primary(temp_pg)
        return temp_pg, temp_primary

    # -- public queries ----------------------------------------------

    def pg_to_raw_osds(self, pg: pg_t) -> tuple[list[int], int]:
        """(raw osds, primary) before upmap/filters (OSDMap.cc:2883)."""
        pool = self.get_pg_pool(pg.pool)
        if pool is None:
            return [], -1
        raw, _ = self._pg_to_raw_osds(pool, pg)
        return raw, self._pick_primary(raw)

    def pg_to_raw_up(self, pg: pg_t) -> tuple[list[int], int]:
        """OSDMap.cc:2909-2925."""
        pool = self.get_pg_pool(pg.pool)
        if pool is None:
            return [], -1
        raw, pps = self._pg_to_raw_osds(pool, pg)
        self._apply_upmap(pool, pg, raw)
        up = self._raw_to_up_osds(pool, raw)
        primary = self._pick_primary(raw)
        primary = self._apply_primary_affinity(pps, pool, up, primary)
        return up, primary

    def pg_to_up_acting_osds(
        self, pg: pg_t, folded: bool = False
    ) -> tuple[list[int], int, list[int], int]:
        """(up, up_primary, acting, acting_primary) —
        OSDMap.cc:2923-2971.  ``pg`` is a raw pg by default (the
        pipeline folds it, raw_pg_to_pg=true branch); with
        ``folded=True`` the ps must already be in [0, pg_num) and
        out-of-range returns empty.

        Results are memoized per epoch (the OSDMapMapping /
        ParallelPGMapper role, src/osd/OSDMapMapping.h:18): every
        daemon subsystem — peering, recovery, scrub, op admission —
        asks for the same mappings many times per epoch, and the
        scalar pipeline is pure given one epoch's state.  Mutators
        bump ``epoch`` (mon commit path) which naturally invalidates;
        in-place mutators below also drop the cache explicitly."""
        cache = self._mapping_cache
        if cache is None or cache[0] != self.epoch:
            cache = (self.epoch, {})
            self._mapping_cache = cache
        hit = cache[1].get((pg, folded))
        if hit is not None:
            up, up_primary, acting, acting_primary = hit
            return list(up), up_primary, list(acting), acting_primary
        pool = self.get_pg_pool(pg.pool)
        if pool is None or (folded and pg.ps >= pool.pg_num):
            return [], -1, [], -1
        acting, acting_primary = self._get_temp_osds(pool, pg)
        raw, pps = self._pg_to_raw_osds(pool, pg)
        self._apply_upmap(pool, pg, raw)
        up = self._raw_to_up_osds(pool, raw)
        up_primary = self._pick_primary(up)
        up_primary = self._apply_primary_affinity(pps, pool, up, up_primary)
        if not acting:
            acting = list(up)
            if acting_primary == -1:
                acting_primary = up_primary
        cache[1][(pg, folded)] = (
            tuple(up), up_primary, tuple(acting), acting_primary)
        return up, up_primary, acting, acting_primary

    def pg_is_ec(self, pg: pg_t) -> bool:
        pool = self.get_pg_pool(pg.pool)
        return pool is not None and pool.is_erasure()
