"""Shared PG-layer constants and helpers.

Split out of the daemon module so the PGBackend seams — EC backend
(ceph_tpu/osd/ec_backend.py), recovery (recovery.py), scrub
(scrubber.py), cache tiering (tiering.py) — can live in their own
files the way the reference splits PGBackend.h / ECBackend.cc /
PrimaryLogPG.cc / scrubber/ without import cycles.  Everything here is
re-exported by ceph_tpu.osd.daemon for compatibility.
"""

from __future__ import annotations

import asyncio
import errno

from ceph_tpu.ops.hashing import ceph_str_hash_rjenkins
from ceph_tpu.osd.pglog import ZERO, eversion_t
from ceph_tpu.osd.types import PgPool, pg_t

NO_SHARD = -1
STRIPE_UNIT = 4096  # logical bytes per data chunk per stripe
SUBOP_TIMEOUT = 30.0

SIZE_ATTR = "_size"
HINFO_ATTR = "hinfo"
VERSION_ATTR = "_v"  # object_info version (oi attr analogue)
USER_XATTR_PREFIX = "u_"  # client xattrs, namespaced off internal attrs

#: snap id of the per-shard ROLLBACK SIDECAR object (the reference
#: ECTransaction's roll-backward info): every versioned EC shard
#: overwrite first clones the pre-write state here, so a partial
#: fan-out can RESTORE a member to the previous version instead of
#: wedging the pg.  Far above any real snap id, below NOSNAP, and
#: within int64 (durable stores encode ghobject snaps as i64).
RB_SNAP = 0x7FFFFFFFFFFFFF00

ECConnErrors = (ConnectionError, asyncio.TimeoutError)


def _read_extents(store, c, o, extents) -> bytes:
    """Serve a multi-run ranged read from ONE covering store read:
    checksummed engines (BlockStore) verify each blob once instead of
    once per run — CLAY sub-chunk repairs issue many runs per chunk."""
    lo = min(eo for eo, _ln in extents)
    hi = max(eo + ln for eo, ln in extents)
    span = bytes(store.read(c, o, lo, hi - lo))
    # per-run slices clamp at the object size exactly like the
    # individual reads they replace (no padding)
    return b"".join(span[eo - lo : eo - lo + ln] for eo, ln in extents)


class ECFetchError(Exception):
    """A version-consistent EC fetch could not complete."""

    def __init__(self, eno: int):
        super().__init__(errno.errorcode.get(eno, str(eno)))
        self.errno = eno


def _v_bytes(v: eversion_t) -> bytes:
    return v.key().encode()


def _v_parse(raw: bytes | None) -> eversion_t:
    if not raw:
        return ZERO
    e, v = raw.decode().split(".")
    return eversion_t(int(e), int(v))


def object_to_pg(pool: PgPool, oid: str) -> pg_t:
    """object_locator_to_pg (src/osd/osd_types.cc): name hash -> raw pg
    (the mapping pipeline folds it into pg_num)."""
    return pg_t(pool.id, int(ceph_str_hash_rjenkins(oid)))
