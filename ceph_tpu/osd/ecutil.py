"""EC <-> OSD glue: stripe math, batched stripe encode/decode, HashInfo.

Behavioral twin of reference src/osd/ECUtil.{h,cc}:

- :class:`StripeInfo`  = ``ECUtil::stripe_info_t`` (ECUtil.h:27-81);
- :func:`encode`       = ``ECUtil::encode`` (ECUtil.cc:123-162);
- :func:`decode_concat`= ``ECUtil::decode`` concat form (ECUtil.cc:12-48);
- :func:`decode_shards`= ``ECUtil::decode`` per-target-shard form with
  CLAY sub-chunk minimums honored (ECUtil.cc:50-121);
- :class:`HashInfo`    = ``ECUtil::HashInfo`` cumulative per-shard
  crc32c chains (ECUtil.cc:164-248).

TPU-first difference: where the reference loops ``encode``/``decode``
per stripe_width slice, matrix codes here assemble the whole multi-
stripe payload into one row-space operand and run ONE GF matmul (on
device above the plugin's batch threshold).  Shard layouts are
bit-identical to the reference's per-stripe loop because shard i's
payload is simply the concatenation of stripe-chunk i over stripes.
"""

from __future__ import annotations

import errno
from typing import Mapping

import numpy as np

from ceph_tpu.ec.interface import ECError, ErasureCodeInterface
from ceph_tpu.ec.plugins.matrix_base import MatrixErasureCode
from ceph_tpu.native import crc32c


class StripeInfo:
    """stripe_info_t (ECUtil.h:27-81): stripe_width = k * chunk_size."""

    def __init__(self, stripe_size: int, stripe_width: int):
        assert stripe_width % stripe_size == 0, (stripe_width, stripe_size)
        self.stripe_width = stripe_width
        self.chunk_size = stripe_width // stripe_size

    def logical_offset_is_stripe_aligned(self, logical: int) -> bool:
        return logical % self.stripe_width == 0

    def logical_to_prev_chunk_offset(self, offset: int) -> int:
        return (offset // self.stripe_width) * self.chunk_size

    def logical_to_next_chunk_offset(self, offset: int) -> int:
        return (
            (offset + self.stripe_width - 1) // self.stripe_width
        ) * self.chunk_size

    def logical_to_prev_stripe_offset(self, offset: int) -> int:
        return offset - (offset % self.stripe_width)

    def logical_to_next_stripe_offset(self, offset: int) -> int:
        rem = offset % self.stripe_width
        return offset + self.stripe_width - rem if rem else offset

    def aligned_logical_offset_to_chunk_offset(self, offset: int) -> int:
        assert offset % self.stripe_width == 0
        return (offset // self.stripe_width) * self.chunk_size

    def aligned_chunk_offset_to_logical_offset(self, offset: int) -> int:
        assert offset % self.chunk_size == 0
        return (offset // self.chunk_size) * self.stripe_width

    def aligned_offset_len_to_chunk(self, off: int, length: int) -> tuple[int, int]:
        return (
            self.aligned_logical_offset_to_chunk_offset(off),
            self.aligned_logical_offset_to_chunk_offset(length),
        )

    def offset_len_to_stripe_bounds(self, off: int, length: int) -> tuple[int, int]:
        start = self.logical_to_prev_stripe_offset(off)
        return start, self.logical_to_next_stripe_offset((off - start) + length)


def bucket_lanes(
    nbytes: int, *, min_bucket: int, tile_cap: int
) -> list[tuple[int, int, int]]:
    """Stripe -> bucket shaping for the batched dispatch layers
    (parallel/decode_batcher, parallel/scrub_batcher): split a shard
    payload of ``nbytes`` into column lanes of ``(offset, width,
    bucket)`` where every bucket is drawn from the CLOSED power-of-two
    ladder [min_bucket .. tile_cap].  Payloads wider than ``tile_cap``
    split into full tile_cap lanes (GF matmuls and crc folds are both
    column-composable); narrower ones pad up to their pow2 bucket —
    so a prewarmed ladder covers every payload size an OSD can see."""
    if nbytes <= 0:
        return []
    if nbytes <= tile_cap:
        b = max(nbytes, min_bucket, 1)
        return [(0, nbytes, 1 << (b - 1).bit_length())]
    lanes = []
    for off in range(0, nbytes, tile_cap):
        lanes.append((off, min(tile_cap, nbytes - off), tile_cap))
    return lanes


def encode(
    sinfo: StripeInfo,
    ec_impl: ErasureCodeInterface,
    data: bytes | np.ndarray,
    want: set[int] | None = None,
) -> dict[int, np.ndarray]:
    """ECUtil::encode (ECUtil.cc:123-162): stripe-aligned logical bytes
    -> per-shard chunk payloads.  Matrix codes take the batched one-
    matmul path; other plugins fall back to the per-stripe loop."""
    arr = (
        np.asarray(data, dtype=np.uint8).reshape(-1)
        if isinstance(data, np.ndarray)
        else np.frombuffer(bytes(data), dtype=np.uint8)
    )
    sw, cs = sinfo.stripe_width, sinfo.chunk_size
    if arr.nbytes % sw:
        raise ECError(errno.EINVAL, f"logical size {arr.nbytes} not stripe aligned")
    n_chunks = ec_impl.get_chunk_count()
    k = ec_impl.get_data_chunk_count()
    if want is None:
        want = set(range(n_chunks))
    if arr.nbytes == 0:
        return {}
    ns = arr.nbytes // sw

    if isinstance(ec_impl, MatrixErasureCode):
        # shard i of the per-stripe loop == concat over stripes of
        # stripe-chunk i: a transpose of (ns, k, cs).  encode_chunks
        # operates on payloads of any superpacket multiple, so the
        # whole multi-stripe batch is one matmul.
        data_shards = np.ascontiguousarray(
            arr.reshape(ns, k, cs).transpose(1, 0, 2).reshape(k, ns * cs)
        )
        encoded: dict[int, np.ndarray] = {}
        for i in range(k):
            encoded[ec_impl.chunk_index(i)] = data_shards[i]
        for j in range(k, n_chunks):
            encoded[ec_impl.chunk_index(j)] = np.zeros(ns * cs, dtype=np.uint8)
        ec_impl.encode_chunks(set(range(n_chunks)), encoded)
        return {s: c for s, c in encoded.items() if s in want}

    out: dict[int, list] = {}
    for s in range(ns):
        encoded = ec_impl.encode(set(range(n_chunks)), arr[s * sw : (s + 1) * sw])
        for shard, chunk in encoded.items():
            assert len(chunk) == cs
            out.setdefault(shard, []).append(chunk)
    return {
        s: np.concatenate(bufs) for s, bufs in out.items() if s in want
    }


# -- async twins: the encode-farm data path ---------------------------------
#
# The OSD daemon's EC write/read/recovery paths call these instead of the
# sync functions; when an EncodeService with a live device mesh is
# attached (ceph_tpu/parallel/encode_service.py), the GF matmul of each
# op is coalesced with concurrent ops into one sharded farm dispatch —
# the production form of the ECSubWrite fan-out seam (reference
# src/osd/ECCommon.cc:749, SURVEY.md §2.9).  Every gate failure falls
# back to the sync single-device path, so behavior is identical.


def _farm_ready(service, ec_impl, nbytes: int) -> bool:
    return (
        service is not None
        and service.active()
        and nbytes >= service.min_bytes
        and isinstance(ec_impl, MatrixErasureCode)
        and ec_impl.rows_per_chunk == 1
    )


async def encode_async(
    sinfo: StripeInfo,
    ec_impl: ErasureCodeInterface,
    data: bytes | np.ndarray,
    want: set[int] | None = None,
    *,
    service=None,
) -> dict[int, np.ndarray]:
    """:func:`encode` routed through the encode farm when available."""
    arr = (
        np.asarray(data, dtype=np.uint8).reshape(-1)
        if isinstance(data, np.ndarray)
        else np.frombuffer(bytes(data), dtype=np.uint8)
    )
    if not _farm_ready(service, ec_impl, arr.nbytes):
        return encode(sinfo, ec_impl, arr, want)
    sw, cs = sinfo.stripe_width, sinfo.chunk_size
    if arr.nbytes % sw:
        raise ECError(errno.EINVAL, f"logical size {arr.nbytes} not stripe aligned")
    if arr.nbytes == 0:
        return {}
    k, m = ec_impl.get_data_chunk_count(), ec_impl.get_chunk_count() - ec_impl.get_data_chunk_count()
    ns = arr.nbytes // sw
    data_shards = np.ascontiguousarray(
        arr.reshape(ns, k, cs).transpose(1, 0, 2).reshape(k, ns * cs)
    )
    parity = await service.apply(ec_impl.coding_matrix, data_shards)
    out = {ec_impl.chunk_index(i): data_shards[i] for i in range(k)}
    for j in range(m):
        out[ec_impl.chunk_index(k + j)] = parity[j]
    if want is not None:
        out = {s: c for s, c in out.items() if s in want}
    return out


async def decode_concat_async(
    sinfo: StripeInfo,
    ec_impl: ErasureCodeInterface,
    to_decode: Mapping[int, np.ndarray],
    *,
    service=None,
) -> np.ndarray:
    """:func:`decode_concat` with farm-batched reconstruction."""
    rec = await _decode_chunks_async(sinfo, ec_impl, to_decode,
                                     range(ec_impl.get_data_chunk_count()),
                                     service=service)
    if rec is None:
        return decode_concat(sinfo, ec_impl, to_decode)
    cs, sw = sinfo.chunk_size, sinfo.stripe_width
    k = ec_impl.get_data_chunk_count()
    total = len(next(iter(to_decode.values())))
    ns = total // cs
    if total == 0:
        return np.zeros(0, dtype=np.uint8)
    stacked = np.stack([rec[c].reshape(ns, cs) for c in range(k)], axis=1)
    return np.ascontiguousarray(stacked.reshape(ns * sw))


async def decode_shards_async(
    sinfo: StripeInfo,
    ec_impl: ErasureCodeInterface,
    to_decode: Mapping[int, np.ndarray],
    need: set[int],
    *,
    packed_repair: bool = False,
    service=None,
    aggregator=None,
) -> dict[int, np.ndarray]:
    """:func:`decode_shards` with batched reconstruction (recovery
    path; falls back for sub-chunk/packed codes).

    ``aggregator`` (a parallel.decode_batcher.DecodeAggregator) takes
    precedence over the encode farm: per-object recovery decodes that
    share an erasure signature coalesce into fixed-shape batched
    launches — the repair-pipelining discipline — instead of one farm
    matmul per object."""
    if packed_repair or (
        not isinstance(ec_impl, MatrixErasureCode)
        or ec_impl.get_sub_chunk_count() != 1
    ):
        return decode_shards(sinfo, ec_impl, to_decode, need,
                             packed_repair=packed_repair)
    inv = {ec_impl.chunk_index(c): c for c in range(ec_impl.get_chunk_count())}
    want_chunks = [inv[s] for s in need]
    if aggregator is not None and aggregator.active() and to_decode:
        rec = await _decode_chunks_batched(
            ec_impl, to_decode, want_chunks, aggregator)
        if rec is not None:
            return {ec_impl.chunk_index(c): v for c, v in rec.items()}
    rec = await _decode_chunks_async(sinfo, ec_impl, to_decode,
                                     want_chunks, service=service)
    if rec is None:
        return decode_shards(sinfo, ec_impl, to_decode, need,
                             packed_repair=packed_repair)
    return {ec_impl.chunk_index(c): v for c, v in rec.items()}


async def _decode_chunks_batched(
    ec_impl, to_decode, want_chunks, aggregator
) -> dict[int, np.ndarray] | None:
    """decode_payloads with the matmul coalesced across concurrent
    recovery decodes by the aggregator; None = take another path."""
    want_chunks = list(want_chunks)
    erasures, survivors, need_rec, D = ec_impl.decode_plan(
        to_decode, want_chunks)
    rec_rows = None
    if need_rec:
        rows = ec_impl.decode_rows(to_decode, survivors)
        if rows.shape[1] == 0:
            return None
        rec_rows = await aggregator.apply(D, rows)
    return ec_impl.decode_assemble(
        to_decode, want_chunks, erasures, need_rec, rec_rows)


async def _decode_chunks_async(
    sinfo, ec_impl, to_decode, want_chunks, *, service
) -> dict[int, np.ndarray] | None:
    """decode_payloads (matrix_base) with the matmul on the farm;
    None = caller should take the sync path."""
    if not to_decode:
        return None
    nbytes = sum(np.asarray(v).size for v in to_decode.values())
    if not _farm_ready(service, ec_impl, nbytes):
        return None
    if not isinstance(ec_impl, MatrixErasureCode) or ec_impl.get_sub_chunk_count() != 1:
        return None
    # same plan/rows/assemble pieces as the sync decode_payloads — the
    # algebra stays single-homed in matrix_base; only the matmul moves
    # onto the farm
    want_chunks = list(want_chunks)
    erasures, survivors, need_rec, D = ec_impl.decode_plan(to_decode, want_chunks)
    rec_rows = None
    if need_rec:
        rec_rows = await service.apply(
            D, ec_impl.decode_rows(to_decode, survivors))
    return ec_impl.decode_assemble(
        to_decode, want_chunks, erasures, need_rec, rec_rows)


def decode_concat(
    sinfo: StripeInfo,
    ec_impl: ErasureCodeInterface,
    to_decode: Mapping[int, np.ndarray],
) -> np.ndarray:
    """ECUtil::decode concat form (ECUtil.cc:12-48): shard payloads ->
    logical byte stream (all stripes' data chunks in order)."""
    assert to_decode
    cs, sw = sinfo.chunk_size, sinfo.stripe_width
    sizes = {len(np.asarray(v).reshape(-1)) for v in to_decode.values()}
    assert len(sizes) == 1, sizes
    total = sizes.pop()
    assert total % cs == 0
    if total == 0:
        return np.zeros(0, dtype=np.uint8)
    ns = total // cs
    k = ec_impl.get_data_chunk_count()

    if isinstance(ec_impl, MatrixErasureCode):
        chunks = ec_impl.decode_payloads(to_decode, range(k))
        # stripe s's logical bytes = concat of chunk 0..k-1 at stripe s
        stacked = np.stack([chunks[c].reshape(ns, cs) for c in range(k)], axis=1)
        return np.ascontiguousarray(stacked.reshape(ns * sw))

    outs = []
    for s in range(ns):
        sub = {
            shard: np.asarray(v)[s * cs : (s + 1) * cs]
            for shard, v in to_decode.items()
        }
        outs.append(ec_impl.decode_concat(sub))
    return np.concatenate(outs)


def decode_shards(
    sinfo: StripeInfo,
    ec_impl: ErasureCodeInterface,
    to_decode: Mapping[int, np.ndarray],
    need: set[int],
    *,
    packed_repair: bool = False,
) -> dict[int, np.ndarray]:
    """ECUtil::decode per-target-shard form (ECUtil.cc:50-121): rebuild
    full shard payloads for ``need`` (shard ids).  This is the recovery
    path.

    ``packed_repair`` declares the payload layout: True means each
    helper payload is the stripe-major concatenation of
    minimum_to_decode's sub-chunk runs (the regenerating-repair ranged
    read); False means full chunks.  The two layouts can be the same
    length (e.g. 2 stripes x half-chunk runs == 1 full chunk), so the
    caller must say which it read — guessing here silently corrupts
    the rebuilt shard."""
    assert to_decode
    cs = sinfo.chunk_size
    for v in to_decode.values():
        if len(np.asarray(v).reshape(-1)) == 0:
            return {s: np.zeros(0, dtype=np.uint8) for s in need}

    if (
        isinstance(ec_impl, MatrixErasureCode)
        and ec_impl.get_sub_chunk_count() == 1
    ):
        inv = {ec_impl.chunk_index(c): c for c in range(ec_impl.get_chunk_count())}
        chunks = ec_impl.decode_payloads(to_decode, [inv[s] for s in need])
        return {ec_impl.chunk_index(c): v for c, v in chunks.items()}

    first_len = len(np.asarray(next(iter(to_decode.values()))).reshape(-1))
    if packed_repair:
        avail = set(to_decode)
        minimum = ec_impl.minimum_to_decode(need, avail)
        sub_chunk = cs // ec_impl.get_sub_chunk_count()
        first_min = next(iter(minimum))
        per_chunk = sub_chunk * sum(c for _, c in minimum[first_min])
    else:
        per_chunk = cs
    chunks_count = first_len // per_chunk

    out: dict[int, list[np.ndarray]] = {s: [] for s in need}
    for i in range(chunks_count):
        piece = {
            shard: np.asarray(v)[i * per_chunk : (i + 1) * per_chunk]
            for shard, v in to_decode.items()
        }
        decoded = ec_impl.decode(need, piece, cs)
        for s in need:
            assert len(decoded[s]) == cs
            out[s].append(decoded[s])
    return {s: np.concatenate(bufs) for s, bufs in out.items()}


class HashInfo:
    """Cumulative per-shard crc32c chains stored as an object xattr
    (reference ECUtil.cc:164-248, hinfo_key).  Seeds start at -1 and
    each append chains the new chunk bytes onto the prior crc."""

    def __init__(self, num_chunks: int = 0):
        self.total_chunk_size = 0
        self.cumulative_shard_hashes: list[int] = [0xFFFFFFFF] * num_chunks
        self.projected_total_chunk_size = 0

    def has_chunk_hash(self) -> bool:
        return bool(self.cumulative_shard_hashes)

    def append(self, old_size: int, to_append: Mapping[int, np.ndarray]) -> None:
        assert old_size == self.total_chunk_size, (old_size, self.total_chunk_size)
        if not to_append:
            return
        size = len(next(iter(to_append.values())))
        if self.has_chunk_hash():
            assert len(to_append) == len(self.cumulative_shard_hashes)
            for shard, buf in to_append.items():
                assert len(buf) == size
                self.cumulative_shard_hashes[shard] = crc32c(
                    buf, self.cumulative_shard_hashes[shard]
                )
        self.total_chunk_size += size

    def clear(self) -> None:
        self.total_chunk_size = 0
        self.cumulative_shard_hashes = [0xFFFFFFFF] * len(
            self.cumulative_shard_hashes
        )

    def get_chunk_hash(self, shard: int) -> int:
        assert shard < len(self.cumulative_shard_hashes)
        return self.cumulative_shard_hashes[shard]

    def get_total_chunk_size(self) -> int:
        return self.total_chunk_size

    # projected size tracking for in-flight ops (ECUtil.h:105-140)
    def get_projected_total_chunk_size(self) -> int:
        return self.projected_total_chunk_size

    def set_projected_total_logical_size(self, sinfo: StripeInfo, size: int) -> None:
        self.projected_total_chunk_size = sinfo.logical_to_next_chunk_offset(size)

    def set_total_chunk_size_clear_hash(self, size: int) -> None:
        self.cumulative_shard_hashes = []
        self.total_chunk_size = size

    # -- xattr serialization (versioned, little-endian; our own denc) --
    def to_bytes(self) -> bytes:
        import struct

        n = len(self.cumulative_shard_hashes)
        return struct.pack(
            f"<BQI{n}I", 1, self.total_chunk_size, n, *self.cumulative_shard_hashes
        )

    @classmethod
    def from_bytes(cls, raw: bytes) -> "HashInfo":
        import struct

        ver, total, n = struct.unpack_from("<BQI", raw)
        assert ver == 1
        hi = cls(n)
        hi.total_chunk_size = total
        hi.cumulative_shard_hashes = list(
            struct.unpack_from(f"<{n}I", raw, struct.calcsize("<BQI"))
        )
        return hi
