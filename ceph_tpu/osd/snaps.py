"""Snapshot model: SnapContext, SnapSet, clone resolution.

Behavioral twin of the reference's snap machinery (src/osd/osd_types.h
``SnapSet``/``SnapContext``, src/osd/SnapMapper.h:122, PrimaryLogPG's
make_writeable/find_object_context):

- a write carries a **SnapContext** (seq = newest snap id, snaps =
  existing snap ids newest-first);
- the primary compares snapc.seq against the object's **SnapSet** seq;
  if the context is newer, the head is **cloned** (copy-on-write) into
  a clone object whose id is the newest snap it covers, and the SnapSet
  (an xattr on the head) records the clone and the snaps it covers;
- a read at snap s resolves to the oldest clone whose id >= s, else the
  head (find_object_context semantics);
- removing a snap adds it to the pool's removed_snaps; the trimmer
  deletes clones once every snap they cover is removed (SnapMapper /
  snap trim worker role).

Self-managed snaps (librados selfmanaged_snap_*) and pool snaps
(``osd pool mksnap``) share this machinery — pool snaps simply use the
pool's own snap context, as in the reference (pg_pool_t::get_snap_context).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

#: CEPH_NOSNAP (src/include/rados.h): "the head object"
NOSNAP = 0xFFFFFFFFFFFFFFFE

#: xattr on the head object holding the encoded SnapSet (reference
#: SS_ATTR "snapset")
SS_ATTR = "ss"
#: xattr on a clone object listing the snaps it covers
SNAPS_ATTR = "snaps"
#: xattr marking a logically-deleted head that still anchors clones —
#: the reference's snapdir object role
WHITEOUT_ATTR = "whiteout"


@dataclass
class SnapContext:
    """seq + existing snap ids, newest first (reference SnapContext)."""

    seq: int = 0
    snaps: list[int] = field(default_factory=list)

    def valid(self) -> bool:
        return not self.snaps or (
            self.seq >= self.snaps[0]
            and all(a > b for a, b in zip(self.snaps, self.snaps[1:]))
        )


@dataclass
class CloneInfo:
    id: int                      # newest snap the clone covers
    snaps: list[int] = field(default_factory=list)  # covered, newest first
    size: int = 0


@dataclass
class SnapSet:
    """Per-object snapshot state (reference SnapSet), stored as the
    head's SS_ATTR xattr.  ``clones`` is ordered oldest -> newest."""

    seq: int = 0
    clones: list[CloneInfo] = field(default_factory=list)

    # -- codec ---------------------------------------------------------

    def to_bytes(self) -> bytes:
        return json.dumps({
            "seq": self.seq,
            "clones": [
                {"id": c.id, "snaps": c.snaps, "size": c.size}
                for c in self.clones
            ],
        }).encode()

    @classmethod
    def from_bytes(cls, raw: bytes | None) -> "SnapSet":
        if not raw:
            return cls()
        d = json.loads(raw)
        return cls(
            seq=d["seq"],
            clones=[CloneInfo(c["id"], list(c["snaps"]), c["size"])
                    for c in d["clones"]],
        )

    # -- write-side (make_writeable) -----------------------------------

    def needs_cow(self, snapc: SnapContext) -> bool:
        """True when a write under ``snapc`` must clone the head first
        (PrimaryLogPG::make_writeable condition)."""
        return bool(snapc.snaps) and snapc.seq > self.seq

    def make_clone(self, snapc: SnapContext, head_size: int) -> CloneInfo:
        """Record the COW clone for a write under ``snapc``; returns the
        new clone (id = newest snap covered)."""
        covered = [s for s in snapc.snaps if s > self.seq]
        assert covered, "needs_cow was False"
        clone = CloneInfo(id=covered[0], snaps=covered, size=head_size)
        self.clones.append(clone)
        self.seq = snapc.seq
        return clone

    def advance_seq(self, snapc: SnapContext) -> None:
        """A write under a newer context with no new snaps to cover
        (e.g. head did not exist): just move seq forward."""
        if snapc.seq > self.seq:
            self.seq = snapc.seq

    # -- read-side (find_object_context) -------------------------------

    def resolve(self, snapid: int) -> int | None:
        """Map a read snap id to the object that serves it: a clone id,
        NOSNAP for the head (oldest clone with id >= snapid), or None
        when no clone covers the snap — the object did not exist at
        that snap (find_object_context checks the covered interval)."""
        for c in self.clones:
            if c.id >= snapid:
                if c.snaps and snapid < c.snaps[-1]:
                    return None  # gap: object absent at that snap
                return c.id
        return NOSNAP

    def clone_by_id(self, cloneid: int) -> CloneInfo | None:
        for c in self.clones:
            if c.id == cloneid:
                return c
        return None

    def drop_clone(self, cloneid: int) -> None:
        self.clones = [c for c in self.clones if c.id != cloneid]


def encode_snaps(snaps: list[int]) -> bytes:
    return json.dumps(snaps).encode()


def decode_snaps(raw: bytes | None) -> list[int]:
    return json.loads(raw) if raw else []
