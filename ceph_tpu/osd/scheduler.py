"""Op scheduling: mClock QoS and weighted-priority queues.

Behavioral twin of the reference's pluggable op scheduler
(src/osd/scheduler/: OpScheduler seam, mClockScheduler.h:92 wrapping
the dmclock library src/dmclock/src/dmclock_server.h, and the legacy
WeightedPriorityQueue).  The dmclock algorithm is the dual-tag mClock
of the paper the reference vendored: each client class declares
(reservation, weight, limit); every op gets R/P/L tags

    R_i = max(now, R_{i-1} + cost/r)      (reservation)
    P_i = max(now, P_{i-1} + cost/w)      (proportional/weight)
    L_i = max(now, L_{i-1} + cost/l)      (limit)

and dequeue serves (1) the earliest R tag <= now — guaranteed
reservations first — else (2) the earliest P tag among clients whose L
tag does not exceed now (ready), adjusting P tags so idle clients do
not starve the active ones (dmclock's tag shifting).
"""

from __future__ import annotations

import heapq
import itertools
from dataclasses import dataclass, field


@dataclass
class ClientProfile:
    """QoS parameters of one client class (dmclock ClientInfo):
    reservation = guaranteed ops/s, weight = share of excess capacity,
    limit = max ops/s (0 = unlimited)."""

    reservation: float = 0.0
    weight: float = 1.0
    limit: float = 0.0


@dataclass
class _ClientState:
    profile: ClientProfile
    r_tag: float = 0.0
    p_tag: float = 0.0
    l_tag: float = 0.0
    queue: list = field(default_factory=list)  # FIFO of (item, cost)
    idle: bool = True


class MClockScheduler:
    """Single-queue dmclock server (PullReq model, one shard)."""

    def __init__(self) -> None:
        self._clients: dict[str, _ClientState] = {}

    def set_profile(self, client: str, profile: ClientProfile) -> None:
        st = self._clients.get(client)
        if st is None:
            self._clients[client] = _ClientState(profile)
        else:
            st.profile = profile

    def enqueue(self, client: str, item, cost: float = 1.0, now: float = 0.0) -> None:
        st = self._clients.setdefault(client, _ClientState(ClientProfile()))
        p = st.profile
        if st.idle:
            # idle -> active (dmclock idle handling): reservation/limit
            # tags restart at real `now` (no banked credit), but the
            # proportional tag lives in VIRTUAL time — re-enter at the
            # system's current virtual time (the smallest active P tag)
            # or a lone busy client would lock newcomers out for as
            # long as it had been running
            active_p = [
                c.p_tag for c in self._clients.values()
                if c is not st and not c.idle and c.queue
            ]
            st.r_tag = st.l_tag = now
            st.p_tag = max(now, min(active_p)) if active_p else now
            st.idle = False
        if not st.queue:
            if p.reservation > 0:
                st.r_tag = max(now, st.r_tag + cost / p.reservation)
            else:
                st.r_tag = float("inf")
            st.p_tag = max(now, st.p_tag + cost / max(p.weight, 1e-9))
            if p.limit > 0:
                st.l_tag = max(now, st.l_tag + cost / p.limit)
            else:
                st.l_tag = now
        st.queue.append((item, cost))

    def _advance(self, st: _ClientState, now: float) -> None:
        """After serving the head op, retag for the next queued op."""
        if not st.queue:
            return
        cost = st.queue[0][1]
        p = st.profile
        if p.reservation > 0:
            st.r_tag = max(now, st.r_tag + cost / p.reservation)
        else:
            st.r_tag = float("inf")
        st.p_tag = max(now, st.p_tag + cost / max(p.weight, 1e-9))
        if p.limit > 0:
            st.l_tag = max(now, st.l_tag + cost / p.limit)
        else:
            st.l_tag = now

    def dequeue(self, now: float):
        """Next (client, item) or None if nothing is ready (all queues
        empty, or every waiting client is limit-capped)."""
        best_r = None
        for name, st in self._clients.items():
            if st.queue and st.r_tag <= now:
                if best_r is None or st.r_tag < self._clients[best_r].r_tag:
                    best_r = name
        chosen = best_r
        if chosen is None:
            best_p = None
            for name, st in self._clients.items():
                if st.queue and st.l_tag <= now:
                    if best_p is None or st.p_tag < self._clients[best_p].p_tag:
                        best_p = name
            chosen = best_p
        if chosen is None:
            for st in self._clients.values():
                if not st.queue:
                    st.idle = True
            return None
        st = self._clients[chosen]
        item, _cost = st.queue.pop(0)
        self._advance(st, now)
        if not st.queue:
            st.idle = True
        return chosen, item

    def empty(self) -> bool:
        return all(not st.queue for st in self._clients.values())

    def __len__(self) -> int:
        return sum(len(st.queue) for st in self._clients.values())


class WeightedPriorityQueue:
    """The legacy WPQ scheduler (src/common/WeightedPriorityQueue.h):
    strict priorities above a cutoff, weighted round-robin below."""

    def __init__(self, cutoff: int = 64) -> None:
        self.cutoff = cutoff
        self._strict: list = []           # heap of (-prio, seq, item)
        self._weighted: dict[int, list] = {}
        self._rr_pos = 0
        self._seq = itertools.count()

    def enqueue(self, priority: int, item) -> None:
        if priority >= self.cutoff:
            heapq.heappush(self._strict, (-priority, next(self._seq), item))
        else:
            # weight-0 levels would never win a round-robin slot (and an
            # all-zero queue would have no slots at all): clamp to 1
            self._weighted.setdefault(max(priority, 1), []).append(item)

    def dequeue(self):
        if self._strict:
            return heapq.heappop(self._strict)[2]
        # weighted round robin: each priority level gets slots
        # proportional to its priority value
        levels = sorted(
            (p for p, q in self._weighted.items() if q), reverse=True
        )
        if not levels:
            return None
        total = sum(levels)
        pick = self._rr_pos % total
        self._rr_pos += 1
        acc = 0
        for p in levels:
            acc += p
            if pick < acc:
                return self._weighted[p].pop(0)
        raise AssertionError("pick < sum(levels) must select a level")

    def empty(self) -> bool:
        return not self._strict and all(
            not q for q in self._weighted.values()
        )
