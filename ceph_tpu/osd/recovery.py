"""Recovery + peering-lite: reservation-gated PG recovery passes,
object reconciliation, pushes, pg_query/pg_log exchange (the
src/osd/PeeringState.cc + RecoveryBackend seam), split out of the
daemon per the PGBackend seam layout."""

from __future__ import annotations

import asyncio
import logging
import time

import numpy as np

from ceph_tpu.crush.types import CRUSH_ITEM_NONE
from ceph_tpu.osd import ecutil
from ceph_tpu.osd.pglog import (
    DELETE,
    PGMETA_OID,
    ZERO,
    eversion_t,
    pg_log_entry_t,
)
from ceph_tpu.osd.types import PgPool, pg_t
from ceph_tpu.store import Transaction, ghobject_t

from ceph_tpu.msg.messages import (
    MBackfillReserve,
    MOSDECSubOpRead,
    MOSDECSubOpWrite,
    MOSDPGInfo,
    MOSDPGLog,
    MOSDPGLogAck,
    MOSDPGPush,
    MOSDPGPushReply,
    MOSDPGQuery,
)
from ceph_tpu.osd.pgutil import (
    NO_SHARD,
    RB_SNAP,
    SIZE_ATTR,
    SUBOP_TIMEOUT,
    VERSION_ATTR,
    _v_parse,
    object_to_pg,
)

log = logging.getLogger("ceph_tpu.osd")


class RecoveryMixin:
    """Peering + recovery + backfill reservations — mixed into
    OSDDaemon; state lives in the daemon's __init__."""

    # -- recovery ------------------------------------------------------

    async def _recover_all(self) -> None:
        """After a map change: for every PG this OSD leads, reconstruct
        missing shards/objects on the current acting set (the
        do_recovery -> recover_object path, §3.3).  Re-runs until a
        full pass has seen the newest map (epochs can land mid-pass).

        PGs run concurrently, but admission is reservation-gated
        (backfill_reservation.rst): each PG takes one of OUR
        osd_max_backfills local slots, then one remote slot on every
        acting peer (MBackfillReserve REQUEST/GRANT); a REJECT_TOOFULL
        releases everything and retries after
        osd_backfill_retry_interval, so cluster-wide concurrent
        backfill load per OSD stays bounded.

        A pass that leaves PGs unclean (a peer mid-restart, a dropped
        connection) re-runs even if no new map arrives — the
        reference's recovery_request_timer retry role.  Without it a
        transient error at the wrong moment parks the PG in peering
        forever (found by the interleaving fuzzer,
        tests/test_interleave_fuzz.py).  Retries back off
        EXPONENTIALLY (interval, 2x, 4x ... capped at 32x) and only
        re-run the still-unclean PGs: a fixed-cadence full re-pass
        saturated contended deployments — every OSD burning a
        pass-worth of CPU each second starved client I/O outright
        (bench config 5, 64 OSDs on few cores)."""
        retry_pgs: set[tuple[int, int]] | None = None  # None = all
        retry_epoch = -1  # epoch retry_pgs was scoped under
        backoff = max(self.conf["osd_backfill_retry_interval"], 0.05)
        max_backoff = backoff * 32
        while not self.stopping:
            done_epoch = self.epoch
            if retry_pgs is not None and done_epoch != retry_epoch:
                # a map landed during the BACKOFF SLEEP (the mid-pass
                # check below never sees it): the retry set was scoped
                # to the old epoch's unclean pgs, and running only
                # those would stamp them clean at the NEW epoch while
                # every other pg keeps its stale clean_epoch — since
                # map arrival spawns no task while this one runs, they
                # report active+peering forever (chaos-fuzz-found:
                # a deferred rollback made incomplete passes, and with
                # them this wedge, routine)
                retry_pgs = None
                backoff = max(
                    self.conf["osd_backfill_retry_interval"], 0.05)
            # GC remote grants whose requesting primary is gone — a
            # primary that died after GRANT can never send RELEASE
            self._sweep_remote_grants()
            try:
                om = self.osdmap
                work: list[tuple[PgPool, pg_t, list[int]]] = []
                scanned = 0
                for pid, pool in list(om.pools.items()):
                    for ps in range(pool.pg_num):
                        pg = pg_t(pid, ps)
                        scanned += 1
                        if scanned % 8 == 0:
                            # the scalar mapping sweep must not hold
                            # the event loop: handshakes/heartbeats
                            # starve and peers file false failures
                            # (bench config 5 post-mortem)
                            await asyncio.sleep(0)
                        _, _, acting, primary = om.pg_to_up_acting_osds(
                            pg, folded=True
                        )
                        if primary != self.id:
                            continue
                        if retry_pgs is not None and \
                                (pid, ps) not in retry_pgs:
                            continue
                        work.append((pool, pg, acting))
                if work:
                    # return_exceptions: one PG's crash must neither
                    # abort the pass (siblings would keep running
                    # DETACHED with reservations held) nor mask the
                    # others' completion
                    results = await asyncio.gather(*[
                        self._recover_pg_reserved(pool, pg, acting,
                                                  done_epoch)
                        for pool, pg, acting in work
                    ], return_exceptions=True)
                    for (_p, pg, _a), r in zip(work, results):
                        if isinstance(r, asyncio.CancelledError):
                            raise r
                        if isinstance(r, BaseException):
                            log.exception(
                                "osd.%d: recovery of %s crashed",
                                self.id, pg, exc_info=r)
                if self.epoch != done_epoch:
                    # a map landed mid-pass: full re-pass, fresh pacing
                    retry_pgs = None
                    backoff = max(
                        self.conf["osd_backfill_retry_interval"], 0.05)
                    continue
                incomplete = [
                    pg for _pool, pg, _a in work
                    if self._clean_epoch.get((pg.pool, pg.ps), -1)
                    < done_epoch
                ]
                if not incomplete:
                    return
                log.info(
                    "osd.%d: %d pgs unclean after pass; retrying in "
                    "%.2fs", self.id, len(incomplete), backoff)
                await asyncio.sleep(backoff)
                retry_pgs = {(pg.pool, pg.ps) for pg in incomplete}
                retry_epoch = done_epoch
                backoff = min(backoff * 2, max_backoff)
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("osd.%d: recovery pass failed", self.id)
                return

    async def _recover_pg_reserved(
        self, pool: PgPool, pg: pg_t, acting: list[int], pass_epoch: int,
    ) -> None:
        key = (pg.pool, pg.ps)
        peers = sorted({
            o for o in acting
            if o != CRUSH_ITEM_NONE and o != self.id
        })
        retry = self.conf["osd_backfill_retry_interval"]
        async with self.local_reserver.request(key, priority=1):
            self.recovery_stats["peak_local"] = max(
                self.recovery_stats["peak_local"],
                self.local_reserver.in_use)
            granted: list[int] = []
            try:
                while not self.stopping and self.epoch == pass_epoch:
                    if await self._reserve_remotes(pg, peers, granted):
                        break
                    # partial holds across the retry sleep invite
                    # cluster-wide deadlock (two primaries each camped
                    # on one of the other's replicas): drop everything
                    self.recovery_stats["reservation_rejects"] += 1
                    await self._release_remotes(pg, granted)
                    granted.clear()
                    # a TOOFULL rejecter may be full of exactly the
                    # logged deletes this pass would replay onto it:
                    # run the delete-replay OUTSIDE the reservation
                    # gate so the peer can dig itself out and GRANT
                    # the next round (fullness-chaos-found deadlock;
                    # reference recovery deletes are never
                    # reservation- or fullness-gated)
                    await self._recover_pg_deletes(pool, pg, acting)
                    await asyncio.sleep(retry)
                else:
                    return
                self._recovering_pgs.add(key)
                try:
                    ok = await self._recover_pg(pool, pg, acting)
                    if ok:
                        # MONOTONE: a pass verified under an older map
                        # must never rewind a newer verdict.  A queued
                        # background pass (_queue_pg_pass) can run for
                        # tens of seconds (sub-op timeouts) while the
                        # map-driven task completes a newer pass and
                        # EXITS believing everything clean; the stale
                        # completion landing afterwards knocked the pg
                        # back to active+peering with nothing left to
                        # re-run recovery — the silent soak-sweep wedge
                        self._clean_epoch[key] = max(
                            pass_epoch, self._clean_epoch.get(key, -1))
                        self.recovery_stats["pgs_recovered"] += 1
                finally:
                    self._recovering_pgs.discard(key)
            finally:
                await self._release_remotes(pg, granted)

    async def _reserve_remotes(
        self, pg: pg_t, peers: list[int], granted: list[int],
    ) -> bool:
        """GRANT from every acting peer, or False on REJECT_TOOFULL.

        A peer the MAP says is down is skipped — it can take no
        recovery load and no pushes will reach it.  A peer that is up
        but unreachable counts as a REJECT: it may come back mid-
        recovery and start absorbing pushes, so proceeding without its
        slot would unbound its inbound backfill load; the retry loop
        re-asks (either it answers, or it gets marked down — a new
        epoch — and the pass restarts without it).  Either way a
        best-effort RELEASE covers the race where the peer GRANTed but
        the reply missed our timeout — without it the replica's slot
        leaks until we restart."""
        for o in peers:
            tid = next(self._tids)
            try:
                rep = await self._sub_op(o, MBackfillReserve(
                    tid=tid, op=MBackfillReserve.REQUEST, pool=pg.pool,
                    ps=pg.ps, from_osd=self.id, priority=1,
                ), tid)
            except (OSError, asyncio.TimeoutError, ConnectionError):
                if not self.osdmap.is_up(o):
                    continue
                await self._release_remotes(pg, [o])
                return False
            if rep.op == MBackfillReserve.GRANT:
                granted.append(o)
            else:
                return False
        return True

    async def _release_remotes(self, pg: pg_t, granted: list[int]) -> None:
        for o in granted:
            try:
                conn = await self._osd_conn(o)
                await conn.send_message(MBackfillReserve(
                    tid=next(self._tids), op=MBackfillReserve.RELEASE,
                    pool=pg.pool, ps=pg.ps, from_osd=self.id,
                ))
            except (OSError, asyncio.TimeoutError, ConnectionError):
                continue

    def _sweep_remote_grants(self) -> None:
        """Release remote backfill GRANTs whose requesting primary can
        never send the RELEASE: the map says it is down, or the grant
        aged past osd_backfill_grant_timeout (a primary that died and
        was never reported, or whose RELEASE was lost).  Without the
        sweep a GRANT held for a dead reserver leaks the remote slot
        forever — with osd_max_backfills=1 that parks every other PG's
        backfill onto this osd behind a ghost."""
        timeout = self.conf["osd_backfill_grant_timeout"]
        now = time.monotonic()
        for key in list(self._remote_grants):
            held = self._remote_grants.get(key)
            if held is None:
                continue
            res, granted_at = held
            down = self.osdmap is not None and not self.osdmap.is_up(key[2])
            aged = timeout > 0 and (now - granted_at) > timeout
            if down or aged:
                self._remote_grants.pop(key, None)
                res.release()
                self.recovery_stats["grants_swept"] += 1
                log.info(
                    "osd.%d: swept backfill grant pg=%d.%d from osd.%d "
                    "(%s)", self.id, key[0], key[1], key[2],
                    "requester down" if down else "grant timed out")

    async def _grant_sweep(self) -> None:
        """Periodic reserver-death sweep — independent of this osd's
        own recovery passes (an IDLE replica must still reclaim slots
        leaked by a dead foreign primary)."""
        while not self.stopping:
            timeout = self.conf["osd_backfill_grant_timeout"]
            period = max(0.25, min(timeout / 4 if timeout > 0 else 15.0,
                                   15.0))
            try:
                await asyncio.sleep(period)
            except asyncio.CancelledError:
                return
            try:
                self._sweep_remote_grants()
            except Exception:
                log.exception("osd.%d: grant sweep failed", self.id)

    async def _handle_backfill_reserve(self, msg: MBackfillReserve) -> None:
        if msg.op == MBackfillReserve.REQUEST:
            key = (msg.pool, msg.ps, msg.from_osd)
            if (self._full_ratio()
                    >= self.conf["mon_osd_backfillfull_ratio"]):
                # backfillfull: absorbing a backfill would push this
                # store toward FULL (reference REJECT_TOOFULL path,
                # doc/dev/osd_internals/backfill_reservation.rst) —
                # the primary backs off and retries; log-based
                # recovery of existing objects is unaffected.  The
                # counter is the fullness-pressure scenario's live
                # proof that backfill actually paused here.
                self.perf.inc("backfill_reject_toofull")
                await msg.conn.send_message(MBackfillReserve(
                    tid=msg.tid, op=MBackfillReserve.REJECT_TOOFULL,
                    pool=msg.pool, ps=msg.ps, from_osd=self.id,
                ))
                return
            held = self._remote_grants.get(key)
            if held is not None:
                # the same primary asking AGAIN means it restarted (or
                # timed out our reply) after we GRANTed: the old hold
                # IS its slot.  Re-GRANT it with a fresh clock instead
                # of rejecting against our own stale hold — the
                # kill-backfiller-mid-transfer deadlock (a revived
                # primary could never re-reserve its own leaked slot).
                self._remote_grants[key] = (held[0], time.monotonic())
                op = MBackfillReserve.GRANT
            else:
                res = self.remote_reserver.try_request(key, msg.priority)
                if res is not None:
                    self._remote_grants[key] = (res, time.monotonic())
                    self.recovery_stats["peak_remote"] = max(
                        self.recovery_stats["peak_remote"],
                        self.remote_reserver.in_use)
                    op = MBackfillReserve.GRANT
                else:
                    op = MBackfillReserve.REJECT_TOOFULL
            await msg.conn.send_message(MBackfillReserve(
                tid=msg.tid, op=op, pool=msg.pool, ps=msg.ps,
                from_osd=self.id,
            ))
        elif msg.op == MBackfillReserve.RELEASE:
            held = self._remote_grants.pop(
                (msg.pool, msg.ps, msg.from_osd), None)
            if held is not None:
                held[0].release()
        else:  # GRANT / REJECT_TOOFULL reply to our REQUEST
            fut = self._waiters.get(msg.tid)
            if fut and not fut.done():
                fut.set_result(msg)

    def _load_backfill_cursor(self, myc, acting) -> str | None:
        """Last-backfill cursor persisted by an interrupted pass —
        valid only for the SAME interval (epoch + acting set); any map
        change voids it, because a member that blinked in between may
        have missed writes to objects below the cursor."""
        import json as _json

        lg = self._pg_log(myc)
        try:
            vals = self.store.omap_get_values(
                myc, lg.meta, ["backfill_cursor"])
        except (FileNotFoundError, OSError):
            return None
        raw = vals.get("backfill_cursor")
        if not raw:
            return None
        try:
            doc = _json.loads(raw)
        except ValueError:
            return None
        if (doc.get("acting") != list(acting)
                or doc.get("epoch") != self.epoch):
            return None
        return doc.get("oid")

    def _save_backfill_cursor(
        self, myc, acting, ordered_all, done, all_ok,
    ) -> None:
        """Persist the longest contiguous prefix of the sorted backfill
        worklist that is verified-done, so a retry of an INTERRUPTED
        pass (same interval) resumes past it instead of re-pushing
        every object from scratch; a COMPLETE pass clears it."""
        import json as _json

        lg = self._pg_log(myc)
        t = Transaction()
        self._ensure_coll(t, myc)
        t.touch(myc, lg.meta)
        cursor = None
        if not all_ok:
            for oid in ordered_all:
                if oid not in done:
                    break
                cursor = oid
        if cursor is None:
            t.omap_rmkeys(myc, lg.meta, ["backfill_cursor"])
        else:
            t.omap_setkeys(myc, lg.meta, {
                "backfill_cursor": _json.dumps({
                    "oid": cursor, "acting": list(acting),
                    "epoch": self.epoch,
                }).encode(),
            })
        self.store.queue_transaction(t)

    def _local_objects(self, pool, pg, shard) -> list[str]:
        c = self._shard_coll(pool, pg, shard)
        if not self.store.collection_exists(c):
            return []
        return sorted(
            {o.name for o in self.store.collection_list(c)} - {PGMETA_OID}
        )

    def _pg_members(
        self, pool: PgPool, acting: list[int]
    ) -> list[tuple[int, int]]:
        """(shard, osd) pairs of the acting set; replicated members all
        use NO_SHARD collections."""
        if pool.is_erasure():
            return [
                (s, o) for s, o in enumerate(acting) if o != CRUSH_ITEM_NONE
            ]
        return [(NO_SHARD, o) for o in acting if o != CRUSH_ITEM_NONE]

    async def _recover_pg_deletes(
        self, pool: PgPool, pg: pg_t, acting: list[int],
    ) -> None:
        """Replay logged deletes WITHOUT holding backfill
        reservations (the reference's recovery-delete semantics:
        MOSDPGRecoveryDelete flows while backfill waits, and deletes
        pass every fullness gate — they are how a peer digs itself
        out).  Found by the fullness-pressure chaos scenario: a
        member that missed a drain while out rejoins over the
        backfillfull ratio, every reservation to it is rejected
        TOOFULL, and without this pass the stale objects holding its
        space are never removed — recovery deadlocks on the very
        space it would free."""
        pairs = self._pg_members(pool, acting)
        if self.id not in [o for _, o in pairs]:
            return
        my_shard = next(s for s, o in pairs if o == self.id)
        lg = self._pg_log(self._shard_coll(pool, pg, my_shard))
        latest: dict[str, pg_log_entry_t] = {}
        for v in sorted(lg.entries):
            latest[lg.entries[v].oid] = lg.entries[v]
        for e in latest.values():
            if e.op != DELETE:
                continue
            try:
                await self._reconcile_object(pool, pg, pairs, e.oid)
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception(
                    "osd.%d: delete replay of %s/%s failed",
                    self.id, pg, e.oid)

    async def _recover_pg(self, pool: PgPool, pg: pg_t, acting: list[int]) -> bool:
        """Peering-lite + recovery for one PG this OSD leads.

        1. collect pg_info from every acting member (MOSDPGQuery);
        2. adopt log entries from any member ahead of us (we may have
           been the one that was down);
        3. scope the object set: exact per-peer missing sets when the
           log covers everyone (PGLog::proc_replica_log), full
           backfill over the union of object lists otherwise;
        4. reconcile each object to its newest version (reconstruct +
           MOSDPGPush / replayed delete);
        5. bring lagging members' logs current (MOSDPGLog).
        """
        pass_epoch = self.epoch
        pairs = self._pg_members(pool, acting)
        if self.id not in [o for _, o in pairs]:
            return True
        # prior-set (PastIntervals role): still-up members of previous
        # acting sets serve as extra data SOURCES — a fully-remapped PG
        # pulls from its old home
        prior = self._prior_pairs(pool, pg, pairs)
        my_shard = next(s for s, o in pairs if o == self.id)
        myc = self._shard_coll(pool, pg, my_shard)
        lg = self._pg_log(myc)

        peer_infos: dict[tuple[int, int], MOSDPGInfo] = {}
        for s, o in pairs:
            if o == self.id:
                continue
            try:
                peer_infos[(s, o)] = await self._pg_query(
                    pool, pg, s, o, since=lg.info.last_update
                )
            except (OSError, asyncio.TimeoutError, ConnectionError):
                continue  # unreachable; next map change retries

        # merge peers' witnessed interval chains into ours
        # (PastIntervals sharing via pg info): a member that joined in
        # a later interval learns the older homes it never saw
        import json as _json

        def _merge_chain(raw: bytes) -> bool:
            if not raw:
                return False
            try:
                chain = _json.loads(raw)
            except ValueError:
                return False
            hist = self._past_acting.setdefault((pg.pool, pg.ps), [])
            changed = False
            for a in chain:
                if a != acting and a not in hist:
                    hist.append(a)
                    del hist[:-16]
                    changed = True
            return changed

        merged = False
        for info in peer_infos.values():
            merged |= _merge_chain(getattr(info, "past_acting", b""))
        if merged:
            self._save_past_acting()
            prior = self._prior_pairs(pool, pg, pairs)

        pre_adopt_lu = lg.info.last_update
        # any participant still carrying a merge_pending marker means
        # listings are a cross-child superposition this pass must not
        # stray-reap from (see _merge_pending)
        merge_seen = self._merge_pending(myc, lg) or any(
            getattr(i, "merge_pending", False) for i in peer_infos.values()
        )
        ahead = [
            i for i in peer_infos.values()
            if i.last_update > lg.info.last_update
        ]
        gapped = False
        if ahead:
            best = max(ahead, key=lambda i: i.last_update)
            # a peer whose log_tail moved past our state means its
            # entries_after(our lu) delta has a hole: everything in the
            # trimmed range must come from backfill, and our own log
            # must admit the gap (set_tail) so covers() stays truthful
            gapped = best.log_tail > pre_adopt_lu
            t = Transaction()
            self._ensure_coll(t, myc)
            ents = [pg_log_entry_t.decode(raw) for raw in best.entries]
            if gapped:
                # adopt_tail (not set_tail+append) pins the contiguity
                # floor at pre_adopt_lu: if this backfill is
                # INTERRUPTED, the restart must re-take the backfill
                # path instead of trusting the adopted last_update —
                # set_tail+append made the adopted window look
                # contiguous and a restart silently lost the gap
                lg.adopt_tail(t, best.log_tail, ents)
            else:
                for e in ents:
                    if e.version > lg.info.last_update:
                        lg.append(t, e)
            self._pg_log_trim(t, lg)
            if not t.empty():
                self.store.queue_transaction(t)

        # scope; prior intervals force the backfill enumeration — the
        # data may live entirely on members our log knows nothing
        # about.  Our OWN contiguity gap forces it too: a primary
        # whose log missed a window cannot compute truthful missing
        # sets from it (it would silently skip the gap's oids).
        scope: set[str] | None = (
            None if (gapped or prior or lg.contig_floor is not None)
            else set())
        if scope is not None:
            for info in peer_infos.values():
                # a gapped peer's last_update overstates what it
                # holds: scope it from its contiguity floor instead
                miss = lg.missing_from(self._peer_effective_lu(info))
                if miss is None:
                    scope = None
                    break
                scope |= set(miss.items)
        log.debug(
            "osd.%d: pg %s scope=%s gapped=%s prior=%s floor=%s "
            "tail=%s lu=%s peers=%s",
            self.id, pg,
            "backfill" if scope is None else sorted(scope),
            gapped, prior, lg.contig_floor, lg.info.log_tail,
            lg.info.last_update,
            {o: (str(i.last_update), str(self._peer_effective_lu(i)))
             for (s, o), i in peer_infos.items()})
        if scope is not None:
            # members' self-audited missing sets, plus our own: a
            # log-current member can still be OBJECT-stale (entries
            # adopted/synced without data — _self_audit_missing), and
            # last_update scoping is blind to it
            for info in peer_infos.values():
                scope |= set(getattr(info, "missing", ()) or ())
            scope |= set(
                self._self_audit_missing(pool, pg, my_shard, lg))
        if ahead and scope is not None:
            # entries adopted above may name objects my own shard lacks
            for raw in max(ahead, key=lambda i: i.last_update).entries:
                e = pg_log_entry_t.decode(raw)
                scope.add(e.oid)
        strays: set[str] = set()
        skip_done: set[str] = set()
        if scope is None:
            # the perf-counter pair is the soak runner's live proof
            # that recovery took the BACKFILL path (full enumeration),
            # not a log delta — started here, completed only after a
            # fully verified pass
            self.perf.inc("backfill_started")
            # backfill: reconcile the union of object lists, but the
            # member with the newest pre-recovery state is authoritative
            # for WHICH objects exist — an object only held by stale
            # members is a stray (deleted while they were down), never
            # resurrected (reference backfill removes strays the same
            # way)
            objs = set(self._local_objects(pool, pg, my_shard))
            lists: dict[tuple[int, int], set[str]] = {
                (my_shard, self.id): set(objs)
            }
            lus = {(my_shard, self.id): pre_adopt_lu}
            worklist = [
                ((s, o), None) for s, o in prior
            ] + [(k, i) for k, i in peer_infos.items()]
            chain_grew = False
            queried: set[tuple[int, int]] = {(my_shard, self.id)}
            qi = 0
            while qi < len(worklist):
                (s, o), info = worklist[qi]
                qi += 1
                if (s, o) in queried:
                    continue
                queried.add((s, o))
                if o == self.id:
                    # a past interval where WE held a different shard:
                    # serve the listing locally (querying self raises)
                    try:
                        lists[(s, o)] = set(
                            self._local_objects(pool, pg, s))
                    except FileNotFoundError:
                        continue
                    sc = self._shard_coll(pool, pg, s)
                    slg = self._pg_log(sc)
                    lus[(s, o)] = slg.info.last_update
                    merge_seen |= self._merge_pending(sc, slg)
                    objs |= lists[(s, o)]
                    continue
                try:
                    full = await self._pg_query(
                        pool, pg, s, o, since=lg.info.last_update,
                        want_objects=True,
                    )
                except (OSError, asyncio.TimeoutError, ConnectionError):
                    continue
                lists[(s, o)] = {oid for oid, _v in full.objects}
                lus[(s, o)] = (
                    info.last_update if info is not None
                    else full.last_update
                )
                merge_seen |= getattr(full, "merge_pending", False)
                objs |= lists[(s, o)]
                if _merge_chain(getattr(full, "past_acting", b"")):
                    # chain-follow: the old home knew an even older one
                    chain_grew = True
                    prior = self._prior_pairs(pool, pg, pairs)
                    for pair in prior:
                        if pair not in queried:
                            worklist.append((pair, None))
                if info is None and full.last_update > lg.info.last_update:
                    # adopt the prior member's log delta so ops from
                    # the foreign interval (e.g. DELETEs) replay here
                    # instead of the old state resurrecting
                    t2 = Transaction()
                    self._ensure_coll(t2, myc)
                    ents2 = [
                        pg_log_entry_t.decode(raw) for raw in full.entries
                    ]
                    if full.log_tail > lg.info.last_update:
                        lg.adopt_tail(t2, full.log_tail, ents2)
                        for e in ents2:
                            if e.version > full.log_tail:
                                objs.add(e.oid)
                    else:
                        for e in ents2:
                            if e.version > lg.info.last_update:
                                lg.append(t2, e)
                                objs.add(e.oid)
                    self._pg_log_trim(t2, lg)
                    if not t2.empty():
                        self.store.queue_transaction(t2)
            if chain_grew:
                self._save_past_acting()  # one write after the drain
            auth = max(lus, key=lambda k: lus[k])
            strays = objs - lists[auth]
            # an object the (adopted) authoritative log names as LIVE
            # but missing from the auth member's listing is not
            # deleted-while-down debris — it is missing ON the auth
            # (log-sync hands members entries without data, so a
            # freshly-seated member can be "newest" while empty).
            # Reaping those deleted shards of acked objects from the
            # members that still held them (chaos-engine-found).  The
            # genuine stray case (DELETE entry trimmed away) has no
            # retained live entry, so it still reaps.
            if strays:
                latest_op: dict[str, int] = {}
                for v in sorted(lg.entries):
                    e = lg.entries[v]
                    latest_op[e.oid] = e.op
                strays -= {
                    o_ for o_, op_ in latest_op.items() if op_ != DELETE
                }
            log.debug(
                "osd.%d: pg %s backfill: objs=%d prior=%s lists=%s "
                "auth=%s strays=%d", self.id, pg, len(objs), prior,
                {k: len(v) for k, v in lists.items()}, auth, len(strays))
            if strays and merge_seen:
                # first pass after a pg merge: per-child version
                # sequences are incomparable, so the listing-based
                # stray heuristic would reap freshly-merged objects
                # (merge only commits on CLEAN pools — see
                # _refile_merge_collections — so no genuine
                # deleted-while-down strays can exist here)
                log.info(
                    "osd.%d: pg %s merge reconcile: %d would-be strays "
                    "kept", self.id, pg, len(strays))
                strays = set()
            cursor = self._load_backfill_cursor(myc, acting)
            if cursor is not None:
                # resume an INTERRUPTED backfill from the persisted
                # cursor: everything at or below it was verified this
                # same interval (same epoch + acting set) and writes
                # since replicate to every acting member normally, so
                # re-pushing the prefix is pure waste.  Strays are
                # never skipped — their removal is this pass's job.
                skip_done = {
                    oid for oid in objs
                    if oid <= cursor and oid not in strays
                }
                if skip_done:
                    log.info(
                        "osd.%d: pg %s backfill resumes past %r: %d of "
                        "%d objects already verified this interval",
                        self.id, pg, cursor, len(skip_done), len(objs))
        else:
            objs = scope
        all_ok = True
        rsleep = self.conf["osd_recovery_sleep"]

        async def _one(oid: str) -> bool:
            # osd_recovery_max_active: in-flight reconciliations per
            # daemon, across every concurrently-reserved PG; each one
            # then admits through the mClock gate at recovery weight,
            # so saturated client I/O overtakes it (admission strictly
            # BEFORE the object lock — a lock holder must never wait
            # on admission, or slots+locks could cycle)
            async with self._recovery_budget:
                async with self.op_gate.admit("recovery"):
                    ok = await self._reconcile_object(
                        pool, pg, pairs, oid, stray=oid in strays,
                        prior_pairs=prior,
                    )
                if rsleep:
                    await asyncio.sleep(rsleep)
                return bool(ok)

        ordered = sorted(objs - skip_done)
        results = await asyncio.gather(
            *[_one(oid) for oid in ordered], return_exceptions=True,
        )
        interrupted = False
        for oid, r in zip(ordered, results):
            if isinstance(r, (OSError, asyncio.TimeoutError, ConnectionError)):
                log.warning(
                    "osd.%d: reconcile %s/%s interrupted: %r",
                    self.id, pg, oid, r,
                )
                interrupted = True
                all_ok = False
                continue
            if isinstance(r, BaseException):
                raise r
            all_ok &= bool(r)
        if scope is None:
            done = skip_done | {
                oid for oid, r in zip(ordered, results) if r is True
            }
            self._save_backfill_cursor(myc, acting, sorted(objs), done,
                                       all_ok)
            if all_ok:
                self.perf.inc("backfill_completed")
        if interrupted:
            return False
        if self.epoch != pass_epoch:
            # interval guard: everything below vouches for state this
            # pass VERIFIED — but its peer snapshots and pushes are
            # evidence about the map it started under.  A pass that
            # straddles map changes (member died, log churned past
            # trim, member revived — all inside one pass, with the
            # final acting set equal to the starting one, so an
            # acting-set compare can't see it) would log-sync a
            # joiner to clear_floor state it never checked there:
            # the joiner's last_update then silently vouches for a
            # trimmed-away window it does not hold, the next pass's
            # missing-set scoping finds nothing, and the shard's
            # objects are unreadable until scrub — a clean-looking
            # data loss.  Report not-ok instead; the pass running
            # under the new map redoes the work with fresh evidence.
            log.info(
                "osd.%d: pg %s map moved mid-pass (%d -> %d); "
                "withholding verified log-sync",
                self.id, pg, pass_epoch, self.epoch)
            return False
        # log sync — ONLY after a fully verified pass.  A lagging
        # peer's last_update IS the next pass's missing-set evidence:
        # syncing the log while an object push failed (member still
        # booting through a near-instant kill+revive) hands the peer
        # entries without data, the retry pass computes an EMPTY
        # missing set from the now-current last_update, and the
        # member stays one version stale until scrub flags it — the
        # long-standing ~1/16 stale-shard flake, root-caused by the
        # chaos x load composition runs (the reference never has this
        # hole because MOSDPGLog populates a PERSISTED per-peer
        # missing set; here last_update carries that burden, so it
        # must stay honest).
        if all_ok:
            for (s, o), info in peer_infos.items():
                eff = self._peer_effective_lu(info)
                floored = bool(getattr(info, "contig_floor", b""))
                if eff >= lg.info.last_update and not floored:
                    continue
                entries = [
                    e.encode() for e in lg.entries_after(eff)
                ]
                try:
                    # clear_floor: this pass verified every object on
                    # this peer AND the entries above fill its gap
                    await self._pg_log_send(
                        pool, pg, s, o, entries, lg.info.log_tail,
                        clear_floor=True)
                except (OSError, asyncio.TimeoutError, ConnectionError):
                    continue
            if lg.contig_floor is not None:
                # our own gap is verified too: every object this log
                # names was reconciled across the acting set
                t_fl = Transaction()
                lg.clear_contig_floor(t_fl)
                if not t_fl.empty():
                    self.store.queue_transaction(t_fl)
        # only a FULLY verified pass (every object confirmed on every
        # target) may forget the prior intervals — a swallowed push
        # failure must keep the old home reachable for the retry
        if all_ok:
            if self._past_acting.pop((pg.pool, pg.ps), None) is not None:
                self._save_past_acting()
            if merge_seen:
                # verified: resolve every participant's merge marker so
                # normal stray semantics resume (best-effort — a missed
                # peer stays conservative, never destructive)
                for s in range(pool.size if pool.is_erasure() else 1):
                    sc = self._shard_coll(
                        pool, pg, s if pool.is_erasure() else NO_SHARD)
                    slg = self._pg_log(sc)
                    if self._merge_pending(sc, slg):
                        t3 = Transaction()
                        t3.omap_rmkeys(sc, slg.meta, ["merge_pending"])
                        self.store.queue_transaction(t3)
                for s, o in set(pairs) | set(prior):
                    if o == self.id:
                        continue
                    try:
                        await self._pg_query(
                            pool, pg, s, o, since=lg.info.last_update,
                            clear_merge=True)
                    except (OSError, asyncio.TimeoutError,
                            ConnectionError):
                        continue
        else:
            log.warning(
                "osd.%d: %s recovery pass incomplete; retaining past "
                "intervals", self.id, pg)
        return all_ok

    def _merge_pending(self, myc, lg) -> bool:
        """True while this PG's first post-merge reconcile has not
        completed (marker written by _refile_merge_collections)."""
        try:
            vals = self.store.omap_get_values(
                myc, lg.meta, ["merge_pending"])
        except (FileNotFoundError, OSError):
            return False
        return vals.get("merge_pending") == b"1"

    async def _reconcile_object(
        self, pool: PgPool, pg: pg_t, pairs: list[tuple[int, int]], oid: str,
        stray: bool = False, have_lock: bool = False,
        prior_pairs: list[tuple[int, int]] | None = None,
    ) -> bool:
        """Bring one object to its newest version on every acting
        member: replay deletes, remove strays, reconstruct
        stale/missing shards from the members holding the newest
        version.

        Serializes against client writes via the object lock — probing
        mid-write would see a partial fan-out and wrongly roll it back
        (``have_lock`` for callers inside the write path that already
        hold it)."""
        with self.tracer.span(
            "recover_object", pg=str(pg), oid=oid,
        ):
            if not have_lock:
                async with self._obj_lock(pool.id, oid):
                    return await self._reconcile_object_locked(
                        pool, pg, pairs, oid, stray, prior_pairs)
            return await self._reconcile_object_locked(
                pool, pg, pairs, oid, stray, prior_pairs)

    async def _reconcile_object_locked(
        self, pool: PgPool, pg: pg_t, pairs: list[tuple[int, int]], oid: str,
        stray: bool = False,
        prior_pairs: list[tuple[int, int]] | None = None,
    ) -> bool:
        """Returns True when the object verifiably reached every
        target (False = retry on a later pass)."""
        from ceph_tpu.common.fault_injector import FAULTS

        await FAULTS.check("osd.recover_object")
        is_ec = pool.is_erasure()
        my_shard = next(s for s, o in pairs if o == self.id)
        lg = self._pg_log(self._shard_coll(pool, pg, my_shard))
        latest: pg_log_entry_t | None = None
        for v in sorted(lg.entries, reverse=True):
            if lg.entries[v].oid == oid:
                latest = lg.entries[v]
                break

        state: dict[tuple[int, int], tuple[bool, eversion_t, dict]] = {}
        unprobed: list[tuple[int, int]] = []
        for s, o in pairs:
            try:
                payload, attrs = await self._probe_shard(pool, pg, s, o, oid)
            except (OSError, asyncio.TimeoutError, ConnectionError):
                # unreachable: not a source nor target now — but its
                # unseen state VETOES destructive decisions below
                unprobed.append((s, o))
                continue
            if payload is None:
                state[(s, o)] = (False, ZERO, {})
            else:
                state[(s, o)] = (
                    True, _v_parse((attrs or {}).get(VERSION_ATTR)), attrs or {}
                )
        # prior-interval members: extra SOURCES (never targets) — data
        # a full remap left on the old acting set
        prior_state: dict[tuple[int, int], tuple[bool, eversion_t, dict]] = {}
        prior_unprobed: list[tuple[int, int]] = []
        for s, o in prior_pairs or ():
            try:
                payload, attrs = await self._probe_shard(pool, pg, s, o, oid)
            except (OSError, asyncio.TimeoutError, ConnectionError):
                # unreachable (typically DOWN-but-in, kept by
                # _prior_pairs): useless as a source now, but its
                # unseen store may hold the newest ACKED version —
                # it vetoes the partial-write rollback below exactly
                # as an unprobed CURRENT member does
                prior_unprobed.append((s, o))
                continue
            if payload is not None:
                prior_state[(s, o)] = (
                    True, _v_parse((attrs or {}).get(VERSION_ATTR)), attrs or {}
                )

        delete_entry = latest is not None and latest.op == DELETE
        if delete_entry or (stray and latest is None):
            # logged delete replay, or a backfill stray (only stale
            # members hold it; its DELETE entry was trimmed)
            guard = latest.version if latest else lg.info.last_update
            for (s, o), (present, _v, _a) in state.items():
                if present:
                    await self._recovery_delete(pool, pg, s, o, oid, guard)
            return not unprobed  # an unseen member may still hold it

        all_state = {**prior_state, **state}
        versions = [v for (p, v, _a) in all_state.values() if p]
        if not versions:
            # nothing REACHABLE to recover from — but an unprobed
            # member's state is unseen, not absent: only full
            # coverage may declare the object whole
            return not unprobed
        vmax = max(versions)
        sources = {
            s: o for (s, o), (p, v, _a) in all_state.items()
            if p and v == vmax
        }
        targets = [
            (s, o) for (s, o), (p, v, _a) in state.items()
            if not p or v < vmax
        ]
        clone_ok = True
        if sources:
            # clone objects are immutable COW copies that never appear
            # in per-name reconciliation: a member rebuilt after data
            # loss gets the head (and its SnapSet) pushed but would
            # serve ENOENT for every snap read — sync any clone the
            # authoritative SnapSet lists (chaos-engine-found gap;
            # the EC variant ALSO must run before the head pushes
            # below, while a COW-missing member's frozen content is
            # still its head — see _sync_clones_ec)
            src_attrs0 = next(
                a for (s, o), (p, v, a) in all_state.items()
                if p and v == vmax
            )
            if is_ec:
                clone_ok = await self._sync_clones_ec(
                    pool, pg, pairs, oid, src_attrs0, state,
                    prior_pairs=prior_pairs)
            else:
                clone_ok = await self._sync_clones(
                    pool, pg, pairs, oid, next(iter(sources.items())),
                    src_attrs0, prior_pairs=prior_pairs,
                )
        if not targets:
            # every PROBED member serves vmax — but success here must
            # mean "verifiably reached every target", and an
            # unreachable acting member is an unverified target, not a
            # non-target.  Returning True with members unprobed was
            # the stale-shard flake: a write-path reconcile racing a
            # near-instant kill+revive probed around the dead member,
            # declared the object whole, skipped the background
            # repair queue — and the member stayed one version stale
            # until the next scrub flagged it (no data loss; the
            # probed quorum held the acked version throughout).
            return clone_ok and not unprobed
        log.info(
            "osd.%d: recovering %s/%s to %s on %s", self.id, pg, oid,
            vmax, targets,
        )
        self.perf.inc("recovery_ops")
        src_attrs = next(
            a for (s, o), (p, v, a) in all_state.items() if p and v == vmax
        )
        if not is_ec:
            s0, o0 = next(iter(sources.items()))
            payload, _a, _e = await self._read_shard_quiet(
                pool, pg, s0, o0, oid
            )
            if payload is None:
                return False
            results = await asyncio.gather(*(
                self._push(pool, pg, s, o, oid, payload, src_attrs)
                for s, o in targets
            ), return_exceptions=True)  # a dead target must not abort
            return clone_ok and not unprobed and not any(
                isinstance(r, BaseException) for r in results)
        ec = self._ec_for(pool)
        sinfo = self._sinfo(ec)
        k = ec.get_data_chunk_count()
        force_push = False
        rb_srcs: set[int] = set()
        if len(sources) < k and (unprobed or prior_unprobed):
            # rollback is DESTRUCTIVE (strips log entries, force-pushes
            # old data) and must never be decided on a partial view: an
            # unreachable member may hold the very shards that make
            # vmax reconstructible.  Absence of evidence is not
            # divergence (chaos-engine-found: mid-partition reconciles
            # rolled logs back to the reachable minority's version,
            # after which stale dup-resends re-applied old payloads as
            # fresh low versions).  A down-but-in PRIOR member vetoes
            # too: a write acked degraded on exactly k shards leaves
            # one holder outside the current acting set when that
            # member is killed, and rolling back before it reboots
            # loses the ack (chaos-fuzz-found; the veto lifts when the
            # map outs it or the trace-end revive lets it answer).
            # Retry when every member answers.
            log.info(
                "osd.%d: %s/%s rollback deferred: %s unprobed",
                self.id, pg, oid, unprobed + prior_unprobed,
            )
            return False
        if len(sources) < k:
            # vmax is not reconstructible (a client write died mid
            # fan-out): ROLL BACK to the newest version at least k
            # shards agree on, overwriting the partial newer shards —
            # the reference's divergent-entry rollback (PGLog merge_log)
            # expressed at shard granularity.  The rolled-back write's
            # log entries are stripped so a client retry re-applies it.
            # rollback candidates come from the CURRENT interval only:
            # prior-interval members hold old versions by definition,
            # and letting them vote would roll back writes whose newer
            # copies merely sit on temporarily-down current members
            by_v: dict = {}
            for (s, o), (p, v, _a) in state.items():
                if p:
                    by_v.setdefault(v, []).append((s, o))
            # rollback-sidecar votes (see _shard_write_txn): a member
            # whose OBJECT moved past the quorum version still holds
            # the pre-write shard state in its sidecar — restorable,
            # so it counts toward reconstructibility of that version
            rb_votes: dict = {}  # (s, o) -> (version, attrs)
            for (s, o), (p, _v, _a) in state.items():
                if not p:
                    continue
                _sp, sa, _se = await self._read_shard_quiet(
                    pool, pg, s, o, oid, length=1, snap=RB_SNAP)
                if _sp is None:
                    continue
                rb_votes[(s, o)] = (
                    _v_parse((sa or {}).get(VERSION_ATTR)), sa or {})
            for (s, o), (rv, _ra) in rb_votes.items():
                lst = by_v.setdefault(rv, [])
                if s not in {s2 for s2, _o2 in lst}:
                    lst.append((s, o))
            candidates = [v for v, lst in by_v.items() if len(lst) >= k]
            if not candidates:
                # current members alone can reconstruct NOTHING — e.g.
                # a remap seated an empty member while a partial write
                # bumped another past the quorum version.  Count
                # prior-interval holders toward reconstructibility too
                # (distinct shard ids).  Safe: an acked write reached
                # every live acting member at ack time, so a version
                # invisible on >= k current+prior shards while an older
                # one IS reconstructible was never acked — rolling it
                # back loses nothing a client was promised (the wedge
                # this unblocks spams "unrecoverable" forever and the
                # PG never converges; chaos-engine-found).
                by_v_all: dict = {}
                for (s, o), (p, v, _a) in all_state.items():
                    if p:
                        by_v_all.setdefault(v, {}).setdefault(s, o)
                for (s, o), (rv, _ra) in rb_votes.items():
                    by_v_all.setdefault(rv, {}).setdefault(s, o)
                candidates = [
                    v for v, m in by_v_all.items() if len(m) >= k
                ]
                by_v = {
                    v: list(m.items()) for v, m in by_v_all.items()
                }
            if not candidates:
                # interval tracking can miss homes under heavy thrash
                # (kills racing remaps faster than past_acting chains
                # propagate): the reference's might_have_unfound sweep
                # — probe EVERY up osd for every shard before declaring
                # the object unfound.  Desperate path only: it is
                # O(shards x osds) probes and runs solely when the
                # normal evidence cannot reconstruct any version.
                om = self.osdmap
                desperate_blind = False
                for s in range(pool.size):
                    for o2 in range(om.max_osd):
                        if not om.is_up(o2) or (s, o2) in all_state:
                            continue
                        try:
                            payload, attrs = await self._probe_shard(
                                pool, pg, s, o2, oid)
                        except (OSError, asyncio.TimeoutError,
                                ConnectionError):
                            # an unanswered probe may hide the k-th
                            # holder: destructive verdicts below need
                            # FULL coverage
                            desperate_blind = True
                            continue
                        if payload is not None:
                            all_state[(s, o2)] = (
                                True,
                                _v_parse((attrs or {}).get(VERSION_ATTR)),
                                attrs or {},
                            )
                by_v_all = {}
                for (s, o2), (p, v, _a) in all_state.items():
                    if p:
                        by_v_all.setdefault(v, {}).setdefault(s, o2)
                for (s, o2), (rv, _ra) in rb_votes.items():
                    by_v_all.setdefault(rv, {}).setdefault(s, o2)
                # a version regaining >= k distinct shards here may be
                # vmax itself — then this is a roll FORWARD onto the
                # acting set, not a rollback
                candidates = [
                    v for v, m in by_v_all.items() if len(m) >= k
                ]
                by_v = {
                    v: list(m.items()) for v, m in by_v_all.items()
                }
            if not candidates:
                if unprobed or desperate_blind:
                    log.error(
                        "osd.%d: %s/%s unrecoverable so far: %d/%d "
                        "consistent shards, view incomplete",
                        self.id, pg, oid, len(sources), k,
                    )
                    return False
                # FULL coverage and still no version on >= k shards:
                # no write to this object can ever have been ACKED (an
                # acked EC write reaches every live acting member, and
                # kills preserve stores) — what remains is debris of
                # partial fan-outs at assorted versions.  Roll the
                # object back to NONEXISTENCE: delete the orphan
                # shards, strip its log entries so reqid dedup stops
                # vouching, and let any client retry re-apply from
                # scratch.  Without this the PG wedges forever — no
                # version reconstructible, nothing deletable
                # (chaos-engine-found terminal state).
                # An ACKED version cannot land here: acking required
                # every live acting member to apply it, and a member
                # whose payload later moved past it keeps the pre-write
                # state in its rollback sidecar — so an acked version
                # that lost its payload quorum still reaches k votes
                # via sidecars and resolves as a restorable CANDIDATE
                # above.  (Residual risk: two+ partial overwrites on
                # the same member rotate its single sidecar slot past
                # an acked version — the bounded-rollback-window
                # tradeoff the reference also makes.)
                log.warning(
                    "osd.%d: %s/%s: no version on >= %d shards anywhere;"
                    " rolling back to nonexistence", self.id, pg, oid, k)
                guard = vmax
                for (s2, o2), (p, _v, _a) in sorted(all_state.items()):
                    if p:
                        try:
                            await self._recovery_delete(
                                pool, pg, s2, o2, oid, guard)
                        except (OSError, asyncio.TimeoutError,
                                ConnectionError):
                            return False  # a holder vanished: retry
                t = Transaction()
                self._ensure_coll(t, self._shard_coll(pool, pg, my_shard))
                lg.rollback_divergent(t, oid, ZERO)
                if t.ops:
                    if getattr(self.store, "blocking_commit", False):
                        await asyncio.to_thread(
                            self.store.queue_transaction, t)
                    else:
                        self.store.queue_transaction(t)
                return True
            v_star = max(candidates)
            log.warning(
                "osd.%d: %s/%s rolling back %s -> %s (partial write)",
                self.id, pg, oid, vmax, v_star,
            )
            vmax = v_star
            sources = dict(by_v[v_star])
            targets = [
                (s, o) for (s, o), (p, v, _a) in state.items()
                if not p or v != v_star
            ]
            # shards whose v_star copy lives in the rollback sidecar,
            # not the object (their object is at a doomed version):
            # reads below must target the sidecar
            rb_srcs = {
                s for (s, o), (rv, _ra) in rb_votes.items()
                if rv == v_star and not (
                    (s, o) in all_state
                    and all_state[(s, o)][0]
                    and all_state[(s, o)][1] == v_star
                )
            }
            src_attrs = next(
                (a for (s, o), (p, v, a) in all_state.items()
                 if p and v == v_star),
                None,
            )
            if src_attrs is None:
                src_attrs = next(
                    ra for (rv, ra) in rb_votes.values() if rv == v_star
                )
            force_push = True
            t = Transaction()
            self._ensure_coll(t, self._shard_coll(pool, pg, my_shard))
            lg.rollback_divergent(t, oid, v_star)
            if getattr(self.store, "blocking_commit", False):
                await asyncio.to_thread(self.store.queue_transaction, t)
            else:
                self.store.queue_transaction(t)
        need = {s for s, _ in targets}
        # single-shard repair of a regenerating code: thread
        # minimum_to_decode's (sub-chunk offset, count) runs down to
        # ranged shard reads so only sub_chunk_no/q of each helper
        # crosses the wire (reference ECCommon.cc:262-299 +
        # ErasureCodeClay::repair_one_lost_chunk) — CLAY's whole point
        repair_extents: dict[int, list[tuple[int, int]]] | None = None
        if (
            len(need) == 1 and ec.get_sub_chunk_count() > 1
            and not rb_srcs
            and not getattr(self, "disable_subchunk_repair", False)
        ):
            try:
                if ec.is_repair(need, set(sources)):
                    minimum = ec.minimum_to_decode(need, set(sources))
                    cs = sinfo.chunk_size
                    sub = cs // ec.get_sub_chunk_count()
                    size = int(src_attrs.get(SIZE_ATTR, b"0"))
                    ns = max(
                        1, sinfo.logical_to_next_chunk_offset(size) // cs
                    )
                    repair_extents = {
                        s: [
                            (stripe * cs + o * sub, c * sub)
                            for stripe in range(ns)
                            for o, c in runs
                        ]
                        for s, runs in minimum.items()
                    }
            except Exception:
                repair_extents = None  # fall back to full-chunk reads
        # helper-shard reads and shard pushes both fan out concurrently
        # (the reference's ECSubRead/MOSDPGPush are fire-and-gather)
        chunks: dict[int, np.ndarray] = {}
        used_packed = False
        if repair_extents is not None and set(repair_extents) <= set(sources):
            src_items = [(s, sources[s]) for s in sorted(repair_extents)]
            payloads = await asyncio.gather(*(
                self._read_shard_quiet(
                    pool, pg, s, o, oid, extents=repair_extents[s]
                )
                for s, o in src_items
            ))
            for (s, o), (payload, _a, _e) in zip(src_items, payloads):
                if payload is not None:
                    chunks[s] = np.frombuffer(payload, np.uint8)
            if len(chunks) < len(repair_extents):
                chunks = {}  # a helper vanished: retry with full reads
            else:
                used_packed = True
        if not chunks:
            src_items = list(sources.items())
            payloads = await asyncio.gather(*(
                self._read_shard_quiet(
                    pool, pg, s, o, oid,
                    **({"snap": RB_SNAP} if s in rb_srcs else {}))
                for s, o in src_items
            ))
            for (s, o), (payload, _a, _e) in zip(src_items, payloads):
                if payload is not None:
                    chunks[s] = np.frombuffer(payload, np.uint8)
            if len(chunks) < k:
                log.error(
                    "osd.%d: %s/%s recovery aborted: %d/%d source reads "
                    "succeeded", self.id, pg, oid, len(chunks), k,
                )
                return False
        # the timed decode stage (BASELINE.md #5; reference
        # ECBackend.cc:365-431 handle_recovery_read_complete): measured
        # IN the running daemon, not inferred from microbenches
        _t0 = time.perf_counter()
        rebuilt = await ecutil.decode_shards_async(
            sinfo, ec, chunks, need, packed_repair=used_packed,
            service=self.encode_service,
            aggregator=self.decode_aggregator,
        )
        self.perf.inc("recovery_decode_seconds",
                      time.perf_counter() - _t0)
        self.perf.inc("recovery_decode_bytes",
                      sum(v.nbytes for v in rebuilt.values()))
        results = await asyncio.gather(*(
            self._push(pool, pg, s, o, oid, rebuilt[s].tobytes(), src_attrs,
                       force=force_push)
            for s, o in targets
        ), return_exceptions=True)  # dead targets retry on the next pass
        return not unprobed and not any(
            isinstance(r, BaseException) for r in results)

    #: reserved push-attr key carrying a clone's snap id (clone pushes
    #: reuse the MOSDPGPush frame; the receiver pops this and files the
    #: payload under ghobject(oid, snap=...) instead of the head)
    CLONE_PUSH_ATTR = "__clone_snap__"

    def _queue_pg_pass(self, pool, pg: pg_t) -> None:
        """A sub-op reply reported a freshly-pinned contiguity floor:
        the replica rejoined mid-traffic and skipped a version window,
        so its earlier objects are stale — and with no map change
        coming, nothing else would run the pass that scopes them (the
        floor/audit machinery only helps a pass that RUNS).  Queue a
        bounded background recovery pass for the pg now.  Deduplicated
        per (pool, ps)."""
        key = (pool.id, pool.raw_pg_to_pg(pg).ps)
        pend = getattr(self, "_pg_pass_pending", None)
        if pend is None:
            pend = self._pg_pass_pending = set()
        if key in pend:
            return
        pend.add(key)

        async def _run() -> None:
            try:
                for attempt in range(20):
                    if self.stopping:
                        return
                    await asyncio.sleep(min(0.2 * (attempt + 1), 1.0))
                    om = self.osdmap
                    cur_pool = om.get_pg_pool(pool.id) if om else None
                    if cur_pool is None:
                        return
                    cur_pg = pg_t(pool.id, key[1])
                    _u, _up, acting, primary = om.pg_to_up_acting_osds(
                        cur_pg, folded=True)
                    if primary != self.id:
                        return  # the new primary's own pass covers it
                    epoch = self.epoch
                    try:
                        await self._recover_pg_reserved(
                            cur_pool, cur_pg, acting, epoch)
                    except asyncio.CancelledError:
                        raise
                    except Exception:
                        continue
                    if self._clean_epoch.get(key, -1) >= epoch:
                        return
                log.warning(
                    "osd.%d: floored-replica pass for %s never "
                    "completed", self.id, key)
            finally:
                pend.discard(key)

        self._spawn_repair_task(_run())

    def _queue_object_repair(self, pool, pg, oid: str) -> None:
        """A write-path repair failed (links cut mid-thrash, member
        unreachable): keep retrying in the background until the object
        reconciles.  Without this, damage inflicted AFTER the last map
        epoch is never repaired — recovery passes only trigger on map
        changes, so the cluster reports clean while a partial write
        sits unreconstructible until the next scrub finds it
        (chaos-engine-found).  Deduplicated per (pool, oid)."""
        key = (pool.id, oid)
        pend = getattr(self, "_repair_pending", None)
        if pend is None:
            pend = self._repair_pending = set()
        if key in pend:
            return
        pend.add(key)
        self.clog.cluster.warn(
            f"pg {pg} object {oid}: write-path repair failed; "
            "requeued background repair")

        async def _retry() -> None:
            try:
                for attempt in range(60):
                    if self.stopping:
                        return
                    await asyncio.sleep(min(0.25 * (attempt + 1), 2.0))
                    om = self.osdmap
                    cur_pool = om.get_pg_pool(pool.id) if om else None
                    if cur_pool is None:
                        return  # pool deleted
                    cur_pg = object_to_pg(cur_pool, oid)
                    acting, primary = self._acting(cur_pool, cur_pg)
                    if primary != self.id:
                        return  # the new primary owns the repair
                    try:
                        if await self._reconcile_object(
                            cur_pool, cur_pg,
                            self._pg_members(cur_pool, acting), oid,
                        ):
                            return
                    except asyncio.CancelledError:
                        raise
                    except Exception:
                        continue
                log.warning(
                    "osd.%d: background repair of %s/%s gave up",
                    self.id, pg, oid)
            finally:
                pend.discard(key)

        t = asyncio.ensure_future(_retry())
        hold = getattr(self, "_repair_tasks", None)
        if hold is None:
            hold = self._repair_tasks = set()
        hold.add(t)
        t.add_done_callback(hold.discard)

    async def _sync_clones(
        self, pool, pg, pairs, oid: str,
        src_pair: tuple[int, int], src_attrs: dict,
        prior_pairs: list | None = None,
    ) -> bool:
        """Replicated pools: ensure every acting member holds every
        clone the authoritative head's SnapSet lists — at the RIGHT
        frozen content.  Presence alone is NOT sufficiency: a member
        whose head was still stale when the first post-snap write
        landed COWs its OLD head into the clone slot (right name,
        wrong content — long-soak chaos found snap reads serving
        pre-outage versions this way).  Every current member freezes
        the same head at COW time and a stale member can only freeze
        an OLDER one, so the newest clone version attr among holders
        IS the true frozen content; older copies are overwritten
        (reference recovery ships clones as ordinary objects because
        its missing-sets are ghobject-keyed; our name-keyed reconcile
        needs this explicit pass)."""
        import errno

        from ceph_tpu.osd.snaps import SS_ATTR, SnapSet

        raw = (src_attrs or {}).get(SS_ATTR)
        if not raw:
            return True
        ss = SnapSet.from_bytes(raw)
        if not ss.clones:
            return True
        ok = True
        for cl in ss.clones:
            if cl.id in pool.removed_snaps:
                # the snap was removed: its clones are trimmer
                # territory (a member may have reaped while another
                # holds a straggler) — syncing reaped debris would
                # either resurrect it or wedge the pass retrying a
                # source nobody has
                continue
            # probe EVERY acting member (version attr included): the
            # authoritative copy is the newest one anywhere, not
            # whichever member happened to be chosen as head source
            vers: dict[tuple[int, int], eversion_t | None] = {}
            best: tuple[eversion_t, int, int] | None = None
            for s, o in pairs:
                if o == CRUSH_ITEM_NONE:
                    continue
                if o == self.id:
                    c = self._shard_coll(pool, pg, s)
                    co = ghobject_t(oid, snap=cl.id, shard=s)
                    if self.store.exists(c, co):
                        v = _v_parse(self.store.getattrs(c, co).get(
                            VERSION_ATTR))
                        vers[(s, o)] = v
                        if best is None or v > best[0]:
                            best = (v, s, o)
                    else:
                        vers[(s, o)] = None
                    continue
                probe, a, perr = await self._read_shard_quiet(
                    pool, pg, s, o, oid, length=1, snap=cl.id)
                if probe is not None:
                    v = _v_parse((a or {}).get(VERSION_ATTR))
                    vers[(s, o)] = v
                    if best is None or v > best[0]:
                        best = (v, s, o)
                elif perr in (errno.ENOENT,):
                    vers[(s, o)] = None
                else:
                    ok = False  # unreachable member: retry next pass
            # prior-interval members: extra SOURCES (never targets) —
            # a remap may have left the only (or only current) copy on
            # the old acting set
            for s, o in prior_pairs or ():
                if o in (CRUSH_ITEM_NONE, self.id):
                    continue
                try:
                    probe, a, _e = await self._read_shard_quiet(
                        pool, pg, s, o, oid, length=1, snap=cl.id)
                except (OSError, asyncio.TimeoutError, ConnectionError):
                    continue
                if probe is not None:
                    v = _v_parse((a or {}).get(VERSION_ATTR))
                    if best is None or v > best[0]:
                        best = (v, s, o)
            if best is None:
                # nowhere to sync from yet: retry on a later pass
                ok = False
                continue
            v_auth, s_b, o_b = best
            payload = attrs = None
            if o_b == self.id:
                c = self._shard_coll(pool, pg, s_b)
                co = ghobject_t(oid, snap=cl.id, shard=s_b)
                if self.store.exists(c, co):
                    payload = bytes(self.store.read(c, co))
                    attrs = dict(self.store.getattrs(c, co))
            else:
                try:
                    payload, attrs, _e = await self._read_shard_quiet(
                        pool, pg, s_b, o_b, oid, snap=cl.id)
                except (OSError, asyncio.TimeoutError, ConnectionError):
                    payload = None
            if payload is None:
                ok = False  # source vanished between probe and read
                continue
            for (s, o), v in vers.items():
                if v is not None and v >= v_auth:
                    continue  # holds the true frozen content
                if o == self.id:
                    c = self._shard_coll(pool, pg, s)
                    co = ghobject_t(oid, snap=cl.id, shard=s)
                    t = Transaction()
                    self._ensure_coll(t, c)
                    t.touch(c, co)
                    t.truncate(c, co, len(payload))
                    if payload:
                        t.write(c, co, 0, payload)
                    if attrs:
                        t.setattrs(c, co, dict(attrs))
                    self.store.queue_transaction(t)
                    continue
                try:
                    await self._push(
                        pool, pg, s, o, oid, payload, dict(attrs or {}),
                        snap=cl.id)
                except (OSError, asyncio.TimeoutError, ConnectionError):
                    ok = False
        return ok

    async def _sync_clones_ec(
        self, pool, pg, pairs, oid: str, src_attrs: dict,
        state: dict, prior_pairs: list | None = None,
    ) -> bool:
        """EC pools: ensure every acting member holds its shard of
        every clone the authoritative head's SnapSet lists.  Two
        repair sources, tried in order:

        1. **file-head-as-clone**: a member that missed the COW write
           entirely (down during the thrash window) still holds the
           FROZEN content as its head — its head version equals the
           clone's version attr (clones copy head attrs at COW time).
           Copy its head into the clone slot BEFORE the head
           roll-forward overwrites it: this replays make_writeable at
           recovery time, exactly what the member would have done had
           it seen the write.
        2. **decode-from-k**: >= k members hold their clone shards —
           rebuild the missing member's shard and push it
           (clone pushes ride MOSDPGPush with the snap id).

        A clone with fewer than k shards anywhere and no filing
        candidate is unrecoverable snap data — logged, never wedging
        head convergence (the chaos snap invariant stays the judge).
        """
        import errno

        from ceph_tpu.osd.snaps import SNAPS_ATTR, SS_ATTR, SnapSet

        raw = (src_attrs or {}).get(SS_ATTR)
        if not raw:
            return True
        ss = SnapSet.from_bytes(raw)
        if not ss.clones:
            return True
        ec = self._ec_for(pool)
        sinfo = self._sinfo(ec)
        k = ec.get_data_chunk_count()
        ok = True
        for cl in ss.clones:
            if cl.id in pool.removed_snaps:
                continue  # reaped by the trimmer (see _sync_clones)
            # collect every member's clone shard WITH its version
            # attr: a member whose head was stale at COW time froze
            # old shard content under the right name (see
            # _sync_clones) — letting such a shard into the decode
            # set would rebuild garbage clones, so only shards at the
            # newest frozen version count as holders; staler ones are
            # re-push targets
            shards: dict[tuple[int, int],
                         tuple["np.ndarray", dict, eversion_t]] = {}
            miss: list[tuple[int, int]] = []
            for s, o in pairs:
                payload, attrs, perr = await self._read_shard_quiet(
                    pool, pg, s, o, oid, snap=cl.id)
                if payload is not None:
                    shards[(s, o)] = (
                        np.frombuffer(payload, np.uint8), dict(attrs or {}),
                        _v_parse((attrs or {}).get(VERSION_ATTR)))
                elif perr in (errno.ENOENT,):
                    miss.append((s, o))
                else:
                    ok = False  # unreachable member: retry next pass
            vset = {v for _p, _a, v in shards.values()}
            if not miss and len(vset) <= 1:
                continue  # every member holds the same frozen content
            # prior-interval members as clone SOURCES (never targets):
            # a freshly-backfilled member got the HEAD pushed but its
            # clone shard only ever existed on the old acting set
            for s, o in prior_pairs or ():
                if any(s == s2 for s2, _o2 in shards):
                    continue
                try:
                    payload, attrs, _e = await self._read_shard_quiet(
                        pool, pg, s, o, oid, snap=cl.id)
                except (OSError, asyncio.TimeoutError, ConnectionError):
                    continue
                if payload is not None:
                    shards[(s, o)] = (
                        np.frombuffer(payload, np.uint8), dict(attrs or {}),
                        _v_parse((attrs or {}).get(VERSION_ATTR)))
            frozen_v = max(
                (v for _p, _a, v in shards.values()), default=None)
            have: dict[int, "np.ndarray"] = {}
            have_attrs: dict | None = None
            for (s, o), (p, a, v) in shards.items():
                if v == frozen_v:
                    if s not in have:
                        have[s] = p
                        if have_attrs is None:
                            have_attrs = a
                elif (s, o) in pairs:
                    miss.append((s, o))  # stale COW: re-push
            if not miss:
                continue
            filed: set[tuple[int, int]] = set()
            if frozen_v is not None:
                for s, o in miss:
                    st = state.get((s, o))
                    if not (st and st[0] and st[1] == frozen_v):
                        continue
                    payload, attrs, _e = await self._read_shard_quiet(
                        pool, pg, s, o, oid)
                    if payload is None:
                        continue
                    at = dict(attrs or {})
                    at.pop(SS_ATTR, None)  # clones carry snaps, not SS
                    if have_attrs and SNAPS_ATTR in have_attrs:
                        at[SNAPS_ATTR] = have_attrs[SNAPS_ATTR]
                    try:
                        await self._push(pool, pg, s, o, oid, payload,
                                         at, snap=cl.id)
                        filed.add((s, o))
                    except (OSError, asyncio.TimeoutError,
                            ConnectionError):
                        ok = False
            remaining = [m for m in miss if m not in filed]
            if not remaining:
                continue
            if len(have) >= k:
                try:
                    rebuilt = await ecutil.decode_shards_async(
                        sinfo, ec, dict(have),
                        {s for s, _o in remaining},
                        service=self.encode_service,
                        aggregator=self.decode_aggregator,
                    )
                except Exception:
                    log.exception(
                        "osd.%d: clone %s/%s@%d decode failed",
                        self.id, pg, oid, cl.id)
                    ok = False
                    continue
                for s, o in remaining:
                    if s not in rebuilt:
                        continue
                    try:
                        await self._push(
                            pool, pg, s, o, oid,
                            rebuilt[s].tobytes(),
                            dict(have_attrs or {}), snap=cl.id)
                    except (OSError, asyncio.TimeoutError,
                            ConnectionError):
                        ok = False
            else:
                log.warning(
                    "osd.%d: clone %s/%s@%d has %d/%d shards and no "
                    "filing candidate: snap unrecoverable",
                    self.id, pg, oid, cl.id, len(have), k)
        return ok

    async def _recovery_delete(
        self, pool, pg, shard, osd, oid, guard: eversion_t
    ) -> None:
        """Replay of a logged delete on a stale member (unlogged: the
        log itself syncs separately).  ``guard`` protects a concurrent
        re-create: members whose object is newer than the delete keep
        it."""
        if osd == self.id:
            c = self._shard_coll(pool, pg, shard)
            if self._object_version(c, ghobject_t(oid, shard=shard)) > guard:
                return
            await self._apply_shard_write_async(
                pool, pg, shard, oid, b"", {}, delete=True
            )
            return
        tid = next(self._tids)
        await self._sub_op(osd, MOSDECSubOpWrite(
            tid=tid, pg=pg, shard=shard, from_osd=self.id, oid=oid,
            off=0, data=b"", attrs={}, epoch=self.epoch, delete=True,
            guard=guard,
        ), tid)

    async def _pg_query(
        self, pool, pg, shard, osd, since, want_objects: bool = False,
        clear_merge: bool = False,
    ) -> MOSDPGInfo:
        if osd == self.id:
            raise ValueError("query self")
        tid = next(self._tids)
        return await self._sub_op(osd, MOSDPGQuery(
            tid=tid, pg=pg, shard=shard, from_osd=self.id, since=since,
            want_objects=want_objects, epoch=self.epoch,
            clear_merge=clear_merge,
        ), tid)

    async def _pg_log_send(self, pool, pg, shard, osd, entries, tail,
                           clear_floor: bool = False) -> None:
        tid = next(self._tids)
        await self._sub_op(osd, MOSDPGLog(
            tid=tid, pg=pg, shard=shard, from_osd=self.id,
            entries=entries, epoch=self.epoch, tail=tail,
            clear_floor=clear_floor,
        ), tid)

    @staticmethod
    def _peer_effective_lu(info) -> eversion_t:
        """What a peer's log can VOUCH for: its last_update, floored
        by its reported contiguity gap (see PGLog.contig_floor)."""
        lu = info.last_update
        raw = getattr(info, "contig_floor", b"") or b""
        if not raw:
            return lu
        try:
            ep, _, ver = raw.decode().partition(".")
            return min(eversion_t(int(ep), int(ver)), lu)
        except ValueError:
            return ZERO  # unreadable floor: trust nothing

    def _spawn_peering(self, coro) -> None:
        """Run a peering handler as its own task, strongly referenced
        (the loop holds tasks weakly)."""
        task = asyncio.ensure_future(coro)
        tasks = getattr(self, "_peering_tasks", None)
        if tasks is None:
            tasks = self._peering_tasks = set()
        tasks.add(task)
        task.add_done_callback(tasks.discard)

    async def _wait_for_epoch(self, epoch: int, timeout: float = 10.0) -> None:
        """Peering messages are meaningful only at (or after) the
        sender's epoch — the reference queues them behind map catch-up
        (OSD::wait_for_new_map).  Without this, a primary splitting a
        PG can query a peer that hasn't refiled yet, read an empty
        child collection, and wrongly conclude the PG is clean."""
        if self.epoch >= epoch:
            return
        try:
            await self._request_map_fill()
        except (ConnectionError, OSError):
            pass
        loop = asyncio.get_running_loop()
        deadline = loop.time() + timeout
        while (self.epoch < epoch and loop.time() < deadline
               and not self.stopping):
            await asyncio.sleep(0.05)

    def _self_audit_missing(self, pool, pg, shard, lg) -> list[str]:
        """Oids this member's OWN log claims at versions its store
        does not serve (reference pg_missing_t, rebuilt log-vs-store).
        Log entries travel without object data — adoption while
        briefly primary, post-pass MOSDPGLog sync — so last_update can
        run ahead of the store; this audit is the persisted truth the
        peering exchange must carry (root cause of the stale-shard
        scrub flake: a log-current/object-stale member was invisible
        to the primary's missing_from scoping).  Bounded by the
        trimmed log length; store reads are local."""
        c = self._shard_coll(pool, pg, shard)
        latest: dict[str, pg_log_entry_t] = {}
        for v in sorted(lg.entries):
            latest[lg.entries[v].oid] = lg.entries[v]
        out: list[str] = []
        for oid, e in latest.items():
            o = ghobject_t(oid, shard=shard)
            try:
                if e.op == DELETE:
                    continue  # absence is the logged state
                if not self.store.collection_exists(c) \
                        or not self.store.exists(c, o):
                    out.append(oid)
                elif self._object_version(c, o) < e.version:
                    out.append(oid)
            except OSError:
                out.append(oid)  # unreadable counts as missing
        return out

    async def _handle_pg_query(self, msg: MOSDPGQuery) -> None:
        await self._wait_for_epoch(msg.epoch)
        pool = self.osdmap.get_pg_pool(msg.pg.pool)
        c = self._shard_coll(pool, msg.pg, msg.shard)
        lg = self._pg_log(c)
        if msg.clear_merge and self._merge_pending(c, lg):
            # primary verified the post-merge reconcile: the listing
            # superposition is resolved, normal stray semantics resume
            tcm = Transaction()
            tcm.omap_rmkeys(c, lg.meta, ["merge_pending"])
            self.store.queue_transaction(tcm)
        entries = [e.encode() for e in lg.entries_after(msg.since)]
        objects: list[tuple[str, bytes]] = []
        if msg.want_objects and self.store.collection_exists(c):
            for name in self._local_objects(pool, msg.pg, msg.shard):
                o = ghobject_t(name, shard=msg.shard)
                try:
                    v = self.store.getattr(c, o, VERSION_ATTR)
                except (FileNotFoundError, KeyError):
                    v = b""
                objects.append((name, v))
        import json as _json

        if not self._past_acting_loaded:
            self._load_past_acting()
        chain = self._past_acting.get((msg.pg.pool, msg.pg.ps), [])
        await msg.conn.send_message(MOSDPGInfo(
            tid=msg.tid, pg=msg.pg, shard=msg.shard, from_osd=self.id,
            last_update=lg.info.last_update, log_tail=lg.info.log_tail,
            entries=entries, objects=objects, epoch=self.epoch,
            past_acting=_json.dumps(chain).encode() if chain else b"",
            merge_pending=self._merge_pending(c, lg),
            missing=self._self_audit_missing(pool, msg.pg, msg.shard, lg),
            contig_floor=(lg.contig_floor.key().encode()
                          if lg.contig_floor is not None else b""),
        ))

    async def _handle_pg_log(self, msg: MOSDPGLog) -> None:
        await self._wait_for_epoch(msg.epoch)
        pool = self.osdmap.get_pg_pool(msg.pg.pool)
        c = self._shard_coll(pool, msg.pg, msg.shard)
        lg = self._pg_log(c)
        t = Transaction()
        self._ensure_coll(t, c)
        # adopt_tail = set_tail + fill + floor bookkeeping in ONE step:
        # every adopted entry's reqid enters the dup window (fill, not
        # append — a gapped log heals by receiving the entries it
        # MISSED as well as the new tail), and the contiguity floor
        # stays honest: clear_floor from the primary means every
        # object through our gap was just verified (floor clears),
        # while an UNVERIFIED adoption that raises last_update pins it
        lg.adopt_tail(
            t, msg.tail,
            [pg_log_entry_t.decode(raw) for raw in msg.entries],
            verified=bool(msg.clear_floor),
        )
        self._pg_log_trim(t, lg)
        if not t.empty():
            self.store.queue_transaction(t)
        await msg.conn.send_message(MOSDPGLogAck(
            tid=msg.tid, pg=msg.pg, shard=msg.shard, from_osd=self.id,
            result=0, epoch=self.epoch,
        ))

    async def _probe_shard(self, pool, pg, shard, osd, oid):
        """Presence probe: zero-length read with attrs."""
        if osd == self.id:
            c = self._shard_coll(pool, pg, shard)
            o = ghobject_t(oid, shard=shard)
            if not self.store.exists(c, o):
                return None, None
            return b"", self.store.getattrs(c, o)
        tid = next(self._tids)
        rep = await self._sub_op(osd, MOSDECSubOpRead(
            tid=tid, pg=pg, shard=shard, from_osd=self.id, oid=oid,
            off=0, length=1, want_attrs=True, epoch=self.epoch,
        ), tid)
        if rep.result != 0:
            return None, None
        return rep.data, rep.attrs

    async def _push(self, pool, pg, shard, osd, oid, payload, attrs,
                    force: bool = False, snap: int | None = None) -> None:
        if snap is not None:
            # clone push: the snap id rides a reserved attr so the
            # frame format stays unchanged (see CLONE_PUSH_ATTR)
            attrs = dict(attrs)
            attrs[self.CLONE_PUSH_ATTR] = str(snap).encode()
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        tid = next(self._tids)
        self._push_waiters[tid] = fut
        try:
            conn = await self._osd_conn(osd)
            await conn.send_message(MOSDPGPush(
                pg=pg, shard=shard, from_osd=self.id,
                pushes=[(oid, payload, attrs)], epoch=self.epoch,
                force=force, tid=tid,
            ))
            await asyncio.wait_for(fut, SUBOP_TIMEOUT)
        finally:
            self._push_waiters.pop(tid, None)
    async def _handle_push(self, msg: MOSDPGPush) -> None:
        pool = self.osdmap.get_pg_pool(msg.pg.pool)
        for oid, payload, attrs in msg.pushes:
            c = self._shard_coll(pool, msg.pg, msg.shard)
            clone_snap = attrs.pop(self.CLONE_PUSH_ATTR, None)
            if clone_snap is not None:
                # clone push (see _sync_clones): clones are immutable,
                # so an existing clone object never gets overwritten
                co = ghobject_t(
                    oid, snap=int(clone_snap), shard=msg.shard)
                if not self.store.exists(c, co):
                    t = Transaction()
                    self._ensure_coll(t, c)
                    t.touch(c, co)
                    t.truncate(c, co, len(payload))
                    if payload:
                        t.write(c, co, 0, payload)
                    if attrs:
                        t.setattrs(c, co, attrs)
                    if getattr(self.store, "blocking_commit", False):
                        await asyncio.to_thread(
                            self.store.queue_transaction, t)
                    else:
                        self.store.queue_transaction(t)
                continue
            # never regress: a write may have landed here between the
            # primary's probe and this push (the reference serializes
            # this with per-object rw locks; we reconcile on the next
            # recovery pass instead)
            o = ghobject_t(oid, shard=msg.shard)
            local_v = self._object_version(c, o)
            pushed_v = _v_parse(attrs.get(VERSION_ATTR))
            if local_v > pushed_v and not msg.force:
                continue
            if local_v > pushed_v:
                # divergent rollback: the newer local write is being
                # rolled back cluster-wide; strip its log entries so
                # dup detection stops vouching for it
                t0 = Transaction()
                self._pg_log(c).rollback_divergent(t0, oid, pushed_v)
                if t0.ops:
                    if getattr(self.store, "blocking_commit", False):
                        await asyncio.to_thread(
                            self.store.queue_transaction, t0)
                    else:
                        self.store.queue_transaction(t0)
            # a push REPLACES the object: stale local attrs the source
            # doesn't carry (e.g. a hinfo dropped by an RMW this member
            # missed) must go, or deep scrub sees a phantom crc chain
            stale_attrs = []
            if self.store.exists(c, o):
                stale_attrs = [
                    n for n in self.store.getattrs(c, o) if n not in attrs
                ]
            await self._apply_shard_write_async(
                pool, msg.pg, msg.shard, oid, payload, attrs,
                rmattrs=stale_attrs,
            )
        await msg.conn.send_message(MOSDPGPushReply(
            pg=msg.pg, shard=msg.shard, from_osd=self.id, epoch=self.epoch,
            tid=msg.tid,
        ))
