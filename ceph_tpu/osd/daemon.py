"""OSD daemon: the object-service process of the mini-cluster.

The asyncio twin of the reference OSD's op path (src/osd/OSD.cc
dispatch -> PrimaryLogPG::do_op -> PGBackend submit, SURVEY.md §3.1):
boots into the mon (MOSDBoot), subscribes to maps, serves client ops as
primary, fans EC chunk writes/reads out to shard peers
(MOSDECSubOpWrite/Read — ECBackend::submit_transaction/handle_sub_*,
src/osd/ECBackend.cc:943,1022,1472), replicates full objects for
replicated pools (MOSDRepOp), and reconstructs missing shards after map
changes (RecoveryBackend::continue_recovery_op, ECBackend.cc:563 →
decode via ECUtil + MOSDPGPush).

Data layout matches the reference: one collection per PG shard
(coll_t(pool, ps, shard), ECTransaction.cc:80-88), chunk payloads at
chunk offsets, per-shard HashInfo crc chains in the ``hinfo`` xattr
(ECUtil.cc:164-248) and the logical size in ``_size`` (the object_info
analogue).

Differences from the reference, deliberate for this slice: peering is
implicit (the map is the authority; the primary probes acting members
instead of exchanging pg_info), there is no PG log yet (recovery is
backfill-style full-object reconstruction), and a brand-new primary
with no local data asks the first data-holding acting member for the
object list instead of running the peering state machine.
"""

from __future__ import annotations

import asyncio
import errno
import itertools
import logging

import numpy as np

from ceph_tpu.crush.types import CRUSH_ITEM_NONE
from ceph_tpu.ec import registry as ec_registry
from ceph_tpu.msg.messages import (
    MMonSubscribe,
    MOSDBeacon,
    MOSDBoot,
    MOSDECSubOpRead,
    MOSDECSubOpReadReply,
    MOSDECSubOpWrite,
    MOSDECSubOpWriteReply,
    MOSDFailure,
    MOSDMap,
    MOSDOp,
    MOSDOpReply,
    MOSDPGPush,
    MOSDPGPushReply,
    MOSDRepOp,
    MOSDRepOpReply,
    OP_DELETE,
    OP_READ,
    OP_STAT,
    OP_WRITE_FULL,
)
from ceph_tpu.msg.messenger import Connection, Message, Messenger
from ceph_tpu.ops.hashing import ceph_str_hash_rjenkins
from ceph_tpu.osd import ecutil
from ceph_tpu.osd.mapenc import decode_osdmap
from ceph_tpu.osd.osdmap import OSDMap
from ceph_tpu.osd.types import PgPool, pg_t
from ceph_tpu.store import MemStore, Transaction, coll_t, ghobject_t

log = logging.getLogger("ceph_tpu.osd")

NO_SHARD = -1
STRIPE_UNIT = 4096  # logical bytes per data chunk per stripe
SUBOP_TIMEOUT = 30.0

SIZE_ATTR = "_size"
HINFO_ATTR = "hinfo"


def object_to_pg(pool: PgPool, oid: str) -> pg_t:
    """object_locator_to_pg (src/osd/osd_types.cc): name hash -> raw pg
    (the mapping pipeline folds it into pg_num)."""
    return pg_t(pool.id, int(ceph_str_hash_rjenkins(oid)))


class OSDDaemon:
    def __init__(
        self,
        osd_id: int,
        mon_addr: tuple[str, int],
        store: MemStore | None = None,
        beacon_interval: float = 0.0,
    ):
        self.id = osd_id
        self.mon_addr = mon_addr
        self.store = store or MemStore()
        self.messenger = Messenger(
            ("osd", osd_id), self._dispatch, on_reset=self._on_reset
        )
        self.osdmap: OSDMap | None = None
        self.beacon_interval = beacon_interval
        self.addr: tuple[str, int] | None = None
        self._mon_conn: Connection | None = None
        self._tids = itertools.count(1)
        self._waiters: dict[int, asyncio.Future] = {}
        self._push_waiters: dict[tuple, asyncio.Future] = {}
        self._ec_cache: dict[str, object] = {}
        self._beacon_task: asyncio.Task | None = None
        self._recovery_task: asyncio.Task | None = None
        self._map_event = asyncio.Event()
        self.stopping = False

    # -- lifecycle -----------------------------------------------------

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> None:
        self.addr = await self.messenger.bind(host, port)
        self._mon_conn = await self.messenger.connect_to(
            ("mon", 0), *self.mon_addr
        )
        await self._mon_conn.send_message(
            MOSDBoot(osd=self.id, host=self.addr[0], port=self.addr[1])
        )
        await self._mon_conn.send_message(MMonSubscribe())
        if self.beacon_interval > 0:
            self._beacon_task = asyncio.ensure_future(self._beacon())
        # wait for the first map so ops can be served
        await asyncio.wait_for(self._map_event.wait(), 10)

    async def stop(self) -> None:
        self.stopping = True
        for t in (self._beacon_task, self._recovery_task):
            if t:
                t.cancel()
        await self.messenger.shutdown()

    async def _beacon(self) -> None:
        while not self.stopping:
            await asyncio.sleep(self.beacon_interval)
            try:
                await self._mon_conn.send_message(
                    MOSDBeacon(osd=self.id, epoch=self.epoch)
                )
            except ConnectionError:
                return

    @property
    def epoch(self) -> int:
        return self.osdmap.epoch if self.osdmap else 0

    # -- plumbing ------------------------------------------------------

    async def _on_reset(self, conn: Connection) -> None:
        """Connection to a peer died: fail pending sub-ops and report
        the peer (the OSD::ms_handle_reset + failure-report path)."""
        if self.stopping or conn.peer is None:
            return
        kind, peer_id = conn.peer
        for tid, fut in list(self._waiters.items()):
            if getattr(fut, "peer", None) == conn.peer and not fut.done():
                fut.set_exception(ConnectionError(f"peer {conn.peer} reset"))
        if kind == "osd" and self.osdmap and self.osdmap.is_up(peer_id):
            try:
                await self._mon_conn.send_message(
                    MOSDFailure(
                        reporter=self.id, failed=peer_id, epoch=self.epoch
                    )
                )
            except ConnectionError:
                pass

    async def _osd_conn(self, osd: int) -> Connection:
        addr = self.osdmap.osd_addrs.get(osd)
        if addr is None:
            raise ConnectionError(f"no address for osd.{osd}")
        return await self.messenger.connect_to(("osd", osd), *addr)

    async def _sub_op(self, osd: int, msg: Message, tid: int):
        """Send a sub-op and await its reply future."""
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        fut.peer = ("osd", osd)
        self._waiters[tid] = fut
        try:
            conn = await self._osd_conn(osd)
            await conn.send_message(msg)
            return await asyncio.wait_for(fut, SUBOP_TIMEOUT)
        finally:
            self._waiters.pop(tid, None)

    def _ec_for(self, pool: PgPool):
        prof_name = pool.erasure_code_profile
        if prof_name not in self._ec_cache:
            profile = dict(self.osdmap.erasure_code_profiles[prof_name])
            ec = ec_registry.factory(profile.get("plugin", "jax"), profile)
            self._ec_cache[prof_name] = ec
        return self._ec_cache[prof_name]

    def _sinfo(self, ec) -> ecutil.StripeInfo:
        k = ec.get_data_chunk_count()
        chunk = ec.get_chunk_size(STRIPE_UNIT * k)
        return ecutil.StripeInfo(k, chunk * k)

    def _acting(self, pool: PgPool, pg: pg_t) -> tuple[list[int], int]:
        _, _, acting, primary = self.osdmap.pg_to_up_acting_osds(pg)
        return acting, primary

    # -- dispatch ------------------------------------------------------

    async def _dispatch(self, msg: Message) -> None:
        try:
            if isinstance(msg, MOSDMap):
                await self._handle_map(msg)
            elif isinstance(msg, MOSDOp):
                asyncio.ensure_future(self._handle_client_op(msg))
            elif isinstance(msg, MOSDECSubOpWrite):
                await self._handle_sub_write(msg)
            elif isinstance(msg, MOSDECSubOpRead):
                await self._handle_sub_read(msg)
            elif isinstance(msg, MOSDRepOp):
                await self._handle_rep_op(msg)
            elif isinstance(msg, MOSDPGPush):
                await self._handle_push(msg)
            elif isinstance(
                msg,
                (MOSDECSubOpWriteReply, MOSDECSubOpReadReply, MOSDRepOpReply),
            ):
                fut = self._waiters.get(msg.tid)
                if fut and not fut.done():
                    fut.set_result(msg)
            elif isinstance(msg, MOSDPGPushReply):
                fut = self._push_waiters.get((msg.pg, msg.shard, msg.from_osd))
                if fut and not fut.done():
                    fut.set_result(msg)
        except Exception:
            log.exception("osd.%d: dispatch failed for %r", self.id, msg)

    async def _handle_map(self, msg: MOSDMap) -> None:
        for epoch in sorted(msg.maps):
            if self.osdmap is None or epoch > self.osdmap.epoch:
                self.osdmap = decode_osdmap(msg.maps[epoch])
        self._map_event.set()
        log.info("osd.%d: map epoch %d", self.id, self.epoch)
        if self._recovery_task is None or self._recovery_task.done():
            self._recovery_task = asyncio.ensure_future(self._recover_all())

    # -- client ops (the PrimaryLogPG::do_op slice) --------------------

    async def _handle_client_op(self, msg: MOSDOp) -> None:
        try:
            reply = await self._execute_op(msg)
        except ECConnErrors as e:
            log.warning("osd.%d: op tid %d failed: %r", self.id, msg.tid, e)
            reply = MOSDOpReply(
                tid=msg.tid, result=-errno.EAGAIN, epoch=self.epoch
            )
        except Exception:
            log.exception("osd.%d: op tid %d crashed", self.id, msg.tid)
            reply = MOSDOpReply(tid=msg.tid, result=-errno.EIO, epoch=self.epoch)
        try:
            await msg.conn.send_message(reply)
        except ConnectionError:
            pass

    async def _execute_op(self, msg: MOSDOp) -> MOSDOpReply:
        pool = self.osdmap.get_pg_pool(msg.pool) if self.osdmap else None
        if pool is None:
            return MOSDOpReply(tid=msg.tid, result=-errno.ENOENT, epoch=self.epoch)
        pg = object_to_pg(pool, msg.oid)
        acting, primary = self._acting(pool, pg)
        if primary != self.id:
            # client raced a map change; tell it to retry on a newer map
            return MOSDOpReply(tid=msg.tid, result=-errno.EAGAIN, epoch=self.epoch)
        if pool.is_erasure():
            return await self._ec_op(pool, pg, acting, msg)
        return await self._rep_op(pool, pg, acting, msg)

    # -- EC backend ----------------------------------------------------

    def _shard_coll(self, pool: PgPool, pg: pg_t, shard: int) -> coll_t:
        return coll_t(pool.id, pool.raw_pg_to_pg(pg).ps, shard)

    def _ensure_coll(self, t: Transaction, c: coll_t) -> None:
        if not self.store.collection_exists(c):
            t.create_collection(c)

    async def _ec_op(
        self, pool: PgPool, pg: pg_t, acting: list[int], msg: MOSDOp
    ) -> MOSDOpReply:
        ec = self._ec_for(pool)
        sinfo = self._sinfo(ec)
        if msg.op == OP_WRITE_FULL:
            return await self._ec_write_full(pool, pg, acting, msg, ec, sinfo)
        if msg.op in (OP_READ, OP_STAT):
            return await self._ec_read(pool, pg, acting, msg, ec, sinfo)
        if msg.op == OP_DELETE:
            return await self._ec_delete(pool, pg, acting, msg)
        return MOSDOpReply(tid=msg.tid, result=-errno.EOPNOTSUPP, epoch=self.epoch)

    async def _ec_write_full(self, pool, pg, acting, msg, ec, sinfo) -> MOSDOpReply:
        data = np.frombuffer(msg.data, dtype=np.uint8)
        padded_len = sinfo.logical_to_next_stripe_offset(len(data))
        padded = np.zeros(padded_len, np.uint8)
        padded[: len(data)] = data
        if padded_len:
            shards = ecutil.encode(sinfo, ec, padded)
        else:  # empty object: every shard holds an empty chunk
            empty = np.zeros(0, np.uint8)
            shards = {s: empty for s in range(ec.get_chunk_count())}
        hinfo = ecutil.HashInfo(ec.get_chunk_count())
        hinfo.append(0, shards)
        attrs = {
            HINFO_ATTR: hinfo.to_bytes(),
            SIZE_ATTR: str(len(data)).encode(),
        }
        live = [
            (shard, osd)
            for shard, osd in enumerate(acting)
            if osd != CRUSH_ITEM_NONE
        ]
        if len(live) < pool.min_size:
            return MOSDOpReply(tid=msg.tid, result=-errno.EAGAIN, epoch=self.epoch)
        waits = []
        for shard, osd in live:
            payload = shards[shard].tobytes()
            if osd == self.id:
                self._apply_shard_write(
                    pool, pg, shard, msg.oid, payload, attrs
                )
            else:
                tid = next(self._tids)
                waits.append(self._sub_op(osd, MOSDECSubOpWrite(
                    tid=tid, pg=pg, shard=shard, from_osd=self.id,
                    oid=msg.oid, off=0, data=payload, attrs=attrs,
                    epoch=self.epoch, truncate=len(payload),
                ), tid))
        if waits:
            replies = await asyncio.gather(*waits)
            for rep in replies:
                if rep.result != 0:
                    return MOSDOpReply(
                        tid=msg.tid, result=rep.result, epoch=self.epoch
                    )
        return MOSDOpReply(tid=msg.tid, result=0, epoch=self.epoch)

    def _apply_shard_write(
        self, pool, pg, shard, oid, payload: bytes, attrs, delete=False
    ) -> None:
        c = self._shard_coll(pool, pg, shard)
        o = ghobject_t(oid, shard=shard)
        t = Transaction()
        self._ensure_coll(t, c)
        if delete:
            if self.store.exists(c, o):
                t.remove(c, o)
        else:
            t.touch(c, o).truncate(c, o, len(payload)).write(c, o, 0, payload)
            t.setattrs(c, o, attrs)
        self.store.queue_transaction(t)

    async def _ec_read(self, pool, pg, acting, msg, ec, sinfo) -> MOSDOpReply:
        k = ec.get_data_chunk_count()
        avail = {
            shard: osd for shard, osd in enumerate(acting)
            if osd != CRUSH_ITEM_NONE and self.osdmap.is_up(osd)
        }
        excluded: dict[int, int] = {}  # shard -> errno seen
        for _attempt in range(len(acting) + 1):
            usable = {s: o for s, o in avail.items() if s not in excluded}
            want = set(range(k))
            try:
                minimum = ec.minimum_to_decode(want, set(usable))
            except Exception:
                break  # not enough shards left to decode
            need_shards = set(minimum)
            chunks: dict[int, np.ndarray] = {}
            attrs: dict[str, bytes] = {}
            failed = None
            for shard in sorted(need_shards):
                osd = usable[shard]
                try:
                    payload, a, eno = await self._read_shard(
                        pool, pg, shard, osd, msg.oid
                    )
                except (OSError, asyncio.TimeoutError, ConnectionError):
                    payload, a, eno = None, None, errno.EIO
                if payload is None:
                    failed = (shard, eno)
                    break
                chunks[shard] = np.frombuffer(payload, np.uint8)
                if a:
                    attrs = a
            if failed is not None:
                excluded[failed[0]] = failed[1]
                continue
            if not attrs or SIZE_ATTR not in attrs:
                return MOSDOpReply(
                    tid=msg.tid, result=-errno.ENOENT, epoch=self.epoch
                )
            size = int(attrs[SIZE_ATTR])
            if msg.op == OP_STAT:
                return MOSDOpReply(
                    tid=msg.tid, result=0, epoch=self.epoch, size=size
                )
            logical = ecutil.decode_concat(sinfo, ec, chunks)[:size]
            off = msg.off
            end = size if msg.length == 0 else min(off + msg.length, size)
            return MOSDOpReply(
                tid=msg.tid, result=0, epoch=self.epoch, size=size,
                data=logical[off:end].tobytes(),
            )
        # decode never succeeded: a fully-absent object reports ENOENT,
        # anything else is a real I/O failure
        if excluded and all(e == errno.ENOENT for e in excluded.values()):
            return MOSDOpReply(tid=msg.tid, result=-errno.ENOENT, epoch=self.epoch)
        return MOSDOpReply(tid=msg.tid, result=-errno.EIO, epoch=self.epoch)

    async def _read_shard(self, pool, pg, shard, osd, oid):
        """Full-chunk read of one shard: (payload, attrs, errno)."""
        if osd == self.id:
            c = self._shard_coll(pool, pg, shard)
            o = ghobject_t(oid, shard=shard)
            if not self.store.exists(c, o):
                return None, None, errno.ENOENT
            return self.store.read(c, o), self.store.getattrs(c, o), 0
        tid = next(self._tids)
        rep = await self._sub_op(osd, MOSDECSubOpRead(
            tid=tid, pg=pg, shard=shard, from_osd=self.id, oid=oid,
            off=0, length=0, want_attrs=True, epoch=self.epoch,
        ), tid)
        if rep.result != 0:
            return None, None, -rep.result
        return rep.data, rep.attrs, 0

    async def _ec_delete(self, pool, pg, acting, msg) -> MOSDOpReply:
        waits = []
        for shard, osd in enumerate(acting):
            if osd == CRUSH_ITEM_NONE:
                continue
            if osd == self.id:
                self._apply_shard_write(
                    pool, pg, shard, msg.oid, b"", {}, delete=True
                )
            else:
                tid = next(self._tids)
                waits.append(self._sub_op(osd, MOSDECSubOpWrite(
                    tid=tid, pg=pg, shard=shard, from_osd=self.id,
                    oid=msg.oid, off=0, data=b"", attrs={},
                    epoch=self.epoch, delete=True,
                ), tid))
        if waits:
            await asyncio.gather(*waits)
        return MOSDOpReply(tid=msg.tid, result=0, epoch=self.epoch)

    async def _handle_sub_write(self, msg: MOSDECSubOpWrite) -> None:
        pool = self.osdmap.get_pg_pool(msg.pg.pool)
        result = 0
        try:
            self._apply_shard_write(
                pool, msg.pg, msg.shard, msg.oid, msg.data, msg.attrs,
                delete=msg.delete,
            )
        except OSError as e:
            result = -(e.errno or errno.EIO)
        await msg.conn.send_message(MOSDECSubOpWriteReply(
            tid=msg.tid, pg=msg.pg, shard=msg.shard, from_osd=self.id,
            result=result, epoch=self.epoch,
        ))

    async def _handle_sub_read(self, msg: MOSDECSubOpRead) -> None:
        pool = self.osdmap.get_pg_pool(msg.pg.pool)
        c = self._shard_coll(pool, msg.pg, msg.shard)
        o = ghobject_t(msg.oid, shard=msg.shard)
        if not self.store.exists(c, o):
            rep = MOSDECSubOpReadReply(
                tid=msg.tid, pg=msg.pg, shard=msg.shard, from_osd=self.id,
                result=-errno.ENOENT, epoch=self.epoch,
            )
        else:
            data = self.store.read(
                c, o, msg.off, None if msg.length == 0 else msg.length
            )
            attrs = self.store.getattrs(c, o) if msg.want_attrs else {}
            rep = MOSDECSubOpReadReply(
                tid=msg.tid, pg=msg.pg, shard=msg.shard, from_osd=self.id,
                result=0, data=data, attrs=attrs, epoch=self.epoch,
            )
        await msg.conn.send_message(rep)

    # -- replicated backend -------------------------------------------

    async def _rep_op(self, pool, pg, acting, msg) -> MOSDOpReply:
        c = self._shard_coll(pool, pg, NO_SHARD)
        o = ghobject_t(msg.oid)
        if msg.op == OP_READ:
            if not self.store.exists(c, o):
                return MOSDOpReply(tid=msg.tid, result=-errno.ENOENT, epoch=self.epoch)
            data = self.store.read(c, o, msg.off, msg.length or None)
            return MOSDOpReply(
                tid=msg.tid, result=0, data=data, epoch=self.epoch,
                size=self.store.stat(c, o),
            )
        if msg.op == OP_STAT:
            if not self.store.exists(c, o):
                return MOSDOpReply(tid=msg.tid, result=-errno.ENOENT, epoch=self.epoch)
            return MOSDOpReply(
                tid=msg.tid, result=0, epoch=self.epoch, size=self.store.stat(c, o)
            )
        if msg.op not in (OP_WRITE_FULL, OP_DELETE):
            return MOSDOpReply(tid=msg.tid, result=-errno.EOPNOTSUPP, epoch=self.epoch)
        delete = msg.op == OP_DELETE
        attrs = {SIZE_ATTR: str(len(msg.data)).encode()}
        self._apply_full_object(pool, pg, msg.oid, msg.data, attrs, delete)
        waits = []
        for osd in acting:
            if osd in (self.id, CRUSH_ITEM_NONE):
                continue
            tid = next(self._tids)
            waits.append(self._sub_op(osd, MOSDRepOp(
                tid=tid, pg=pg, from_osd=self.id, oid=msg.oid,
                data=b"" if delete else msg.data, attrs=attrs,
                delete=delete, epoch=self.epoch,
            ), tid))
        if waits:
            replies = await asyncio.gather(*waits)
            for rep in replies:
                if rep.result != 0:
                    return MOSDOpReply(tid=msg.tid, result=rep.result, epoch=self.epoch)
        return MOSDOpReply(tid=msg.tid, result=0, epoch=self.epoch)

    def _apply_full_object(self, pool, pg, oid, data, attrs, delete=False):
        c = self._shard_coll(pool, pg, NO_SHARD)
        o = ghobject_t(oid)
        t = Transaction()
        self._ensure_coll(t, c)
        if delete:
            if self.store.exists(c, o):
                t.remove(c, o)
        else:
            t.touch(c, o).truncate(c, o, len(data)).write(c, o, 0, data)
            t.setattrs(c, o, attrs)
        self.store.queue_transaction(t)

    async def _handle_rep_op(self, msg: MOSDRepOp) -> None:
        pool = self.osdmap.get_pg_pool(msg.pg.pool)
        result = 0
        try:
            self._apply_full_object(
                pool, msg.pg, msg.oid, msg.data, msg.attrs, msg.delete
            )
        except OSError as e:
            result = -(e.errno or errno.EIO)
        await msg.conn.send_message(MOSDRepOpReply(
            tid=msg.tid, pg=msg.pg, from_osd=self.id, result=result,
            epoch=self.epoch,
        ))

    # -- recovery ------------------------------------------------------

    async def _recover_all(self) -> None:
        """After a map change: for every PG this OSD leads, reconstruct
        missing shards/objects on the current acting set (the
        do_recovery -> recover_object path, §3.3).  Re-runs until a
        full pass has seen the newest map (epochs can land mid-pass)."""
        done_epoch = -1
        while done_epoch != self.epoch and not self.stopping:
            done_epoch = self.epoch
            try:
                om = self.osdmap
                for pid, pool in list(om.pools.items()):
                    for ps in range(pool.pg_num):
                        pg = pg_t(pid, ps)
                        _, _, acting, primary = om.pg_to_up_acting_osds(
                            pg, folded=True
                        )
                        if primary != self.id:
                            continue
                        if pool.is_erasure():
                            await self._recover_pg_ec(pool, pg, acting)
                        else:
                            await self._recover_pg_rep(pool, pg, acting)
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("osd.%d: recovery pass failed", self.id)
                return

    def _local_objects(self, pool, pg, shard) -> list[str]:
        c = coll_t(pool.id, pg.ps, shard)
        if not self.store.collection_exists(c):
            return []
        return sorted({o.name for o in self.store.collection_list(c)})

    async def _recover_pg_ec(self, pool: PgPool, pg: pg_t, acting: list[int]) -> None:
        ec = self._ec_for(pool)
        sinfo = self._sinfo(ec)
        my_shard = next(
            (s for s, o in enumerate(acting) if o == self.id), None
        )
        if my_shard is None:
            return
        names = self._local_objects(pool, pg, my_shard)
        for oid in names:
            # probe which acting members miss this object's shard
            present: dict[int, int] = {}
            missing: list[tuple[int, int]] = []
            for shard, osd in enumerate(acting):
                if osd == CRUSH_ITEM_NONE:
                    continue
                try:
                    payload, attrs = await self._probe_shard(
                        pool, pg, shard, osd, oid
                    )
                except (OSError, asyncio.TimeoutError, ConnectionError):
                    continue
                if payload is None:
                    missing.append((shard, osd))
                else:
                    present[shard] = osd
            if not missing:
                continue
            log.info(
                "osd.%d: recovering %s/%s shards %s", self.id, pg, oid,
                [s for s, _ in missing],
            )
            # read enough present shards to rebuild the missing ones
            need = {s for s, _ in missing}
            chunks: dict[int, np.ndarray] = {}
            attrs_src: dict[str, bytes] = {}
            for shard, osd in present.items():
                payload, attrs, _eno = await self._read_shard(pool, pg, shard, osd, oid)
                if payload is not None:
                    chunks[shard] = np.frombuffer(payload, np.uint8)
                    if attrs:
                        attrs_src = attrs
            rebuilt = ecutil.decode_shards(sinfo, ec, chunks, need)
            for shard, osd in missing:
                payload = rebuilt[shard].tobytes()
                await self._push(pool, pg, shard, osd, oid, payload, attrs_src)

    async def _recover_pg_rep(self, pool: PgPool, pg: pg_t, acting: list[int]) -> None:
        names = self._local_objects(pool, pg, NO_SHARD)
        c = self._shard_coll(pool, pg, NO_SHARD)
        for oid in names:
            data = self.store.read(c, ghobject_t(oid))
            attrs = self.store.getattrs(c, ghobject_t(oid))
            for osd in acting:
                if osd in (self.id, CRUSH_ITEM_NONE):
                    continue
                payload, _ = await self._probe_shard(pool, pg, NO_SHARD, osd, oid)
                if payload is None:
                    await self._push(pool, pg, NO_SHARD, osd, oid, data, attrs)

    async def _probe_shard(self, pool, pg, shard, osd, oid):
        """Presence probe: zero-length read with attrs."""
        if osd == self.id:
            c = self._shard_coll(pool, pg, shard)
            o = ghobject_t(oid, shard=shard)
            if not self.store.exists(c, o):
                return None, None
            return b"", self.store.getattrs(c, o)
        tid = next(self._tids)
        rep = await self._sub_op(osd, MOSDECSubOpRead(
            tid=tid, pg=pg, shard=shard, from_osd=self.id, oid=oid,
            off=0, length=1, want_attrs=True, epoch=self.epoch,
        ), tid)
        if rep.result != 0:
            return None, None
        return rep.data, rep.attrs

    async def _push(self, pool, pg, shard, osd, oid, payload, attrs) -> None:
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._push_waiters[(pg, shard, osd)] = fut
        try:
            conn = await self._osd_conn(osd)
            await conn.send_message(MOSDPGPush(
                pg=pg, shard=shard, from_osd=self.id,
                pushes=[(oid, payload, attrs)], epoch=self.epoch,
            ))
            await asyncio.wait_for(fut, SUBOP_TIMEOUT)
        finally:
            self._push_waiters.pop((pg, shard, osd), None)

    async def _handle_push(self, msg: MOSDPGPush) -> None:
        pool = self.osdmap.get_pg_pool(msg.pg.pool)
        for oid, payload, attrs in msg.pushes:
            if msg.shard == NO_SHARD:
                self._apply_full_object(pool, msg.pg, oid, payload, attrs)
            else:
                self._apply_shard_write(
                    pool, msg.pg, msg.shard, oid, payload, attrs
                )
        await msg.conn.send_message(MOSDPGPushReply(
            pg=msg.pg, shard=msg.shard, from_osd=self.id, epoch=self.epoch,
        ))


ECConnErrors = (ConnectionError, asyncio.TimeoutError)
