"""OSD daemon: the object-service process of the mini-cluster.

The asyncio twin of the reference OSD's op path (src/osd/OSD.cc
dispatch -> PrimaryLogPG::do_op -> PGBackend submit, SURVEY.md §3.1):
boots into the mon (MOSDBoot), subscribes to maps, serves client ops as
primary, fans EC chunk writes/reads out to shard peers
(MOSDECSubOpWrite/Read — ECBackend::submit_transaction/handle_sub_*,
src/osd/ECBackend.cc:943,1022,1472), replicates full objects for
replicated pools (MOSDRepOp), and reconstructs missing shards after map
changes (RecoveryBackend::continue_recovery_op, ECBackend.cc:563 →
decode via ECUtil + MOSDPGPush).

Data layout matches the reference: one collection per PG shard
(coll_t(pool, ps, shard), ECTransaction.cc:80-88), chunk payloads at
chunk offsets, per-shard HashInfo crc chains in the ``hinfo`` xattr
(ECUtil.cc:164-248) and the logical size in ``_size`` (the object_info
analogue).

Consistency is log-based (ceph_tpu/osd/pglog.py): every write commits
a pg-log entry with the data; after a map change the primary runs
peering-lite (_recover_pg): pg_info exchange, log adoption from
newer members, per-peer missing sets from the log delta, and full
backfill with authoritative-list stray removal when trimmed past a
peer.  Reads verify object versions across chunks so revived members
with stale shards cannot corrupt results.

Deliberate simplifications vs the reference: the peering state machine
is a linear pass rather than boost::statechart, there is no
ObjectContext rw-locking (recovery races resolve by version guards and
the next pass), and sub-chunk (CLAY) recovery I/O goes through full
chunk reads.
"""

from __future__ import annotations

import asyncio
import errno
import itertools
import logging
import time

import numpy as np

from ceph_tpu.crush.types import CRUSH_ITEM_NONE
from ceph_tpu.ec import registry as ec_registry
from ceph_tpu.msg.messages import (
    MMonSubscribe,
    MOSDBeacon,
    MOSDBoot,
    MOSDECSubOpRead,
    MOSDECSubOpReadReply,
    MOSDECSubOpWrite,
    MOSDECSubOpWriteReply,
    MOSDFailure,
    MOSDMap,
    MOSDOp,
    MOSDOpReply,
    MOSDPGPush,
    MOSDPGPushReply,
    MOSDRepOp,
    MOSDRepOpReply,
    MOSDPGInfo,
    MOSDPGLog,
    MOSDPGLogAck,
    MOSDPGQuery,
    MOSDScrub,
    MOSDScrubReply,
    OP_DELETE,
    OP_READ,
    OP_STAT,
    OP_WRITE_FULL,
)
from ceph_tpu.msg.messenger import Connection, Message, Messenger
from ceph_tpu.ops.hashing import ceph_str_hash_rjenkins
from ceph_tpu.osd import ecutil
from ceph_tpu.osd.mapenc import apply_map_message
from ceph_tpu.osd.osdmap import OSDMap
from ceph_tpu.osd.pglog import (
    DELETE,
    MODIFY,
    PGMETA_OID,
    ZERO,
    PGLog,
    eversion_t,
    pg_log_entry_t,
)
from ceph_tpu.osd.types import PgPool, pg_t
from ceph_tpu.store import MemStore, Transaction, coll_t, ghobject_t

log = logging.getLogger("ceph_tpu.osd")

NO_SHARD = -1
STRIPE_UNIT = 4096  # logical bytes per data chunk per stripe
SUBOP_TIMEOUT = 30.0

SIZE_ATTR = "_size"
HINFO_ATTR = "hinfo"
VERSION_ATTR = "_v"  # object_info version (oi attr analogue)


def _v_bytes(v: eversion_t) -> bytes:
    return v.key().encode()


def _v_parse(raw: bytes | None) -> eversion_t:
    if not raw:
        return ZERO
    e, v = raw.decode().split(".")
    return eversion_t(int(e), int(v))


def object_to_pg(pool: PgPool, oid: str) -> pg_t:
    """object_locator_to_pg (src/osd/osd_types.cc): name hash -> raw pg
    (the mapping pipeline folds it into pg_num)."""
    return pg_t(pool.id, int(ceph_str_hash_rjenkins(oid)))


class OSDDaemon:
    def __init__(
        self,
        osd_id: int,
        mon_addr: tuple[str, int],
        store: MemStore | None = None,
        beacon_interval: float | None = None,
        conf=None,
    ):
        from ceph_tpu.common import ConfigProxy, get_perf_counters

        self.id = osd_id
        # one address or a monmap; the daemon hunts for a live monitor
        self.mon_addrs: list[tuple[str, int]] = (
            list(mon_addr) if isinstance(mon_addr, list) else [mon_addr]
        )
        self.mon_addr = self.mon_addrs[0]
        self.conf = conf if conf is not None else ConfigProxy()
        self.store = store or MemStore()
        self.messenger = Messenger(
            ("osd", osd_id), self._dispatch, on_reset=self._on_reset
        )
        self.messenger.inject_socket_failures = self.conf[
            "ms_inject_socket_failures"
        ]
        self.perf = get_perf_counters(f"osd.{osd_id}")
        self._log_keep = self.conf["osd_min_pg_log_entries"]
        self.osdmap: OSDMap | None = None
        self.beacon_interval = (
            beacon_interval
            if beacon_interval is not None
            else self.conf["osd_beacon_report_interval"]
        )
        self.addr: tuple[str, int] | None = None
        self._mon_conn: Connection | None = None
        self._tids = itertools.count(1)
        self._waiters: dict[int, asyncio.Future] = {}
        self._push_waiters: dict[tuple, asyncio.Future] = {}
        self._ec_cache: dict[str, object] = {}
        self._pg_logs: dict[coll_t, PGLog] = {}
        self._beacon_task: asyncio.Task | None = None
        self._recovery_task: asyncio.Task | None = None
        self._map_event = asyncio.Event()
        self.stopping = False
        # fresh per daemon start: lets the mon distinguish a fast
        # restart (new incarnation -> epoch bump, peers re-peer) from a
        # paxos replay of the same boot (no-op)
        self.incarnation = time.time_ns()

    # -- lifecycle -----------------------------------------------------

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> None:
        self.addr = await self.messenger.bind(host, port)
        await self._mon_hunt()
        if self.beacon_interval > 0:
            self._beacon_task = asyncio.ensure_future(self._beacon())
        # wait for the first map so ops can be served
        await asyncio.wait_for(self._map_event.wait(), 10)

    async def _mon_hunt(self) -> None:
        """Find a live monitor, (re)boot and (re)subscribe — the
        MonClient hunting behavior on monitor loss."""
        last: Exception | None = None
        for mhost, mport in self.mon_addrs:
            try:
                conn = await self.messenger.connect(mhost, mport)
                await conn.send_message(MOSDBoot(
                    osd=self.id, host=self.addr[0], port=self.addr[1],
                    incarnation=self.incarnation,
                ))
                await conn.send_message(MMonSubscribe(
                    start_epoch=self.osdmap.epoch if self.osdmap else 0
                ))
                self._mon_conn = conn
                return
            except (ConnectionError, OSError) as e:
                last = e
        raise ConnectionError(f"osd.{self.id}: no monitor reachable: {last}")

    async def stop(self) -> None:
        self.stopping = True
        for t in (
            self._beacon_task, self._recovery_task,
            getattr(self, "_rehome_task", None),
        ):
            if t:
                t.cancel()
        await self.messenger.shutdown()

    async def _beacon(self) -> None:
        while not self.stopping:
            await asyncio.sleep(self.beacon_interval)
            try:
                await self._mon_conn.send_message(
                    MOSDBeacon(osd=self.id, epoch=self.epoch)
                )
            except ConnectionError:
                continue  # mon died; the rehome task is hunting

    @property
    def epoch(self) -> int:
        return self.osdmap.epoch if self.osdmap else 0

    # -- plumbing ------------------------------------------------------

    async def _on_reset(self, conn: Connection) -> None:
        """Connection to a peer died: fail pending sub-ops and report
        the peer (the OSD::ms_handle_reset + failure-report path)."""
        if self.stopping or conn.peer is None:
            return
        kind, peer_id = conn.peer
        if kind == "mon" and conn is self._mon_conn:
            async def _rehome():
                for _ in range(20):
                    await asyncio.sleep(0.2)
                    if self.stopping:
                        return
                    try:
                        await self._mon_hunt()
                        return
                    except (ConnectionError, OSError):
                        continue
            self._rehome_task = asyncio.ensure_future(_rehome())
            return
        for tid, fut in list(self._waiters.items()):
            if getattr(fut, "peer", None) == conn.peer and not fut.done():
                fut.set_exception(ConnectionError(f"peer {conn.peer} reset"))
        if kind == "osd" and self.osdmap and self.osdmap.is_up(peer_id):
            try:
                await self._mon_conn.send_message(
                    MOSDFailure(
                        reporter=self.id, failed=peer_id, epoch=self.epoch
                    )
                )
            except ConnectionError:
                pass

    async def _osd_conn(self, osd: int) -> Connection:
        addr = self.osdmap.osd_addrs.get(osd)
        if addr is None:
            raise ConnectionError(f"no address for osd.{osd}")
        return await self.messenger.connect_to(("osd", osd), *addr)

    async def _sub_op(self, osd: int, msg: Message, tid: int):
        """Send a sub-op and await its reply future."""
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        fut.peer = ("osd", osd)
        self._waiters[tid] = fut
        try:
            conn = await self._osd_conn(osd)
            await conn.send_message(msg)
            return await asyncio.wait_for(fut, SUBOP_TIMEOUT)
        finally:
            self._waiters.pop(tid, None)

    def _ec_for(self, pool: PgPool):
        prof_name = pool.erasure_code_profile
        if prof_name not in self._ec_cache:
            profile = dict(self.osdmap.erasure_code_profiles[prof_name])
            ec = ec_registry.factory(profile.get("plugin", "jax"), profile)
            self._ec_cache[prof_name] = ec
        return self._ec_cache[prof_name]

    def _sinfo(self, ec) -> ecutil.StripeInfo:
        k = ec.get_data_chunk_count()
        chunk = ec.get_chunk_size(STRIPE_UNIT * k)
        return ecutil.StripeInfo(k, chunk * k)

    def _acting(self, pool: PgPool, pg: pg_t) -> tuple[list[int], int]:
        _, _, acting, primary = self.osdmap.pg_to_up_acting_osds(pg)
        return acting, primary

    def _pg_log(self, c: coll_t) -> PGLog:
        lg = self._pg_logs.get(c)
        if lg is None:
            lg = PGLog(c)
            lg.load(self.store)
            self._pg_logs[c] = lg
        return lg

    def _next_version(self, c: coll_t) -> eversion_t:
        lu = self._pg_log(c).info.last_update
        return eversion_t(self.epoch, lu.version + 1)

    def _object_version(self, c: coll_t, o: ghobject_t) -> eversion_t:
        try:
            return _v_parse(self.store.getattr(c, o, VERSION_ATTR))
        except (FileNotFoundError, KeyError):
            return ZERO

    # -- dispatch ------------------------------------------------------

    async def _dispatch(self, msg: Message) -> None:
        try:
            if isinstance(msg, MOSDMap):
                await self._handle_map(msg)
            elif isinstance(msg, MOSDOp):
                asyncio.ensure_future(self._handle_client_op(msg))
            elif isinstance(msg, MOSDECSubOpWrite):
                await self._handle_sub_write(msg)
            elif isinstance(msg, MOSDECSubOpRead):
                await self._handle_sub_read(msg)
            elif isinstance(msg, MOSDRepOp):
                await self._handle_rep_op(msg)
            elif isinstance(msg, MOSDPGPush):
                await self._handle_push(msg)
            elif isinstance(msg, MOSDPGQuery):
                await self._handle_pg_query(msg)
            elif isinstance(msg, MOSDPGLog):
                await self._handle_pg_log(msg)
            elif isinstance(msg, MOSDScrub):
                asyncio.ensure_future(self._handle_scrub(msg))
            elif isinstance(
                msg,
                (
                    MOSDECSubOpWriteReply, MOSDECSubOpReadReply,
                    MOSDRepOpReply, MOSDPGInfo, MOSDPGLogAck,
                ),
            ):
                fut = self._waiters.get(msg.tid)
                if fut and not fut.done():
                    fut.set_result(msg)
            elif isinstance(msg, MOSDPGPushReply):
                fut = self._push_waiters.get((msg.pg, msg.shard, msg.from_osd))
                if fut and not fut.done():
                    fut.set_result(msg)
        except Exception:
            log.exception("osd.%d: dispatch failed for %r", self.id, msg)

    async def _handle_map(self, msg: MOSDMap) -> None:
        # copy-on-write swap: code that captured self.osdmap mid-pass
        # keeps a stable snapshot (recovery, in-flight ops)
        new_map, gap = apply_map_message(self.osdmap, msg.maps, msg.incs)
        if new_map is not None:
            self.osdmap = new_map
        if gap:
            # ask the mon for the missing range (or a full map)
            await self._request_map_fill()
        self._map_event.set()
        log.info("osd.%d: map epoch %d", self.id, self.epoch)
        if self._recovery_task is None or self._recovery_task.done():
            self._recovery_task = asyncio.ensure_future(self._recover_all())

    async def _request_map_fill(self) -> None:
        try:
            if self._mon_conn is not None:
                await self._mon_conn.send_message(MMonSubscribe(
                    start_epoch=self.osdmap.epoch if self.osdmap else 0
                ))
        except ConnectionError:
            pass  # mon hunt will re-subscribe

    # -- client ops (the PrimaryLogPG::do_op slice) --------------------

    async def _handle_client_op(self, msg: MOSDOp) -> None:
        try:
            self.perf.inc("op")
            if msg.op in (OP_WRITE_FULL,):
                self.perf.inc("op_w")
                self.perf.inc("op_in_bytes", len(msg.data))
            elif msg.op in (OP_READ, OP_STAT):
                self.perf.inc("op_r")
            reply = await self._execute_op(msg)
            if msg.op == OP_READ and reply.result == 0:
                self.perf.inc("op_out_bytes", len(reply.data))
        except ECConnErrors as e:
            log.warning("osd.%d: op tid %d failed: %r", self.id, msg.tid, e)
            reply = MOSDOpReply(
                tid=msg.tid, result=-errno.EAGAIN, epoch=self.epoch
            )
        except Exception:
            log.exception("osd.%d: op tid %d crashed", self.id, msg.tid)
            reply = MOSDOpReply(tid=msg.tid, result=-errno.EIO, epoch=self.epoch)
        try:
            await msg.conn.send_message(reply)
        except ConnectionError:
            pass

    async def _execute_op(self, msg: MOSDOp) -> MOSDOpReply:
        pool = self.osdmap.get_pg_pool(msg.pool) if self.osdmap else None
        if pool is None:
            return MOSDOpReply(tid=msg.tid, result=-errno.ENOENT, epoch=self.epoch)
        pg = object_to_pg(pool, msg.oid)
        acting, primary = self._acting(pool, pg)
        if primary != self.id:
            # client raced a map change; tell it to retry on a newer map
            return MOSDOpReply(tid=msg.tid, result=-errno.EAGAIN, epoch=self.epoch)
        if pool.is_erasure():
            return await self._ec_op(pool, pg, acting, msg)
        return await self._rep_op(pool, pg, acting, msg)

    # -- EC backend ----------------------------------------------------

    def _shard_coll(self, pool: PgPool, pg: pg_t, shard: int) -> coll_t:
        return coll_t(pool.id, pool.raw_pg_to_pg(pg).ps, shard)

    def _ensure_coll(self, t: Transaction, c: coll_t) -> None:
        if not self.store.collection_exists(c):
            t.create_collection(c)

    async def _ec_op(
        self, pool: PgPool, pg: pg_t, acting: list[int], msg: MOSDOp
    ) -> MOSDOpReply:
        ec = self._ec_for(pool)
        sinfo = self._sinfo(ec)
        if msg.op == OP_WRITE_FULL:
            return await self._ec_write_full(pool, pg, acting, msg, ec, sinfo)
        if msg.op in (OP_READ, OP_STAT):
            return await self._ec_read(pool, pg, acting, msg, ec, sinfo)
        if msg.op == OP_DELETE:
            return await self._ec_delete(pool, pg, acting, msg)
        return MOSDOpReply(tid=msg.tid, result=-errno.EOPNOTSUPP, epoch=self.epoch)

    async def _ec_write_full(self, pool, pg, acting, msg, ec, sinfo) -> MOSDOpReply:
        data = np.frombuffer(msg.data, dtype=np.uint8)
        padded_len = sinfo.logical_to_next_stripe_offset(len(data))
        padded = np.zeros(padded_len, np.uint8)
        padded[: len(data)] = data
        if padded_len:
            shards = ecutil.encode(sinfo, ec, padded)
        else:  # empty object: every shard holds an empty chunk
            empty = np.zeros(0, np.uint8)
            shards = {s: empty for s in range(ec.get_chunk_count())}
        live = [
            (shard, osd)
            for shard, osd in enumerate(acting)
            if osd != CRUSH_ITEM_NONE
        ]
        if len(live) < pool.min_size:
            return MOSDOpReply(tid=msg.tid, result=-errno.EAGAIN, epoch=self.epoch)
        my_shard = next((s for s, o in live if o == self.id), None)
        if my_shard is None:
            # a primary that holds no shard of the live set would mint
            # versions from a PG log it never writes, defeating the
            # stale-shard guards — bounce the op instead
            return MOSDOpReply(tid=msg.tid, result=-errno.EAGAIN, epoch=self.epoch)
        version = self._next_version(self._shard_coll(pool, pg, my_shard))
        hinfo = ecutil.HashInfo(ec.get_chunk_count())
        hinfo.append(0, shards)
        attrs = {
            HINFO_ATTR: hinfo.to_bytes(),
            SIZE_ATTR: str(len(data)).encode(),
            VERSION_ATTR: _v_bytes(version),
        }
        waits = []
        for shard, osd in live:
            payload = shards[shard].tobytes()
            if osd == self.id:
                await self._apply_shard_write_async(
                    pool, pg, shard, msg.oid, payload, attrs, version=version
                )
            else:
                tid = next(self._tids)
                waits.append(self._sub_op(osd, MOSDECSubOpWrite(
                    tid=tid, pg=pg, shard=shard, from_osd=self.id,
                    oid=msg.oid, off=0, data=payload, attrs=attrs,
                    epoch=self.epoch, truncate=len(payload), version=version,
                ), tid))
        if waits:
            replies = await asyncio.gather(*waits)
            for rep in replies:
                if rep.result != 0:
                    return MOSDOpReply(
                        tid=msg.tid, result=rep.result, epoch=self.epoch
                    )
        return MOSDOpReply(tid=msg.tid, result=0, epoch=self.epoch)

    def _apply_shard_write(
        self, pool, pg, shard, oid, payload: bytes, attrs,
        delete=False, version: eversion_t = ZERO,
    ) -> None:
        """Apply a shard write + (when versioned) its pg-log entry in
        ONE transaction — the reference couples data and log the same
        way (ECTransaction appends log entries to the shard txn)."""
        self.store.queue_transaction(
            self._shard_write_txn(pool, pg, shard, oid, payload, attrs,
                                  delete, version)
        )

    async def _apply_shard_write_async(
        self, pool, pg, shard, oid, payload: bytes, attrs,
        delete=False, version: eversion_t = ZERO,
    ) -> None:
        """Same, but journaling stores fsync: run their commit on a
        worker thread so one OSD's disk flush never stalls the whole
        event loop (the reference's journaling happens on dedicated
        finisher threads for the same reason)."""
        t = self._shard_write_txn(
            pool, pg, shard, oid, payload, attrs, delete, version
        )
        if getattr(self.store, "blocking_commit", False):
            await asyncio.to_thread(self.store.queue_transaction, t)
        else:
            self.store.queue_transaction(t)

    def _shard_write_txn(
        self, pool, pg, shard, oid, payload, attrs, delete, version
    ) -> Transaction:
        c = self._shard_coll(pool, pg, shard)
        o = ghobject_t(oid, shard=shard)
        t = Transaction()
        self._ensure_coll(t, c)
        if delete:
            if self.store.exists(c, o):
                t.remove(c, o)
        else:
            t.touch(c, o).truncate(c, o, len(payload)).write(c, o, 0, payload)
            t.setattrs(c, o, attrs)
        if version > ZERO:
            lg = self._pg_log(c)
            if version > lg.info.last_update:
                prior = self._object_version(c, o)
                lg.append(t, pg_log_entry_t(
                    DELETE if delete else MODIFY, oid, version, prior,
                ))
                lg.trim(t, self._log_keep)
        return t

    async def _ec_read(self, pool, pg, acting, msg, ec, sinfo) -> MOSDOpReply:
        k = ec.get_data_chunk_count()
        avail = {
            shard: osd for shard, osd in enumerate(acting)
            if osd != CRUSH_ITEM_NONE and self.osdmap.is_up(osd)
        }
        excluded: dict[int, int] = {}  # shard -> errno seen
        for _attempt in range(len(acting) + 1):
            usable = {s: o for s, o in avail.items() if s not in excluded}
            want = set(range(k))
            try:
                minimum = ec.minimum_to_decode(want, set(usable))
            except Exception:
                break  # not enough shards left to decode
            need_shards = set(minimum)
            chunks: dict[int, np.ndarray] = {}
            shard_attrs: dict[int, dict[str, bytes]] = {}
            # concurrent fan-out: degraded-read latency is the max
            # shard RTT, not the sum (the reference sends ECSubRead to
            # all shards at once, src/osd/ECCommon.cc:440-445)
            results = await asyncio.gather(*(
                self._read_shard_quiet(pool, pg, s, usable[s], msg.oid)
                for s in sorted(need_shards)
            ))
            failed = False
            for shard, (payload, a, eno) in zip(sorted(need_shards), results):
                if payload is None:
                    excluded[shard] = eno
                    failed = True
                else:
                    chunks[shard] = np.frombuffer(payload, np.uint8)
                    shard_attrs[shard] = a or {}
            if failed:
                continue
            # a revived OSD may hold a STALE chunk from before it went
            # down: all chunks used in one decode must carry the same
            # object version (object_info consistency; the reference
            # reaches this via peering/recovery before serving)
            versions = {
                s: _v_parse(a.get(VERSION_ATTR)) for s, a in shard_attrs.items()
            }
            vmax = max(versions.values(), default=ZERO)
            stale = [s for s, v in versions.items() if v < vmax]
            if stale:
                for s in stale:
                    excluded[s] = errno.ESTALE
                continue
            attrs = next(iter(shard_attrs.values()), {})
            if not attrs or SIZE_ATTR not in attrs:
                return MOSDOpReply(
                    tid=msg.tid, result=-errno.ENOENT, epoch=self.epoch
                )
            size = int(attrs[SIZE_ATTR])
            if msg.op == OP_STAT:
                return MOSDOpReply(
                    tid=msg.tid, result=0, epoch=self.epoch, size=size
                )
            logical = ecutil.decode_concat(sinfo, ec, chunks)[:size]
            off = msg.off
            end = size if msg.length == 0 else min(off + msg.length, size)
            return MOSDOpReply(
                tid=msg.tid, result=0, epoch=self.epoch, size=size,
                data=logical[off:end].tobytes(),
            )
        # decode never succeeded: a fully-absent object reports ENOENT,
        # anything else is a real I/O failure
        if excluded and all(e == errno.ENOENT for e in excluded.values()):
            return MOSDOpReply(tid=msg.tid, result=-errno.ENOENT, epoch=self.epoch)
        return MOSDOpReply(tid=msg.tid, result=-errno.EIO, epoch=self.epoch)

    async def _read_shard_quiet(self, pool, pg, shard, osd, oid):
        """_read_shard with transport failures mapped to EIO."""
        try:
            return await self._read_shard(pool, pg, shard, osd, oid)
        except (OSError, asyncio.TimeoutError, ConnectionError):
            return None, None, errno.EIO

    async def _read_shard(self, pool, pg, shard, osd, oid):
        """Full-chunk read of one shard: (payload, attrs, errno)."""
        if osd == self.id:
            c = self._shard_coll(pool, pg, shard)
            o = ghobject_t(oid, shard=shard)
            if not self.store.exists(c, o):
                return None, None, errno.ENOENT
            return self.store.read(c, o), self.store.getattrs(c, o), 0
        tid = next(self._tids)
        rep = await self._sub_op(osd, MOSDECSubOpRead(
            tid=tid, pg=pg, shard=shard, from_osd=self.id, oid=oid,
            off=0, length=0, want_attrs=True, epoch=self.epoch,
        ), tid)
        if rep.result != 0:
            return None, None, -rep.result
        return rep.data, rep.attrs, 0

    async def _ec_delete(self, pool, pg, acting, msg) -> MOSDOpReply:
        my_shard = next(
            (s for s, o in enumerate(acting) if o == self.id), None
        )
        if my_shard is None:
            # same guard as _ec_write_full: never mint versions from a
            # shard log this OSD doesn't own
            return MOSDOpReply(tid=msg.tid, result=-errno.EAGAIN, epoch=self.epoch)
        version = self._next_version(self._shard_coll(pool, pg, my_shard))
        waits = []
        for shard, osd in enumerate(acting):
            if osd == CRUSH_ITEM_NONE:
                continue
            if osd == self.id:
                await self._apply_shard_write_async(
                    pool, pg, shard, msg.oid, b"", {}, delete=True,
                    version=version,
                )
            else:
                tid = next(self._tids)
                waits.append(self._sub_op(osd, MOSDECSubOpWrite(
                    tid=tid, pg=pg, shard=shard, from_osd=self.id,
                    oid=msg.oid, off=0, data=b"", attrs={},
                    epoch=self.epoch, delete=True, version=version,
                ), tid))
        if waits:
            await asyncio.gather(*waits)
        return MOSDOpReply(tid=msg.tid, result=0, epoch=self.epoch)

    async def _handle_sub_write(self, msg: MOSDECSubOpWrite) -> None:
        pool = self.osdmap.get_pg_pool(msg.pg.pool)
        result = 0
        try:
            skip = False
            if msg.guard > ZERO:
                c = self._shard_coll(pool, msg.pg, msg.shard)
                o = ghobject_t(msg.oid, shard=msg.shard)
                skip = self._object_version(c, o) > msg.guard
            if not skip:
                await self._apply_shard_write_async(
                    pool, msg.pg, msg.shard, msg.oid, msg.data, msg.attrs,
                    delete=msg.delete, version=msg.version,
                )
        except OSError as e:
            result = -(e.errno or errno.EIO)
        await msg.conn.send_message(MOSDECSubOpWriteReply(
            tid=msg.tid, pg=msg.pg, shard=msg.shard, from_osd=self.id,
            result=result, epoch=self.epoch,
        ))

    async def _handle_sub_read(self, msg: MOSDECSubOpRead) -> None:
        pool = self.osdmap.get_pg_pool(msg.pg.pool)
        c = self._shard_coll(pool, msg.pg, msg.shard)
        o = ghobject_t(msg.oid, shard=msg.shard)
        if not self.store.exists(c, o):
            rep = MOSDECSubOpReadReply(
                tid=msg.tid, pg=msg.pg, shard=msg.shard, from_osd=self.id,
                result=-errno.ENOENT, epoch=self.epoch,
            )
        else:
            data = self.store.read(
                c, o, msg.off, None if msg.length == 0 else msg.length
            )
            attrs = self.store.getattrs(c, o) if msg.want_attrs else {}
            rep = MOSDECSubOpReadReply(
                tid=msg.tid, pg=msg.pg, shard=msg.shard, from_osd=self.id,
                result=0, data=data, attrs=attrs, epoch=self.epoch,
            )
        await msg.conn.send_message(rep)

    # -- replicated backend -------------------------------------------

    async def _rep_op(self, pool, pg, acting, msg) -> MOSDOpReply:
        c = self._shard_coll(pool, pg, NO_SHARD)
        o = ghobject_t(msg.oid)
        if msg.op == OP_READ:
            if not self.store.exists(c, o):
                return MOSDOpReply(tid=msg.tid, result=-errno.ENOENT, epoch=self.epoch)
            data = self.store.read(c, o, msg.off, msg.length or None)
            return MOSDOpReply(
                tid=msg.tid, result=0, data=data, epoch=self.epoch,
                size=self.store.stat(c, o),
            )
        if msg.op == OP_STAT:
            if not self.store.exists(c, o):
                return MOSDOpReply(tid=msg.tid, result=-errno.ENOENT, epoch=self.epoch)
            return MOSDOpReply(
                tid=msg.tid, result=0, epoch=self.epoch, size=self.store.stat(c, o)
            )
        if msg.op not in (OP_WRITE_FULL, OP_DELETE):
            return MOSDOpReply(tid=msg.tid, result=-errno.EOPNOTSUPP, epoch=self.epoch)
        delete = msg.op == OP_DELETE
        version = self._next_version(self._shard_coll(pool, pg, NO_SHARD))
        attrs = {
            SIZE_ATTR: str(len(msg.data)).encode(),
            VERSION_ATTR: _v_bytes(version),
        }
        await self._apply_full_object(pool, pg, msg.oid, msg.data, attrs, delete, version)
        waits = []
        for osd in acting:
            if osd in (self.id, CRUSH_ITEM_NONE):
                continue
            tid = next(self._tids)
            waits.append(self._sub_op(osd, MOSDRepOp(
                tid=tid, pg=pg, from_osd=self.id, oid=msg.oid,
                data=b"" if delete else msg.data, attrs=attrs,
                delete=delete, epoch=self.epoch, version=version,
            ), tid))
        if waits:
            replies = await asyncio.gather(*waits)
            for rep in replies:
                if rep.result != 0:
                    return MOSDOpReply(tid=msg.tid, result=rep.result, epoch=self.epoch)
        return MOSDOpReply(tid=msg.tid, result=0, epoch=self.epoch)

    async def _apply_full_object(
        self, pool, pg, oid, data, attrs, delete=False,
        version: eversion_t = ZERO,
    ):
        await self._apply_shard_write_async(
            pool, pg, NO_SHARD, oid, data, attrs, delete=delete,
            version=version,
        )

    async def _handle_rep_op(self, msg: MOSDRepOp) -> None:
        pool = self.osdmap.get_pg_pool(msg.pg.pool)
        result = 0
        try:
            await self._apply_full_object(
                pool, msg.pg, msg.oid, msg.data, msg.attrs, msg.delete,
                msg.version,
            )
        except OSError as e:
            result = -(e.errno or errno.EIO)
        await msg.conn.send_message(MOSDRepOpReply(
            tid=msg.tid, pg=msg.pg, from_osd=self.id, result=result,
            epoch=self.epoch,
        ))

    # -- recovery ------------------------------------------------------

    async def _recover_all(self) -> None:
        """After a map change: for every PG this OSD leads, reconstruct
        missing shards/objects on the current acting set (the
        do_recovery -> recover_object path, §3.3).  Re-runs until a
        full pass has seen the newest map (epochs can land mid-pass)."""
        done_epoch = -1
        while done_epoch != self.epoch and not self.stopping:
            done_epoch = self.epoch
            try:
                om = self.osdmap
                for pid, pool in list(om.pools.items()):
                    for ps in range(pool.pg_num):
                        pg = pg_t(pid, ps)
                        _, _, acting, primary = om.pg_to_up_acting_osds(
                            pg, folded=True
                        )
                        if primary != self.id:
                            continue
                        await self._recover_pg(pool, pg, acting)
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("osd.%d: recovery pass failed", self.id)
                return

    def _local_objects(self, pool, pg, shard) -> list[str]:
        c = self._shard_coll(pool, pg, shard)
        if not self.store.collection_exists(c):
            return []
        return sorted(
            {o.name for o in self.store.collection_list(c)} - {PGMETA_OID}
        )

    def _pg_members(
        self, pool: PgPool, acting: list[int]
    ) -> list[tuple[int, int]]:
        """(shard, osd) pairs of the acting set; replicated members all
        use NO_SHARD collections."""
        if pool.is_erasure():
            return [
                (s, o) for s, o in enumerate(acting) if o != CRUSH_ITEM_NONE
            ]
        return [(NO_SHARD, o) for o in acting if o != CRUSH_ITEM_NONE]

    async def _recover_pg(self, pool: PgPool, pg: pg_t, acting: list[int]) -> None:
        """Peering-lite + recovery for one PG this OSD leads.

        1. collect pg_info from every acting member (MOSDPGQuery);
        2. adopt log entries from any member ahead of us (we may have
           been the one that was down);
        3. scope the object set: exact per-peer missing sets when the
           log covers everyone (PGLog::proc_replica_log), full
           backfill over the union of object lists otherwise;
        4. reconcile each object to its newest version (reconstruct +
           MOSDPGPush / replayed delete);
        5. bring lagging members' logs current (MOSDPGLog).
        """
        pairs = self._pg_members(pool, acting)
        if self.id not in [o for _, o in pairs]:
            return
        my_shard = next(s for s, o in pairs if o == self.id)
        myc = self._shard_coll(pool, pg, my_shard)
        lg = self._pg_log(myc)

        peer_infos: dict[tuple[int, int], MOSDPGInfo] = {}
        for s, o in pairs:
            if o == self.id:
                continue
            try:
                peer_infos[(s, o)] = await self._pg_query(
                    pool, pg, s, o, since=lg.info.last_update
                )
            except (OSError, asyncio.TimeoutError, ConnectionError):
                continue  # unreachable; next map change retries

        pre_adopt_lu = lg.info.last_update
        ahead = [
            i for i in peer_infos.values()
            if i.last_update > lg.info.last_update
        ]
        gapped = False
        if ahead:
            best = max(ahead, key=lambda i: i.last_update)
            # a peer whose log_tail moved past our state means its
            # entries_after(our lu) delta has a hole: everything in the
            # trimmed range must come from backfill, and our own log
            # must admit the gap (set_tail) so covers() stays truthful
            gapped = best.log_tail > pre_adopt_lu
            t = Transaction()
            self._ensure_coll(t, myc)
            if gapped:
                lg.set_tail(t, best.log_tail)
            for raw in best.entries:
                e = pg_log_entry_t.decode(raw)
                if e.version > lg.info.last_update:
                    lg.append(t, e)
            lg.trim(t, self._log_keep)
            if not t.empty():
                self.store.queue_transaction(t)

        # scope
        scope: set[str] | None = None if gapped else set()
        if scope is not None:
            for info in peer_infos.values():
                miss = lg.missing_from(info.last_update)
                if miss is None:
                    scope = None
                    break
                scope |= set(miss.items)
        if ahead and scope is not None:
            # entries adopted above may name objects my own shard lacks
            for raw in max(ahead, key=lambda i: i.last_update).entries:
                e = pg_log_entry_t.decode(raw)
                scope.add(e.oid)
        strays: set[str] = set()
        if scope is None:
            # backfill: reconcile the union of object lists, but the
            # member with the newest pre-recovery state is authoritative
            # for WHICH objects exist — an object only held by stale
            # members is a stray (deleted while they were down), never
            # resurrected (reference backfill removes strays the same
            # way)
            objs = set(self._local_objects(pool, pg, my_shard))
            lists: dict[tuple[int, int], set[str]] = {
                (my_shard, self.id): set(objs)
            }
            lus = {(my_shard, self.id): pre_adopt_lu}
            for (s, o), info in list(peer_infos.items()):
                try:
                    full = await self._pg_query(
                        pool, pg, s, o, since=lg.info.last_update,
                        want_objects=True,
                    )
                except (OSError, asyncio.TimeoutError, ConnectionError):
                    continue
                lists[(s, o)] = {oid for oid, _v in full.objects}
                lus[(s, o)] = info.last_update
                objs |= lists[(s, o)]
            auth = max(lus, key=lambda k: lus[k])
            strays = objs - lists[auth]
        else:
            objs = scope
        for oid in sorted(objs):
            try:
                await self._reconcile_object(
                    pool, pg, pairs, oid, stray=oid in strays
                )
            except (OSError, asyncio.TimeoutError, ConnectionError):
                log.warning(
                    "osd.%d: reconcile %s/%s interrupted", self.id, pg, oid
                )
                return
        # log sync
        for (s, o), info in peer_infos.items():
            if info.last_update >= lg.info.last_update:
                continue
            entries = [
                e.encode() for e in lg.entries_after(info.last_update)
            ]
            try:
                await self._pg_log_send(pool, pg, s, o, entries, lg.info.log_tail)
            except (OSError, asyncio.TimeoutError, ConnectionError):
                continue

    async def _reconcile_object(
        self, pool: PgPool, pg: pg_t, pairs: list[tuple[int, int]], oid: str,
        stray: bool = False,
    ) -> None:
        """Bring one object to its newest version on every acting
        member: replay deletes, remove strays, reconstruct
        stale/missing shards from the members holding the newest
        version."""
        is_ec = pool.is_erasure()
        my_shard = next(s for s, o in pairs if o == self.id)
        lg = self._pg_log(self._shard_coll(pool, pg, my_shard))
        latest: pg_log_entry_t | None = None
        for v in sorted(lg.entries, reverse=True):
            if lg.entries[v].oid == oid:
                latest = lg.entries[v]
                break

        state: dict[tuple[int, int], tuple[bool, eversion_t, dict]] = {}
        for s, o in pairs:
            try:
                payload, attrs = await self._probe_shard(pool, pg, s, o, oid)
            except (OSError, asyncio.TimeoutError, ConnectionError):
                continue  # unreachable: not a source nor target now
            if payload is None:
                state[(s, o)] = (False, ZERO, {})
            else:
                state[(s, o)] = (
                    True, _v_parse((attrs or {}).get(VERSION_ATTR)), attrs or {}
                )

        delete_entry = latest is not None and latest.op == DELETE
        if delete_entry or (stray and latest is None):
            # logged delete replay, or a backfill stray (only stale
            # members hold it; its DELETE entry was trimmed)
            guard = latest.version if latest else lg.info.last_update
            for (s, o), (present, _v, _a) in state.items():
                if present:
                    await self._recovery_delete(pool, pg, s, o, oid, guard)
            return

        versions = [v for (p, v, _a) in state.values() if p]
        if not versions:
            return  # nothing anywhere to recover from
        vmax = max(versions)
        sources = {
            s: o for (s, o), (p, v, _a) in state.items() if p and v == vmax
        }
        targets = [
            (s, o) for (s, o), (p, v, _a) in state.items()
            if not p or v < vmax
        ]
        if not targets:
            return
        log.info(
            "osd.%d: recovering %s/%s to %s on %s", self.id, pg, oid,
            vmax, targets,
        )
        self.perf.inc("recovery_ops")
        src_attrs = next(
            a for (s, o), (p, v, a) in state.items() if p and v == vmax
        )
        if not is_ec:
            s0, o0 = next(iter(sources.items()))
            payload, _a, _e = await self._read_shard_quiet(
                pool, pg, s0, o0, oid
            )
            if payload is None:
                return
            await asyncio.gather(*(
                self._push(pool, pg, s, o, oid, payload, src_attrs)
                for s, o in targets
            ), return_exceptions=True)  # a dead target must not abort
            return                      # the rest of the recovery pass
        ec = self._ec_for(pool)
        sinfo = self._sinfo(ec)
        k = ec.get_data_chunk_count()
        if len(sources) < k:
            log.error(
                "osd.%d: %s/%s unrecoverable: %d/%d consistent shards",
                self.id, pg, oid, len(sources), k,
            )
            return
        # helper-shard reads and shard pushes both fan out concurrently
        # (the reference's ECSubRead/MOSDPGPush are fire-and-gather)
        chunks: dict[int, np.ndarray] = {}
        src_items = list(sources.items())
        payloads = await asyncio.gather(*(
            self._read_shard_quiet(pool, pg, s, o, oid) for s, o in src_items
        ))
        for (s, o), (payload, _a, _e) in zip(src_items, payloads):
            if payload is not None:
                chunks[s] = np.frombuffer(payload, np.uint8)
        if len(chunks) < k:
            log.error(
                "osd.%d: %s/%s recovery aborted: %d/%d source reads "
                "succeeded", self.id, pg, oid, len(chunks), k,
            )
            return
        need = {s for s, _ in targets}
        rebuilt = ecutil.decode_shards(sinfo, ec, chunks, need)
        await asyncio.gather(*(
            self._push(pool, pg, s, o, oid, rebuilt[s].tobytes(), src_attrs)
            for s, o in targets
        ), return_exceptions=True)  # dead targets retry on the next pass

    async def _recovery_delete(
        self, pool, pg, shard, osd, oid, guard: eversion_t
    ) -> None:
        """Replay of a logged delete on a stale member (unlogged: the
        log itself syncs separately).  ``guard`` protects a concurrent
        re-create: members whose object is newer than the delete keep
        it."""
        if osd == self.id:
            c = self._shard_coll(pool, pg, shard)
            if self._object_version(c, ghobject_t(oid, shard=shard)) > guard:
                return
            await self._apply_shard_write_async(
                pool, pg, shard, oid, b"", {}, delete=True
            )
            return
        tid = next(self._tids)
        await self._sub_op(osd, MOSDECSubOpWrite(
            tid=tid, pg=pg, shard=shard, from_osd=self.id, oid=oid,
            off=0, data=b"", attrs={}, epoch=self.epoch, delete=True,
            guard=guard,
        ), tid)

    async def _pg_query(
        self, pool, pg, shard, osd, since, want_objects: bool = False
    ) -> MOSDPGInfo:
        if osd == self.id:
            raise ValueError("query self")
        tid = next(self._tids)
        return await self._sub_op(osd, MOSDPGQuery(
            tid=tid, pg=pg, shard=shard, from_osd=self.id, since=since,
            want_objects=want_objects, epoch=self.epoch,
        ), tid)

    async def _pg_log_send(self, pool, pg, shard, osd, entries, tail) -> None:
        tid = next(self._tids)
        await self._sub_op(osd, MOSDPGLog(
            tid=tid, pg=pg, shard=shard, from_osd=self.id,
            entries=entries, epoch=self.epoch, tail=tail,
        ), tid)

    async def _handle_pg_query(self, msg: MOSDPGQuery) -> None:
        pool = self.osdmap.get_pg_pool(msg.pg.pool)
        c = self._shard_coll(pool, msg.pg, msg.shard)
        lg = self._pg_log(c)
        entries = [e.encode() for e in lg.entries_after(msg.since)]
        objects: list[tuple[str, bytes]] = []
        if msg.want_objects and self.store.collection_exists(c):
            for name in self._local_objects(pool, msg.pg, msg.shard):
                o = ghobject_t(name, shard=msg.shard)
                try:
                    v = self.store.getattr(c, o, VERSION_ATTR)
                except (FileNotFoundError, KeyError):
                    v = b""
                objects.append((name, v))
        await msg.conn.send_message(MOSDPGInfo(
            tid=msg.tid, pg=msg.pg, shard=msg.shard, from_osd=self.id,
            last_update=lg.info.last_update, log_tail=lg.info.log_tail,
            entries=entries, objects=objects, epoch=self.epoch,
        ))

    async def _handle_pg_log(self, msg: MOSDPGLog) -> None:
        pool = self.osdmap.get_pg_pool(msg.pg.pool)
        c = self._shard_coll(pool, msg.pg, msg.shard)
        lg = self._pg_log(c)
        t = Transaction()
        self._ensure_coll(t, c)
        lg.set_tail(t, msg.tail)
        for raw in msg.entries:
            e = pg_log_entry_t.decode(raw)
            if e.version > lg.info.last_update:
                lg.append(t, e)
        lg.trim(t, self._log_keep)
        if not t.empty():
            self.store.queue_transaction(t)
        await msg.conn.send_message(MOSDPGLogAck(
            tid=msg.tid, pg=msg.pg, shard=msg.shard, from_osd=self.id,
            result=0, epoch=self.epoch,
        ))

    async def _probe_shard(self, pool, pg, shard, osd, oid):
        """Presence probe: zero-length read with attrs."""
        if osd == self.id:
            c = self._shard_coll(pool, pg, shard)
            o = ghobject_t(oid, shard=shard)
            if not self.store.exists(c, o):
                return None, None
            return b"", self.store.getattrs(c, o)
        tid = next(self._tids)
        rep = await self._sub_op(osd, MOSDECSubOpRead(
            tid=tid, pg=pg, shard=shard, from_osd=self.id, oid=oid,
            off=0, length=1, want_attrs=True, epoch=self.epoch,
        ), tid)
        if rep.result != 0:
            return None, None
        return rep.data, rep.attrs

    async def _push(self, pool, pg, shard, osd, oid, payload, attrs) -> None:
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._push_waiters[(pg, shard, osd)] = fut
        try:
            conn = await self._osd_conn(osd)
            await conn.send_message(MOSDPGPush(
                pg=pg, shard=shard, from_osd=self.id,
                pushes=[(oid, payload, attrs)], epoch=self.epoch,
            ))
            await asyncio.wait_for(fut, SUBOP_TIMEOUT)
        finally:
            self._push_waiters.pop((pg, shard, osd), None)

    # -- scrub (src/osd/scrubber/, simplified to one pass) -------------

    async def _handle_scrub(self, msg: MOSDScrub) -> None:
        import json

        try:
            report = await self.scrub_pg(msg.pool, msg.ps, deep=msg.deep)
            reply = MOSDScrubReply(
                tid=msg.tid, result=0, report=json.dumps(report).encode()
            )
        except Exception as e:
            log.exception("osd.%d: scrub failed", self.id)
            reply = MOSDScrubReply(
                tid=msg.tid, result=-errno.EIO, report=str(e).encode()
            )
        try:
            await msg.conn.send_message(reply)
        except ConnectionError:
            pass

    async def scrub_pg(self, pool_id: int, ps: int, deep: bool = False) -> dict:
        """Consistency check of one PG across its acting set: object
        sets and versions must agree (shallow); with ``deep``, every
        shard payload's crc32c must match the stored HashInfo chain
        (reference: scrub_backend comparing shard crcs vs hinfo,
        src/osd/scrubber/scrub_backend.cc)."""
        from ceph_tpu.native import crc32c

        pool = self.osdmap.get_pg_pool(pool_id)
        if pool is None:
            return {"error": f"no pool {pool_id}"}
        pg = pg_t(pool_id, ps)
        _, _, acting, primary = self.osdmap.pg_to_up_acting_osds(pg, folded=True)
        if primary != self.id:
            return {"error": f"osd.{self.id} is not primary for {pool_id}.{ps}"}
        pairs = self._pg_members(pool, acting)

        member_objects: dict[str, dict[str, bytes]] = {}
        for s, o in pairs:
            key = f"{s}@osd.{o}"
            if o == self.id:
                objs = {}
                c = self._shard_coll(pool, pg, s)
                for name in self._local_objects(pool, pg, s):
                    go = ghobject_t(name, shard=s)
                    try:
                        objs[name] = self.store.getattr(c, go, VERSION_ATTR)
                    except (FileNotFoundError, KeyError):
                        objs[name] = b""
                member_objects[key] = objs
            else:
                info = await self._pg_query(
                    pool, pg, s, o, since=ZERO, want_objects=True
                )
                member_objects[key] = dict(info.objects)

        inconsistencies: list[dict] = []
        all_oids = sorted(set().union(*member_objects.values()) if member_objects else set())
        for oid in all_oids:
            versions = {
                key: objs.get(oid) for key, objs in member_objects.items()
            }
            have = {k: v for k, v in versions.items() if v is not None}
            if len(have) != len(member_objects) or len(set(have.values())) > 1:
                inconsistencies.append({
                    "object": oid, "kind": "shallow",
                    "versions": {
                        k: (v.decode() if v else None) for k, v in versions.items()
                    },
                })
                continue
            if not deep:
                continue
            # deep: payload crc vs the stored HashInfo chain
            hinfo_raw = None
            crcs: dict[str, int] = {}
            sizes: dict[str, int] = {}
            for s, o in pairs:
                key = f"{s}@osd.{o}"
                payload, attrs, _e = await self._read_shard(pool, pg, s, o, oid)
                if payload is None:
                    inconsistencies.append({
                        "object": oid, "kind": "deep-missing", "member": key,
                    })
                    continue
                crcs[key] = crc32c(payload)
                sizes[key] = len(payload)
                if attrs and HINFO_ATTR in attrs:
                    hinfo_raw = attrs[HINFO_ATTR]
                if pool.is_erasure() and hinfo_raw:
                    hi = ecutil.HashInfo.from_bytes(hinfo_raw)
                    want = hi.get_chunk_hash(s)
                    if want != crcs[key]:
                        inconsistencies.append({
                            "object": oid, "kind": "deep-crc", "member": key,
                            "stored": want, "computed": crcs[key],
                        })
            if not pool.is_erasure() and len(set(crcs.values())) > 1:
                inconsistencies.append({
                    "object": oid, "kind": "deep-replica-crc",
                    "crcs": crcs,
                })
        return {
            "pg": f"{pool_id}.{ps}",
            "acting": [o for _, o in pairs],
            "objects": len(all_oids),
            "deep": deep,
            "inconsistencies": inconsistencies,
        }

    async def _handle_push(self, msg: MOSDPGPush) -> None:
        pool = self.osdmap.get_pg_pool(msg.pg.pool)
        for oid, payload, attrs in msg.pushes:
            # never regress: a write may have landed here between the
            # primary's probe and this push (the reference serializes
            # this with per-object rw locks; we reconcile on the next
            # recovery pass instead)
            c = self._shard_coll(pool, msg.pg, msg.shard)
            local_v = self._object_version(c, ghobject_t(oid, shard=msg.shard))
            pushed_v = _v_parse(attrs.get(VERSION_ATTR))
            if local_v > pushed_v:
                continue
            if msg.shard == NO_SHARD:
                await self._apply_full_object(pool, msg.pg, oid, payload, attrs)
            else:
                await self._apply_shard_write_async(
                    pool, msg.pg, msg.shard, oid, payload, attrs
                )
        await msg.conn.send_message(MOSDPGPushReply(
            pg=msg.pg, shard=msg.shard, from_osd=self.id, epoch=self.epoch,
        ))


ECConnErrors = (ConnectionError, asyncio.TimeoutError)
